"""Binned-dataset serialization (reference: Dataset::SaveBinaryFile,
dataset.h:416, loader fast path dataset_loader.cpp:274).

Uses a numpy archive instead of the reference's custom binary layout; the
purpose — skip text parsing and re-binning on reload — is the same.
"""
from __future__ import annotations

import json

import numpy as np

from ..utils import log
from .binning import BinMapper
from .dataset import BinnedDataset, Metadata

_MAGIC = "lightgbm_tpu.dataset.v1"


def save_dataset(ds: BinnedDataset, path: str) -> None:
    meta = {
        "magic": _MAGIC,
        "num_data": ds.num_data,
        "num_total_features": ds.num_total_features,
        "max_bin": ds.max_bin,
        "feature_names": ds.feature_names,
        "bin_mappers": [m.to_dict() for m in ds.bin_mappers],
    }
    arrays = {
        "X_bin": ds.X_bin,
        "used_feature_map": ds.used_feature_map,
        "real_feature_idx": ds.real_feature_idx,
        "bin_offsets": ds.bin_offsets,
    }
    if ds.bundle is not None:
        meta["bundle_groups"] = ds.bundle.groups
        arrays["bundle_feat2phys"] = ds.bundle.feat2phys
        arrays["bundle_feat_offset"] = ds.bundle.feat_offset
        arrays["bundle_needs_fix"] = ds.bundle.needs_fix
        arrays["bundle_phys_num_bin"] = ds.bundle.phys_num_bin
    md = ds.metadata
    for name in ("label", "weights", "init_score"):
        v = getattr(md, name)
        if v is not None:
            arrays["md_" + name] = v
    if md.query_boundaries is not None:
        # store per-query sizes: boundaries like [0, N] would be re-read as
        # sizes by set_query and grow a phantom query
        arrays["md_query_sizes"] = np.diff(md.query_boundaries)
    np.savez_compressed(path, meta=json.dumps(meta), **arrays)


def load_dataset(path: str) -> BinnedDataset:
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["meta"]))
        if meta.get("magic") != _MAGIC:
            log.fatal(f"{path} is not a lightgbm_tpu binned dataset")
        ds = BinnedDataset()
        ds.num_data = int(meta["num_data"])
        ds.num_total_features = int(meta["num_total_features"])
        ds.max_bin = int(meta["max_bin"])
        ds.feature_names = list(meta["feature_names"])
        ds.bin_mappers = [BinMapper.from_dict(d) for d in meta["bin_mappers"]]
        ds.X_bin = z["X_bin"]
        ds.used_feature_map = z["used_feature_map"]
        ds.real_feature_idx = z["real_feature_idx"]
        ds.bin_offsets = z["bin_offsets"]
        if "bundle_feat2phys" in z:
            from .bundling import BundleInfo
            ds.bundle = BundleInfo(
                feat2phys=z["bundle_feat2phys"],
                feat_offset=z["bundle_feat_offset"],
                needs_fix=z["bundle_needs_fix"],
                num_phys=int(ds.X_bin.shape[1]),
                phys_num_bin=z["bundle_phys_num_bin"],
                groups=[list(g) for g in meta.get("bundle_groups", [])],
            )
        ds.metadata = Metadata(ds.num_data)
        if "md_label" in z:
            ds.metadata.set_label(z["md_label"])
        if "md_weights" in z:
            ds.metadata.set_weights(z["md_weights"])
        if "md_query_sizes" in z:
            ds.metadata.set_query(z["md_query_sizes"])
        if "md_init_score" in z:
            ds.metadata.set_init_score(z["md_init_score"])
        return ds
