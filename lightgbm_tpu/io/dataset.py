"""Binned dataset: the HBM-resident training representation.

TPU-native rebuild of the reference's ``Dataset``/``Metadata``/``DatasetLoader``
(reference: include/LightGBM/dataset.h:41-669, src/io/dataset_loader.cpp).
Instead of per-feature-group ``Bin`` columns with sparse/dense variants and
most-frequent-bin elision, the TPU representation is a single dense
unsigned-int matrix ``X_bin[num_data, num_features]`` (uint8 normally; widened
to uint16/uint32 when a categorical feature exceeds 256 bins) laid out for
streaming into the Pallas histogram kernel, plus a flat bin-offset table so
all features share one histogram address space (the analog of the reference's
``NumTotalBin`` flat layout). Sparse storage is intentionally dropped: EFB
densifies exclusive sparse features into shared columns instead
(SURVEY.md §7 "hard parts" #5 documents the deviation).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..config import Config
from ..utils import log
from ..utils.random import Random
from .binning import (BIN_CATEGORICAL, BIN_NUMERICAL, MISSING_NAN, MISSING_NONE,
                      MISSING_ZERO, BinMapper)


class Metadata:
    """Labels, weights, query boundaries and init scores
    (reference: Metadata, dataset.h:41-250)."""

    def __init__(self, num_data: int = 0):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None          # float32 [num_data]
        self.weights: Optional[np.ndarray] = None        # float32 [num_data]
        self.query_boundaries: Optional[np.ndarray] = None  # int32 [num_queries+1]
        self.query_weights: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None     # float64 [num_data * k]

    def set_label(self, label) -> None:
        label = np.ascontiguousarray(label, dtype=np.float32).ravel()
        log.check(len(label) == self.num_data, "label length != num_data")
        self.label = label

    def set_weights(self, weights) -> None:
        if weights is None:
            self.weights = None
            return
        weights = np.ascontiguousarray(weights, dtype=np.float32).ravel()
        log.check(len(weights) == self.num_data, "weights length != num_data")
        log.check(bool((weights >= 0).all()), "weights should be non-negative")
        self.weights = weights
        self._update_query_weights()

    def set_query(self, group) -> None:
        """``group`` is per-query sizes (LightGBM convention) or boundaries."""
        if group is None:
            self.query_boundaries = None
            self.query_weights = None
            return
        group = np.ascontiguousarray(group, dtype=np.int64).ravel()
        if group.sum() == self.num_data:  # sizes
            self.query_boundaries = np.concatenate(
                [[0], np.cumsum(group)]).astype(np.int32)
        elif len(group) >= 1 and group[0] == 0 and group[-1] == self.num_data:
            self.query_boundaries = group.astype(np.int32)
        else:
            log.fatal("Initial sizes of queries do not sum to num_data")
        self._update_query_weights()

    def _update_query_weights(self) -> None:
        if self.query_boundaries is None or self.weights is None:
            self.query_weights = None
            return
        b = self.query_boundaries
        sums = np.add.reduceat(self.weights, b[:-1])
        cnts = np.diff(b)
        self.query_weights = (sums / np.maximum(cnts, 1)).astype(np.float32)

    def set_init_score(self, init_score) -> None:
        if init_score is None:
            self.init_score = None
            return
        init_score = np.ascontiguousarray(init_score, dtype=np.float64).ravel()
        log.check(len(init_score) % self.num_data == 0,
                  "init_score length must be a multiple of num_data")
        self.init_score = init_score

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1


class BinnedDataset:
    """The constructed training dataset (reference: Dataset, dataset.h:283).

    Attributes
    ----------
    X_bin : np.ndarray  uint8/uint16/uint32 [num_data, num_features]
        Binned feature matrix (only non-trivial features).
    bin_mappers : list[BinMapper]
        One per *original* feature column (trivial ones included).
    used_feature_map : np.ndarray int32 [num_total_features]
        original feature → inner column index, -1 if unused
        (reference: used_feature_map_, dataset.h:629).
    bin_offsets : np.ndarray int32 [num_features+1]
        flat histogram offsets per inner column.
    """

    def __init__(self):
        self.num_data: int = 0
        self.num_total_features: int = 0
        self.X_bin: Optional[np.ndarray] = None
        self.bin_mappers: List[BinMapper] = []
        self.used_feature_map: Optional[np.ndarray] = None
        self.real_feature_idx: Optional[np.ndarray] = None  # inner → original
        self.bin_offsets: Optional[np.ndarray] = None
        self.metadata: Metadata = Metadata()
        self.feature_names: List[str] = []
        self.max_bin: int = 255
        # EFB bundle info (io.bundling.BundleInfo; None = no bundling)
        self.bundle = None

    # ------------------------------------------------------------------
    @property
    def num_features(self) -> int:
        """Number of used (inner) features — NOT physical columns; with
        EFB several features share one ``X_bin`` column."""
        if self.real_feature_idx is not None:
            return len(self.real_feature_idx)
        return 0 if self.X_bin is None else self.X_bin.shape[1]

    @property
    def num_phys_features(self) -> int:
        """Physical ``X_bin`` columns (== num_features unless bundled)."""
        return 0 if self.X_bin is None else self.X_bin.shape[1]

    def phys_max_bins(self) -> np.ndarray:
        """Bins per PHYSICAL column (kernel histogram width)."""
        if self.bundle is not None:
            return self.bundle.phys_num_bin
        return self.feature_max_bins()

    @property
    def num_total_bin(self) -> int:
        return 0 if self.bin_offsets is None else int(self.bin_offsets[-1])

    def num_bin(self, inner_feature: int) -> int:
        return int(self.bin_offsets[inner_feature + 1] - self.bin_offsets[inner_feature])

    def inner_to_mapper(self, inner_feature: int) -> BinMapper:
        return self.bin_mappers[int(self.real_feature_idx[inner_feature])]

    # ------------------------------------------------------------------
    @classmethod
    def from_matrix(cls, data: np.ndarray, config: Config,
                    categorical_features: Sequence[int] = (),
                    feature_names: Optional[List[str]] = None,
                    reference: Optional["BinnedDataset"] = None,
                    sample_indices: Optional[np.ndarray] = None) -> "BinnedDataset":
        """Construct from a dense float matrix.

        Mirrors the reference path DatasetLoader::CostructFromSampleData →
        BinMapper::FindBin → Dataset::Construct (dataset_loader.cpp:574,
        bin.cpp:325, dataset.cpp:265): sample rows, find per-feature bin
        bounds, then binarize every row. With ``reference`` given, bin mappers
        are shared so validation data aligns with the training bin space
        (reference: LoadFromFileAlignWithOtherDataset, dataset_loader.cpp:230).
        """
        data = np.asarray(data)
        if data.dtype not in (np.float32, np.float64):
            data = data.astype(np.float64)
        n, p = data.shape
        if n == 0:
            log.fatal("Cannot construct a Dataset from an empty matrix (0 rows)")

        if reference is not None:
            ds = cls()
            ds.num_data = n
            ds.num_total_features = p
            ds.metadata = Metadata(n)
            log.check(p == reference.num_total_features,
                      "validation data has a different number of features")
            ds.bin_mappers = reference.bin_mappers
            ds.used_feature_map = reference.used_feature_map
            ds.real_feature_idx = reference.real_feature_idx
            ds.bin_offsets = reference.bin_offsets
            ds.feature_names = reference.feature_names
            ds.max_bin = reference.max_bin
            ds.bundle = reference.bundle
            ds._binarize(data)
            return ds

        # ---- sample rows for bin finding ----
        sample_cnt = min(config.bin_construct_sample_cnt, n)
        if sample_indices is None:
            rng = Random(config.data_random_seed)
            sample_indices = (np.arange(n, dtype=np.int64) if sample_cnt >= n
                              else rng.sample(n, sample_cnt).astype(np.int64))
        sample = data[sample_indices]
        ds = cls.from_sample(sample, n, config,
                             categorical_features=categorical_features,
                             feature_names=feature_names)
        from ..utils.timetag import timetag
        ds._alloc_X()
        with timetag("binarize"):
            ds._binarize_chunk(data, 0)
        return ds

    @classmethod
    def from_sample(cls, sample: np.ndarray, num_data: int, config: Config,
                    categorical_features: Sequence[int] = (),
                    feature_names: Optional[List[str]] = None) -> "BinnedDataset":
        """Build mappers/feature-map/bundles from a row SAMPLE, leaving
        ``X_bin`` unallocated — the constructor half of the reference's
        two-pass loading (DatasetLoader::ConstructFromSampleData +
        two_round, dataset_loader.cpp:574,807-827).  Callers then
        ``_alloc_X()`` and stream rows through ``_binarize_chunk``.
        """
        ds = cls()
        p = sample.shape[1]
        ds.num_data = int(num_data)
        ds.num_total_features = p
        ds.metadata = Metadata(ds.num_data)
        ds.max_bin = config.max_bin
        ds.feature_names = (list(feature_names) if feature_names
                            else [f"Column_{i}" for i in range(p)])
        sample_csc = sample.tocsc() if hasattr(sample, "tocsc") else None
        if sample_csc is None:
            # multi-host: pool every host's sample so all processes derive
            # identical mappers; sample-vs-data ratios below must then use
            # the GLOBAL row count (no-op single-host;
            # parallel/distributed.py)
            from ..parallel.distributed import global_bin_sample
            sample, n_global = global_bin_sample(sample, ds.num_data)
        else:
            # multi-host sparse: pool the samples as COO triplets so
            # every process derives identical mappers (no densifying)
            from ..parallel.distributed import global_bin_sample_sparse
            sample_csc, n_global = global_bin_sample_sparse(
                sample_csc, ds.num_data)
            sample = sample_csc

        from ..utils.timetag import timetag
        cat_set = set(int(c) for c in categorical_features)
        ds.bin_mappers = []
        forced = _load_forced_bins(config.forcedbins_filename, p, config.max_bin)
        # min-data filter threshold scaled to the bin-finding sample
        # (reference: dataset_loader.cpp:599 filter_cnt)
        filter_cnt = int(config.min_data_in_leaf * sample.shape[0] / n_global)
        mbf = [int(v) for v in (config.max_bin_by_feature or [])]
        if mbf:
            # reference: dataset_loader.cpp:438-441
            log.check(len(mbf) == p, "max_bin_by_feature should be the "
                      "same size as feature number")
            log.check(min(mbf) > 1,
                      "max_bin_by_feature values should be greater than 1")
        bin_finding = timetag("bin finding")
        bin_finding.__enter__()
        for j in range(p):
            if sample_csc is not None:
                # only stored entries can be non-zero; implicit zeros are
                # exactly the dropped |v| <= kZeroThreshold values below
                lo, hi = sample_csc.indptr[j], sample_csc.indptr[j + 1]
                col = np.asarray(sample_csc.data[lo:hi], np.float64)
            else:
                col = sample[:, j]
            # drop "zero" values (|v| <= kZeroThreshold); NaN compares False so
            # NaNs are kept for the missing-type decision
            non_zero = col[~((col > -1e-35) & (col <= 1e-35))]
            mapper = BinMapper()
            bt = BIN_CATEGORICAL if j in cat_set else BIN_NUMERICAL
            mapper.find_bin(non_zero, sample.shape[0],
                            mbf[j] if mbf else config.max_bin,
                            config.min_data_in_bin, filter_cnt,
                            bt, config.use_missing, config.zero_as_missing,
                            forced.get(j))
            ds.bin_mappers.append(mapper)
        bin_finding.__exit__()
        ds._finalize_features()
        if (config.enable_bundle and len(ds.real_feature_idx) >= 2
                and config.max_bin <= 255
                and getattr(config, "tree_learner", "serial") == "serial"):
            from .bundling import build_bundles
            # wide-sparse datasets get uint16-wide bundle columns so EFB
            # can pack hundreds of features per column (the histogram
            # switches to the scatter path past 32k physical bins)
            wide = len(ds.real_feature_idx) > 2048
            bundle = build_bundles(ds.bin_mappers, ds.real_feature_idx,
                                   sample, n_global,
                                   config.max_conflict_rate,
                                   max_bins_per_group=4096 if wide else 256)
            if not bundle.is_trivial:
                ds.bundle = bundle
        from .. import obs
        if obs.enabled():
            obs.event("dataset", num_data=ds.num_data,
                      num_total_features=p,
                      num_used_features=int(len(ds.real_feature_idx)),
                      total_bins=int(ds.bin_offsets[-1]),
                      bundled=getattr(ds, "bundle", None) is not None,
                      sample_rows=int(sample.shape[0]))
        return ds

    def _finalize_features(self) -> None:
        used = [j for j, m in enumerate(self.bin_mappers) if not m.is_trivial]
        self.used_feature_map = np.full(self.num_total_features, -1, dtype=np.int32)
        for inner, j in enumerate(used):
            self.used_feature_map[j] = inner
        self.real_feature_idx = np.asarray(used, dtype=np.int32)
        nbins = [self.bin_mappers[j].num_bin for j in used]
        self.bin_offsets = np.concatenate([[0], np.cumsum(nbins)]).astype(np.int32)
        if not used:
            log.warning("There are no meaningful features, as all feature values are constant.")

    @classmethod
    def from_csr(cls, X, config: Config,
                 categorical_features: Sequence[int] = (),
                 feature_names: Optional[List[str]] = None,
                 reference: Optional["BinnedDataset"] = None) -> "BinnedDataset":
        """Construct from a scipy.sparse matrix WITHOUT densifying the raw
        values — the memory-bounded replacement for the reference's
        ``SparseBin`` streams (src/io/sparse_bin.hpp:72,
        ordered_sparse_bin.hpp:1; trade-off at bin.h:224-277).

        Bin finding reads stored entries per CSC column; EFB packs the
        mutually-exclusive (within ``max_conflict_rate``) sparse features
        into shared physical columns; binarization scatters only stored
        non-default bins.  Peak memory is the CSC copy + the binned
        matrix — never rows x features x 8 bytes.  Genuinely conflicting
        wide data that EFB cannot pack still materializes one physical
        column per feature; raise ``max_conflict_rate`` (the reference's
        own EFB knob) to trade exactness for packing.
        """
        import scipy.sparse as sp

        X = X.tocsr() if not sp.issparse(X) or X.format != "csr" else X
        n, p = X.shape
        if n == 0:
            log.fatal("Cannot construct a Dataset from an empty matrix (0 rows)")

        if reference is not None:
            ds = cls()
            ds.num_data = n
            ds.num_total_features = p
            ds.metadata = Metadata(n)
            log.check(p == reference.num_total_features,
                      "validation data has a different number of features")
            ds.bin_mappers = reference.bin_mappers
            ds.used_feature_map = reference.used_feature_map
            ds.real_feature_idx = reference.real_feature_idx
            ds.bin_offsets = reference.bin_offsets
            ds.feature_names = reference.feature_names
            ds.max_bin = reference.max_bin
            ds.bundle = reference.bundle
            ds._binarize_csc(X.tocsc())
            return ds

        sample_cnt = min(config.bin_construct_sample_cnt, n)
        rng = Random(config.data_random_seed)
        sample_indices = (np.arange(n, dtype=np.int64) if sample_cnt >= n
                          else rng.sample(n, sample_cnt).astype(np.int64))
        ds = cls.from_sample(X[sample_indices], n, config,
                             categorical_features=categorical_features,
                             feature_names=feature_names)
        from ..utils.timetag import timetag
        with timetag("binarize"):
            ds._binarize_csc(X.tocsc())
        return ds

    def _binarize_csc(self, X_csc) -> None:
        """Scatter stored non-default bins into the physical matrix.

        Unbundled columns init to the feature's default bin (the bin of
        value 0.0 — implicit entries); bundle columns init to physical
        bin 0 (= every member at default, io/bundling.py layout)."""
        from .binning import BIN_CATEGORICAL

        used = self.real_feature_idx
        groups = (self.bundle.groups if self.bundle is not None
                  else [[i] for i in range(len(used))])
        self._alloc_X()  # single source of the widest/dtype ladder
        X = self.X_bin
        X.fill(0)  # implicit entries: bin 0 until default-bin init below
        dtype = X.dtype
        indptr, indices, data = X_csc.indptr, X_csc.indices, X_csc.data
        for gp, members in enumerate(groups):
            if len(members) == 1:
                inner = members[0]
                j = int(used[inner])
                m = self.bin_mappers[j]
                lo, hi = indptr[j], indptr[j + 1]
                fb = np.asarray(m.value_to_bin(
                    np.asarray(data[lo:hi], np.float64)))
                if m.default_bin:
                    X[:, gp] = m.default_bin
                X[indices[lo:hi], gp] = fb.astype(dtype)
                continue
            for inner in members:
                j = int(used[inner])
                m = self.bin_mappers[j]
                lo, hi = indptr[j], indptr[j + 1]
                fb = np.asarray(m.value_to_bin(
                    np.asarray(data[lo:hi], np.float64)))
                nz = fb != m.default_bin
                off = self.bundle.feat_offset[inner]
                X[indices[lo:hi][nz], gp] = (off + fb[nz]).astype(dtype)
        self.X_bin = X

    def _bin_matrix_spec(self):
        """``(columns, dtype)`` of the physical bin matrix — the single
        source of the width/dtype ladder, shared by the in-RAM
        ``_alloc_X`` and the streaming ingestion path's memmap
        allocation (ingest/stream.py)."""
        if self.bundle is not None:
            widest = int(max(self.bundle.phys_num_bin.max(initial=0),
                             self.feature_max_bins().max(initial=0)))
            cols = self.bundle.num_phys
        else:
            # size storage by the ACTUAL bin counts: categorical bin
            # finding can exceed max_bin (reference sizes by num_bin,
            # bin.cpp CreateBin)
            widest = int(self.feature_max_bins().max(initial=0))
            cols = len(self.real_feature_idx)
        dtype = (np.uint8 if widest <= 256
                 else np.uint16 if widest <= 65536 else np.uint32)
        if dtype != np.uint8 and self.max_bin <= 256:
            log.warning(
                "A feature has %d bins (> 256, from a high-cardinality "
                "categorical); the whole binned matrix is widened to %s",
                widest, np.dtype(dtype).name)
        return cols, dtype

    def _alloc_X(self) -> None:
        """Allocate the binned matrix for ``num_data`` rows (filled by
        ``_binarize_chunk`` — whole-matrix or streaming two_round)."""
        cols, dtype = self._bin_matrix_spec()
        self.X_bin = np.empty((self.num_data, cols), dtype=dtype)

    def _binarize(self, data: np.ndarray) -> None:
        self._alloc_X()
        self._binarize_chunk(data, 0)

    def _binarize_chunk(self, data: np.ndarray, row0: int) -> None:
        """Bin ``data``'s rows into ``X_bin[row0:row0+len(data)]``."""
        if self.bundle is not None:
            self._binarize_bundled_chunk(data, row0)
            return
        used = self.real_feature_idx
        n = len(data)
        X = self.X_bin[row0:row0 + n]
        dtype = X.dtype
        from .. import native as _native
        from .binning import BIN_NUMERICAL, MISSING_NAN
        fast = _native.lib() is not None and dtype == np.uint8
        # one contiguous transpose of ONLY the used numerical columns:
        # per-feature reads become sequential instead of 8-bytes-per-
        # cache-line strided column walks, without doubling peak memory
        # on wide matrices with unused/categorical columns
        dt, dt_row = None, {}
        if fast and data.dtype == np.float64:
            num_cols = [int(j) for j in used
                        if self.bin_mappers[int(j)].bin_type == BIN_NUMERICAL]
            if num_cols:
                # fill a preallocated transpose column-by-column: one extra
                # copy of the numerical submatrix, never two at once
                dt = np.empty((len(num_cols), n), np.float64)
                for r, j in enumerate(num_cols):
                    dt[r] = data[:, j]
                dt_row = {j: r for r, j in enumerate(num_cols)}
        for inner, j in enumerate(used):
            m = self.bin_mappers[int(j)]
            if dt is not None and int(j) in dt_row:
                ns = m.num_bin - (1 if m.missing_type == MISSING_NAN else 0)
                _native.binarize_numerical_u8(
                    dt[dt_row[int(j)]], m.bin_upper_bound, ns - 1,
                    m.missing_type, m.num_bin, X[:, inner])
            else:
                X[:, inner] = m.value_to_bin(data[:, int(j)]).astype(dtype)

    def _binarize_bundled(self, data: np.ndarray) -> None:
        self._alloc_X()
        self._binarize_bundled_chunk(data, 0)

    def _binarize_bundled_chunk(self, data: np.ndarray, row0: int) -> None:
        """Binarize into EFB physical columns (see io/bundling.py layout;
        reference: Dataset::PushOneRow -> FeatureGroup::PushData,
        dataset.h:333-359)."""
        from .bundling import encode_column
        bundle = self.bundle
        used = self.real_feature_idx
        n = len(data)
        X = self.X_bin[row0:row0 + n]
        dtype = X.dtype
        for gp, members in enumerate(bundle.groups):
            if len(members) == 1:
                inner = members[0]
                m = self.bin_mappers[int(used[inner])]
                X[:, gp] = m.value_to_bin(data[:, int(used[inner])]).astype(dtype)
                continue
            mappers = [self.bin_mappers[int(used[inner])]
                       for inner in members]
            feat_bins = [np.asarray(m.value_to_bin(data[:, int(used[i])]))
                         for m, i in zip(mappers, members)]
            X[:, gp] = encode_column(
                bundle, members, feat_bins,
                [m.default_bin for m in mappers], n, dtype)

    # ------------------------------------------------------------------
    def create_valid(self, data: np.ndarray) -> "BinnedDataset":
        """Bin a validation matrix in this dataset's bin space."""
        return BinnedDataset.from_matrix(data, Config(), reference=self)

    def feature_max_bins(self) -> np.ndarray:
        """num_bin per inner feature, int32 [num_features]."""
        return np.diff(self.bin_offsets).astype(np.int32)


def _load_forced_bins(path: str, num_features: int, max_bin: int) -> Dict[int, List[float]]:
    """Read forced bin bounds from JSON: [{"feature": i, "bin_upper_bound":
    [...]}] (reference: DatasetLoader::GetForcedBins, dataset_loader.cpp:1246)."""
    if not path:
        return {}
    import json
    with open(path) as fh:
        entries = json.load(fh)
    out: Dict[int, List[float]] = {}
    for e in entries:
        j = int(e["feature"])
        if 0 <= j < num_features:
            bounds = sorted(float(x) for x in e["bin_upper_bound"])[: max_bin]
            out[j] = bounds
    return out
