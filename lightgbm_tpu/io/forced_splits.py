"""Forced splits: a BFS split prescription loaded from JSON.

The analog of the reference's ``forcedsplits_filename``
(reference: src/treelearner/serial_tree_learner.cpp:607-770 ForceSplits;
config.h forcedsplits_filename).  The JSON is a binary tree of
``{"feature": <original index>, "threshold": <value>, "left": {...},
"right": {...}}`` nodes applied breadth-first at the start of EVERY tree,
before gain-driven growth.

The TPU formulation flattens the BFS into three fixed arrays indexed by
split step ``k`` — (leaf, inner_feature, threshold_bin) — exploiting the
grower's leaf-numbering invariant (left child keeps the parent's leaf
index, the right child becomes leaf ``k+1``, core/grower.py TreeArrays).
The grower consumes them as compile-time constants: step ``k`` splits
``leaf[k]`` on ``feature[k]`` at ``threshold_bin[k]`` when the JSON
prescribes one, falling back to best-gain search afterwards.

Deviations from the reference, both documented here on purpose:
- thresholds are binned with ``value_to_bin`` and rows route left when
  ``bin <= threshold_bin`` — the framework's single split convention —
  rather than reproducing GatherInfoForThreshold's strict-< scan;
- categorical features cannot be forced (the reference allows a single
  category threshold); a warning is raised and forcing stops there.
"""
from __future__ import annotations

import json
import os
from typing import Optional, Tuple

import numpy as np

from ..utils import log
from .binning import BIN_NUMERICAL


def load_forced_splits(path: str, ds, num_leaves: int
                       ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                           np.ndarray]]:
    """Parse ``forcedsplits_filename`` into step-indexed arrays.

    Returns ``(leaf, feature, threshold_bin)`` int32 arrays of length
    ``num_leaves - 1`` padded with -1 where growth is gain-driven, or
    ``None`` when no file is configured.  ``ds`` is the BinnedDataset
    whose mappers define the bin space.
    """
    if not path:
        return None
    if not os.path.exists(path):
        log.fatal(f"Forced splits file {path} does not exist")
    with open(path) as fh:
        root = json.load(fh)
    if not root:
        return None

    n = max(num_leaves - 1, 1)
    fl = np.full(n, -1, np.int32)
    ff = np.full(n, -1, np.int32)
    ft = np.zeros(n, np.int32)
    queue = [(root, 0)]  # (json node, leaf index) — BFS like the reference
    k = 0
    while queue and k < n:
        node, leaf = queue.pop(0)
        orig = int(node["feature"])
        thr = float(node["threshold"])
        if orig < 0 or orig >= ds.num_total_features:
            log.fatal(f"Forced split feature {orig} out of range")
        inner = int(ds.used_feature_map[orig])
        if inner < 0:
            log.warning("Forced split on unused feature %d ignored; "
                        "remaining forced splits dropped", orig)
            break
        mapper = ds.inner_to_mapper(inner)
        if mapper.bin_type != BIN_NUMERICAL:
            log.warning("Forced split on categorical feature %d is not "
                        "supported; remaining forced splits dropped", orig)
            break
        fl[k] = leaf
        ff[k] = inner
        ft[k] = int(np.asarray(mapper.value_to_bin(np.asarray([thr])))[0])
        if isinstance(node.get("left"), dict):
            queue.append((node["left"], leaf))
        if isinstance(node.get("right"), dict):
            queue.append((node["right"], k + 1))
        k += 1
    if queue and k >= n:
        log.warning("Forced splits exceed num_leaves-1=%d; extra nodes "
                    "ignored", n)
    if k == 0:
        return None
    return fl, ff, ft
