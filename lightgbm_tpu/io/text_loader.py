"""Text dataset loading for the CLI driver.

The analog of the reference's DatasetLoader text path (reference:
src/io/dataset_loader.cpp:168,807-1042): dense TSV/CSV files with the
label in a configurable column, optional header, weight/group columns, and
the ``<data>.weight`` / ``<data>.query`` sidecar files.  Sparse LibSVM
input is not supported (the TPU path is dense; see io/dataset.py).
"""
from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..utils import log


def _sniff_delimiter(line: str) -> str:
    for d in ("\t", ",", " "):
        if d in line:
            return d
    return "\t"


_CHUNK_BYTES = 64 * 1024 * 1024


class _ParseError(Exception):
    """Native parser rejected the file; fall back to np.loadtxt."""


def _file_ncol(mm, pos: int, size: int, delim: str) -> int:
    nl = mm.find(b"\n", pos)
    first = mm[pos:(nl if nl >= 0 else size)].decode(
        "utf-8", "replace").rstrip("\r")
    return len(first.split() if delim == " " else first.split(delim))


def _mmap_windows(path: str, skiprows: int, chunk_bytes: int = _CHUNK_BYTES):
    """Yield ``(mm, lo, hi)`` newline-aligned windows over an mmap of the
    file — the parser reads straight out of the page cache, no bytes
    copies, no carry-over concatenation."""
    import mmap

    with open(path, "rb") as fh:
        size = os.fstat(fh.fileno()).st_size
        if size == 0:
            return
        mm = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
        try:
            pos = 0
            for _ in range(skiprows):
                nl = mm.find(b"\n", pos)
                pos = (nl + 1) if nl >= 0 else size
            while pos < size:
                hi = min(pos + chunk_bytes, size)
                if hi < size:
                    nl = mm.rfind(b"\n", pos, hi)
                    if nl < pos:  # a single line longer than the window
                        nl = mm.find(b"\n", hi)
                        hi = size if nl < 0 else nl + 1
                    else:
                        hi = nl + 1
                yield mm, pos, hi
                pos = hi
        finally:
            mm.close()


def _iter_dense_chunks(path: str, delim: str, skiprows: int,
                       chunk_bytes: int = _CHUNK_BYTES):
    """Stream-parse a dense numeric text file with the native chunk parser
    (native/binning_native.cpp csv_parse — the reference's
    TextReader/PipelineReader analog, utils/text_reader.h:1-341), yielding
    row-major f64 arrays.  Raises ``_ParseError`` when the native library
    is unavailable or the file needs np.loadtxt's leniency.
    """
    from .. import native as _native
    if _native.lib() is None:
        raise _ParseError("native library unavailable")
    ncol = None
    for mm, lo, hi in _mmap_windows(path, skiprows, chunk_bytes):
        if ncol is None:
            ncol = _file_ncol(mm, lo, len(mm), delim)
        arr = _native.csv_parse(mm, delim, ncol, offset=lo, length=hi - lo)
        if arr is None:
            raise _ParseError("malformed row (inconsistent columns?)")
        if len(arr):
            yield arr


def _read_dense(path: str, delim: str, skiprows: int) -> np.ndarray:
    """Whole-file dense parse: native mmap parse with the lenient
    np.loadtxt fallback."""
    try:
        # one window over the whole file: a single exactly-sized output
        # array, no per-chunk vstack copy
        size = max(os.path.getsize(path), 1)
        parts = list(_iter_dense_chunks(path, delim, skiprows,
                                        chunk_bytes=size))
        if parts:
            return parts[0] if len(parts) == 1 else np.vstack(parts)
    except _ParseError as exc:
        log.info("Native text parse unavailable (%s); using np.loadtxt",
                 exc)
    return np.loadtxt(path, delimiter=None if delim == " " else delim,
                      skiprows=skiprows, ndmin=2, dtype=np.float64)


def _resolve_column(spec: str, names: List[str], what: str) -> Optional[int]:
    """Column spec: "" -> None, "3" -> 3, "name:foo" -> index of foo
    (reference: dataset_loader.cpp column-by-name needs a header)."""
    if spec == "":
        return None
    if spec.startswith("name:"):
        name = spec[5:]
        if name not in names:
            log.fatal(f"{what} column {name!r} not found in header")
        return names.index(name)
    try:
        return int(spec)
    except ValueError:
        log.fatal(f"Bad {what} column spec {spec!r}")


def load_text(path: str, config) -> Tuple[np.ndarray, Optional[np.ndarray],
                                          Optional[np.ndarray],
                                          Optional[np.ndarray], List[str]]:
    """Load a dense text data file.

    Returns (X, label, weight, group, feature_names); label/weight/group
    are None when absent.  ``label_column`` counts ALL file columns;
    integer weight/group/ignore indices do NOT count the label column
    (reference: config.h weight_column doc), while ``name:`` specs are
    absolute header positions.
    """
    if not os.path.exists(path):
        log.fatal(f"Data file {path} does not exist")
    with open(path) as fh:
        first = fh.readline()
    if ":" in first and not getattr(config, "header", False):
        return _load_libsvm(path, config)
    delim = _sniff_delimiter(first.rstrip("\n"))
    names: List[str] = []
    skip = 0
    if getattr(config, "header", False):
        names = [t.strip() for t in first.rstrip("\n").split(delim)]
        skip = 1
    data = _read_dense(path, delim, skip)
    ncol = data.shape[1]
    names, label_col, weight_col, group_col, keep = _column_plan(
        names, ncol, config)

    label = data[:, label_col]
    weight = data[:, weight_col] if weight_col is not None else None
    group_raw = data[:, group_col] if group_col is not None else None
    X = data[:, keep]
    feat_names = [names[i] for i in keep]

    weight, group = _load_sidecars(path, weight, None)
    return X, label, weight, group if group is not None else _group_from_col(
        group_raw), feat_names


def _column_plan(names: List[str], ncol: int, config):
    """Resolve the label/weight/group/ignore column layout of a data file
    -> (names, label_col, weight_col, group_col, keep_columns)."""
    if not names:
        names = [f"Column_{i}" for i in range(ncol)]

    label_col = _resolve_column(getattr(config, "label_column", ""),
                                names, "label")
    if label_col is None:
        label_col = 0

    def skip_label(col: Optional[int], spec) -> Optional[int]:
        """Integer weight/group/ignore indices do NOT count the label
        column (reference: config.h weight_column doc — "index starts
        from 0 and it doesn't count the label column when passing type
        is int"); name: specs are absolute."""
        if col is None or str(spec).startswith("name:"):
            return col
        return col + 1 if col >= label_col else col

    wspec = getattr(config, "weight_column", "")
    gspec = getattr(config, "group_column", "")
    weight_col = skip_label(_resolve_column(wspec, names, "weight"), wspec)
    group_col = skip_label(_resolve_column(gspec, names, "group"), gspec)

    drop = {label_col}
    if weight_col is not None:
        drop.add(weight_col)
    if group_col is not None:
        drop.add(group_col)
    ignore = getattr(config, "ignore_column", "")
    if ignore:
        for tok in str(ignore).split(","):
            tok = tok.strip()
            c = skip_label(_resolve_column(tok, names, "ignore"), tok)
            if c is not None:
                drop.add(c)
    keep = [i for i in range(ncol) if i not in drop]
    return names, label_col, weight_col, group_col, keep


def _group_from_col(group_raw):
    if group_raw is None:
        return None
    # per-row query ids -> query sizes (reference converts ordered ids)
    ids = group_raw.astype(np.int64)
    change = np.flatnonzero(np.diff(ids)) + 1
    bounds = np.concatenate([[0], change, [len(ids)]])
    return np.diff(bounds)


def _load_libsvm(path: str, config):
    """Sparse ``label [qid:Q] idx:val ...`` rows (the MSLR-WEB30K format)
    -> a scipy CSR matrix (implicit entries are 0.0, which the zero-bin /
    SparseBin-analog handling treats natively; reference:
    dataset_loader.cpp sparse parser).  Native chunked parser with a
    Python fallback; ``qid:`` tokens become query boundaries unless a
    ``.query`` sidecar overrides them."""
    from .. import native as _native

    labels_l, qids_l, trip = [], [], []
    max_idx = -1
    if _native.lib() is not None:
        for mm, lo, hi in _mmap_windows(path, 0):
            out = _native.libsvm_parse(mm, offset=lo, length=hi - lo)
            if out is None:
                labels_l = []
                break  # malformed for the strict parser: Python fallback
            lab, qid, indptr, idx, vals, mf = out
            labels_l.append(lab)
            qids_l.append(qid)
            trip.append((indptr, idx, vals))
            max_idx = max(max_idx, mf)
    if labels_l:
        label = np.concatenate(labels_l)
        qids = np.concatenate(qids_l)
        counts = np.concatenate([np.diff(t[0]) for t in trip])
        indptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        indices = np.concatenate([t[1] for t in trip])
        values = np.concatenate([t[2] for t in trip])
    else:
        # lenient Python fallback (also exercised with
        # LIGHTGBM_TPU_NO_NATIVE=1)
        labels_py: List[float] = []
        qids_py: List[int] = []
        indptr_py = [0]
        idx_py: List[int] = []
        val_py: List[float] = []
        with open(path) as fh:
            for line in fh:
                toks = line.split()
                if not toks:
                    continue
                labels_py.append(float(toks[0]))
                q = -1
                for tok in toks[1:]:
                    i, _, v = tok.partition(":")
                    if i == "qid":
                        q = int(v)
                        continue
                    fi = int(i)
                    idx_py.append(fi)
                    val_py.append(float(v))
                    if fi > max_idx:
                        max_idx = fi
                qids_py.append(q)
                indptr_py.append(len(idx_py))
        label = np.asarray(labels_py)
        qids = np.asarray(qids_py, np.int64)
        indptr = np.asarray(indptr_py, np.int64)
        indices = np.asarray(idx_py, np.int32)
        values = np.asarray(val_py, np.float64)

    import scipy.sparse as sp
    X = sp.csr_matrix((values, indices, indptr),
                      shape=(len(label), max_idx + 1))
    names = [f"Column_{i}" for i in range(max_idx + 1)]
    has_q = qids >= 0
    qid_group = None
    if len(qids) and has_q.any():
        if has_q.all():
            qid_group = _group_from_col(qids)
        else:
            log.warning("LibSVM file has qid: on only %d of %d rows; "
                        "ignoring qids (provide a .query sidecar or "
                        "annotate every row)", int(has_q.sum()), len(qids))
    weight, group = _load_sidecars(path, None, None)
    return X, label, weight, group if group is not None else qid_group, names


def _load_sidecars(path: str, weight, group):
    """``<data>.weight`` / ``<data>.query`` / ``<data>.group`` files
    (reference: dataset_loader.cpp LoadWeights/LoadQueryBoundaries)."""
    wpath = path + ".weight"
    if weight is None and os.path.exists(wpath):
        weight = np.loadtxt(wpath, dtype=np.float64, ndmin=1)
        log.info("Loading weights from %s", wpath)
    if group is None:
        for suffix in (".query", ".group"):
            qpath = path + suffix
            if os.path.exists(qpath):
                group = np.loadtxt(qpath, dtype=np.int64, ndmin=1)
                log.info("Loading query boundaries from %s", qpath)
                break
    return weight, group


def load_text_two_round(path: str, config, categorical_features=(),
                        reference=None):
    """Two-pass streaming load: construct a ``BinnedDataset`` from a text
    file WITHOUT materializing the full float64 matrix (the reference's
    ``two_round`` path: sample on the first read, push binned rows on the
    second — dataset_loader.cpp:807-827, config.h two_round).

    Pass 1 streams the file counting rows, reservoir-sampling
    ``bin_construct_sample_cnt`` rows for bin finding, and collecting the
    label/weight/group columns.  Pass 2 streams again, binning each chunk
    straight into the preallocated ``X_bin``.  Peak memory is one parsed
    chunk + the binned matrix (1-2 bytes/cell) instead of 8 bytes/cell.

    Returns ``(handle, label, weight, group, feature_names)`` where
    ``handle`` is a constructed BinnedDataset.  With ``reference`` given
    (a constructed BinnedDataset), its bin mappers are reused and the
    sampling pass only counts rows (validation alignment).
    """
    from .dataset import BinnedDataset, Metadata

    if not os.path.exists(path):
        log.fatal(f"Data file {path} does not exist")
    with open(path) as fh:
        first = fh.readline()
    if ":" in first and not getattr(config, "header", False):
        # LibSVM streams through the chunked ingest reader: reservoir
        # bin-sampling over the whole stream, chunk-at-a-time binning —
        # the full sparse matrix (and its dense sample slice) is never
        # materialized, and the constructed dataset bit-matches the
        # in-RAM from_csr path (tests/test_ingest_stream.py)
        from ..ingest.readers import LibSVMSource
        from ..ingest.stream import chunk_rows_from_config, ingest_dataset
        log.info("two_round: streaming LibSVM input through the "
                 "chunked ingest reader")
        src = LibSVMSource(path,
                           chunk_rows=chunk_rows_from_config(config))
        # two_round keeps the pre-ingest contract: the WHOLE file, in
        # RAM — the tpu_ingest_shards/tpu_ingest_memmap knobs (and the
        # memmap env var) only govern the explicit tpu_ingest path, so
        # an ambient ingest config can't silently halve this dataset
        # or write X_bin files from an unrelated job's location
        handle = ingest_dataset(
            src, config, categorical_features=categorical_features,
            reference=reference, num_shards=1, shard_id=0,
            memmap_path="")
        md = handle.metadata
        group_sizes = (np.diff(md.query_boundaries)
                       if md.query_boundaries is not None else None)
        weight, group = _load_sidecars(path, md.weights, group_sizes)
        if weight is not None and md.weights is None:
            handle.metadata.set_weights(weight)
        if group is not None and group_sizes is None:
            handle.metadata.set_query(group)
        return handle, md.label, weight, group, list(handle.feature_names)
    delim = _sniff_delimiter(first.rstrip("\n"))
    names: List[str] = []
    skip = 0
    if getattr(config, "header", False):
        names = [t.strip() for t in first.rstrip("\n").split(delim)]
        skip = 1
    try:
        return _two_round_streamed(path, config, categorical_features,
                                   reference, names, skip, delim)
    except _ParseError as exc:
        # the strict native parser rejected the file (or is unavailable):
        # degrade to the lenient in-memory path rather than erroring
        log.warning("two_round streaming unavailable (%s); falling back "
                    "to in-memory loading", exc)
        X, label, weight, group, fnames = load_text(path, config)
        cats = []
        for c in categorical_features or ():
            if isinstance(c, str):
                if c in fnames:
                    cats.append(fnames.index(c))
            else:
                cats.append(int(c))
        handle = BinnedDataset.from_matrix(
            X, config, categorical_features=cats, feature_names=fnames,
            reference=reference)
        return handle, label, weight, group, fnames


def _two_round_streamed(path, config, categorical_features, reference,
                        names, skip, delim):
    from .dataset import BinnedDataset, Metadata

    # ---- pass 1: count rows, parse ONLY the side columns, and
    # reservoir-sample line BYTE RANGES (the sampled lines are fully
    # parsed once at the end — ~200k lines instead of the whole file)
    from .. import native as _native
    sample_cnt = int(getattr(config, "bin_construct_sample_cnt", 200000))
    rng = np.random.default_rng(getattr(config, "data_random_seed", 1))
    plan = None
    n_rows = 0
    res_off = res_len = None  # sampled line byte ranges
    labels, weights, groups = [], [], []
    side_vals = {}
    for mm, lo, hi in _mmap_windows(path, skip):
        if plan is None:
            if _native.lib() is None:
                log.fatal("two_round loading needs the native parser "
                          "(g++ unavailable?); set two_round=false")
            ncol = _file_ncol(mm, lo, len(mm), delim)
            plan = _column_plan(names, ncol, config)
            names, label_col, weight_col, group_col, keep = plan
            side_cols = sorted({label_col}
                               | ({weight_col} if weight_col is not None
                                  else set())
                               | ({group_col} if group_col is not None
                                  else set()))
            side_pos = {c: i for i, c in enumerate(side_cols)}
        sv = _native.csv_parse_cols(mm, delim, side_cols, offset=lo,
                                    length=hi - lo)
        if sv is None:
            raise _ParseError("malformed row (inconsistent columns?)")
        labels.append(sv[:, side_pos[label_col]].copy())
        if weight_col is not None:
            weights.append(sv[:, side_pos[weight_col]].copy())
        if group_col is not None:
            groups.append(sv[:, side_pos[group_col]].copy())
        if reference is None:
            offs = _native.csv_line_offsets(mm, offset=lo, length=hi - lo)
            offs = offs[:len(sv)]  # a dropped trailing blank line
            lens = np.diff(np.append(offs, hi - lo)).astype(np.int64)
            offs = offs + lo
            if res_off is None:
                res_off = np.empty(sample_cnt, np.int64)
                res_len = np.empty(sample_cnt, np.int64)
            filled = min(n_rows, sample_cnt)
            take = min(max(sample_cnt - filled, 0), len(offs))
            if take:
                res_off[filled:filled + take] = offs[:take]
                res_len[filled:filled + take] = lens[:take]
            if take < len(offs):
                # Algorithm R, vectorized per chunk: row with global index
                # g replaces a random slot with probability sample_cnt/(g+1)
                gi = np.arange(n_rows + take, n_rows + len(offs))
                slots = rng.integers(0, gi + 1)
                hit = slots < sample_cnt
                res_off[slots[hit]] = offs[take:][hit]
                res_len[slots[hit]] = lens[take:][hit]
        n_rows += len(sv)
    if n_rows == 0:
        log.fatal(f"Data file {path} is empty")
    label = np.concatenate(labels)
    weight = np.concatenate(weights) if weights else None
    group_raw = np.concatenate(groups) if groups else None
    feat_names = [names[i] for i in keep]
    # name-based categorical specs resolve against the KEPT feature names
    # (same convention as basic.Dataset._resolve_categorical)
    cats = []
    for c in categorical_features or ():
        if isinstance(c, str):
            if c in feat_names:
                cats.append(feat_names.index(c))
            else:
                log.warning("categorical_feature %r not found in feature "
                            "names; ignored", c)
        else:
            cats.append(int(c))
    categorical_features = sorted(set(cats))

    # ---- mappers from the sample --------------------------------------
    if reference is None:
        m = min(n_rows, sample_cnt)
        with open(path, "rb") as fh:
            import mmap as _mmap
            mm = _mmap.mmap(fh.fileno(), 0, access=_mmap.ACCESS_READ)
            try:
                # per-line newline: the file's FINAL line may lack one, and
                # it can land in any reservoir slot
                pieces = []
                for o, l in zip(res_off[:m], res_len[:m]):
                    b = bytes(mm[int(o):int(o + l)])
                    pieces.append(b if b.endswith(b"\n") else b + b"\n")
                joined = b"".join(pieces)
            finally:
                mm.close()
        sample_full = _native.csv_parse(joined, delim, len(names))
        if sample_full is None:
            raise _ParseError("malformed sampled row")
        sample = sample_full[:, keep]
        handle = BinnedDataset.from_sample(
            sample, n_rows, config,
            categorical_features=categorical_features,
            feature_names=feat_names)
    else:
        log.check(len(keep) == reference.num_total_features,
                  "validation data has a different number of features")
        handle = BinnedDataset()
        handle.num_data = n_rows
        handle.num_total_features = len(keep)
        handle.metadata = Metadata(n_rows)
        handle.bin_mappers = reference.bin_mappers
        handle.used_feature_map = reference.used_feature_map
        handle.real_feature_idx = reference.real_feature_idx
        handle.bin_offsets = reference.bin_offsets
        handle.feature_names = reference.feature_names
        handle.max_bin = reference.max_bin
        handle.bundle = reference.bundle

    # ---- pass 2: stream rows into the binned matrix -------------------
    from ..utils.timetag import timetag
    handle._alloc_X()
    with timetag("binarize"):
        row0 = 0
        for chunk in _iter_dense_chunks(path, delim, skip):
            handle._binarize_chunk(chunk[:, keep], row0)
            row0 += len(chunk)
    log.check(row0 == n_rows, "data file changed between two_round passes")

    weight, group = _load_sidecars(path, weight, None)
    if group is None:
        group = _group_from_col(group_raw)
    return handle, label, weight, group, feat_names
