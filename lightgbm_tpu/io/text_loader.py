"""Text dataset loading for the CLI driver.

The analog of the reference's DatasetLoader text path (reference:
src/io/dataset_loader.cpp:168,807-1042): dense TSV/CSV files with the
label in a configurable column, optional header, weight/group columns, and
the ``<data>.weight`` / ``<data>.query`` sidecar files.  Sparse LibSVM
input is not supported (the TPU path is dense; see io/dataset.py).
"""
from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..utils import log


def _sniff_delimiter(line: str) -> str:
    for d in ("\t", ",", " "):
        if d in line:
            return d
    return "\t"


def _resolve_column(spec: str, names: List[str], what: str) -> Optional[int]:
    """Column spec: "" -> None, "3" -> 3, "name:foo" -> index of foo
    (reference: dataset_loader.cpp column-by-name needs a header)."""
    if spec == "":
        return None
    if spec.startswith("name:"):
        name = spec[5:]
        if name not in names:
            log.fatal(f"{what} column {name!r} not found in header")
        return names.index(name)
    try:
        return int(spec)
    except ValueError:
        log.fatal(f"Bad {what} column spec {spec!r}")


def load_text(path: str, config) -> Tuple[np.ndarray, Optional[np.ndarray],
                                          Optional[np.ndarray],
                                          Optional[np.ndarray], List[str]]:
    """Load a dense text data file.

    Returns (X, label, weight, group, feature_names); label/weight/group
    are None when absent.  ``label_column`` counts ALL file columns;
    integer weight/group/ignore indices do NOT count the label column
    (reference: config.h weight_column doc), while ``name:`` specs are
    absolute header positions.
    """
    if not os.path.exists(path):
        log.fatal(f"Data file {path} does not exist")
    with open(path) as fh:
        first = fh.readline()
    if ":" in first and not getattr(config, "header", False):
        return _load_libsvm(path, config)
    delim = _sniff_delimiter(first.rstrip("\n"))
    names: List[str] = []
    skip = 0
    if getattr(config, "header", False):
        names = [t.strip() for t in first.rstrip("\n").split(delim)]
        skip = 1
    data = np.loadtxt(path, delimiter=None if delim == " " else delim,
                      skiprows=skip, ndmin=2, dtype=np.float64)
    ncol = data.shape[1]
    if not names:
        names = [f"Column_{i}" for i in range(ncol)]

    label_col = _resolve_column(getattr(config, "label_column", ""),
                                names, "label")
    if label_col is None:
        label_col = 0

    def skip_label(col: Optional[int], spec) -> Optional[int]:
        """Integer weight/group/ignore indices do NOT count the label
        column (reference: config.h weight_column doc — "index starts
        from 0 and it doesn't count the label column when passing type
        is int"); name: specs are absolute."""
        if col is None or str(spec).startswith("name:"):
            return col
        return col + 1 if col >= label_col else col

    wspec = getattr(config, "weight_column", "")
    gspec = getattr(config, "group_column", "")
    weight_col = skip_label(_resolve_column(wspec, names, "weight"), wspec)
    group_col = skip_label(_resolve_column(gspec, names, "group"), gspec)

    drop = {label_col}
    if weight_col is not None:
        drop.add(weight_col)
    if group_col is not None:
        drop.add(group_col)
    ignore = getattr(config, "ignore_column", "")
    if ignore:
        for tok in str(ignore).split(","):
            tok = tok.strip()
            c = skip_label(_resolve_column(tok, names, "ignore"), tok)
            if c is not None:
                drop.add(c)

    label = data[:, label_col]
    weight = data[:, weight_col] if weight_col is not None else None
    group_raw = data[:, group_col] if group_col is not None else None
    keep = [i for i in range(ncol) if i not in drop]
    X = data[:, keep]
    feat_names = [names[i] for i in keep]

    weight, group = _load_sidecars(path, weight, None)
    return X, label, weight, group if group is not None else _group_from_col(
        group_raw), feat_names


def _group_from_col(group_raw):
    if group_raw is None:
        return None
    # per-row query ids -> query sizes (reference converts ordered ids)
    ids = group_raw.astype(np.int64)
    change = np.flatnonzero(np.diff(ids)) + 1
    bounds = np.concatenate([[0], change, [len(ids)]])
    return np.diff(bounds)


def _load_libsvm(path: str, config):
    """Sparse ``label idx:val ...`` rows, densified (missing entries are
    0.0, which the zero-bin handling treats natively; reference:
    dataset_loader.cpp sparse parser)."""
    labels: List[float] = []
    rows: List[List[Tuple[int, float]]] = []
    max_idx = -1
    with open(path) as fh:
        for line in fh:
            toks = line.split()
            if not toks:
                continue
            labels.append(float(toks[0]))
            pairs = []
            for tok in toks[1:]:
                i, _, v = tok.partition(":")
                idx = int(i)
                pairs.append((idx, float(v)))
                if idx > max_idx:
                    max_idx = idx
            rows.append(pairs)
    X = np.zeros((len(rows), max_idx + 1), dtype=np.float64)
    for r, pairs in enumerate(rows):
        for idx, v in pairs:
            X[r, idx] = v
    label = np.asarray(labels)
    names = [f"Column_{i}" for i in range(max_idx + 1)]
    weight, group = _load_sidecars(path, None, None)
    return X, label, weight, group, names


def _load_sidecars(path: str, weight, group):
    """``<data>.weight`` / ``<data>.query`` / ``<data>.group`` files
    (reference: dataset_loader.cpp LoadWeights/LoadQueryBoundaries)."""
    wpath = path + ".weight"
    if weight is None and os.path.exists(wpath):
        weight = np.loadtxt(wpath, dtype=np.float64, ndmin=1)
        log.info("Loading weights from %s", wpath)
    if group is None:
        for suffix in (".query", ".group"):
            qpath = path + suffix
            if os.path.exists(qpath):
                group = np.loadtxt(qpath, dtype=np.int64, ndmin=1)
                log.info("Loading query boundaries from %s", qpath)
                break
    return weight, group
