"""Model dump to JSON and to standalone if-else code.

Matches the reference's key set and nesting (reference:
GBDT::DumpModel src/boosting/gbdt_model_text.cpp:20-85, Tree::ToJSON /
Tree::NodeToJSON src/io/tree.cpp:248-321, Tree::ToIfElse
src/io/tree.cpp:323-420 + tree.h:177-183) so downstream consumers of
``Booster.dump_model()`` (plotting, model inspectors) can switch without
changes.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.tree import Tree

_MISSING_STR = {0: "None", 1: "Zero", 2: "NaN"}


def _avoid_inf(v: float) -> float:
    if np.isinf(v):
        return 1.7976931348623157e308 if v > 0 else -1.7976931348623157e308
    return float(v)


def _node_cats(tree: Tree, node: int) -> List[int]:
    ci = int(tree.threshold[node])
    lo, hi = int(tree.cat_boundaries[ci]), int(tree.cat_boundaries[ci + 1])
    cats = []
    for w in range(lo, hi):
        word = int(tree.cat_threshold[w])
        for j in range(32):
            if (word >> j) & 1:
                cats.append((w - lo) * 32 + j)
    return cats


def node_to_dict(tree: Tree, index: int) -> dict:
    """Recursive node dict (reference: Tree::NodeToJSON, tree.cpp:263-321)."""
    if index >= 0:
        d = {
            "split_index": int(index),
            "split_feature": int(tree.split_feature[index]),
            "split_gain": _avoid_inf(tree.split_gain[index]),
        }
        if tree.is_categorical(index):
            d["threshold"] = "||".join(str(c) for c in _node_cats(tree, index))
            d["decision_type"] = "=="
        else:
            d["threshold"] = _avoid_inf(tree.threshold[index])
            d["decision_type"] = "<="
        d["default_left"] = bool(tree.default_left(index))
        d["missing_type"] = _MISSING_STR[tree.missing_type(index)]
        d["internal_value"] = float(tree.internal_value[index])
        d["internal_weight"] = float(tree.internal_weight[index])
        d["internal_count"] = int(tree.internal_count[index])
        d["left_child"] = node_to_dict(tree, int(tree.left_child[index]))
        d["right_child"] = node_to_dict(tree, int(tree.right_child[index]))
        return d
    index = ~index
    return {
        "leaf_index": int(index),
        "leaf_value": float(tree.leaf_value[index]),
        "leaf_weight": float(tree.leaf_weight[index]),
        "leaf_count": int(tree.leaf_count[index]),
    }


def tree_to_dict(tree: Tree, tree_index: int) -> dict:
    """(reference: Tree::ToJSON, tree.cpp:248-261)."""
    num_cat = max(len(tree.cat_boundaries) - 1, 0) \
        if tree.cat_threshold.size else 0
    d = {
        "tree_index": int(tree_index),
        "num_leaves": int(tree.num_leaves),
        "num_cat": int(num_cat),
        "shrinkage": float(tree.shrinkage),
    }
    if tree.num_leaves == 1:
        d["tree_structure"] = {"leaf_value": float(tree.leaf_value[0])}
    else:
        d["tree_structure"] = node_to_dict(tree, 0)
    return d


def dump_model(gbdt, num_iteration: Optional[int] = None,
               start_iteration: int = 0) -> dict:
    """Full model as a dict (reference: GBDT::DumpModel,
    gbdt_model_text.cpp:20-85; python Booster.dump_model returns the
    parsed dict)."""
    K = gbdt.num_tpi
    models = list(gbdt.models)
    total_iteration = len(models) // max(K, 1)
    start_iteration = min(max(start_iteration, 0), total_iteration)
    stop = total_iteration if not num_iteration or num_iteration <= 0 \
        else min(start_iteration + num_iteration, total_iteration)

    feature_names = list(
        gbdt.train_ds.feature_names if gbdt.train_ds is not None
        else getattr(gbdt, "feature_names", []))
    max_feature_idx = (gbdt.train_ds.num_total_features - 1
                      if gbdt.train_ds is not None
                      else max(len(feature_names) - 1, 0))
    obj = getattr(gbdt, "objective", None)
    cfg = getattr(gbdt, "config", None)
    mono = list(getattr(cfg, "monotone_constraints", None) or []) if cfg else []

    d = {
        "name": "tree",
        "version": "v3",
        "num_class": int(getattr(cfg, "num_class", 1) or 1) if cfg else K,
        "num_tree_per_iteration": K,
        "label_index": 0,
        "max_feature_idx": int(max_feature_idx),
        "average_output": bool(getattr(gbdt, "average_output", False)),
        "feature_names": feature_names,
        "monotone_constraints": mono,
    }
    if obj is not None:
        from .model_io import _objective_string
        d["objective"] = _objective_string(obj)
    d["tree_info"] = [
        tree_to_dict(models[i], i)
        for i in range(start_iteration * K, stop * K)
    ]
    imp = gbdt.feature_importance("split", start_iteration, stop)
    d["feature_importances"] = {
        feature_names[i] if i < len(feature_names) else f"Column_{i}": int(v)
        for i, v in enumerate(imp) if v > 0
    }
    return d


# ----------------------------------------------------------------------
# if-else code generation (reference: Tree::ToIfElse tree.cpp:323-420,
# GBDT::ModelToIfElse gbdt_model_text.cpp:88-270).  Generates standalone
# dependency-free C so the output compiles anywhere (the reference emits
# code against its own headers; the traversal logic is identical).

def _node_code(tree: Tree, index: int, indent: str) -> str:
    if index < 0:
        return f"{indent}return {float(tree.leaf_value[~index])!r};\n"
    f = int(tree.split_feature[index])
    out = f"{indent}fval = row[{f}];\n"
    if tree.is_categorical(index):
        cats = _node_cats(tree, index)
        cond = " || ".join(f"ival == {c}" for c in cats) or "0"
        out += (f"{indent}ival = (isnan(fval) || fval < 0) ? -1 : (int)fval;\n"
                f"{indent}if ({cond}) {{\n")
    else:
        thr = _avoid_inf(tree.threshold[index])
        mt = tree.missing_type(index)
        dl = tree.default_left(index)
        if mt == 0:
            cond = f"fval <= {thr!r}"
        elif mt == 1:  # Zero
            if dl:
                cond = f"fval <= {thr!r} || fabs(fval) < 1e-35 || isnan(fval)"
            else:
                cond = f"fval <= {thr!r} && fabs(fval) >= 1e-35 && !isnan(fval)"
        else:          # NaN
            cond = (f"fval <= {thr!r} || isnan(fval)" if dl
                    else f"fval <= {thr!r} && !isnan(fval)")
        out += f"{indent}if ({cond}) {{\n"
    out += _node_code(tree, int(tree.left_child[index]), indent + "  ")
    out += f"{indent}}} else {{\n"
    out += _node_code(tree, int(tree.right_child[index]), indent + "  ")
    out += f"{indent}}}\n"
    return out


def model_to_if_else(gbdt, num_iteration: Optional[int] = None) -> str:
    """Standalone C source scoring the forest row-by-row (reference:
    GBDT::ModelToIfElse, gbdt_model_text.cpp:88-270)."""
    K = gbdt.num_tpi
    models = list(gbdt.models)
    n = len(models)
    if num_iteration and num_iteration > 0:
        n = min(num_iteration * K, n)
    out = ["#include <math.h>", ""]
    for i in range(n):
        t = models[i]
        out.append(f"static double PredictTree{i}(const double* row) {{")
        if t.num_leaves <= 1:
            out.append(f"  return {float(t.leaf_value[0])!r};")
        else:
            out.append("  double fval; int ival; (void)fval; (void)ival;")
            out.append(_node_code(t, 0, "  ").rstrip("\n"))
        out.append("}")
        out.append("")
    out.append(f"#define NUM_TREES {n}")
    out.append(f"#define NUM_CLASS {K}")
    out.append("typedef double (*TreeFn)(const double*);")
    out.append("static const TreeFn PredictTreePtr[NUM_TREES] = {")
    out.append("  " + ", ".join(f"PredictTree{i}" for i in range(n)))
    out.append("};")
    out.append("""
void PredictRaw(const double* row, double* output) {
  for (int k = 0; k < NUM_CLASS; ++k) output[k] = 0.0;
  for (int i = 0; i < NUM_TREES; ++i)
    output[i % NUM_CLASS] += PredictTreePtr[i](row);
}""")
    return "\n".join(out)
