"""Exclusive Feature Bundling (EFB) — host-side bundle construction.

Mutually-exclusive sparse features (rarely non-default in the same row) are
packed into shared physical columns, so the device histograms F_phys ≪ F
columns per pass (reference: src/io/dataset.cpp:41-263 — GetConflictCount
:51, FindGroups :91, FastFeatureBundling :169).

Physical bin layout per multi-feature bundle (TPU-first simplification of
the reference's ``FeatureGroup`` bin offsets, feature_group.h:37-55):

- physical bin 0  = every member at its default (zero) bin;
- member i owns [offset_i, offset_i + num_bin_i); its feature-space bin b
  is stored verbatim as ``offset_i + b`` whenever ``b != default_bin_i``.

Decode is branch-free on device: a row whose physical bin falls outside a
member's range is at that member's default bin.  The member's default-bin
histogram mass is reconstructed from leaf totals, exactly the reference's
elided-bin trick (Dataset::FixHistogram, dataset.cpp:1044-1063).

Conflicts (two members non-default in one row) lose the earlier member's
value to its default — EFB's documented approximation, bounded by
``max_conflict_rate``.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

import numpy as np

from ..utils import log


@dataclass
class BundleInfo:
    """Feature→physical-column mapping for a constructed dataset."""
    feat2phys: np.ndarray       # i32 [F_inner] physical column per feature
    feat_offset: np.ndarray     # i32 [F_inner] bin offset inside the column
    needs_fix: np.ndarray       # bool [F_inner] default-bin mass elided
    num_phys: int
    phys_num_bin: np.ndarray    # i32 [num_phys] bins used per column
    groups: List[List[int]] = field(default_factory=list)

    @classmethod
    def identity(cls, nbins: np.ndarray) -> "BundleInfo":
        F = len(nbins)
        return cls(
            feat2phys=np.arange(F, dtype=np.int32),
            feat_offset=np.zeros(F, dtype=np.int32),
            needs_fix=np.zeros(F, dtype=bool),
            num_phys=F,
            phys_num_bin=np.asarray(nbins, dtype=np.int32),
            groups=[[i] for i in range(F)],
        )

    @property
    def is_trivial(self) -> bool:
        return self.num_phys == len(self.feat2phys) and not self.needs_fix.any()


def find_groups(nonzero_idx: List[np.ndarray], nbins: List[int],
                sparse_rates: List[float], total_sample: int,
                max_conflict_rate: float, sparse_threshold: float = 0.8,
                max_bins_per_group: int = 256,
                max_search_group: int = 128) -> List[List[int]]:
    """Greedy conflict-budgeted grouping over a row sample.

    ``nonzero_idx[i]``: int [nnz_i] — sample-row indices where feature i
    is non-default (index arrays, NOT bool masks: a 50k-feature sparse
    dataset would need 10GB of [S] masks; indices are nnz-bound).
    Features with sparse_rate < ``sparse_threshold`` are kept as
    singletons (bundling dense features buys nothing and eats the
    conflict budget; the reference reaches the same outcome through its
    budget arithmetic, dataset.cpp:110-140).

    Mirrors FindGroups (reference: dataset.cpp:91-167): features visited
    in descending non-default count, first group with enough remaining
    budget and bin capacity wins; like the reference's random-subset
    probe cap, at most ``max_search_group`` groups are tried per feature.
    """
    F = len(nonzero_idx)
    budget_total = int(max_conflict_rate * total_sample)
    candidates = [i for i in range(F) if sparse_rates[i] >= sparse_threshold]
    cand_set = set(candidates)
    dense = [i for i in range(F) if i not in cand_set]

    order = sorted(candidates, key=lambda i: -len(nonzero_idx[i]))
    group_masks: List[np.ndarray] = []  # bool [S] per GROUP (not feature)
    group_bins: List[int] = []
    group_conflicts: List[int] = []
    groups: List[List[int]] = []
    # cap total mask memory at ~512MB: past it, unplaceable features fall
    # back to mask-less singleton groups (they could never accept members
    # anyway once nothing bundles) instead of re-creating the old
    # bool-per-feature blowup in the all-conflicting worst case
    mask_cap = max(8, (512 << 20) // max(total_sample, 1))
    singles: List[List[int]] = []
    for i in order:
        ii = nonzero_idx[i]
        placed = False
        lo = max(0, len(groups) - max_search_group)
        for gi in range(lo, len(groups)):
            # bin 0 is the shared all-default bin
            if group_bins[gi] + nbins[i] > max_bins_per_group:
                continue
            conflicts = int(group_masks[gi][ii].sum())
            if group_conflicts[gi] + conflicts <= budget_total:
                groups[gi].append(i)
                group_masks[gi][ii] = True
                group_bins[gi] += nbins[i]
                group_conflicts[gi] += conflicts
                placed = True
                break
        if not placed:
            if len(group_masks) >= mask_cap:
                singles.append([i])
                continue
            m = np.zeros(total_sample, bool)
            m[ii] = True
            groups.append([i])
            group_masks.append(m)
            group_bins.append(1 + nbins[i])
            group_conflicts.append(0)
    return groups + singles + [[i] for i in dense]


def build_bundles(mappers, used_features: np.ndarray, sample,
                  total_rows: int, max_conflict_rate: float,
                  max_bins_per_group: int = 256) -> BundleInfo:
    """Construct the bundle mapping from the bin-finding row sample.

    ``mappers``: all BinMappers (original feature indexing);
    ``used_features``: original indices of non-trivial features (inner
    order); ``sample``: [S, P] raw values used for bin finding — dense
    ndarray or a scipy.sparse matrix (the CSR ingestion path; only stored
    entries can be non-default, so masks come straight from the CSC
    columns without densifying).
    """
    F = len(used_features)
    nbins = [mappers[int(j)].num_bin for j in used_features]
    if F < 2:
        return BundleInfo.identity(np.asarray(nbins))

    S = sample.shape[0]
    sample_csc = sample.tocsc() if hasattr(sample, "tocsc") else None
    idxs, rates = [], []
    for inner, j in enumerate(used_features):
        m = mappers[int(j)]
        if sample_csc is not None:
            lo, hi = sample_csc.indptr[int(j)], sample_csc.indptr[int(j) + 1]
            rows = sample_csc.indices[lo:hi]
            fb = np.asarray(m.value_to_bin(sample_csc.data[lo:hi]))
            nz_idx = np.asarray(rows[fb != m.default_bin])
        else:
            fb = m.value_to_bin(sample[:, int(j)])
            nz_idx = np.flatnonzero(np.asarray(fb) != m.default_bin)
        idxs.append(nz_idx)
        rates.append(1.0 - float(len(nz_idx)) / max(S, 1))

    groups = find_groups(idxs, nbins, rates, S, max_conflict_rate,
                         max_bins_per_group=max_bins_per_group)
    if all(len(g) <= 1 for g in groups):
        return BundleInfo.identity(np.asarray(nbins))

    feat2phys = np.zeros(F, np.int32)
    feat_offset = np.zeros(F, np.int32)
    needs_fix = np.zeros(F, bool)
    phys_num_bin = []
    for gp, members in enumerate(groups):
        if len(members) == 1:
            i = members[0]
            feat2phys[i] = gp
            feat_offset[i] = 0
            phys_num_bin.append(nbins[i])
        else:
            off = 1  # bin 0 = all-default
            for i in members:
                feat2phys[i] = gp
                feat_offset[i] = off
                needs_fix[i] = True
                off += nbins[i]
            phys_num_bin.append(off)
    n_bundled = sum(len(g) for g in groups if len(g) > 1)
    log.info("EFB: bundled %d sparse features into %d columns "
             "(%d physical columns total, was %d)",
             n_bundled, sum(1 for g in groups if len(g) > 1),
             len(groups), F)
    return BundleInfo(
        feat2phys=feat2phys, feat_offset=feat_offset, needs_fix=needs_fix,
        num_phys=len(groups),
        phys_num_bin=np.asarray(phys_num_bin, np.int32),
        groups=groups,
    )


def encode_column(bundle: BundleInfo, members: List[int], feat_bins: List[np.ndarray],
                  default_bins: List[int], n: int, dtype) -> np.ndarray:
    """Encode one multi-member physical column from members' feature bins."""
    col = np.zeros(n, dtype=dtype)
    for i, fb, db in zip(members, feat_bins, default_bins):
        nz = fb != db
        col[nz] = (bundle.feat_offset[i] + fb[nz]).astype(dtype)
    return col
