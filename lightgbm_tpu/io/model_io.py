"""Model text serialization in the LightGBM v3 format.

Save mirrors ``GBDT::SaveModelToString`` (reference:
src/boosting/gbdt_model_text.cpp:271-368); load mirrors
``GBDT::LoadModelFromString`` (:380-480) plus ``Tree``'s parsing ctor
(src/io/tree.cpp:398-607).  Files written here load in the reference CLI and
vice versa, which is the cross-framework parity check.
"""
from __future__ import annotations

import io as _io
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..boosting.gbdt import PredictorBase
from ..core.tree import Tree
from ..utils import log


def _fmt(x: float) -> str:
    """Shortest round-trip float formatting (C++ uses %.17g-equivalent)."""
    return np.format_float_positional(
        float(x), unique=True, trim="0") if np.isfinite(x) else repr(float(x))


def _fmt_list(arr) -> str:
    return " ".join(_fmt(v) for v in arr)


def _int_list(arr) -> str:
    return " ".join(str(int(v)) for v in arr)


def _objective_string(objective) -> str:
    if objective is None:
        return "custom"
    name = objective.name
    if name == "binary":
        return f"binary sigmoid:{objective.sigmoid:g}"
    if name in ("multiclass", "multiclassova"):
        extra = f" num_class:{objective.num_class}"
        if name == "multiclassova":
            extra += f" sigmoid:{objective.sigmoid:g}"
        return name + extra
    if name == "lambdarank":
        return "lambdarank"
    return name


def tree_to_string(tree: Tree, index: int) -> str:
    """One ``Tree=i`` block (reference: Tree::ToString, src/io/tree.cpp:341)."""
    nn = max(tree.num_leaves - 1, 0)
    buf = _io.StringIO()
    buf.write(f"Tree={index}\n")
    buf.write(f"num_leaves={tree.num_leaves}\n")
    num_cat = int(len(tree.cat_boundaries) - 1)
    buf.write(f"num_cat={num_cat}\n")
    buf.write(f"split_feature={_int_list(tree.split_feature[:nn])}\n")
    buf.write(f"split_gain={_fmt_list(tree.split_gain[:nn])}\n")
    buf.write(f"threshold={_fmt_list(tree.threshold[:nn])}\n")
    buf.write(f"decision_type={_int_list(tree.decision_type[:nn])}\n")
    buf.write(f"left_child={_int_list(tree.left_child[:nn])}\n")
    buf.write(f"right_child={_int_list(tree.right_child[:nn])}\n")
    buf.write(f"leaf_value={_fmt_list(tree.leaf_value[:tree.num_leaves])}\n")
    buf.write(f"leaf_weight={_fmt_list(tree.leaf_weight[:tree.num_leaves])}\n")
    buf.write(f"leaf_count={_int_list(tree.leaf_count[:tree.num_leaves])}\n")
    buf.write(f"internal_value={_fmt_list(tree.internal_value[:nn])}\n")
    buf.write(f"internal_weight={_fmt_list(tree.internal_weight[:nn])}\n")
    buf.write(f"internal_count={_int_list(tree.internal_count[:nn])}\n")
    if num_cat > 0:
        buf.write(f"cat_boundaries={_int_list(tree.cat_boundaries)}\n")
        buf.write(f"cat_threshold={_int_list(tree.cat_threshold)}\n")
    buf.write(f"shrinkage={_fmt(tree.shrinkage)}\n")
    buf.write("\n\n")
    return buf.getvalue()


def model_to_string(gbdt, num_iteration: int = -1,
                    start_iteration: int = 0) -> str:
    """(reference: GBDT::SaveModelToString, gbdt_model_text.cpp:271-368)."""
    from ..boosting.gbdt import GBDT
    K = gbdt.num_tpi
    start, stop = GBDT._iter_window(gbdt, num_iteration, start_iteration)
    trees = gbdt.models[start * K:stop * K]

    ds = gbdt.train_ds
    feature_names = (list(ds.feature_names) if ds is not None
                     else list(getattr(gbdt, "feature_names", [])))
    max_feature_idx = (len(feature_names) - 1 if feature_names else 0)
    if ds is not None:
        infos = []
        for j in range(ds.num_total_features):
            m = ds.bin_mappers[j]
            if m.is_trivial:
                infos.append("none")
            elif m.bin_type == 1:  # categorical
                infos.append(":".join(str(c) for c in sorted(
                    c for c in m.bin_2_categorical if c >= 0)))
            else:
                infos.append(f"[{_fmt(m.min_val)}:{_fmt(m.max_val)}]")
    else:
        infos = list(getattr(gbdt, "feature_infos", ["none"] * (max_feature_idx + 1)))

    buf = _io.StringIO()
    buf.write("tree\n")
    buf.write("version=v3\n")
    buf.write(f"num_class={K if gbdt.objective is None or gbdt.objective.num_tree_per_iteration == K else 1}\n")
    buf.write(f"num_tree_per_iteration={K}\n")
    buf.write("label_index=0\n")
    buf.write(f"max_feature_idx={max_feature_idx}\n")
    buf.write(f"objective={_objective_string(gbdt.objective)}\n")
    if getattr(gbdt, "average_output", False):
        buf.write("average_output\n")
    buf.write(f"feature_names={' '.join(feature_names)}\n")
    buf.write(f"feature_infos={' '.join(infos)}\n")

    tree_strs = [tree_to_string(t, i) for i, t in enumerate(trees)]
    buf.write(f"tree_sizes={' '.join(str(len(s)) for s in tree_strs)}\n\n")
    for s in tree_strs:
        buf.write(s)
    buf.write("end of trees\n")

    # feature importances, descending (gbdt_model_text.cpp:330-358)
    if ds is not None and feature_names:
        imp = gbdt.feature_importance("split")
        order = np.argsort(-imp, kind="stable")
        buf.write("\nfeature_importances:\n")
        for j in order:
            if imp[j] > 0:
                buf.write(f"{feature_names[int(j)]}={int(imp[int(j)])}\n")
    buf.write("\nparameters:\n")
    if getattr(gbdt, "config", None) is not None:
        for k, v in gbdt.config.to_params().items():
            if isinstance(v, (list, tuple)):
                v = ",".join(str(x) for x in v)
            buf.write(f"[{k}: {v}]\n")
    buf.write("\nend of parameters\n")
    return buf.getvalue()


# ----------------------------------------------------------------------
def _parse_tree_block(lines: Dict[str, str]) -> Tree:
    nl = int(lines["num_leaves"])
    nn = max(nl - 1, 0)

    def farr(key, n, default=0.0):
        if key not in lines or not lines[key].strip():
            return np.full(n, default, dtype=np.float64)
        return np.asarray([float(x) for x in lines[key].split()], dtype=np.float64)

    def iarr(key, n, default=0):
        if key not in lines or not lines[key].strip():
            return np.full(n, default, dtype=np.int32)
        return np.asarray([int(float(x)) for x in lines[key].split()], dtype=np.int32)

    num_cat = int(lines.get("num_cat", "0"))
    cat_boundaries = iarr("cat_boundaries", 1) if num_cat > 0 else np.zeros(1, np.int32)
    cat_threshold = (np.asarray([int(x) for x in lines["cat_threshold"].split()],
                                dtype=np.uint32)
                     if num_cat > 0 else np.zeros(0, np.uint32))
    return Tree(
        num_leaves=nl,
        split_feature=iarr("split_feature", nn),
        threshold=farr("threshold", nn),
        threshold_bin=np.zeros(nn, np.int32),
        decision_type=iarr("decision_type", nn),
        left_child=iarr("left_child", nn),
        right_child=iarr("right_child", nn),
        leaf_value=farr("leaf_value", nl),
        leaf_count=iarr("leaf_count", nl),
        leaf_weight=farr("leaf_weight", nl),
        split_gain=farr("split_gain", nn),
        internal_value=farr("internal_value", nn),
        internal_count=iarr("internal_count", nn),
        internal_weight=farr("internal_weight", nn),
        cat_boundaries=cat_boundaries,
        cat_threshold=cat_threshold,
        shrinkage=float(lines.get("shrinkage", "1")),
    )


class LoadedGBDT(PredictorBase):
    """Prediction-only booster built from a model file (the reference
    reconstructs a full GBDT; prediction needs only the trees + objective).
    The whole prediction surface is inherited from ``PredictorBase`` —
    with ``train_ds = None`` small inputs walk the trees in value space
    on the host, and above the work threshold the device path rebuilds a
    serving bin space from the model itself (serve/packing.py), so
    ``Booster(model_file=...)`` predictions hit the TPU too."""

    def __init__(self, models: List[Tree], num_tpi: int, objective,
                 feature_names: List[str], feature_infos: List[str],
                 average_output: bool, max_feature_idx: int = -1):
        self.models = models
        self.num_tpi = num_tpi
        self.objective = objective
        self.feature_names = feature_names
        self.feature_infos = feature_infos
        self.average_output = average_output
        # the declared feature-space width (model header); serving uses
        # it to size the rebuilt bin space when names are absent
        self.num_features = (max_feature_idx + 1 if max_feature_idx >= 0
                             else len(feature_names))
        self.train_ds = None
        self.config = None
        self.metrics = []
        self.best_iteration = -1

    def predict_raw(self, X, num_iteration=None, start_iteration: int = 0,
                    early_stop=None):
        raw = super().predict_raw(X, num_iteration, start_iteration,
                                  early_stop)
        if self.average_output:
            start, stop = self._iter_window(num_iteration, start_iteration)
            raw /= max(stop - start, 1)
        return raw


def load_model_string(model_str: str):
    """Parse a LightGBM model text (ours or the reference's)."""
    from ..config import Config
    from ..objective import create_objective

    # trailing pandas category mapping appended by Booster.model_to_string
    # (reference: basic.py:377 _load_pandas_categorical)
    pandas_categorical = None
    key = "\npandas_categorical:"
    kpos = model_str.rfind(key)
    if kpos >= 0:
        import json as _json
        rest = model_str[kpos + len(key):].splitlines()
        try:
            pandas_categorical = _json.loads(rest[0].strip()) if rest \
                else None
        except ValueError:
            pandas_categorical = None
        model_str = model_str[:kpos]

    header: Dict[str, str] = {}
    pos = model_str.find("\nTree=")
    head_part = model_str[:pos] if pos >= 0 else model_str
    for line in head_part.splitlines():
        if "=" in line:
            k, _, v = line.partition("=")
            header[k.strip()] = v.strip()

    average_output = "average_output" in head_part.splitlines()

    objective = None
    obj_str = header.get("objective", "")
    num_class = int(header.get("num_class", "1"))
    if obj_str and obj_str != "custom":
        parts = obj_str.split()
        params = {"objective": parts[0]}
        for tok in parts[1:]:
            if ":" in tok:
                k, v = tok.split(":", 1)
                params[k] = v
        if num_class > 1:
            params["num_class"] = num_class
        try:
            objective = create_objective(Config.from_params(params))
        except Exception:  # objective param mismatch shouldn't kill loading
            log.warning("Could not reconstruct objective %r from model file",
                        obj_str)

    # tree blocks
    models: List[Tree] = []
    chunks = model_str.split("\nTree=")[1:]
    for chunk in chunks:
        body = chunk.split("end of trees")[0]
        lines: Dict[str, str] = {}
        for line in body.splitlines():
            if "=" in line:
                k, _, v = line.partition("=")
                lines[k.strip()] = v.strip()
        models.append(_parse_tree_block(lines))

    num_tpi = int(header.get("num_tree_per_iteration", "1"))
    feature_names = header.get("feature_names", "").split()
    feature_infos = header.get("feature_infos", "").split()
    try:
        max_feature_idx = int(header.get("max_feature_idx", "-1"))
    except ValueError:
        max_feature_idx = -1
    gbdt = LoadedGBDT(models, num_tpi, objective, feature_names,
                      feature_infos, average_output,
                      max_feature_idx=max_feature_idx)
    gbdt.pandas_categorical = pandas_categorical
    cfg_params: Dict[str, object] = {}
    if obj_str and obj_str != "custom":
        cfg_params["objective"] = obj_str.split()[0]
        if num_class > 1:
            # the minimal config must carry num_class or multiclass
            # objectives fail Config's consistency check on load
            cfg_params["num_class"] = num_class
    config = Config.from_params(cfg_params)
    return gbdt, config


def load_model_file(path: str):
    with open(path) as fh:
        return load_model_string(fh.read())
