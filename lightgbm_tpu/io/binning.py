"""Feature binning: value → bin mapping.

TPU-native rebuild of the reference's ``BinMapper``
(reference: include/LightGBM/bin.h:65-222, src/io/bin.cpp:78-529). The
*algorithm* is the same — greedy near-equal-count bin boundaries over a value
sample, with zero isolated in its own bin, the three missing modes
{None, Zero, NaN}, and count-ordered categorical mapping — but the
implementation is host-side NumPy producing a dense unsigned-int binned
matrix for the device, instead of per-feature-group ``Bin`` objects.

All bin construction happens once on the host; the device only ever sees the
binned matrix and the per-feature bound arrays needed to binarize prediction
inputs.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils import log
from .. import native as _native

# Values in (-kZeroThreshold, kZeroThreshold] are "zero"
# (reference: include/LightGBM/meta.h:53).
K_ZERO_THRESHOLD = 1e-35

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

BIN_NUMERICAL = 0
BIN_CATEGORICAL = 1

_MISSING_NAMES = {MISSING_NONE: "None", MISSING_ZERO: "Zero", MISSING_NAN: "NaN"}


def _upper_bound(v: float) -> float:
    """Smallest double strictly greater than v (reference: Common::GetDoubleUpperBound)."""
    return float(np.nextafter(v, np.inf))


def _close_ordered(a: float, b: float) -> bool:
    """b <= nextafter(a, inf) (reference: Common::CheckDoubleEqualOrdered)."""
    return b <= np.nextafter(a, np.inf)


def greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray, max_bin: int,
                    total_cnt: int, min_data_in_bin: int) -> List[float]:
    """Greedy near-equal-count bin upper bounds over sorted distinct values.

    Values with count >= mean bin size get dedicated bins; the rest are packed
    to roughly equal counts (reference: GreedyFindBin, bin.cpp:78-155).
    Returns ascending upper bounds; the last is +inf.
    """
    if _native.lib() is not None:
        return _native.greedy_find_bin(
            np.asarray(distinct_values, np.float64),
            np.asarray(counts, np.int64), max_bin, total_cnt, min_data_in_bin)
    n = len(distinct_values)
    if n == 0:
        return [math.inf]
    bounds: List[float] = []
    if n <= max_bin:
        cur = 0
        for i in range(n - 1):
            cur += int(counts[i])
            if cur >= min_data_in_bin:
                val = _upper_bound((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                if not bounds or not _close_ordered(bounds[-1], val):
                    bounds.append(val)
                    cur = 0
        bounds.append(math.inf)
        return bounds

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_size = total_cnt / max_bin
    is_big = counts >= mean_size
    rest_bins = max_bin - int(is_big.sum())
    rest_cnt = total_cnt - int(counts[is_big].sum())
    mean_size = rest_cnt / rest_bins if rest_bins > 0 else math.inf

    uppers: List[float] = []
    lowers: List[float] = [float(distinct_values[0])]
    cur = 0
    for i in range(n - 1):
        if not is_big[i]:
            rest_cnt -= int(counts[i])
        cur += int(counts[i])
        if (is_big[i] or cur >= mean_size
                or (is_big[i + 1] and cur >= max(1.0, mean_size * 0.5))):
            uppers.append(float(distinct_values[i]))
            lowers.append(float(distinct_values[i + 1]))
            if len(uppers) >= max_bin - 1:
                break
            cur = 0
            if not is_big[i]:
                rest_bins -= 1
                mean_size = rest_cnt / rest_bins if rest_bins > 0 else math.inf
    for i in range(len(uppers)):
        val = _upper_bound((uppers[i] + lowers[i + 1]) / 2.0)
        if not bounds or not _close_ordered(bounds[-1], val):
            bounds.append(val)
    bounds.append(math.inf)
    return bounds


def find_bin_with_zero_as_one_bin(distinct_values: np.ndarray, counts: np.ndarray,
                                  max_bin: int, total_cnt: int,
                                  min_data_in_bin: int) -> List[float]:
    """Bin bounds with zero guaranteed its own bin: negative side and positive
    side are binned independently around (-eps, eps]
    (reference: FindBinWithZeroAsOneBin, bin.cpp:256-312)."""
    neg = distinct_values <= -K_ZERO_THRESHOLD
    pos = distinct_values > K_ZERO_THRESHOLD
    zero_cnt = int(counts[~neg & ~pos].sum())
    left_cnt_data = int(counts[neg].sum())
    right_cnt_data = int(counts[pos].sum())
    n_left = int(neg.sum())

    bounds: List[float] = []
    if n_left > 0 and max_bin > 1:
        denom = max(total_cnt - zero_cnt, 1)
        left_max_bin = max(1, int(left_cnt_data / denom * (max_bin - 1)))
        bounds = greedy_find_bin(distinct_values[:n_left], counts[:n_left],
                                 left_max_bin, left_cnt_data, min_data_in_bin)
        if bounds:
            bounds[-1] = -K_ZERO_THRESHOLD

    right_start = None
    idx = np.nonzero(pos)[0]
    if len(idx) > 0:
        right_start = int(idx[0])
    right_max_bin = max_bin - 1 - len(bounds)
    if right_start is not None and right_max_bin > 0:
        right = greedy_find_bin(distinct_values[right_start:], counts[right_start:],
                                right_max_bin, right_cnt_data, min_data_in_bin)
        bounds.append(K_ZERO_THRESHOLD)
        bounds.extend(right)
    else:
        bounds.append(math.inf)
    return bounds


def find_bin_with_predefined_bounds(distinct_values: np.ndarray, counts: np.ndarray,
                                    max_bin: int, total_cnt: int, min_data_in_bin: int,
                                    forced_bounds: Sequence[float]) -> List[float]:
    """Forced-bounds variant: user bounds are fixed, remaining bin budget is
    spread across the gaps proportionally to their sample mass
    (reference: FindBinWithPredefinedBin, bin.cpp:157-254)."""
    neg = distinct_values <= -K_ZERO_THRESHOLD
    pos = distinct_values > K_ZERO_THRESHOLD
    n_left = int(neg.sum())
    has_right = bool(pos.any())

    bounds: List[float] = []
    if max_bin == 2:
        bounds.append(K_ZERO_THRESHOLD if n_left == 0 else -K_ZERO_THRESHOLD)
    elif max_bin >= 3:
        if n_left > 0:
            bounds.append(-K_ZERO_THRESHOLD)
        if has_right:
            bounds.append(K_ZERO_THRESHOLD)
    bounds.append(math.inf)

    max_to_insert = max_bin - len(bounds)
    inserted = 0
    for b in forced_bounds:
        if inserted >= max_to_insert:
            break
        if abs(b) > K_ZERO_THRESHOLD:
            bounds.append(float(b))
            inserted += 1
    bounds.sort()

    free_bins = max_bin - len(bounds)
    to_add: List[float] = []
    value_ind = 0
    n = len(distinct_values)
    for i, ub in enumerate(bounds):
        cnt_in_bin = 0
        bin_start = value_ind
        while value_ind < n and distinct_values[value_ind] < ub:
            cnt_in_bin += int(counts[value_ind])
            value_ind += 1
        bins_remaining = max_bin - len(bounds) - len(to_add)
        num_sub_bins = int(round(cnt_in_bin * free_bins / max(total_cnt, 1)))
        num_sub_bins = min(num_sub_bins, bins_remaining) + 1
        if i == len(bounds) - 1:
            num_sub_bins = bins_remaining + 1
        sub = greedy_find_bin(distinct_values[bin_start:value_ind],
                              counts[bin_start:value_ind],
                              num_sub_bins, cnt_in_bin, min_data_in_bin)
        to_add.extend(sub[:-1])  # last bound is inf
    bounds.extend(to_add)
    bounds.sort()
    return bounds


def _distinct_with_zero(values: np.ndarray, zero_cnt: int):
    """Sorted distinct values + counts, with the implicit zeros inserted at
    their ordered position (reference: BinMapper::FindBin, bin.cpp:353-389).
    ``values`` excludes zeros and NaNs."""
    values = np.sort(values.astype(np.float64), kind="stable")
    if _native.lib() is not None:
        return _native.distinct_with_zero(values, zero_cnt)
    if len(values) == 0:
        return np.array([0.0]), np.array([zero_cnt], dtype=np.int64)
    # merge near-equal neighbours (keep the larger value, sum counts)
    distinct: List[float] = [float(values[0])]
    counts: List[int] = [1]
    for v in values[1:]:
        if _close_ordered(distinct[-1], v):
            distinct[-1] = float(v)
            counts[-1] += 1
        else:
            if distinct[-1] < 0.0 and v > 0.0:
                distinct.append(0.0)
                counts.append(zero_cnt)
            distinct.append(float(v))
            counts.append(1)
    if values[0] > 0.0 and zero_cnt > 0:
        distinct.insert(0, 0.0)
        counts.insert(0, zero_cnt)
    if values[-1] < 0.0 and zero_cnt > 0:
        distinct.append(0.0)
        counts.append(zero_cnt)
    return np.asarray(distinct), np.asarray(counts, dtype=np.int64)


class BinMapper:
    """Per-feature value↔bin mapping (reference: BinMapper, bin.h:65)."""

    def __init__(self):
        self.num_bin: int = 1
        self.missing_type: int = MISSING_NONE
        self.is_trivial: bool = True
        self.sparse_rate: float = 1.0
        self.bin_type: int = BIN_NUMERICAL
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.bin_upper_bound: np.ndarray = np.array([np.inf])
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: Dict[int, int] = {}
        self.default_bin: int = 0
        self.most_freq_bin: int = 0

    # ------------------------------------------------------------------
    def find_bin(self, values: np.ndarray, total_sample_cnt: int, max_bin: int,
                 min_data_in_bin: int = 3, min_split_data: int = 20,
                 bin_type: int = BIN_NUMERICAL, use_missing: bool = True,
                 zero_as_missing: bool = False,
                 forced_bounds: Optional[Sequence[float]] = None) -> None:
        """Build the mapping from a value sample. ``values`` excludes zeros;
        ``total_sample_cnt - len(values)`` are implicit zeros
        (reference: BinMapper::FindBin, bin.cpp:325)."""
        values = np.asarray(values, dtype=np.float64)
        nan_mask = np.isnan(values)
        na_cnt = int(nan_mask.sum())
        values = values[~nan_mask]
        if not use_missing:
            self.missing_type = MISSING_NONE
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        else:
            self.missing_type = MISSING_NAN if na_cnt > 0 else MISSING_NONE

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - len(values) - na_cnt)
        distinct, counts = _distinct_with_zero(values, zero_cnt)
        self.min_val = float(distinct[0])
        self.max_val = float(distinct[-1])

        if bin_type == BIN_NUMERICAL:
            forced = list(forced_bounds) if forced_bounds else []
            if self.missing_type == MISSING_NAN:
                eff_max_bin, eff_total = max_bin - 1, total_sample_cnt - na_cnt
            else:
                eff_max_bin, eff_total = max_bin, total_sample_cnt
            if forced:
                bounds = find_bin_with_predefined_bounds(
                    distinct, counts, eff_max_bin, eff_total, min_data_in_bin, forced)
            else:
                bounds = find_bin_with_zero_as_one_bin(
                    distinct, counts, eff_max_bin, eff_total, min_data_in_bin)
            if self.missing_type == MISSING_ZERO and len(bounds) == 2:
                self.missing_type = MISSING_NONE
            if self.missing_type == MISSING_NAN:
                bounds.append(math.nan)
            self.bin_upper_bound = np.asarray(bounds)
            self.num_bin = len(bounds)
            # each distinct value lands in the first bin whose upper bound
            # is >= it (bounds ascend; the count loop of the reference)
            n_num = (self.num_bin - 1 if self.missing_type == MISSING_NAN
                     else self.num_bin)
            which = np.searchsorted(self.bin_upper_bound[:n_num - 1],
                                    distinct, side="left")
            cnt_in_bin = np.bincount(
                which, weights=counts, minlength=self.num_bin
            ).astype(np.int64)
            if self.missing_type == MISSING_NAN:
                cnt_in_bin[-1] = na_cnt
            log.check(self.num_bin <= max_bin, "num_bin exceeds max_bin")
        else:
            cnt_in_bin = self._find_bin_categorical(
                distinct, counts, total_sample_cnt, na_cnt, max_bin, min_data_in_bin)

        self.is_trivial = self.num_bin <= 1
        if not self.is_trivial and self._need_filter(cnt_in_bin, total_sample_cnt,
                                                     min_split_data):
            self.is_trivial = True
        if not self.is_trivial:
            self.default_bin = int(self.value_to_bin(0.0))
            if bin_type == BIN_CATEGORICAL:
                log.check(self.default_bin > 0, "categorical default_bin must be > 0")
            self.most_freq_bin = int(np.argmax(cnt_in_bin))
            self.sparse_rate = float(cnt_in_bin[self.default_bin]) / max(total_sample_cnt, 1)
            max_rate = float(cnt_in_bin[self.most_freq_bin]) / max(total_sample_cnt, 1)
            if self.most_freq_bin != self.default_bin and max_rate > 0.7:
                self.sparse_rate = max_rate
            else:
                self.most_freq_bin = self.default_bin
        else:
            self.sparse_rate = 1.0

    def _find_bin_categorical(self, distinct, counts, total_sample_cnt, na_cnt,
                              max_bin, min_data_in_bin):
        """Count-ordered categorical mapping; rare categories and negatives go
        to the NaN bin (reference: bin.cpp:424-497)."""
        vals_int: List[int] = []
        counts_int: List[int] = []
        for v, c in zip(distinct, counts):
            iv = int(v)
            if iv < 0:
                na_cnt += int(c)
                log.warning("Met negative value in categorical features, converting to NaN")
            elif vals_int and iv == vals_int[-1]:
                counts_int[-1] += int(c)
            else:
                vals_int.append(iv)
                counts_int.append(int(c))
        self.num_bin = 0
        cnt_in_bin: List[int] = []
        rest_cnt = total_sample_cnt - na_cnt
        if rest_cnt > 0 and vals_int:
            order = np.argsort(np.asarray(counts_int), kind="stable")[::-1]
            vals_sorted = [vals_int[i] for i in order]
            cnts_sorted = [counts_int[i] for i in order]
            # bin 0 must not be category 0 (0 is the "default"/elided value)
            if vals_sorted[0] == 0:
                if len(vals_sorted) == 1:
                    vals_sorted.append(vals_sorted[0] + 1)
                    cnts_sorted.append(0)
                vals_sorted[0], vals_sorted[1] = vals_sorted[1], vals_sorted[0]
                cnts_sorted[0], cnts_sorted[1] = cnts_sorted[1], cnts_sorted[0]
            cut_cnt = int((total_sample_cnt - na_cnt) * 0.99)
            eff_max_bin = min(len(vals_sorted), max_bin)
            self.categorical_2_bin = {}
            self.bin_2_categorical = []
            used_cnt = 0
            cur = 0
            while cur < len(vals_sorted) and (used_cnt < cut_cnt or self.num_bin < eff_max_bin):
                if cnts_sorted[cur] < min_data_in_bin and cur > 1:
                    break
                self.bin_2_categorical.append(vals_sorted[cur])
                self.categorical_2_bin[vals_sorted[cur]] = self.num_bin
                used_cnt += cnts_sorted[cur]
                cnt_in_bin.append(cnts_sorted[cur])
                self.num_bin += 1
                cur += 1
            if cur == len(vals_sorted) and na_cnt > 0:
                self.bin_2_categorical.append(-1)
                self.categorical_2_bin[-1] = self.num_bin
                cnt_in_bin.append(0)
                self.num_bin += 1
            self.missing_type = (MISSING_NONE if cur == len(vals_sorted) and na_cnt == 0
                                 else MISSING_NAN)
            if cnt_in_bin:
                cnt_in_bin[-1] += total_sample_cnt - used_cnt
        return np.asarray(cnt_in_bin, dtype=np.int64)

    def _need_filter(self, cnt_in_bin: np.ndarray, total_cnt: int,
                     filter_cnt: int) -> bool:
        """True if no split on this feature could satisfy min_data_in_leaf on
        both sides (reference: NeedFilter, bin.cpp:54-76). Numerical features
        use the cumulative left/right check over every boundary; categoricals
        are only filtered when they have <= 2 bins (per-bin check)."""
        if self.bin_type == BIN_NUMERICAL:
            left = 0
            for i in range(len(cnt_in_bin) - 1):
                left += int(cnt_in_bin[i])
                if left >= filter_cnt and total_cnt - left >= filter_cnt:
                    return False
            return True
        if len(cnt_in_bin) <= 2:
            for i in range(len(cnt_in_bin) - 1):
                left = int(cnt_in_bin[i])
                if left >= filter_cnt and total_cnt - left >= filter_cnt:
                    return False
            return True
        return False

    # ------------------------------------------------------------------
    def value_to_bin(self, value) -> np.ndarray:
        """Vectorized value→bin (reference: BinMapper::ValueToBin, bin.h:472)."""
        scalar = np.isscalar(value)
        v = np.atleast_1d(np.asarray(value, dtype=np.float64))
        if self.bin_type == BIN_NUMERICAL:
            n_search = self.num_bin - (1 if self.missing_type == MISSING_NAN else 0)
            if _native.lib() is not None and v.ndim == 1 and len(v) > 1024:
                res = _native.binarize_numerical(
                    v, self.bin_upper_bound, n_search - 1,
                    self.missing_type, self.num_bin)
            else:
                nan = np.isnan(v)
                vv = np.where(nan, 0.0, v)
                # first bin i with value <= bin_upper_bound[i]; bounds
                # ascend, the last searchable bound is +inf so the result
                # is always < n_search
                out = np.searchsorted(self.bin_upper_bound[:n_search - 1], vv,
                                      side="left")
                if self.missing_type == MISSING_NAN:
                    out = np.where(nan, self.num_bin - 1, out)
                res = out.astype(np.int32)
        else:
            res = np.full(v.shape, self.num_bin - 1, dtype=np.int32)
            # NaN is converted to 0.0 before categorical lookup unless this
            # feature's missing type is NaN (reference: bin.h:473-478)
            nan_cat = -1 if self.missing_type == MISSING_NAN else 0
            iv = np.where(np.isnan(v), nan_cat, v).astype(np.int64)
            for cat, b in self.categorical_2_bin.items():
                res = np.where(iv == cat, b, res)
        return int(res[0]) if scalar else res

    def value_to_bin_predict(self, value, sentinel: int) -> np.ndarray:
        """Prediction-time value→bin for CATEGORICAL features: any value
        that is NaN, negative or an unseen category maps to ``sentinel`` (a
        bin index outside every node's category bitset), so bin-space
        traversal routes it right — exactly the reference's
        CategoricalDecision, which casts to int and sends negatives/unknowns
        down the right child before any missing handling (reference:
        include/LightGBM/tree.h:262-303)."""
        v = np.atleast_1d(np.asarray(value, dtype=np.float64))
        res = np.full(v.shape, sentinel, dtype=np.int32)
        iv = np.where(np.isnan(v) | (v < 0), -1, v).astype(np.int64)
        for cat, b in self.categorical_2_bin.items():
            if cat >= 0:
                res = np.where(iv == cat, b, res)
        return res

    # ------------------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin, "missing_type": self.missing_type,
            "is_trivial": self.is_trivial, "sparse_rate": self.sparse_rate,
            "bin_type": self.bin_type, "min_val": self.min_val, "max_val": self.max_val,
            "bin_upper_bound": [float(x) for x in self.bin_upper_bound],
            "bin_2_categorical": list(self.bin_2_categorical),
            "default_bin": self.default_bin, "most_freq_bin": self.most_freq_bin,
        }

    @classmethod
    def from_thresholds(cls, thresholds, missing_type: int = MISSING_NONE
                        ) -> "BinMapper":
        """Serving-side numerical mapper built from a forest's split
        thresholds instead of a data sample (serve/packing.py).

        Traversal only needs every node DECISION reproduced, not the
        training quantization: with the sorted distinct thresholds as bin
        upper bounds, ``value_to_bin(v) <= value_to_bin(thr)`` holds
        exactly when ``v <= thr`` for every threshold in the set, so
        bin-space compares equal the host's value-space compares.  Under
        MISSING_ZERO the zero value gets its own bin (bounds at
        +-kZeroThreshold, reference: meta.h:53) so only "zero" rows take
        the default-left route; under MISSING_NAN the trailing NaN bin is
        appended like ``find_bin``'s."""
        m = cls()
        vals = np.unique(np.asarray(thresholds, dtype=np.float64))
        vals = vals[np.isfinite(vals)]
        if missing_type == MISSING_ZERO:
            vals = np.unique(np.concatenate(
                [vals, [-K_ZERO_THRESHOLD, K_ZERO_THRESHOLD]]))
        bounds = list(vals) + [math.inf]
        if missing_type == MISSING_NAN:
            bounds.append(math.nan)
        m.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
        m.num_bin = len(bounds)
        m.missing_type = int(missing_type)
        m.bin_type = BIN_NUMERICAL
        m.is_trivial = False
        if len(vals):
            m.min_val, m.max_val = float(vals[0]), float(vals[-1])
        m.default_bin = int(m.value_to_bin(0.0))
        m.most_freq_bin = m.default_bin
        m.sparse_rate = 0.0
        return m

    @classmethod
    def categorical_from_categories(cls, categories) -> "BinMapper":
        """Model-derived CATEGORICAL mapper for the online
        train-continue path (online/binspace.py): the bins are exactly
        the category values the forest's bitsets reference, plus a
        trailing NaN/unseen bin that no node bitset can contain — so
        NaN, negatives and categories the model never saw all land in a
        bin whose bit is set nowhere and route right, exactly like the
        reference's CategoricalDecision (tree.h:262-303).

        Follows ``_find_bin_categorical``'s conventions: bin 0 must not
        be category 0 (0 is the default/elided value; find_bin swaps it
        out of bin 0, and ``find_bin`` checks ``default_bin > 0``), and
        the NaN catch-all is category ``-1`` at the LAST bin (which is
        also where ``value_to_bin`` sends unmatched categories)."""
        m = cls()
        cats = sorted({int(c) for c in categories if int(c) >= 0})
        if not cats:
            return m  # trivial: the model references no category
        if cats[0] == 0:
            if len(cats) == 1:
                cats.append(1)
            cats[0], cats[1] = cats[1], cats[0]
        cats.append(-1)  # NaN / unseen catch-all, never in a bitset
        m.bin_2_categorical = cats
        m.categorical_2_bin = {c: i for i, c in enumerate(cats)}
        m.num_bin = len(cats)
        m.bin_type = BIN_CATEGORICAL
        m.missing_type = MISSING_NAN
        m.is_trivial = False
        m.sparse_rate = 0.0
        m.min_val = float(min(c for c in cats if c >= 0))
        m.max_val = float(max(cats))
        m.default_bin = int(m.value_to_bin(0.0))
        m.most_freq_bin = m.default_bin
        return m

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(d["num_bin"])
        m.missing_type = int(d["missing_type"])
        m.is_trivial = bool(d["is_trivial"])
        m.sparse_rate = float(d["sparse_rate"])
        m.bin_type = int(d["bin_type"])
        m.min_val = float(d["min_val"])
        m.max_val = float(d["max_val"])
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = [int(x) for x in d["bin_2_categorical"]]
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        m.default_bin = int(d["default_bin"])
        m.most_freq_bin = int(d["most_freq_bin"])
        return m

    def missing_type_name(self) -> str:
        return _MISSING_NAMES[self.missing_type]

    def bin_to_value(self, bin_idx: int) -> float:
        """Representative threshold value for a bin (its upper bound)."""
        if self.bin_type == BIN_NUMERICAL:
            return float(self.bin_upper_bound[bin_idx])
        return float(self.bin_2_categorical[bin_idx])
