"""Single-pass reservoir sampling for out-of-core bin finding.

The reference's ``DatasetLoader`` samples ``bin_construct_sample_cnt``
rows BEFORE it ever materializes the dataset (dataset_loader.cpp:574
ConstructFromSampleData; the two_round text path re-reads the file), and
its sample is drawn from the WHOLE file — ``SampleFromFile`` walks every
line.  A streaming ingestion path must preserve that property: taking
the first ``sample_cnt`` rows of the stream would bias the bin bounds
toward the head (a time-ordered log whose distribution drifts would get
bins that cannot resolve the tail — the regression
``tests/test_ingest_stream.py::test_reservoir_sample_covers_shifted_tail``
pins this).  :class:`ReservoirSampler` is Algorithm R, vectorized per
chunk, over either dense chunks or scipy-sparse row blocks — the same
per-row replacement probabilities as the sequential algorithm (numpy
fancy assignment keeps the LAST write per slot, matching sequential
order), deterministic under ``seed`` and INDEPENDENT of how the stream
is chunked (the bounded-integer draws consume the bit stream row by
row; ``test_chunking_invariance`` pins it).

Distributed bin finding: when every rank streams only ITS OWN row shard
(pre-partitioned data), each rank feeds its local reservoir and then
calls ``BinnedDataset.from_sample(local_sample, local_rows)`` — the
pooling inside ``from_sample`` (``parallel/distributed.py
global_bin_sample``: an allgather in rank order over the host
collectives) makes every rank derive bit-identical ``BinMapper``s, the
TPU analog of the reference's sample-sync between ``DatasetLoader`` and
``Network``.  :func:`merge_shard_samples` is the host-side pooling
oracle the single-process tests pin that path against.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class ReservoirSampler:
    """Uniform ``sample_cnt``-row reservoir over a chunked row stream.

    ``add`` one chunk at a time (2-D ndarray or scipy-sparse rows; all
    chunks must be one or the other).  ``finish`` returns the sampled
    rows (dense [m, F] f64, or a scipy CSR when the stream was sparse)
    plus the sampled rows' GLOBAL stream indices in slot order — the
    differential tests feed those indices to the in-RAM oracle
    (``BinnedDataset.from_matrix(sample_indices=...)``) so streamed and
    in-RAM construction see the exact same sample.
    """

    def __init__(self, sample_cnt: int, seed: int = 1):
        self.k = int(sample_cnt)
        if self.k < 1:
            raise ValueError("sample_cnt must be >= 1")
        self._rng = np.random.default_rng(int(seed))
        self.n = 0                       # stream rows seen so far
        self._dense: Optional[np.ndarray] = None     # [k, F] f64
        self._sparse_parts: List[Tuple[np.ndarray, object]] = []
        self._sparse_cols = 0
        self._sparse = None              # None until the first chunk
        self.indices = np.full(self.k, -1, dtype=np.int64)

    # ------------------------------------------------------------------
    def _slots_for(self, m: int) -> Tuple[np.ndarray, np.ndarray]:
        """(slots, local_rows) hit by this chunk's ``m`` rows: the fill
        phase takes rows verbatim; past the fill, row with global index
        ``g`` replaces a uniform slot in [0, g] and survives only when
        that slot is < k (Algorithm R).  Draw order is strictly by
        global row index, so chunk boundaries cannot change the
        schedule."""
        filled = min(self.n, self.k)
        take = min(self.k - filled, m) if filled < self.k else 0
        slots = [np.arange(filled, filled + take, dtype=np.int64)]
        local = [np.arange(take, dtype=np.int64)]
        if take < m:
            gi = np.arange(self.n + take, self.n + m, dtype=np.int64)
            draws = self._rng.integers(0, gi + 1)
            hit = draws < self.k
            slots.append(draws[hit])
            local.append(np.arange(take, m, dtype=np.int64)[hit])
        return np.concatenate(slots), np.concatenate(local)

    def add(self, chunk) -> None:
        sparse = hasattr(chunk, "tocsr")
        if self._sparse is None:
            self._sparse = sparse
        elif self._sparse != sparse:
            raise ValueError("reservoir stream mixed dense and sparse "
                             "chunks")
        m = int(chunk.shape[0])
        if m == 0:
            return
        slots, local = self._slots_for(m)
        if len(slots):
            if sparse:
                block = chunk.tocsr()[local]
                self._sparse_parts.append((slots, block))
                self._sparse_cols = max(self._sparse_cols,
                                        int(chunk.shape[1]))
                self._maybe_compact()
            else:
                arr = np.asarray(chunk, dtype=np.float64)
                if self._dense is None:
                    self._dense = np.empty((self.k, arr.shape[1]),
                                           np.float64)
                elif arr.shape[1] != self._dense.shape[1]:
                    raise ValueError(
                        f"chunk has {arr.shape[1]} columns, stream "
                        f"started with {self._dense.shape[1]}")
                self._dense[slots] = arr[local]
            self.indices[slots] = self.n + local
        self.n += m

    # ------------------------------------------------------------------
    def _live_sparse(self):
        """slot -> (part index, row in part) for the LAST write per slot
        (later parts — and later rows within a part — win, matching the
        sequential reservoir)."""
        live = {}
        for pi, (slots, _) in enumerate(self._sparse_parts):
            for r, s in enumerate(slots):
                live[int(s)] = (pi, r)
        return live

    def _maybe_compact(self) -> None:
        """Replacement blocks accumulate until ``finish``; past ~4x the
        reservoir size, rewrite them down to the live rows so memory
        stays O(sample) on arbitrarily long streams."""
        stored = sum(p[1].shape[0] for p in self._sparse_parts)
        if stored <= max(4 * self.k, self.k + 64):
            return
        sample = self._assemble_sparse()
        live_slots = np.asarray(sorted(self._live_sparse()), np.int64)
        self._sparse_parts = [(live_slots, sample)]

    def _assemble_sparse(self):
        import scipy.sparse as sp

        live = self._live_sparse()
        order = sorted(live.items())              # by slot
        if not order:
            return sp.csr_matrix((0, self._sparse_cols))
        pos_parts, row_parts = [], []
        by_part = {}
        for pos, (_, (pi, r)) in enumerate(order):
            by_part.setdefault(pi, []).append((pos, r))
        for pi, lst in by_part.items():
            rows = [r for _, r in lst]
            blk = self._sparse_parts[pi][1][rows]
            blk = sp.csr_matrix((blk.data, blk.indices, blk.indptr),
                                shape=(blk.shape[0], self._sparse_cols))
            row_parts.append(blk)
            pos_parts.append(np.asarray([p for p, _ in lst], np.int64))
        stacked = sp.vstack(row_parts, format="csr")
        return stacked[np.argsort(np.concatenate(pos_parts),
                                  kind="stable")]

    def finish(self) -> Tuple[object, np.ndarray]:
        """``(sample_rows, global_indices)`` in slot order.  With fewer
        stream rows than ``sample_cnt`` the sample is every row (the
        fill phase never completed)."""
        m = min(self.n, self.k)
        if self._sparse:
            sample = self._assemble_sparse()[:m]
        elif self._dense is not None:
            sample = self._dense[:m]
        else:
            sample = np.empty((0, 0), np.float64)
        return sample, self.indices[:m].copy()


def merge_shard_samples(samples, shard_rows) -> Tuple[np.ndarray, int]:
    """Host-side pooling oracle for pre-sharded distributed bin finding:
    the rank-ordered concatenation (and summed global row count) that
    ``parallel/distributed.py global_bin_sample`` produces over the real
    collectives.  The single-process shard-agreement tests build every
    shard's reservoir locally, pool with this, and assert the mappers
    match what each rank of a real 2-process run derives
    (``tests/dist_worker.py``)."""
    mats = list(samples)
    if not mats:
        return np.empty((0, 0), np.float64), 0
    if hasattr(mats[0], "tocsr"):
        import scipy.sparse as sp
        pooled = sp.vstack([m.tocsr() for m in mats], format="csc")
    else:
        pooled = np.concatenate([np.asarray(m, np.float64) for m in mats])
    return pooled, int(sum(int(r) for r in shard_rows))


def sample_seed(config) -> int:
    """The reservoir seed: ``tpu_ingest_sample_seed`` when set (>= 0),
    else ``data_random_seed`` — the same knob the in-RAM sampler uses,
    so flipping ``tpu_ingest`` keeps the sampling seed stable."""
    s = int(getattr(config, "tpu_ingest_sample_seed", -1))
    if s >= 0:
        return s
    return int(getattr(config, "data_random_seed", 1))
