"""Two-pass streaming construction of ``BinnedDataset`` — bounded host
memory, no raw [N, F] matrix, shard-aware.

The reference architecture separates exactly these concerns (PAPER.md
layers 2-3): ``DatasetLoader`` samples, finds bins, then streams rows
through ``BinMapper``s; ``Network`` syncs the mappers so every rank bins
identically.  This module composes the repo's existing primitives the
same way:

- **pass 1** — one guarded walk of the chunk source: count rows,
  feed the seeded reservoir (``ingest/sample.py``, honoring
  ``bin_construct_sample_cnt``), collect the streamed label/weight/query
  side columns;
- **bin finding** — ``BinnedDataset.from_sample`` on the reservoir
  sample (its internal ``global_bin_sample`` pooling makes pre-sharded
  multi-host ranks derive bit-identical mappers over the host
  collectives);
- **pass 2** — a second guarded walk binning chunk-at-a-time through
  the existing ``_binarize_chunk``/``_binarize_bundled_chunk`` into a
  preallocated (optionally ``np.memmap``-backed) bin matrix, each shard
  touching ONLY its rows of the :class:`~.shard.RowShardPlan`.

Peak host memory is O(chunk + sample + bin matrix) — never
O(N * F * 8).  Correctness is differential: with the same sample, the
streamed dataset (bin matrix, mappers, metadata, and the model trained
from it) is BIT-IDENTICAL to the in-RAM ``from_matrix``/``from_csr``
oracle (tests/test_ingest_stream.py pins dense/NaN/categorical/bundled/
ranking fixtures and a sharded 2-process agreement leg).

Fault surface: every chunk fetch passes the ``ingest_chunk`` injection
point under a ``robust/watchdog.DeviceGuard`` — transient read faults
retry with backoff, fatal ones abort loudly, and a stalled read is
stamped (``device_stall`` event + flight dump) when
``tpu_wedge_timeout_s`` is set.  A chunk whose geometry disagrees with
the stream (column-count drift, a pass-2 row count different from
pass 1's) raises :class:`IngestError` — corrupt input must never bin
silently.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import List, Optional, Sequence

import numpy as np

from ..config import Config
from ..utils import log
from .readers import open_source
from .sample import ReservoirSampler, sample_seed
from .shard import local_query_sizes, plan_row_shards, resolve_shard

_DONE = object()


class IngestError(RuntimeError):
    """Corrupt or inconsistent stream input — ingestion aborts loudly
    rather than binning garbage."""


def chunk_rows_from_config(config) -> int:
    """``tpu_ingest_chunk_rows`` with the ``LGBM_TPU_INGEST_CHUNK_ROWS``
    env override (ops retune without editing configs, like the serve
    knobs)."""
    env = os.environ.get("LGBM_TPU_INGEST_CHUNK_ROWS", "")
    if env:
        try:
            return max(int(env), 1)
        except ValueError:
            log.warning("ignoring malformed LGBM_TPU_INGEST_CHUNK_ROWS=%r",
                        env)
    return max(int(getattr(config, "tpu_ingest_chunk_rows", 65536)), 1)


def memmap_from_config(config) -> str:
    env = os.environ.get("LGBM_TPU_INGEST_MEMMAP", "")
    return env or str(getattr(config, "tpu_ingest_memmap", "") or "")


def _memmap_file(base: str, shard_id: int, num_shards: int) -> str:
    """Resolve the memmap target: a directory (or trailing separator)
    gets a per-shard file inside it; a file path gains a shard suffix
    only when sharding.  An EXISTING target is never reused — open_memmap
    mode='w+' would truncate the inode a live dataset (e.g. the train
    set, while its valid set ingests with the same config) still maps —
    so the name walks to the first free ``.k`` suffix instead."""
    if os.path.isdir(base) or base.endswith(os.sep):
        os.makedirs(base, exist_ok=True)
        path = os.path.join(base, f"X_bin.shard{shard_id}.npy")
    elif num_shards > 1:
        root, ext = os.path.splitext(base)
        path = f"{root}.shard{shard_id}{ext or '.npy'}"
    else:
        path = base
    if os.path.exists(path):
        root, ext = os.path.splitext(path)
        k = 1
        while os.path.exists(f"{root}.{k}{ext}"):
            k += 1
        log.warning("ingest: memmap target %s already exists (another "
                    "dataset may still map it); writing %s.%d%s instead",
                    path, root, k, ext)
        path = f"{root}.{k}{ext}"
    return path


def _guard(config):
    from ..robust.watchdog import DeviceGuard
    timeout = float(getattr(config, "tpu_wedge_timeout_s", 0.0) or 0.0)
    return DeviceGuard(
        policy="retry",
        retries=int(getattr(config, "tpu_device_retries", 3)),
        stall_timeout_s=timeout if timeout > 0 else -1.0,
        enabled=bool(getattr(config, "tpu_watchdog", False)),
        name="ingest")


def _iter_guarded(source, guard, pass_no: int, expect_cols=None):
    """Yield ``(chunk_index, stream_row0, X, side)`` with the
    ``ingest_chunk`` fault point, retry/stall guard, and corrupt-chunk
    validation applied to every fetch."""
    from .. import obs
    it = iter(source)

    def _next():
        try:
            return next(it)
        except StopIteration:
            return _DONE

    ci = 0
    row0 = 0
    cols = expect_cols
    while True:
        out = guard.run(_next, point="ingest_chunk")
        if out is _DONE:
            break
        try:
            X, side = out
        except (TypeError, ValueError):
            raise IngestError(
                f"ingest pass {pass_no}: chunk {ci} is not an "
                f"(X, side) pair (got {type(out).__name__})")
        if getattr(X, "ndim", 2) != 2:
            raise IngestError(
                f"ingest pass {pass_no}: chunk {ci} is not 2-D "
                f"(shape {getattr(X, 'shape', None)})")
        sparse = hasattr(X, "tocsr")
        if not sparse:
            if cols is None:
                cols = int(X.shape[1])
            elif int(X.shape[1]) != cols:
                raise IngestError(
                    f"ingest pass {pass_no}: chunk {ci} has "
                    f"{int(X.shape[1])} columns, stream started with "
                    f"{cols} — corrupt chunk, aborting")
        m = int(X.shape[0])
        for name, arr in (side or {}).items():
            if arr is not None and len(arr) != m:
                raise IngestError(
                    f"ingest pass {pass_no}: chunk {ci} side column "
                    f"{name!r} has {len(arr)} rows for {m} data rows")
        if obs.enabled():
            obs.event("ingest_chunk", **{"pass": int(pass_no)},
                      chunk=ci, rows=m, stream_row0=row0)
        yield ci, row0, X, side or {}
        ci += 1
        row0 += m


def _group_sizes_from_qids(qids: np.ndarray):
    """Per-row query ids -> per-query sizes (ids must be grouped; same
    convention as ``io/text_loader._group_from_col``)."""
    if qids is None or not len(qids):
        return None
    has_q = qids >= 0
    if not has_q.any():
        return None
    if not has_q.all():
        log.warning("ingest: qid present on only %d of %d rows; "
                    "ignoring query structure", int(has_q.sum()),
                    len(qids))
        return None
    change = np.flatnonzero(np.diff(qids)) + 1
    bounds = np.concatenate([[0], change, [len(qids)]])
    return np.diff(bounds)


def _densify(chunk, n_cols: int) -> np.ndarray:
    """One sparse row block -> dense f64 with the stream's final width
    (implicit entries are 0.0 — the zero-bin handling makes that exact,
    io/dataset.py)."""
    out = np.zeros((int(chunk.shape[0]), int(n_cols)), np.float64)
    coo = chunk.tocoo()
    out[coo.row, coo.col] = coo.data
    return out


def dataset_digest(ds) -> str:
    """Content digest of a constructed dataset — bin matrix (hashed in
    bounded row blocks: the matrix may be a memmap far larger than
    RAM), mappers, offsets and labels.  Two deterministic re-streams of
    the same source produce the same digest, which is what makes
    crash-mid-ingest resume provable (re-ingest, compare, resume
    bit-exactly — tests/test_ingest_stream.py)."""
    h = hashlib.sha256()
    X = ds.X_bin
    if X is not None:
        h.update(str(X.dtype).encode())
        h.update(np.asarray(X.shape, np.int64).tobytes())
        step = max((1 << 24) // max(int(X.shape[1]), 1), 1)
        for lo in range(0, int(X.shape[0]), step):
            h.update(np.ascontiguousarray(X[lo:lo + step]).tobytes())
    h.update(json.dumps([m.to_dict() for m in ds.bin_mappers],
                        sort_keys=True).encode())
    if ds.bin_offsets is not None:
        h.update(np.asarray(ds.bin_offsets, np.int64).tobytes())
    md = ds.metadata
    for arr in (md.label, md.weights, md.query_boundaries):
        if arr is not None:
            h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()[:16]


# ---------------------------------------------------------------------------
def ingest_dataset(source, config=None, *, categorical_features: Sequence = (),
                   feature_names: Optional[List[str]] = None,
                   reference=None, num_shards: Optional[int] = None,
                   shard_id: Optional[int] = None,
                   memmap_path: Optional[str] = None,
                   group=None, weight=None, seed: Optional[int] = None):
    """Construct a ``BinnedDataset`` from a chunked ``source`` without
    materializing the raw matrix.  Returns the LOCAL shard's dataset
    (the whole stream when unsharded); ``ds.ingest_row_range`` records
    the global ``[lo, hi)`` rows it holds so callers can align other
    whole-stream side arrays (init scores) to the shard.

    ``source``: re-iterable of ``(X_chunk, side)`` (ingest/readers.py).
    ``reference``: a constructed BinnedDataset whose mappers are reused
    (validation-set alignment; sampling is skipped).  ``num_shards`` /
    ``shard_id`` default to the config surface (``resolve_shard``);
    ``memmap_path`` (or ``tpu_ingest_memmap``) backs the bin matrix
    with an ``np.memmap`` file.  ``group`` / ``weight`` override the
    stream's query structure (per-query sizes) and row weights — both
    whole-stream length, sliced to the shard here (sidecar files ride
    in this way so the shard plan can still query-align on them).
    """
    from .. import obs
    from ..io.dataset import BinnedDataset, Metadata
    from ..utils.timetag import timetag

    config = config if config is not None else Config()
    t_start = time.perf_counter()
    guard = _guard(config)
    if num_shards is None or shard_id is None:
        d_cfg, s_cfg = resolve_shard(config)
        num_shards = d_cfg if num_shards is None else int(num_shards)
        shard_id = s_cfg if shard_id is None else int(shard_id)
    num_shards = max(int(num_shards), 1)
    shard_id = int(shard_id)
    log.check(0 <= shard_id < num_shards,
              f"shard_id {shard_id} out of range for {num_shards} shards")
    if memmap_path is None:
        memmap_path = memmap_from_config(config) or None

    # ---- pass 1: count, sample, side columns -------------------------
    sampler = None
    if reference is None:
        sample_cnt = int(getattr(config, "bin_construct_sample_cnt",
                                 200000))
        sampler = ReservoirSampler(
            sample_cnt, seed=sample_seed(config) if seed is None
            else int(seed))
    n_rows = 0
    chunks_seen = 0
    labels, weights, qids = [], [], []
    with timetag("ingest pass1"):
        for ci, row0, X, side in _iter_guarded(source, guard, 1):
            m = int(X.shape[0])
            if sampler is not None:
                sampler.add(X)
            if side.get("label") is not None:
                labels.append(np.asarray(side["label"], np.float64))
            if side.get("weight") is not None:
                weights.append(np.asarray(side["weight"], np.float64))
            if side.get("qid") is not None:
                qids.append(np.asarray(side["qid"], np.int64))
            n_rows += m
            chunks_seen = ci + 1
    if n_rows == 0:
        raise IngestError("ingest: the source yielded no rows")

    label = np.concatenate(labels) if labels else None
    # a weight column IN the stream wins over the sidecar fallback (the
    # load_text convention); an explicit query override (sidecar) wins
    # over stream qids (ditto)
    if weights:
        weight = np.concatenate(weights)
    elif weight is not None:
        weight = np.asarray(weight, np.float64).ravel()
    if label is not None and len(label) != n_rows:
        raise IngestError(
            f"ingest: stream carried {len(label)} labels for "
            f"{n_rows} rows")
    if weight is not None and len(weight) != n_rows:
        raise IngestError(
            f"ingest: {len(weight)} weights for {n_rows} rows")
    if group is None:
        group = getattr(source, "group_sizes", None)
    if group is None and qids:
        group = _group_sizes_from_qids(np.concatenate(qids))
    group = None if group is None else np.asarray(group).ravel()
    if group is not None and int(group.sum()) != n_rows:
        raise IngestError(
            f"ingest: query sizes sum to {int(group.sum())} for "
            f"{n_rows} rows")
    boundaries = (np.concatenate([[0], np.cumsum(group)]).astype(np.int64)
                  if group is not None else None)

    # sparse streams discover their width in pass 1 (LibSVM max index)
    n_cols = getattr(source, "n_features", None)
    if feature_names is None:
        feature_names = getattr(source, "feature_names", None)

    # ---- shard plan --------------------------------------------------
    plan = plan_row_shards(n_rows, num_shards, boundaries) \
        if num_shards > 1 else None
    lo, hi = (plan.shard_range(shard_id) if plan is not None
              else (0, n_rows))
    local_n = hi - lo

    # ---- bin mappers -------------------------------------------------
    sample_rows = 0
    if reference is not None:
        ds = BinnedDataset()
        ds.num_data = local_n
        ds.num_total_features = reference.num_total_features
        if n_cols is not None:
            log.check(int(n_cols) <= reference.num_total_features,
                      "ingest stream has more features than the "
                      "reference dataset")
        ds.metadata = Metadata(local_n)
        ds.bin_mappers = reference.bin_mappers
        ds.used_feature_map = reference.used_feature_map
        ds.real_feature_idx = reference.real_feature_idx
        ds.bin_offsets = reference.bin_offsets
        ds.feature_names = reference.feature_names
        ds.max_bin = reference.max_bin
        ds.bundle = reference.bundle
        n_cols = reference.num_total_features
    else:
        sample, _indices = sampler.finish()
        sample_rows = int(sample.shape[0])
        if n_cols is None:
            n_cols = int(sample.shape[1])
        if hasattr(sample, "tocsr") and int(sample.shape[1]) < n_cols:
            import scipy.sparse as sp
            s = sample.tocsr()
            sample = sp.csr_matrix((s.data, s.indices, s.indptr),
                                   shape=(s.shape[0], n_cols))
        # name-based categorical specs resolve against the KEPT feature
        # names (same convention as io/text_loader._two_round_streamed)
        cats = []
        for c in categorical_features or ():
            if isinstance(c, str):
                if feature_names and c in feature_names:
                    cats.append(feature_names.index(c))
                else:
                    log.warning("categorical_feature %r not found in "
                                "feature names; ignored", c)
            else:
                cats.append(int(c))
        # ``from_sample`` builds mappers/feature-map/bundles and — under
        # an initialized multi-host runtime — pools every rank's sample
        # over the host collectives so pre-sharded ranks derive
        # bit-identical mappers (parallel/distributed.global_bin_sample)
        ds = BinnedDataset.from_sample(
            sample, n_rows, config,
            categorical_features=sorted(set(cats)),
            feature_names=feature_names)
        if plan is not None:
            # mappers/bundles describe the GLOBAL stream; this process
            # materializes only its shard's rows
            ds.num_data = local_n
            ds.metadata = Metadata(local_n)

    # ---- allocate the bin matrix (RAM or memmap) ---------------------
    memmap_file = None
    if memmap_path:
        cols, dtype = ds._bin_matrix_spec()
        memmap_file = _memmap_file(memmap_path, shard_id, num_shards)
        ds.X_bin = np.lib.format.open_memmap(
            memmap_file, mode="w+", dtype=dtype, shape=(local_n, cols))
    else:
        ds._alloc_X()

    # ---- pass 2: bin chunk-at-a-time into [lo, hi) -------------------
    # bin-occupancy capture rides the binarize pass for free: each
    # just-binned slice of X_bin folds into the per-feature occupancy
    # accumulator the quality profile (obs/drift.py) is built from —
    # no extra scan over a matrix that may be memmap-backed
    from ..obs.drift import accumulate_occupancy, init_occupancy
    occupancy = init_occupancy(ds)
    with timetag("binarize"):
        seen = 0
        filled = 0
        for ci, row0, X, side in _iter_guarded(source, guard, 2):
            m = int(X.shape[0])
            s = max(lo - row0, 0)
            e = min(hi - row0, m)
            if s < e:
                sub = X[s:e]
                if hasattr(sub, "tocsr"):
                    sub = _densify(sub, n_cols)
                else:
                    sub = np.asarray(sub, np.float64)
                    if sub.shape[1] != n_cols:
                        raise IngestError(
                            f"ingest pass 2: chunk {ci} width "
                            f"{sub.shape[1]} != stream width {n_cols}")
                ds._binarize_chunk(sub, filled)
                accumulate_occupancy(ds, occupancy, filled, e - s)
                filled += e - s
            seen += m
    ds.quality_occupancy = occupancy
    if seen != n_rows:
        raise IngestError(
            f"ingest: stream changed between passes ({seen} rows on "
            f"pass 2, {n_rows} on pass 1)")
    if filled != local_n:
        raise IngestError(
            f"ingest: shard {shard_id} binned {filled} rows, plan "
            f"expected {local_n}")

    # ---- metadata ----------------------------------------------------
    if label is not None:
        ds.metadata.set_label(label[lo:hi])
    if weight is not None:
        ds.metadata.set_weights(weight[lo:hi])
    if group is not None:
        sizes = (local_query_sizes(plan, shard_id, boundaries)
                 if plan is not None else group)
        if sizes is not None and len(sizes):
            ds.metadata.set_query(sizes)

    # the global rows this local dataset holds — callers align other
    # whole-stream side arrays (init scores, sidecars) with this
    ds.ingest_row_range = (int(lo), int(hi))
    ds.ingest_num_rows = int(n_rows)

    # ---- telemetry ---------------------------------------------------
    from .. import obs as _obs
    wall = time.perf_counter() - t_start
    fields = dict(rows=int(n_rows), local_rows=int(local_n),
                  chunks=int(chunks_seen), sample_rows=int(sample_rows),
                  shards=int(num_shards), shard_id=int(shard_id),
                  memmap=bool(memmap_file),
                  wall_s=round(wall, 4),
                  rows_per_s=round(n_rows / wall, 1) if wall > 0 else 0.0,
                  source=str(getattr(source, "kind", type(source).__name__)))
    if _obs.enabled() or _obs.flight_enabled():
        fields["digest"] = dataset_digest(ds)
    _obs.event("ingest_summary", **fields)
    log.info("ingest: %d rows (%d local, shard %d/%d) through %d "
             "chunk(s), %d-row sample, %.2fs (%s rows/s)%s",
             n_rows, local_n, shard_id, num_shards, chunks_seen,
             sample_rows, wall, f"{fields['rows_per_s']:,.0f}",
             f", memmap {memmap_file}" if memmap_file else "")
    return ds


# ---------------------------------------------------------------------------
def ingest_file(path: str, config, categorical_features: Sequence = (),
                reference=None, **kw):
    """CLI-facing file ingestion: pick a chunked reader for ``path``
    (CSV/TSV via the native parser, LibSVM, ``.npy``/``.npz``), stream
    it through :func:`ingest_dataset`, and return
    ``(handle, label, weight, group, feature_names)`` — the same
    contract as ``io/text_loader.load_text_two_round``, with the
    returned side arrays LOCAL to the shard.  The ``<data>.weight``/
    ``.query`` sidecars are read BEFORE ingestion so the shard plan can
    query-align on a sidecar's boundaries and the whole-stream weights
    slice to the shard (instead of crashing a sharded load)."""
    from ..io.text_loader import _load_sidecars

    sc_weight, sc_group = _load_sidecars(path, None, None)
    src = open_source(path, config,
                      chunk_rows=chunk_rows_from_config(config))
    ds = ingest_dataset(src, config,
                        categorical_features=categorical_features,
                        reference=reference, weight=sc_weight,
                        group=sc_group, **kw)
    md = ds.metadata
    group = (np.diff(md.query_boundaries)
             if md.query_boundaries is not None else None)
    return ds, md.label, md.weights, group, list(ds.feature_names)


def dataset_from_stream(source, params=None, *,
                        categorical_features: Sequence = (),
                        feature_names=None, **kw):
    """Engine-facing entry: stream ``source`` into a constructed
    :class:`lightgbm_tpu.Dataset` ready for ``lightgbm_tpu.train`` —
    labels/weights/queries carried by the stream are already attached
    to the handle's metadata."""
    from ..basic import Dataset

    params = dict(params or {})
    cfg = Config.from_params(params)
    handle = ingest_dataset(source, cfg,
                            categorical_features=categorical_features,
                            feature_names=feature_names, **kw)
    ds = Dataset(None, params=params,
                 feature_name=list(handle.feature_names))
    ds._handle = handle
    return ds
