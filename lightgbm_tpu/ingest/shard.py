"""Row-shard plans for pod-scale ingestion.

Data-parallel training at dataset sizes past host RAM needs every
worker to bin ONLY its own contiguous row range — never the full
matrix.  A :class:`RowShardPlan` is the static geometry both ingestion
passes agree on: contiguous ``[cuts[d], cuts[d+1])`` row ranges per
shard, near-equal by rows, and — for ranking data — snapped to QUERY
boundaries by reusing ``parallel/rank_shard.plan_query_shards``'s
greedy balanced cuts (the reference keeps query boundaries in
``Metadata`` for exactly this: its data-parallel learner never splits a
query across workers).  A shard's local ``BinnedDataset`` then feeds
``parallel/mesh.py``'s row-sharded growers directly: the mesh sees
``num_data_local`` rows whose histograms psum to the global ones.

Shard identity resolves like the rest of the distributed plumbing:
explicit config (``tpu_ingest_shards`` / ``tpu_ingest_shard_id``) wins,
else the process rank recorded by ``parallel/distributed.py``.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np

from ..utils import log


@dataclass
class RowShardPlan:
    """Contiguous row ranges per shard; ``query_cuts`` set when the
    cuts were snapped to query boundaries."""
    num_shards: int
    n_rows: int
    cuts: np.ndarray                       # int64 [num_shards + 1]
    query_cuts: Optional[np.ndarray] = field(default=None)

    def shard_range(self, shard_id: int) -> Tuple[int, int]:
        return int(self.cuts[shard_id]), int(self.cuts[shard_id + 1])

    def local_rows(self, shard_id: int) -> int:
        lo, hi = self.shard_range(shard_id)
        return hi - lo

    @property
    def query_aligned(self) -> bool:
        return self.query_cuts is not None

    def replan(self, num_shards: int,
               query_boundaries=None) -> "RowShardPlan":
        """The same row stream re-cut for a DIFFERENT world size — what
        the elastic fleet (fleet/elastic.py) does after it shrinks or
        heals: ``n_rows`` is invariant, only the cuts move.  Pass the
        original ``query_boundaries`` again to keep the new cuts
        query-aligned (alignment is derived from boundaries, not
        carried over — the old cuts are for the old world)."""
        return plan_row_shards(self.n_rows, num_shards, query_boundaries)


def plan_row_shards(n_rows: int, num_shards: int,
                    query_boundaries=None) -> RowShardPlan:
    """Near-equal contiguous row cuts over ``num_shards``.  With
    ``query_boundaries`` (int [Q+1], ascending, last == n_rows) every
    cut lands ON a query boundary — the greedy balanced partition of
    ``parallel/rank_shard.plan_query_shards`` — so per-query work
    (lambdarank pair passes, NDCG eval) stays shard-local."""
    D = max(int(num_shards), 1)
    n = int(n_rows)
    if query_boundaries is None:
        cuts = (np.arange(D + 1, dtype=np.int64) * n) // D
        return RowShardPlan(D, n, cuts)
    from ..parallel.rank_shard import plan_query_shards
    b = np.asarray(query_boundaries, dtype=np.int64)
    log.check(int(b[-1]) == n,
              "query boundaries do not cover the row stream "
              f"({int(b[-1])} != {n})")
    qp = plan_query_shards(b, D)
    cuts = np.asarray(qp.row_cuts, dtype=np.int64)
    if (np.diff(cuts) == 0).any():
        log.warning("row-shard plan: %d of %d shards got zero rows "
                    "(fewer queries than shards?)",
                    int((np.diff(cuts) == 0).sum()), D)
    return RowShardPlan(D, n, cuts,
                        query_cuts=np.asarray(qp.query_cuts, np.int64))


def local_query_sizes(plan: RowShardPlan, shard_id: int,
                      query_boundaries) -> Optional[np.ndarray]:
    """Per-query sizes of the queries living wholly inside ``shard_id``
    (the plan guarantees no straddlers).  None when the plan was not
    query-aligned."""
    if plan.query_cuts is None:
        return None
    b = np.asarray(query_boundaries, dtype=np.int64)
    q0, q1 = int(plan.query_cuts[shard_id]), int(plan.query_cuts[shard_id + 1])
    return np.diff(b[q0:q1 + 1]).astype(np.int64)


def resolve_shard(config) -> Tuple[int, int]:
    """``(num_shards, shard_id)`` for this process: explicit
    ``tpu_ingest_shards``/``tpu_ingest_shard_id`` win; an unset shard id
    falls back to the recorded process rank (``parallel/mesh.NETWORK``,
    fed by ``init_distributed``/``set_network``), and unset shards to 1
    (no sharding)."""
    D = int(getattr(config, "tpu_ingest_shards", 0) or 0)
    if D <= 1:
        return 1, 0
    sid = int(getattr(config, "tpu_ingest_shard_id", -1))
    if sid < 0:
        from ..parallel.mesh import NETWORK
        sid = int(NETWORK.get("rank") or 0)
    log.check(0 <= sid < D,
              f"tpu_ingest_shard_id {sid} out of range for "
              f"{D} shards")
    return D, sid
