"""Chunked row sources for streaming ingestion.

A source is a RE-ITERABLE of ``(X_chunk, side)`` pairs — ``X_chunk`` a
2-D f64 ndarray (or scipy-sparse row block for the LibSVM path) and
``side`` a dict that may carry per-row ``label`` / ``weight`` / ``qid``
arrays of the chunk's length.  The two-pass ingestion
(``ingest/stream.py``) iterates a source twice: once to count rows,
reservoir-sample for bin finding and collect the side columns, once to
bin chunk-at-a-time into the preallocated matrix — so a source must
yield the SAME rows in the same order on every pass (the analog of the
reference's two_round re-read, dataset_loader.cpp:807-827).

Optional source attributes the ingestion driver reads when present:

- ``feature_names`` — list of kept-column names;
- ``group_sizes``   — whole-stream per-query sizes (when the source
  carries query structure out of band instead of per-row ``qid``);
- ``n_features``    — may be None until a full pass completed (the
  LibSVM reader discovers the width from the max feature index seen).
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..utils import log


def _is_sparse(x) -> bool:
    return hasattr(x, "tocsr") and hasattr(x, "shape")


class ArraySource:
    """Chunk an in-memory (or ``np.memmap``-backed) matrix.  The API
    entry for ``ingest.dataset_from_stream`` when the rows already live
    behind an array-like; with a memmap the raw values never fully
    materialize in RAM."""

    kind = "array"

    def __init__(self, data, label=None, weight=None, group=None,
                 chunk_rows: int = 65536, feature_names=None):
        self.data = data
        self.label = None if label is None else np.asarray(label).ravel()
        self.weight = (None if weight is None
                       else np.asarray(weight).ravel())
        # per-query sizes (LightGBM convention), whole-stream
        self.group_sizes = (None if group is None
                            else np.asarray(group).ravel())
        self.chunk_rows = max(int(chunk_rows), 1)
        self.n_features = int(data.shape[1])
        self.feature_names = feature_names

    def __iter__(self):
        n = int(self.data.shape[0])
        sparse = _is_sparse(self.data)
        mat = self.data.tocsr() if sparse else self.data
        for lo in range(0, n, self.chunk_rows):
            hi = min(lo + self.chunk_rows, n)
            X = mat[lo:hi]
            if not sparse:
                X = np.asarray(X, dtype=np.float64)
            side = {}
            if self.label is not None:
                side["label"] = self.label[lo:hi]
            if self.weight is not None:
                side["weight"] = self.weight[lo:hi]
            yield X, side


class SyntheticSource:
    """Deterministic generated stream — chunks are computed on the fly
    (each from its own child seed), so a >= 10^8-row leg never holds
    more than one chunk of raw values (``tools/ingest_bench.py``)."""

    kind = "synthetic"

    def __init__(self, n_rows: int, n_features: int = 16,
                 chunk_rows: int = 65536, seed: int = 0,
                 tail_shift: float = 0.0):
        self.n_rows = int(n_rows)
        self.n_features = int(n_features)
        self.chunk_rows = max(int(chunk_rows), 1)
        self.seed = int(seed)
        # distribution shift applied to the last 10% of the stream — the
        # sampling-bias regression fixture (a head-only sample cannot
        # place bin bounds over the shifted tail)
        self.tail_shift = float(tail_shift)
        self.feature_names = None
        self.group_sizes = None

    def __iter__(self):
        for ci, lo in enumerate(range(0, self.n_rows, self.chunk_rows)):
            m = min(self.chunk_rows, self.n_rows - lo)
            rng = np.random.default_rng((self.seed, ci))
            X = rng.normal(size=(m, self.n_features))
            if self.tail_shift:
                gi = lo + np.arange(m)
                X[gi >= int(0.9 * self.n_rows)] += self.tail_shift
            y = (X[:, 0] + 0.5 * X[:, 1 % self.n_features]
                 - 0.25 * X[:, 2 % self.n_features] > 0).astype(np.float64)
            yield X, {"label": y}


class NpzSource:
    """Chunk a ``.npy``/``.npz`` archive.  A ``.npy`` matrix is opened
    as a read-only memmap (true out-of-core: the OS pages rows in per
    chunk) with optional ``<base>.y.npy`` / ``<base>.weight.npy`` /
    ``<base>.query.npy`` sidecars; a ``.npz`` archive (keys ``X`` and
    optional ``y``/``weight``/``group``) decompresses its arrays once —
    a convenience format, not an out-of-core one (zip members cannot be
    memmapped)."""

    kind = "npz"

    def __init__(self, path: str, chunk_rows: int = 65536):
        self.path = path
        self.chunk_rows = max(int(chunk_rows), 1)
        self.feature_names = None
        self._X = None
        self._label = None
        self._weight = None
        self.group_sizes = None
        self._open()
        self.n_features = int(self._X.shape[1])

    def _open(self) -> None:
        if self.path.endswith(".npy"):
            self._X = np.lib.format.open_memmap(self.path, mode="r")
            base = self.path[:-len(".npy")]
            for attr, suffix in (("_label", ".y.npy"),
                                 ("_weight", ".weight.npy")):
                p = base + suffix
                if os.path.exists(p):
                    setattr(self, attr,
                            np.lib.format.open_memmap(p, mode="r"))
            q = base + ".query.npy"
            if os.path.exists(q):
                self.group_sizes = np.asarray(
                    np.lib.format.open_memmap(q, mode="r")).ravel()
        else:
            with np.load(self.path, allow_pickle=False) as z:
                if "X" not in z:
                    log.fatal(f"{self.path} has no 'X' array")
                self._X = z["X"]
                self._label = z["y"] if "y" in z else None
                self._weight = z["weight"] if "weight" in z else None
                self.group_sizes = (np.asarray(z["group"]).ravel()
                                    if "group" in z else None)
        if self._X.ndim != 2:
            log.fatal(f"{self.path}: 'X' must be 2-D, got shape "
                      f"{self._X.shape}")

    def __iter__(self):
        n = int(self._X.shape[0])
        for lo in range(0, n, self.chunk_rows):
            hi = min(lo + self.chunk_rows, n)
            side = {}
            if self._label is not None:
                side["label"] = np.asarray(self._label[lo:hi],
                                           np.float64).ravel()
            if self._weight is not None:
                side["weight"] = np.asarray(self._weight[lo:hi],
                                            np.float64).ravel()
            yield np.asarray(self._X[lo:hi], dtype=np.float64), side


class TextSource:
    """Chunk a dense CSV/TSV data file through the native mmap parser
    (``io/text_loader._iter_dense_chunks``), resolving the label/weight/
    group/ignore column layout the same way the in-RAM loader does.
    Raises ``io.text_loader._ParseError`` when the strict native parser
    is unavailable or rejects the file (callers degrade to the in-RAM
    path, exactly like ``load_text_two_round``)."""

    kind = "text"

    def __init__(self, path: str, config, chunk_bytes: Optional[int] = None):
        from ..io.text_loader import _CHUNK_BYTES, _sniff_delimiter
        self.path = path
        self.config = config
        self.chunk_bytes = int(chunk_bytes or _CHUNK_BYTES)
        with open(path) as fh:
            first = fh.readline()
        self.delim = _sniff_delimiter(first.rstrip("\n"))
        self.names: List[str] = []
        self.skip = 0
        if getattr(config, "header", False):
            self.names = [t.strip()
                          for t in first.rstrip("\n").split(self.delim)]
            self.skip = 1
        self._plan = None
        self.feature_names = None
        self.n_features = None
        self.group_sizes = None

    def _resolve_plan(self, ncol: int):
        from ..io.text_loader import _column_plan
        if self._plan is None:
            self._plan = _column_plan(list(self.names), ncol, self.config)
            names, _, _, _, keep = self._plan
            self.feature_names = [names[i] for i in keep]
            self.n_features = len(keep)
        return self._plan

    def __iter__(self):
        from ..io.text_loader import _iter_dense_chunks
        for arr in _iter_dense_chunks(self.path, self.delim, self.skip,
                                      self.chunk_bytes):
            _, label_col, weight_col, group_col, keep = \
                self._resolve_plan(arr.shape[1])
            side = {"label": arr[:, label_col]}
            if weight_col is not None:
                side["weight"] = arr[:, weight_col]
            if group_col is not None:
                side["qid"] = arr[:, group_col].astype(np.int64)
            yield np.ascontiguousarray(arr[:, keep]), side


class LibSVMSource:
    """Chunk a sparse ``label [qid:Q] idx:val`` file (the MSLR-WEB30K
    format) — native mmap-window parser with a pure-Python line-chunk
    fallback, both streaming.  Yields scipy CSR row blocks whose width
    is the max feature index seen SO FAR; ``n_features`` is final only
    after a full pass (the driver's pass 1), and the second pass re-pads
    every chunk to it.  This is what lets ``two_round=true`` stream
    LibSVM instead of warning-and-falling-back to the full in-RAM load
    (io/text_loader.py load_text_two_round)."""

    kind = "libsvm"

    def __init__(self, path: str, chunk_rows: int = 65536,
                 chunk_bytes: Optional[int] = None):
        from ..io.text_loader import _CHUNK_BYTES
        self.path = path
        self.chunk_rows = max(int(chunk_rows), 1)
        self.chunk_bytes = int(chunk_bytes or _CHUNK_BYTES)
        self.n_features: Optional[int] = None   # final after one pass
        self._max_idx = -1
        self.feature_names = None
        self.group_sizes = None

    def _emit(self, label, qid, indptr, indices, values):
        import scipy.sparse as sp
        self._max_idx = max(self._max_idx,
                            int(indices.max()) if len(indices) else -1)
        width = max(self._max_idx + 1, 1)
        X = sp.csr_matrix((values, indices, indptr),
                          shape=(len(label), width))
        return X, {"label": np.asarray(label, np.float64),
                   "qid": np.asarray(qid, np.int64)}

    def __iter__(self):
        from .. import native as _native
        from ..io.text_loader import _mmap_windows
        if _native.lib() is not None:
            for mm, lo, hi in _mmap_windows(self.path, 0,
                                            self.chunk_bytes):
                out = _native.libsvm_parse(mm, offset=lo, length=hi - lo)
                if out is None:
                    from .stream import IngestError
                    raise IngestError(
                        f"{self.path}: malformed LibSVM chunk at byte "
                        f"{lo} (strict parser rejected it)")
                lab, qid, indptr, idx, vals, _ = out
                yield self._emit(lab, qid, np.asarray(indptr, np.int64),
                                 np.asarray(idx, np.int32),
                                 np.asarray(vals, np.float64))
        else:
            yield from self._iter_python()
        self.n_features = max(self._max_idx + 1, 1)

    def _iter_python(self):
        """Lenient per-line fallback, chunked at ``chunk_rows``."""
        labels: List[float] = []
        qids: List[int] = []
        indptr = [0]
        idx: List[int] = []
        vals: List[float] = []

        def flush():
            return self._emit(
                labels, qids, np.asarray(indptr, np.int64),
                np.asarray(idx, np.int32), np.asarray(vals, np.float64))

        with open(self.path) as fh:
            for line in fh:
                toks = line.split()
                if not toks:
                    continue
                labels.append(float(toks[0]))
                q = -1
                for tok in toks[1:]:
                    i, _, v = tok.partition(":")
                    if i == "qid":
                        q = int(v)
                        continue
                    idx.append(int(i))
                    vals.append(float(v))
                qids.append(q)
                indptr.append(len(idx))
                if len(labels) >= self.chunk_rows:
                    yield flush()
                    labels, qids, indptr = [], [], [0]
                    idx, vals = [], []
        if labels:
            yield flush()


def open_source(path: str, config, chunk_rows: int = 65536):
    """Pick a chunked reader for a data file: ``.npy``/``.npz`` ->
    :class:`NpzSource`, headerless colon rows -> :class:`LibSVMSource`,
    else :class:`TextSource` (same sniff as ``io/text_loader.load_text``)."""
    if not os.path.exists(path):
        log.fatal(f"Data file {path} does not exist")
    if path.endswith((".npy", ".npz")):
        return NpzSource(path, chunk_rows=chunk_rows)
    with open(path) as fh:
        first = fh.readline()
    if ":" in first and not getattr(config, "header", False):
        return LibSVMSource(path, chunk_rows=chunk_rows)
    return TextSource(path, config)
