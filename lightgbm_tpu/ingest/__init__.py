"""Out-of-core streaming ingestion (``ingest/``).

Every other path into training materializes the raw [N, F] f64 matrix
in host RAM before binning; this subsystem never does.  Two passes over
a re-iterable chunk source: a seeded reservoir sample for bin finding
(uniform over the WHOLE stream, merged over the host collectives in
pre-sharded multi-host mode so every rank derives bit-identical
``BinMapper``s), then chunk-at-a-time binning into a preallocated —
optionally ``np.memmap``-backed — bin matrix.  Row-shard plans
(query-aligned for ranking) let each data-parallel worker bin only its
own rows.  Peak memory: O(chunk + sample + bin matrix).

API::

    from lightgbm_tpu import ingest
    src = ingest.ArraySource(big_memmap, label=y, chunk_rows=65536)
    ds = ingest.dataset_from_stream(src, params)      # a lightgbm_tpu.Dataset
    bst = lightgbm_tpu.train(params, ds, ...)

CLI: ``task=train tpu_ingest=true`` routes file loading through the
chunked readers (CSV/TSV, LibSVM, ``.npy``/``.npz``); ``two_round=true``
LibSVM input streams through here unconditionally.  See README
"Out-of-core ingestion".
"""
from .readers import (ArraySource, LibSVMSource, NpzSource,
                      SyntheticSource, TextSource, open_source)
from .sample import ReservoirSampler, merge_shard_samples, sample_seed
from .shard import (RowShardPlan, local_query_sizes, plan_row_shards,
                    resolve_shard)
from .stream import (IngestError, chunk_rows_from_config, dataset_digest,
                     dataset_from_stream, ingest_dataset, ingest_file,
                     memmap_from_config)

__all__ = [
    "ArraySource", "LibSVMSource", "NpzSource", "SyntheticSource",
    "TextSource", "open_source",
    "ReservoirSampler", "merge_shard_samples", "sample_seed",
    "RowShardPlan", "local_query_sizes", "plan_row_shards",
    "resolve_shard",
    "IngestError", "chunk_rows_from_config", "dataset_digest",
    "dataset_from_stream", "ingest_dataset", "ingest_file",
    "memmap_from_config",
]
