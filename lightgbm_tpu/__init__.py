"""lightgbm_tpu — a TPU-native gradient boosting framework.

A ground-up rebuild of LightGBM v2.3.2's capabilities with the compute plane
designed for TPU: an HBM-resident binned feature matrix, fixed-shape
leaf-wise tree growth under ``jit``, histogram construction as one-hot MXU
matmuls, and distributed modes expressed as ``jax.lax`` collectives over a
``jax.sharding.Mesh``.

The public API mirrors the reference Python package
(reference: python-package/lightgbm/__init__.py).
"""

import os as _os

# The container's site config pins jax's platform list to "axon,cpu" at
# interpreter start, silently overriding a JAX_PLATFORMS env var set by a
# parent process (e.g. the test suite spawning the CLI with
# JAX_PLATFORMS=cpu). Re-apply the env var ONLY while the config still
# leads with that pinned "axon" AND the env var asks for something else:
# a config that was changed programmatically (jax.config.update before
# importing this package) is a deliberate choice and must win over the
# ambient container env.
_env_plat = _os.environ.get("JAX_PLATFORMS")
if _env_plat:
    import jax as _jax

    _cur = _jax.config.jax_platforms or ""
    # Heuristic, not provenance (the site config lives outside this repo
    # so it cannot export a marker): an axon-led list is assumed to be
    # the container pin. The one false positive — a user programmatically
    # pinning the same axon-led list while the env var differs — resolves
    # in favor of the env var, which is the contract this block restores.
    if _cur.split(",")[0] == "axon" and _env_plat != _cur:
        _jax.config.update("jax_platforms", _env_plat)

from .basic import Booster, Dataset
from .config import Config
from .engine import cv, train
from . import ingest
from .utils.log import LightGBMError
from .callback import early_stopping, print_evaluation, record_evaluation, reset_parameter

try:  # sklearn wrappers are optional at import time
    from .sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor
    _SKLEARN = ["LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"]
except ImportError:  # pragma: no cover
    _SKLEARN = []

try:
    from .plotting import create_tree_digraph, plot_importance, plot_metric, plot_split_value_histogram, plot_tree
    _PLOT = ["plot_importance", "plot_metric", "plot_tree", "create_tree_digraph",
             "plot_split_value_histogram"]
except ImportError:  # pragma: no cover
    _PLOT = []

__version__ = "0.1.0"

__all__ = ["Dataset", "Booster", "Config", "train", "cv", "ingest",
           "LightGBMError",
           "early_stopping", "print_evaluation", "record_evaluation",
           "reset_parameter"] + _SKLEARN + _PLOT
