"""lightgbm_tpu — a TPU-native gradient boosting framework.

A ground-up rebuild of LightGBM v2.3.2's capabilities with the compute plane
designed for TPU: an HBM-resident binned feature matrix, fixed-shape
leaf-wise tree growth under ``jit``, histogram construction as one-hot MXU
matmuls, and distributed modes expressed as ``jax.lax`` collectives over a
``jax.sharding.Mesh``.

The public API mirrors the reference Python package
(reference: python-package/lightgbm/__init__.py).
"""

import os as _os

# The container's sitecustomize pins jax's platform list at import time,
# which silently overrides a JAX_PLATFORMS env var set by a parent process
# (e.g. the test suite spawning the CLI with JAX_PLATFORMS=cpu). Re-apply
# the env var so subprocess platform selection behaves as documented.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

from .basic import Booster, Dataset
from .config import Config
from .engine import cv, train
from .utils.log import LightGBMError
from .callback import early_stopping, print_evaluation, record_evaluation, reset_parameter

try:  # sklearn wrappers are optional at import time
    from .sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor
    _SKLEARN = ["LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"]
except ImportError:  # pragma: no cover
    _SKLEARN = []

try:
    from .plotting import create_tree_digraph, plot_importance, plot_metric, plot_split_value_histogram, plot_tree
    _PLOT = ["plot_importance", "plot_metric", "plot_tree", "create_tree_digraph",
             "plot_split_value_histogram"]
except ImportError:  # pragma: no cover
    _PLOT = []

__version__ = "0.1.0"

__all__ = ["Dataset", "Booster", "Config", "train", "cv", "LightGBMError",
           "early_stopping", "print_evaluation", "record_evaluation",
           "reset_parameter"] + _SKLEARN + _PLOT
