"""``task=online``: the closed loop from served traffic to fresh models.

The serving fleet (serve/) answers predictions; ground-truth labels for
those predictions arrive later as a stream.  This driver consumes that
prediction+label stream (JSONL lines ``{"x": [...], "y": <label>}``),
accumulates a bounded window of the freshest rows, and on a cadence —
every ``tpu_online_refit_every`` rows and/or
``tpu_online_refit_every_s`` seconds — produces a refreshed model:

- ``tpu_online_mode=refit``: leaf re-estimation over the frozen forest
  (the device refit kernel, online/refit.py), decay-mixed by
  ``tpu_online_decay``;
- ``tpu_online_mode=continue``: ``tpu_online_trees`` NEW trees boosted
  in the model's own bin space (online/binspace.py).

Both run from the current model FILE alone — no training data is kept.
Each refreshed version is then pushed through the registry's
``POST /models/{name}/swap``, so the canary gate (parity/finite/latency
checks), the post-swap rollback watch, and the chaos matrix stand
between a bad refit and traffic: a poisoned refresh is a rejected swap,
not an incident.  A rejected push leaves the previous model as the
refresh base, so one bad window cannot poison every later refresh.

Fault injection points (robust/faults.py): ``online_ingest`` on every
ingest batch, ``online_refit`` at the top of a refresh,
``online_swap`` before the push.  Telemetry: one ``online_refresh``
event per cadence firing (including skipped ones — an ingest stall is
an event, not silence); ``obs/report.py`` folds them into the digest.
"""
from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Callable, Iterator, Optional, Tuple

import numpy as np

from .. import obs
from ..robust import faults
from ..utils import log


def _knob(config, name, cast, default, env=None):
    """Config attr with an optional env-var override (env wins, like the
    serving knobs in serve/session.py)."""
    v = getattr(config, name, default) if config is not None else default
    if isinstance(config, dict):
        v = config.get(name, default)
    if env:
        raw = os.environ.get(env, "")
        if raw:
            try:
                return cast(raw)
            except ValueError:
                log.warning("ignoring non-numeric %s=%r", env, raw)
    try:
        return cast(v)
    except (TypeError, ValueError):
        return default


def read_label_stream(path: str, follow: bool = False,
                      poll_s: float = 0.2, batch_rows: int = 256,
                      stop: Optional[Callable[[], bool]] = None
                      ) -> Iterator[Optional[Tuple[np.ndarray, np.ndarray]]]:
    """Yield ``(X, y)`` batches from a JSONL prediction+label stream.

    Each line is ``{"x": [floats], "y": label}`` (``"features"`` /
    ``"label"`` accepted as synonyms); malformed lines — bad JSON,
    non-numeric fields, or a row whose width disagrees with the
    stream's first row — are counted and skipped, like obs/report.py's
    loader.  ``follow=True`` tails the file for appended lines (the
    socket-less streaming mode — a feeder process appends, this
    generator never ends until ``stop()``); while idle it yields
    ``None`` heartbeats each poll so the consumer's TIME cadence (and
    ingest-stall detection) keeps firing with no data flowing.  A
    partially-written trailing line (no newline yet) is buffered and
    re-joined with the next read, never parsed as two fragments."""
    rows, labels, bad = [], [], 0
    width = None
    pending = ""

    def flush():
        nonlocal rows, labels
        if not rows:
            return None
        out = (np.asarray(rows, np.float64), np.asarray(labels, np.float64))
        rows, labels = [], []
        return out

    def parse(line):
        nonlocal bad, width
        line = line.strip()
        if not line:
            return
        try:
            rec = json.loads(line)
            x = [float(v) for v in rec.get("x", rec.get("features"))]
            y = float(rec.get("y", rec.get("label")))
        except (ValueError, TypeError, AttributeError):
            bad += 1
            return
        if width is None:
            width = len(x)
        elif len(x) != width:
            bad += 1
            return
        rows.append(x)
        labels.append(y)

    with open(path) as fh:
        while True:
            chunk = fh.readline()
            if not chunk:
                batch = flush()
                if batch is not None:
                    yield batch
                if not follow or (stop is not None and stop()):
                    break
                time.sleep(poll_s)
                yield None   # heartbeat: let the consumer's cadence tick
                continue
            if follow and not chunk.endswith("\n"):
                # a feeder's write landed mid-line: hold the fragment
                pending += chunk
                continue
            parse(pending + chunk)
            pending = ""
            if len(rows) >= batch_rows:
                yield flush()
    if pending:
        parse(pending)
        batch = flush()
        if batch is not None:
            yield batch
    if bad:
        log.warning("label stream %s: skipped %d malformed line(s)",
                    path, bad)


class OnlineLoop:
    """Bounded-window ingest + cadence-driven refresh + registry push.

    ``push`` is a callable ``(model_path) -> report dict`` (HTTP POST to
    ``/models/{name}/swap`` in the CLI driver, ``registry.swap`` in
    in-process tests); it must raise on a rejected swap.  The loop never
    dies for a failed refresh — the old model keeps serving AND stays
    the base for the next refresh."""

    def __init__(self, model_file: str, config=None,
                 push: Optional[Callable[[str], dict]] = None,
                 workdir: Optional[str] = None,
                 params: Optional[dict] = None):
        self.base = str(model_file)
        self.config = config
        self.push = push
        self.params = dict(params or {})
        self.mode = str(_knob(config, "tpu_online_mode", str, "refit"))
        self.window_cap = max(int(_knob(config, "tpu_online_window", int,
                                        50000, "LGBM_TPU_ONLINE_WINDOW")), 1)
        self.refresh_rows = int(_knob(config, "tpu_online_refit_every", int,
                                      5000, "LGBM_TPU_ONLINE_REFIT_EVERY"))
        self.refresh_s = float(_knob(config, "tpu_online_refit_every_s",
                                     float, 0.0))
        self.trees = max(int(_knob(config, "tpu_online_trees", int, 10)), 1)
        decay = float(_knob(config, "tpu_online_decay", float, -1.0))
        self.decay = (decay if decay >= 0.0 else
                      float(_knob(config, "refit_decay_rate", float, 0.9)))
        self.workdir = workdir or tempfile.mkdtemp(prefix="lgbm_online_")
        os.makedirs(self.workdir, exist_ok=True)
        self._X: list = []          # window rows (list of [F] arrays)
        self._y: list = []
        self._rows_since = 0        # rows ingested since the last refresh
        self._last_refresh_t = time.monotonic()
        self.versions = 0           # successful pushes
        self.rejected = 0           # pushes the canary gate bounced
        self.failed = 0             # refreshes that died before the push
        self.skipped = 0            # cadence firings with no fresh rows
        self.rows_ingested = 0
        # serve/quality.py tracker, attached by the driver when the
        # served model carries a quality-profile sidecar: every labeled
        # batch this loop sees doubles as ground truth for the rolling
        # per-version quality windows
        self.quality = None

    # ------------------------------------------------------------------
    def ingest(self, X, y) -> int:
        """Append labeled rows to the bounded window (oldest rows fall
        out past ``tpu_online_window``).  Returns rows accepted."""
        faults.check("online_ingest")
        X = np.atleast_2d(np.asarray(X, np.float64))
        y = np.atleast_1d(np.asarray(y, np.float64))
        if X.shape[0] != y.shape[0]:
            raise ValueError("ingest rows/labels length mismatch")
        self._X.extend(X)
        self._y.extend(y)
        if len(self._X) > self.window_cap:
            drop = len(self._X) - self.window_cap
            del self._X[:drop]
            del self._y[:drop]
        self._rows_since += X.shape[0]
        self.rows_ingested += X.shape[0]
        if self.quality is not None:
            try:
                self.quality.add(X, y)
            except Exception as exc:  # noqa: BLE001 — quality eval must
                # never take the ingest path down with it
                log.warning("online: quality window update failed: %s",
                            exc)
        return X.shape[0]

    def due(self, now: Optional[float] = None) -> bool:
        """Has the refresh cadence fired?  Row cadence and time cadence
        compose as OR; both disabled means never."""
        now = time.monotonic() if now is None else now
        if self.refresh_rows > 0 and self._rows_since >= self.refresh_rows:
            return True
        return (self.refresh_s > 0
                and now - self._last_refresh_t >= self.refresh_s)

    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        """Refresh when due; None when the cadence hasn't fired.  A due
        tick with NO fresh rows is an ingest stall: the refresh is
        SKIPPED with a logged + telemetry-stamped event (refitting to a
        stale window would only launder old data as fresh)."""
        if not self.due(now):
            return None
        if self._rows_since == 0 or not self._X:
            self.skipped += 1
            self._last_refresh_t = time.monotonic()
            log.warning("online: refresh cadence fired with no fresh "
                        "rows (ingest stall) — skipping this cycle "
                        "(window holds %d stale row(s))", len(self._X))
            obs.event("online_refresh", mode=self.mode, ok=False,
                      skipped="ingest_stall", rows=0)
            return {"ok": False, "skipped": "ingest_stall"}
        return self.refresh()

    # ------------------------------------------------------------------
    def refresh(self) -> dict:
        """One refresh: refit/continue from the current model FILE over
        the window, save the candidate, push it through the registry.
        Never raises — the report (and the ``online_refresh`` event)
        carries the outcome."""
        t0 = time.perf_counter()
        rows = len(self._X)
        report = {"ok": False, "mode": self.mode, "rows": rows}
        attempt = self.versions + self.rejected + self.failed + 2
        out_path = os.path.join(self.workdir, f"model_v{attempt}.txt")
        try:
            faults.check("online_refit")
            Xw = np.asarray(self._X, np.float64)
            yw = np.asarray(self._y, np.float64)
            if self.mode == "continue":
                from .binspace import train_continue
                bst = train_continue(self.base, Xw, yw, params=self.params,
                                     num_boost_round=self.trees)
            else:
                from .binspace import refit_from_model
                bst = refit_from_model(self.base, Xw, yw,
                                       params=self.params,
                                       decay_rate=self.decay)
            bst.save_model(out_path)
            faults.check("online_swap")
            if self.push is not None:
                report["push"] = self.push(out_path)
            self.base = out_path        # adopted: next refresh's base
            self.versions += 1
            report.update(ok=True, version=self.versions, path=out_path)
        except Exception as exc:  # noqa: BLE001 — a bad refresh is a
            # non-event by design: the canary/rollback plane already
            # decided traffic never sees it, so the loop records and
            # moves on with the OLD base
            import urllib.error

            from ..serve.registry import SwapRejected
            rejected = isinstance(exc, SwapRejected) or (
                isinstance(exc, urllib.error.HTTPError)
                and exc.code == 409)
            if rejected:
                self.rejected += 1
            else:
                self.failed += 1
            report["error"] = f"{type(exc).__name__}: {exc}"
            log.warning("online: refresh %s — previous model keeps "
                        "serving and stays the refresh base (%s)",
                        "rejected by the canary gate" if rejected
                        else "FAILED", report["error"])
        ms = round((time.perf_counter() - t0) * 1e3, 1)
        report["ms"] = ms
        self._rows_since = 0
        self._last_refresh_t = time.monotonic()
        obs.event("online_refresh", mode=self.mode, ok=bool(report["ok"]),
                  rows=rows, ms=ms,
                  **({"version": self.versions} if report["ok"] else {}),
                  **({"error": report["error"][:200]}
                     if report.get("error") else {}))
        return report

    def stats(self) -> dict:
        out = {"mode": self.mode, "versions": self.versions,
               "rejected": self.rejected, "failed": self.failed,
               "skipped": self.skipped,
               "rows_ingested": self.rows_ingested,
               "window_rows": len(self._X), "base": self.base,
               "last_refresh_age_s": round(
                   time.monotonic() - self._last_refresh_t, 3)}
        if self.quality is not None:
            out["quality"] = self.quality.stats()
        return out


def run_online(cfg, params: dict) -> None:
    """CLI driver: serve ``input_model`` behind the registry-managed
    fleet AND feed the label stream back into it — daily-fresh models
    with zero downtime, one process.  The push goes through the HTTP
    ``POST /models/{name}/swap`` endpoint of this process's own server,
    so every refresh rides the exact path an external pusher would."""
    import urllib.request

    from ..serve import ModelRegistry, PredictServer

    if not cfg.input_model:
        log.fatal("task=online needs input_model (alias: model_file)")
    source = getattr(cfg, "tpu_online_source", "") or cfg.data
    if not source:
        log.fatal("task=online needs a label stream: tpu_online_source "
                  "(or data) pointing at a JSONL file of "
                  '{"x": [...], "y": <label>} lines')
    name = getattr(cfg, "tpu_online_model", "default") or "default"
    reg = ModelRegistry(config=cfg)
    reg.add_model(name, cfg.input_model)
    server = PredictServer(reg, host=cfg.tpu_serve_host,
                           port=cfg.tpu_serve_port).start()

    def push(model_path: str) -> dict:
        req = urllib.request.Request(
            f"{server.url}/models/{name}/swap",
            data=json.dumps({"model_file": model_path}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as resp:
            return json.loads(resp.read())

    loop = OnlineLoop(cfg.input_model, config=cfg, push=push,
                      workdir=getattr(cfg, "tpu_online_dir", "") or None,
                      params=dict(params))
    # the fleet /metrics endpoint renders this loop's counters as the
    # tpu_online_* series — the registry just holds the provider hook
    reg.online_provider = loop.stats
    from ..obs.drift import QualityProfile, profile_path
    prof_file = profile_path(cfg.input_model)
    if os.path.isfile(prof_file):
        try:
            from ..serve.quality import QualityTracker
            prof = QualityProfile.load(prof_file)
            loop.quality = QualityTracker(
                lambda X: reg.resolve(name).router.predict(
                    X, raw_score=True),
                prof, config=cfg, registry=reg, model_name=name)
            log.info("online: quality windows armed from %s "
                     "(train_auc=%s)", prof_file,
                     prof.meta.get("train_auc"))
        except (ValueError, OSError) as exc:
            log.warning("online: quality profile unusable, windows "
                        "disarmed: %s", exc)
    follow = bool(getattr(cfg, "tpu_online_follow", False))
    log.info("online: serving %r on %s, ingesting %s (mode=%s, cadence "
             "%d rows / %gs, window %d)", name, server.url, source,
             loop.mode, loop.refresh_rows, loop.refresh_s,
             loop.window_cap)
    try:
        for batch in read_label_stream(source, follow=follow):
            if batch is not None:
                loop.ingest(*batch)
            # a None heartbeat still ticks: the time cadence and the
            # ingest-stall skip must fire while the stream is quiet
            loop.tick()
        if loop._rows_since > 0:
            loop.refresh()   # drain: the tail of a finite stream counts
    except KeyboardInterrupt:
        log.warning("online: interrupted — shutting down")
    finally:
        st = loop.stats()
        log.info("online: %d refreshed version(s) pushed, %d rejected, "
                 "%d failed, %d skipped, %d row(s) ingested",
                 st["versions"], st["rejected"], st["failed"],
                 st["skipped"], st["rows_ingested"])
        server.stop(close_session=True)
