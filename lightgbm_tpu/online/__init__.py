"""online/ — the closed-loop learning service (ROADMAP direction 4).

Four layers turn the resilient serving fleet into a daily-fresh-model
system: the device refit kernel (refit.py — jitted leaf re-estimation
over the frozen forest), the model-own bin space (binspace.py —
``train_continue``/``refit_from_model`` work from a model file alone,
binning new rows through ``BinMapper.from_thresholds``), the streaming
driver (loop.py — ``task=online``: ingest window, refresh cadence,
registry push), and the faults/obs wiring that makes a bad refresh a
rejected swap instead of an incident.
"""
from .binspace import (continue_dataset, model_bin_mappers,
                       refit_from_model, train_continue)
from .loop import OnlineLoop, read_label_stream, run_online
from .refit import device_refit_models

__all__ = [
    "OnlineLoop",
    "continue_dataset",
    "device_refit_models",
    "model_bin_mappers",
    "read_label_stream",
    "refit_from_model",
    "run_online",
    "train_continue",
]
