"""Model-own training bin space: incremental training without the
original training data.

Continued training normally rebins the NEW data from scratch, which
(a) needs a big enough sample to find good quantiles and (b) produces a
bin space unrelated to the one the serving fleet rebuilt from the model
(serve/packing.py).  The online loop instead bins new rows through the
MODEL'S OWN bin space — ``BinMapper.from_thresholds`` for numerical
features (the sorted distinct split thresholds become the bin bounds,
so every node decision is reproduced exactly) and
``BinMapper.categorical_from_categories`` for categorical ones (the
bitset categories become the bins, plus a NaN/unseen catch-all) — so
``train_continue`` works from a ``model_file`` alone, exactly like
``serve/`` does, and the replayed forest routes every row identically
to the host's value-space traversal (tests/test_online.py pins the
round trip on categorical-bitset and NaN-default-left features).

New trees then grow IN that space: their split thresholds are existing
model thresholds (numerical splits pick a bin upper bound), which keeps
every downstream serving bin space stable across refreshes.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..io.binning import BinMapper
from ..serve.packing import collect_split_state
from ..utils import log


def model_bin_mappers(models, num_features: int) -> List[BinMapper]:
    """One training ``BinMapper`` per original feature, derived from
    the forest's own split state.  Features the model never splits on
    get a trivial mapper (excluded from the constructed dataset — the
    replayed trees never read them, and new trees cannot split on what
    the bin space cannot distinguish)."""
    thr_vals, miss, is_cat, cats, _ = collect_split_state(
        models, num_features, want_cats=True)
    mappers: List[BinMapper] = []
    for f in range(num_features):
        if is_cat[f]:
            mappers.append(BinMapper.categorical_from_categories(cats[f]))
        elif thr_vals[f]:
            mappers.append(BinMapper.from_thresholds(thr_vals[f],
                                                     int(miss[f])))
        else:
            mappers.append(BinMapper())  # trivial
    return mappers


def continue_dataset(models, X, label=None, weight=None,
                     params: Optional[dict] = None,
                     num_features: Optional[int] = None,
                     feature_names: Optional[List[str]] = None):
    """A constructed :class:`~lightgbm_tpu.basic.Dataset` whose bin
    space is the MODEL'S, not the data's — the train-continue analog of
    ``serve.packing.ServeBinSpace.bin_matrix``.  ``models`` is the
    loaded forest (list of host ``Tree``); ``X`` raw float rows."""
    from ..basic import Dataset
    from ..io.dataset import BinnedDataset, Metadata

    X = np.ascontiguousarray(np.asarray(X, dtype=np.float64))
    if X.ndim != 2:
        raise ValueError("continue_dataset needs a 2-D feature matrix")
    F = X.shape[1]
    need = max((int(t.split_feature[i]) + 1 for t in models
                for i in range(max(t.num_leaves - 1, 0))), default=0)
    if F < need:
        raise ValueError(f"continue data has {F} features, the model "
                         f"splits on feature {need - 1}")
    binned = BinnedDataset()
    binned.num_data = int(X.shape[0])
    binned.num_total_features = F
    binned.metadata = Metadata(binned.num_data)
    binned.bin_mappers = model_bin_mappers(models, F)
    binned.max_bin = int(max((m.num_bin for m in binned.bin_mappers),
                             default=1))
    binned.feature_names = (list(feature_names)
                            if feature_names and len(feature_names) == F
                            else [f"Column_{i}" for i in range(F)])
    binned._finalize_features()
    binned._binarize(X)
    ds = Dataset(None, params=dict(params or {}))
    ds._handle = binned
    if label is not None:
        ds.set_label(np.asarray(label, dtype=np.float64).ravel())
    if weight is not None:
        ds.set_weight(np.asarray(weight, dtype=np.float64).ravel())
    return ds


def train_continue(model, X, label, params: Optional[dict] = None,
                   num_boost_round: int = 10, weight=None, **train_kw):
    """Boost ``num_boost_round`` additional trees onto ``model`` using
    ONLY the model file and the new rows: the new data is binned in the
    model's own bin space (no training-data rebinning) and the existing
    ``init_model`` warm-start path replays the forest before the first
    new iteration.  ``model`` is a model-file path or a ``Booster``;
    the model's objective/num_class seed the params (explicit ``params``
    entries win).  Returns the continued :class:`Booster`."""
    from ..basic import Booster
    from ..engine import train as train_api

    if not (isinstance(model, (Booster, str, bytes))
            or hasattr(model, "__fspath__")):
        raise TypeError("train_continue needs a Booster or a model file "
                        f"path, met {type(model).__name__}")
    models, base_params, feature_names = _load_models_and_params(model)
    merged = dict(base_params)
    merged.update(params or {})
    if not models:
        raise ValueError("cannot continue an empty model")
    log.info("train_continue: %d new rows binned in the model's own bin "
             "space, boosting %d more round(s) onto %d tree(s)",
             int(np.asarray(X).shape[0]), num_boost_round, len(models))
    ds = continue_dataset(models, X, label=label, weight=weight,
                          params=merged, feature_names=feature_names)
    return train_api(merged, ds, num_boost_round=num_boost_round,
                     init_model=model, verbose_eval=False, **train_kw)


def _load_models_and_params(model):
    """(models, base_params, feature_names) from a Booster or file."""
    import os as _os

    from ..basic import Booster

    if isinstance(model, Booster):
        return (list(model._gbdt.models), dict(model.params or {}), None)
    from ..io.model_io import load_model_file
    loaded, model_cfg = load_model_file(_os.fsdecode(model))
    base = {"objective": model_cfg.objective}
    if model_cfg.num_class > 1:
        base["num_class"] = model_cfg.num_class
    return (list(loaded.models), base,
            loaded.feature_names if loaded.feature_names else None)


def refit_from_model(model, X, label, params: Optional[dict] = None,
                     decay_rate: Optional[float] = None, weight=None):
    """Leaf re-estimation from a model FILE over new rows, binned in the
    model's own bin space — the online loop's refit leg.

    ``Booster.refit`` rebins the new data from scratch, which quantizes
    the frozen split thresholds to the NEW data's bins and can misroute
    rows that fall inside the same new bin as a threshold.  Binning in
    the model's threshold space instead reproduces every node decision
    exactly (the ``from_thresholds`` contract), so the refit re-estimates
    leaves over precisely the rows the serving forest would route there."""
    import copy

    from ..basic import Booster

    models, base_params, feature_names = _load_models_and_params(model)
    if not models:
        raise ValueError("cannot refit an empty model")
    merged = dict(base_params)
    merged.update(params or {})
    if decay_rate is not None:
        merged["refit_decay_rate"] = float(decay_rate)
    ds = continue_dataset(models, X, label=label, weight=weight,
                          params=merged, feature_names=feature_names)
    bst = Booster(params=merged, train_set=ds)
    bst._gbdt.load_initial_models([copy.deepcopy(t) for t in models],
                                  replay_scores=False)
    bst._gbdt.refit_models(decay_rate)
    return bst
