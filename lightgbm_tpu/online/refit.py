"""Device leaf refit: the jitted replacement for the host per-tree
``bincount`` loop in ``GBDT.refit_models`` (reference: GBDT::RefitTree
gbdt.cpp:298-321 + SerialTreeLearner::FitByExistingTree
serial_tree_learner.cpp:239-264).

Tree STRUCTURE is frozen during a refit, so every tree's leaf index per
row is a pure function of the (fixed) binned matrix — one stacked
``forest_leaf_fn`` scan computes the whole [T, N] leaf-id table up
front, instead of T separate traversal dispatches.  The part that stays
sequential is the reference's gradient recurrence: gradients are
recomputed once per boosting ITERATION from the scores of every
previously-refit tree (the reference calls Boosting() once per iter,
gbdt.cpp:303), so the kernel walks iterations with ONE compiled step —
per-leaf ``segment_sum`` of fresh grad/hess, the L1/L2/max_delta_step
closed form, the decay mix, and the score update all fused in a single
jit — where the host oracle runs K ``np.bincount`` calls plus K device
dispatches per iteration.

The host loop is retained as the differential oracle
(``tpu_refit_device=false``); tests/test_online.py pins per-leaf parity
at 1e-6 across plain/multiclass/categorical/NaN fixtures and a
2-device mesh leg.
"""
from __future__ import annotations

import numpy as np


def device_refit_models(gbdt, decay: float) -> dict:
    """Refit ``gbdt``'s loaded forest to its (new) training data on
    device, mixing old and new leaf outputs by ``decay``.  Mutates the
    host trees' ``leaf_value`` and rebuilds ``_train_score`` exactly
    like the host loop in ``GBDT.refit_models``; returns a small report
    dict for the ``refit`` telemetry event."""
    import jax
    import jax.numpy as jnp

    from ..core.forest import forest_leaf_fn, stack_forest

    trees = list(gbdt.models)
    K = gbdt.num_tpi
    T = len(trees)
    if T == 0:
        return {"trees": 0, "rows": 0, "iterations": 0}
    iters = T // K
    cfg = gbdt.split_cfg
    from ..boosting.gbdt import K_EPSILON

    forest = stack_forest(
        [gbdt._tree_arrays_np(t) for t in trees],
        np.asarray([i % K for i in range(T)], np.int32))
    # [T, N] leaf ids in one scan — the training bin matrix keeps its
    # (possibly EFB-bundled) physical layout, so the scan decodes
    # feature columns exactly like training's score replay does.
    # Both jits ride the process-wide cache: DeviceMeta is content-
    # cached (build_device_meta), so a steady-state online loop's
    # refreshes — same model bin space every cycle — reuse the
    # compiled kernels instead of re-tracing per refresh
    from ..boosting.gbdt import _cached_jit
    leaf_fn = _cached_jit(("online_leaf", id(gbdt.meta), gbdt._bundled),
                          lambda: forest_leaf_fn(gbdt.meta,
                                                 phys=gbdt._bundled))
    leaf_ids = leaf_fn(forest, gbdt._bins)
    L = int(forest.leaf_value.shape[1])
    N = int(leaf_ids.shape[1])
    lids = leaf_ids.reshape(iters, K, N)
    old_lv = forest.leaf_value.reshape(iters, K, L)
    shrink = jnp.asarray([t.shrinkage for t in trees],
                         jnp.float32).reshape(iters, K)
    l1 = float(cfg.lambda_l1)
    l2 = float(cfg.lambda_l2)
    mds = float(cfg.max_delta_step)

    def build_step():
        @jax.jit
        def step(score, g, h, lid_k, old_k, shr_k, dec):
            """One boosting iteration's K trees: segment-sum the fresh
            grad/hess per leaf, CalculateSplittedLeafOutput with
            L1/L2/max_delta_step, decay-mix, and apply to the score."""
            new_ks = []
            for k in range(K):
                lid = lid_k[k]
                sum_g = jax.ops.segment_sum(g[:, k], lid, num_segments=L)
                sum_h = jax.ops.segment_sum(h[:, k], lid,
                                            num_segments=L) + K_EPSILON
                sg = jnp.sign(sum_g) * jnp.maximum(jnp.abs(sum_g) - l1,
                                                   0.0)
                out = -sg / (sum_h + l2)
                if mds > 0:
                    out = jnp.clip(out, -mds, mds)
                new_lv = dec * old_k[k] + (1.0 - dec) * out * shr_k[k]
                score = score.at[:, k].add(new_lv[lid])
                new_ks.append(new_lv)
            return score, jnp.stack(new_ks)
        return step

    step = _cached_jit(("online_refit_step", K, L, l1, l2, mds),
                       build_step)
    dec = jnp.float32(decay)
    score = jnp.zeros_like(gbdt._train_score)
    new_all = []
    for it in range(iters):
        # gradients once per iteration, BEFORE any of its K class trees
        g, h = gbdt._grad_fn(score)
        score, new_k = step(score, g, h, lids[it], old_lv[it],
                            shrink[it], dec)
        new_all.append(new_k)
    gbdt._train_score = score
    new_np = np.asarray(jnp.concatenate(new_all, axis=0), np.float64)
    for t, tree in enumerate(trees):
        nl = tree.num_leaves
        tree.leaf_value = new_np[t, :nl].copy()
    return {"trees": T, "rows": N, "iterations": iters}
