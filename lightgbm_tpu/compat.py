"""Optional-dependency detection flags (reference:
python-package/lightgbm/compat.py) — the guide scripts branch on these
(``lgb.compat.MATPLOTLIB_INSTALLED`` etc.)."""
from __future__ import annotations


def json_default_with_numpy(obj):
    """JSON serializer fallback for numpy scalars/arrays
    (reference: compat.py:51-60)."""
    if hasattr(obj, "item") and not hasattr(obj, "__len__"):
        return obj.item()
    if hasattr(obj, "tolist"):
        return obj.tolist()
    raise TypeError(f"{type(obj).__name__} is not JSON serializable")


try:
    from pandas import DataFrame, Series  # noqa: F401

    PANDAS_INSTALLED = True
except ImportError:  # pragma: no cover
    PANDAS_INSTALLED = False

    class DataFrame:  # type: ignore[no-redef]
        pass

    class Series:  # type: ignore[no-redef]
        pass

try:
    import matplotlib  # noqa: F401

    MATPLOTLIB_INSTALLED = True
except ImportError:  # pragma: no cover
    MATPLOTLIB_INSTALLED = False

try:
    import graphviz  # noqa: F401

    GRAPHVIZ_INSTALLED = True
except ImportError:  # pragma: no cover
    GRAPHVIZ_INSTALLED = False

DATATABLE_INSTALLED = False  # datatable is not shipped in this image

try:
    import sklearn  # noqa: F401

    SKLEARN_INSTALLED = True
except ImportError:  # pragma: no cover
    SKLEARN_INSTALLED = False


class LGBMDeprecationWarning(UserWarning):
    """(reference: compat.py:161)."""
