"""Configuration system.

TPU-native rebuild of the reference's single-source-of-truth parameter struct
(reference: include/LightGBM/config.h:31-872 and the generated alias table in
src/io/config_auto.cpp:10). Every public LightGBM v2.3.2 parameter name and
alias is accepted, so configs and ``train.conf`` files written for the
reference work unchanged. New here: ``device_type`` gains ``"tpu"`` (the
default), and TPU-specific knobs live in the ``tpu_*`` namespace.

Parsing follows the reference's pipeline: raw strings → alias resolution →
typed ``Config`` fields → inter-parameter consistency checks
(reference: src/io/config.cpp Config::Set).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .utils import log

# ---------------------------------------------------------------------------
# Alias table (reference: src/io/config_auto.cpp:10-200). Maps alias → canonical.
# ---------------------------------------------------------------------------
_ALIASES: Dict[str, str] = {
    "config_file": "config",
    "task_type": "task",
    "objective_type": "objective", "app": "objective", "application": "objective",
    "boosting_type": "boosting", "boost": "boosting",
    "train": "data", "train_data": "data", "train_data_file": "data", "data_filename": "data",
    "test": "valid", "valid_data": "valid", "valid_data_file": "valid",
    "test_data": "valid", "test_data_file": "valid", "valid_filenames": "valid",
    "num_iteration": "num_iterations", "n_iter": "num_iterations",
    "num_tree": "num_iterations", "num_trees": "num_iterations",
    "num_round": "num_iterations", "num_rounds": "num_iterations",
    "num_boost_round": "num_iterations", "n_estimators": "num_iterations",
    "shrinkage_rate": "learning_rate", "eta": "learning_rate",
    "num_leaf": "num_leaves", "max_leaves": "num_leaves", "max_leaf": "num_leaves",
    "tree": "tree_learner", "tree_type": "tree_learner", "tree_learner_type": "tree_learner",
    "num_thread": "num_threads", "nthread": "num_threads", "nthreads": "num_threads",
    "n_jobs": "num_threads",
    "device": "device_type",
    "random_seed": "seed", "random_state": "seed",
    "min_data_per_leaf": "min_data_in_leaf", "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf", "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "sub_row": "bagging_fraction", "subsample": "bagging_fraction", "bagging": "bagging_fraction",
    "pos_sub_row": "pos_bagging_fraction", "pos_subsample": "pos_bagging_fraction",
    "pos_bagging": "pos_bagging_fraction",
    "neg_sub_row": "neg_bagging_fraction", "neg_subsample": "neg_bagging_fraction",
    "neg_bagging": "neg_bagging_fraction",
    "subsample_freq": "bagging_freq",
    "bagging_fraction_seed": "bagging_seed",
    "sub_feature": "feature_fraction", "colsample_bytree": "feature_fraction",
    "sub_feature_bynode": "feature_fraction_bynode", "colsample_bynode": "feature_fraction_bynode",
    "early_stopping_rounds": "early_stopping_round", "early_stopping": "early_stopping_round",
    "n_iter_no_change": "early_stopping_round",
    "max_tree_output": "max_delta_step", "max_leaf_output": "max_delta_step",
    "reg_alpha": "lambda_l1",
    "reg_lambda": "lambda_l2", "lambda": "lambda_l2",
    "min_split_gain": "min_gain_to_split",
    "rate_drop": "drop_rate",
    "topk": "top_k",
    "mc": "monotone_constraints", "monotone_constraint": "monotone_constraints",
    "feature_contrib": "feature_contri", "fc": "feature_contri", "fp": "feature_contri",
    "feature_penalty": "feature_contri",
    "fs": "forcedsplits_filename", "forced_splits_filename": "forcedsplits_filename",
    "forced_splits_file": "forcedsplits_filename", "forced_splits": "forcedsplits_filename",
    "verbose": "verbosity",
    "max_bins": "max_bin",
    "subsample_for_bin": "bin_construct_sample_cnt",
    "hist_pool_size": "histogram_pool_size",
    "data_seed": "data_random_seed",
    "model_output": "output_model", "model_out": "output_model",
    "save_period": "snapshot_freq",
    "model_input": "input_model", "model_in": "input_model",
    "model_file": "input_model",
    "predict_result": "output_result", "prediction_result": "output_result",
    "predict_name": "output_result", "prediction_name": "output_result",
    "pred_name": "output_result", "name_pred": "output_result",
    "init_score_filename": "initscore_filename", "init_score_file": "initscore_filename",
    "init_score": "initscore_filename", "input_init_score": "initscore_filename",
    "is_pre_partition": "pre_partition",
    "is_enable_bundle": "enable_bundle", "bundle": "enable_bundle",
    "is_sparse": "is_enable_sparse", "enable_sparse": "is_enable_sparse", "sparse": "is_enable_sparse",
    "two_round_loading": "two_round", "use_two_round_loading": "two_round",
    "is_save_binary": "save_binary", "is_save_binary_file": "save_binary",
    "has_header": "header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column", "group_id": "group_column",
    "query_column": "group_column", "query": "group_column", "query_id": "group_column",
    "ignore_feature": "ignore_column", "blacklist": "ignore_column",
    "cat_feature": "categorical_feature", "categorical_column": "categorical_feature",
    "cat_column": "categorical_feature",
    "is_predict_raw_score": "predict_raw_score", "predict_rawscore": "predict_raw_score",
    "raw_score": "predict_raw_score",
    "is_predict_leaf_index": "predict_leaf_index", "leaf_index": "predict_leaf_index",
    "is_predict_contrib": "predict_contrib", "contrib": "predict_contrib",
    "convert_model_file": "convert_model",
    "num_classes": "num_class",
    "unbalance": "is_unbalance", "unbalanced_sets": "is_unbalance",
    "metrics": "metric", "metric_types": "metric",
    "output_freq": "metric_freq",
    "training_metric": "is_provide_training_metric", "is_training_metric": "is_provide_training_metric",
    "train_metric": "is_provide_training_metric",
    "ndcg_eval_at": "eval_at", "ndcg_at": "eval_at", "map_eval_at": "eval_at", "map_at": "eval_at",
    "num_machine": "num_machines",
    "local_port": "local_listen_port", "port": "local_listen_port",
    "machine_list_file": "machine_list_filename", "machine_list": "machine_list_filename",
    "mlist": "machine_list_filename",
    "workers": "machines", "nodes": "machines",
}

# Parameters whose value is a comma-separated list.
_MULTI_VALUE = {
    "valid", "metric", "monotone_constraints", "feature_contri", "label_gain",
    "eval_at", "auc_mu_weights", "cegb_penalty_feature_lazy", "cegb_penalty_feature_coupled",
    "ignore_column", "categorical_feature", "interaction_constraints",
    "max_bin_by_feature",
}

_OBJECTIVE_ALIASES = {
    "regression": "regression", "regression_l2": "regression", "l2": "regression",
    "mean_squared_error": "regression", "mse": "regression", "l2_root": "regression",
    "root_mean_squared_error": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "l1": "regression_l1",
    "mean_absolute_error": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson", "quantile": "quantile",
    "mape": "mape", "mean_absolute_percentage_error": "mape",
    "gamma": "gamma", "tweedie": "tweedie",
    "binary": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "cross_entropy": "cross_entropy", "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda", "xentlambda": "cross_entropy_lambda",
    "lambdarank": "lambdarank",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}


def parse_objective_alias(name: str) -> str:
    name = name.strip().lower()
    if name in _OBJECTIVE_ALIASES:
        return _OBJECTIVE_ALIASES[name]
    return name


@dataclass
class Config:
    """Typed parameter set. Field names match reference parameter names.

    Groups follow the reference layout: Core, Learning Control, IO, Objective,
    Metric, Network, Device (reference: include/LightGBM/config.h regions).
    """
    # ---- Core ----
    config: str = ""
    task: str = "train"                 # train, predict, serve, online, convert_model, refit
    objective: str = "regression"
    boosting: str = "gbdt"              # gbdt, rf, dart, goss
    data: str = ""
    valid: List[str] = field(default_factory=list)
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    tree_learner: str = "serial"        # serial, feature, data, voting
    num_threads: int = 0
    device_type: str = "tpu"            # cpu, tpu (reference: cpu, gpu)
    seed: int = 0

    # ---- Learning control ----
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    bagging_fraction: float = 1.0
    pos_bagging_fraction: float = 1.0
    neg_bagging_fraction: float = 1.0
    bagging_freq: int = 0
    bagging_seed: int = 3
    feature_fraction: float = 1.0
    feature_fraction_bynode: float = 1.0
    feature_fraction_seed: int = 2
    early_stopping_round: int = 0
    first_metric_only: bool = False
    max_delta_step: float = 0.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4
    top_rate: float = 0.2
    other_rate: float = 0.1
    min_data_per_group: int = 100
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    top_k: int = 20
    monotone_constraints: List[int] = field(default_factory=list)
    feature_contri: List[float] = field(default_factory=list)
    max_bin_by_feature: List[int] = field(default_factory=list)
    forcedsplits_filename: str = ""
    forcedbins_filename: str = ""
    refit_decay_rate: float = 0.9
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    cegb_penalty_feature_lazy: List[float] = field(default_factory=list)
    cegb_penalty_feature_coupled: List[float] = field(default_factory=list)
    verbosity: int = 1

    # ---- IO ----
    max_bin: int = 255
    min_data_in_bin: int = 3
    bin_construct_sample_cnt: int = 200000
    histogram_pool_size: float = -1.0
    data_random_seed: int = 1
    output_model: str = "LightGBM_model.txt"
    snapshot_freq: int = -1
    input_model: str = ""
    output_result: str = "LightGBM_predict_result.txt"
    initscore_filename: str = ""
    valid_data_initscores: List[str] = field(default_factory=list)
    pre_partition: bool = False
    enable_bundle: bool = True
    max_conflict_rate: float = 0.0
    is_enable_sparse: bool = True
    sparse_threshold: float = 0.8
    use_missing: bool = True
    zero_as_missing: bool = False
    two_round: bool = False
    save_binary: bool = False
    header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_feature: str = ""
    predict_raw_score: bool = False
    predict_leaf_index: bool = False
    predict_contrib: bool = False
    num_iteration_predict: int = -1
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0
    predict_disable_shape_check: bool = False
    convert_model_language: str = ""
    convert_model: str = "gbdt_prediction.cpp"

    # ---- Objective ----
    num_class: int = 1
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0
    sigmoid: float = 1.0
    boost_from_average: bool = True
    reg_sqrt: bool = False
    alpha: float = 0.9
    fair_c: float = 1.0
    poisson_max_delta_step: float = 0.7
    tweedie_variance_power: float = 1.5
    max_position: int = 20
    lambdamart_norm: bool = True
    label_gain: List[float] = field(default_factory=list)

    # ---- Metric ----
    metric: List[str] = field(default_factory=list)
    metric_freq: int = 1
    is_provide_training_metric: bool = False
    eval_at: List[int] = field(default_factory=lambda: [1, 2, 3, 4, 5])
    multi_error_top_k: int = 1
    auc_mu_weights: List[float] = field(default_factory=list)

    # ---- Network ----
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_filename: str = ""
    machines: str = ""

    # ---- Elastic multi-host fleet (fleet/ subsystem) ----
    tpu_fleet: int = 0                  # task=train gang size: launch
                                        # this many training ranks with
                                        # file/TCP rendezvous + elastic
                                        # lost-host recovery; 0/1 = off
    tpu_fleet_heartbeat_s: float = 30.0  # silence window (relative to
                                        # the other ranks' heartbeat
                                        # arrivals) before a rank is
                                        # classified dead; heartbeats
                                        # ride the fingerprint cadence —
                                        # no new sync points
    tpu_fleet_transport: str = "auto"   # auto = jax.distributed when the
                                        # backend runs cross-process
                                        # device collectives, else the
                                        # host-TCP CI-twin transport;
                                        # jax / host force one
    tpu_fleet_dir: str = ""             # rendezvous + fleet artifact
                                        # directory (rank logs, event
                                        # trail, default checkpoints);
                                        # empty = a fresh temp dir
    tpu_fleet_port: int = 0             # coordinator TCP port
                                        # (0 = ephemeral)
    tpu_fleet_min_ranks: int = 1        # abort instead of resuming when
                                        # survivors drop below this
    tpu_fleet_heal: bool = True         # relaunch a lost rank and fold
                                        # it back in at the next resize
    tpu_fleet_max_recoveries: int = 2   # elastic recoveries tolerated
                                        # per rank (and heals per
                                        # launcher) before aborting

    # ---- Device (reference gpu_* kept for compat; tpu_* are new) ----
    gpu_platform_id: int = -1
    gpu_device_id: int = -1
    gpu_use_dp: bool = False
    tpu_hist_dtype: str = "2xbf16"      # histogram matmul input precision,
                                        # by kernel-mode name: 2xbf16 =
                                        # hi/lo bf16 split (~16 mantissa
                                        # bits on g/h, f32 accum, 2 MXU
                                        # passes), highest = exact f32
                                        # (3 passes; also via gpu_use_dp),
                                        # bf16 = 1 pass (~8 bits).
                                        # int16 / int8 = QUANTIZED
                                        # accumulation (LightGBM 4.x's
                                        # quantized-training trick):
                                        # per-tree symmetric scales
                                        # computed on device, stochastic-
                                        # rounded integer g/h, exact
                                        # integer MXU accumulation (2 / 1
                                        # passes) with in-kernel f32
                                        # dequant before the split scan;
                                        # halves the per-row HBM vector
                                        # stream.  Wave-kernel path only
                                        # (mixed-width datasets fall back
                                        # to 2xbf16); f32 modes stay the
                                        # bit-exactness oracle.
                                        # Back-compat aliases: float32 ->
                                        # 2xbf16, bfloat16 -> bf16
    tpu_fused_grad: bool = True         # fold objective.get_gradients
                                        # into the SAME jit as tree
                                        # growth, so the per-iteration
                                        # [N] f32 g/h arrays are never
                                        # materialized to HBM and read
                                        # back (and under int16/int8 the
                                        # quantize+pack fuses with the
                                        # gradient math).  Bit-identical
                                        # to the unfused path; engages
                                        # only where eligible (single
                                        # tree/iter objectives, plain
                                        # gbdt/dart — GOSS and RF consume
                                        # materialized gradients, custom
                                        # objectives and health taps keep
                                        # the unfused path).  false =
                                        # the differential oracle
    tpu_rank_device_eval: bool = True   # ranking eval path: true = the
                                        # device NDCG@k kernel over the
                                        # shared padded query blocks
                                        # (metric/rank.py — stable sort
                                        # per block, gain-discount
                                        # cumsum, per-k gather; one tiny
                                        # [len(eval_at)] D2H per eval
                                        # instead of the full [N] score
                                        # copy + ~per-query host loop);
                                        # false = the host per-query
                                        # loop (the differential oracle)
    tpu_rank_sharded_grad: bool = True  # under tree_learner=data with
                                        # >1 mesh device, compute the
                                        # lambdarank pair lambdas INSIDE
                                        # the mesh over query-aligned
                                        # row shards (parallel/
                                        # rank_shard.py): shard
                                        # boundaries snap to query
                                        # boundaries so every pair stays
                                        # shard-local, instead of the
                                        # whole pair pass running
                                        # globally on one device.
                                        # Per-row lambdas are the same
                                        # per-query sums, so results
                                        # match the single-device oracle
    tpu_wave_overlap: bool = False      # double-buffered wave scheduling:
                                        # defer each wave's child split-
                                        # scan by one loop body so it
                                        # executes AFTER the next wave's
                                        # kernel dispatch (no data
                                        # dependency between the two), at
                                        # the cost of the commit phase
                                        # seeing gains one wave late — a
                                        # split-ORDER deviation of the
                                        # kind wave scheduling already
                                        # tolerates, never wrong
                                        # histograms.  Off by default
                                        # until a TPU window prices it
                                        # (bench A/B: BENCH_OVERLAP=1)
    tpu_block_rows: int = 1024          # Pallas histogram kernel row-block
    tpu_wave_capacity: int = 63         # leaves histogrammed per wave pass
                                        # (<= 63: a g/h lane pair each in
                                        # the 128-lane Pallas kernel, the
                                        # count channel folded into one
                                        # extra single-pass matmul)
    tpu_fused_sibling: bool = True      # compute each wave's sibling
                                        # histograms (parent minus smaller
                                        # child) INSIDE the wave kernel
                                        # launch instead of a separate XLA
                                        # subtraction pass — histograms
                                        # are bit-identical either way;
                                        # false keeps the unfused path as
                                        # the differential-test oracle
    tpu_wave_gain_gate: float = 0.5     # split-phase throttle: only commit
                                        # leaves with gain >= gate * best
                                        # ready gain (1 = strict best-first
                                        # order, 0 = max wave throughput)
    tpu_batched_split_apply: bool = True  # apply each wave's committed
                                        # splits to the row partition in
                                        # ONE vectorized pass (O(N) per
                                        # wave) instead of one full-array
                                        # walk per split (O(splits x N));
                                        # trees are identical either way —
                                        # false keeps the sequential walk
                                        # as the differential-test oracle
    tpu_compile_cache_dir: str = ""     # persistent XLA compilation-cache
                                        # directory: compiled growers
                                        # survive process restarts, so
                                        # steady-state reruns skip the
                                        # multi-second compile (also via
                                        # LGBM_TPU_COMPILE_CACHE env var;
                                        # "" leaves the cache off)
    tpu_mesh_shape: str = ""            # e.g. "data:8" or "data:4,feature:2"
    tpu_telemetry: str = ""             # structured-telemetry sink: a dir
                                        # (telemetry.{proc}.jsonl inside) or
                                        # a .jsonl path; same switch as the
                                        # LGBM_TPU_TELEMETRY env var
    tpu_health: str = ""                # training-health sentinels
                                        # (obs/health.py): "" off,
                                        # monitor = per-iteration numerics
                                        # guards + model fingerprints +
                                        # cross-rank divergence audit with
                                        # health/fingerprint telemetry
                                        # events, strict = additionally
                                        # abort on the first failure with
                                        # phase/node/feature attribution.
                                        # PROCESS-WIDE once on (like
                                        # tpu_telemetry); syncs the device
                                        # per iteration (LGBM_TPU_HEALTH
                                        # env var)
    tpu_fingerprint_freq: int = 1       # iterations between model-state
                                        # fingerprints (and the divergence
                                        # audit under multi-process
                                        # training) when tpu_health is on;
                                        # 0 disables fingerprinting
    tpu_profile: bool = False           # profile mode: sync-bracket every
                                        # phase/kernel, emit kernel_profile
                                        # roofline events + HBM memory
                                        # census (LGBM_TPU_PROFILE env).
                                        # PROCESS-WIDE once enabled (like
                                        # tpu_telemetry); breaks async
                                        # pipelining — attribution runs
                                        # only, never benchmarks
    tpu_xprof: bool = False             # measured-roofline capture
                                        # (obs/xprof.py): arm a windowed
                                        # jax.profiler trace around
                                        # tpu_xprof_iters mid-train
                                        # iterations (warmup/compile
                                        # iteration skipped), parse the
                                        # trace, attribute device ops by
                                        # lgbm/* scope and emit
                                        # kernel_measured roofline events
                                        # into the telemetry dir.
                                        # LGBM_TPU_XPROF env wins: 1/true
                                        # arms, a number > 1 sets the
                                        # window width, 0/false disarms
    tpu_xprof_iters: int = 3            # captured iterations per xprof
                                        # window when tpu_xprof is armed
                                        # (LGBM_TPU_XPROF=<n> overrides)
    tpu_trace: bool = False             # trace mode (obs/spans.py): emit
                                        # span events (trace_id/span_id/
                                        # parent_id, one schema for serve
                                        # requests AND training iteration
                                        # phases; export to Perfetto with
                                        # tools/trace_export.py).
                                        # PROCESS-WIDE once on; like
                                        # profile mode it sync-brackets
                                        # phases — attribution, never
                                        # benchmarks (LGBM_TPU_TRACE env)
    tpu_checkpoint_dir: str = ""        # fault-tolerance checkpoint
                                        # directory (robust/checkpoint.py):
                                        # when set, engine.train writes an
                                        # atomic versioned checkpoint
                                        # (forest + RNG + score state +
                                        # eval history) every
                                        # tpu_checkpoint_freq iterations
                                        # and RESUMES bit-exactly from the
                                        # newest valid one on restart;
                                        # "" disables checkpointing
    tpu_checkpoint_freq: int = 100      # boosting iterations between
                                        # checkpoints (0 = only the
                                        # preemption/wedge checkpoints);
                                        # used only with
                                        # tpu_checkpoint_dir set
    tpu_checkpoint_keep: int = 3        # newest checkpoints retained;
                                        # older ones are pruned after
                                        # each successful save
    tpu_on_device_error: str = "retry"  # device-wedge policy
                                        # (robust/watchdog.py): retry =
                                        # re-dispatch transient failures
                                        # with bounded exponential
                                        # backoff + seeded jitter, abort
                                        # on fatal; abort = fail fast
                                        # (flight dump + boundary
                                        # checkpoint + DeviceWedgedError);
                                        # fallback = after the dump/
                                        # checkpoint, re-execute the step
                                        # on the CPU backend and continue
                                        # (best-effort)
    tpu_watchdog: bool = False          # arm the device-wedge watchdog
                                        # for this trainer even without
                                        # faults injected: every device
                                        # step is synced + guarded
                                        # (classify/retry/stall heartbeat)
                                        # — trades the async-dispatch
                                        # overlap for fail-safety, like
                                        # health mode trades it for
                                        # certainty
    tpu_device_retries: int = 3         # bounded retry budget for
                                        # transient device failures
                                        # (watchdog policy retry/fallback)
    tpu_wedge_timeout_s: float = 0.0    # stall heartbeat deadline in
                                        # seconds; 0 = automatic (4x the
                                        # rolling per-step p99, floored
                                        # at 60s).  A step exceeding it
                                        # is stamped with a device_stall
                                        # event + flight dump (advisory:
                                        # a hung XLA call cannot be
                                        # interrupted from Python)
    tpu_flight_len: int = 256           # flight-recorder ring length:
                                        # the last N spans + operational
                                        # events kept in memory and
                                        # dumped as FLIGHT_rN.json on a
                                        # serve degradation, an overload
                                        # storm, a TrainingHealthError,
                                        # or GET /debug/flight; 0
                                        # disables (LGBM_TPU_FLIGHT env)
    tpu_train_metrics_port: int = -1    # live train introspection board
                                        # (obs/board.py): HTTP port for
                                        # GET /metrics + /progress +
                                        # /debug/flight during training.
                                        # -1 disables, 0 picks an
                                        # ephemeral port and logs it, >0
                                        # binds port+rank per process
                                        # (LGBM_TPU_TRAIN_METRICS env
                                        # wins: a port number, or
                                        # off/false to disarm)
    tpu_straggler_factor: float = 2.0   # live straggler detector: a rank
                                        # whose per-iteration hist/split
                                        # wall exceeds the fleet median
                                        # by this factor is suspect
                                        # (multi-process runs only)
    tpu_straggler_iters: int = 3        # consecutive suspect iterations
                                        # before a straggler event is
                                        # emitted (+ flight dump on
                                        # rank 0); 0 disables detection

    # ---- Serving (serve/ subsystem) ----
    tpu_serve_max_batch: int = 1024     # row cap per coalesced device
                                        # batch; requests pad to power-of-
                                        # two buckets, so the jitted
                                        # predictor compiles at most
                                        # ceil(log2(max_batch))+1 shapes
                                        # (LGBM_TPU_SERVE_MAX_BATCH env)
    tpu_serve_max_wait_ms: float = 2.0  # longest the microbatcher holds
                                        # the oldest queued request while
                                        # coalescing — the latency knob
                                        # (LGBM_TPU_SERVE_MAX_WAIT_MS env)
    tpu_serve_queue_depth: int = 8192   # queued-ROW bound: a full queue
                                        # rejects submits with an explicit
                                        # overload error (backpressure,
                                        # never OOM)
                                        # (LGBM_TPU_SERVE_QUEUE_DEPTH env)
    tpu_serve_host: str = "127.0.0.1"   # bind address for task=serve
    tpu_serve_port: int = 0             # task=serve HTTP port (0 = pick
                                        # an ephemeral port and log it)
    tpu_serve_reprobe_s: float = 30.0   # seconds between device
                                        # re-probes while a serving
                                        # session is degraded to the
                                        # host predictor: a successful
                                        # probe flips /health back from
                                        # "degraded" (probe-and-recover
                                        # instead of the old one-way
                                        # latch); 0 disables re-probing
                                        # (LGBM_TPU_SERVE_REPROBE_S env)
    tpu_serve_slo_p99_ms: float = 250.0  # serving p99 latency objective:
                                        # /metrics + /health report the
                                        # SLO-burn rate against it (the
                                        # fraction of recent requests
                                        # over the target divided by the
                                        # 1% budget a p99 allows; 1.0 =
                                        # burning at exactly the allowed
                                        # rate); 0 disables the gauge
                                        # (LGBM_TPU_SERVE_SLO_P99_MS env)

    # ---- Serving fleet (serve/router.py + serve/registry.py) ----
    tpu_serve_replicas: int = 2         # PredictorSession replicas per
                                        # model version behind the
                                        # router: per-device on a multi-
                                        # chip host, thread-pool
                                        # replicas on CPU — one wedged
                                        # replica costs capacity, not
                                        # availability
                                        # (LGBM_TPU_SERVE_REPLICAS env)
    tpu_serve_breaker_trip: int = 3     # consecutive transient failures
                                        # that open a replica's circuit
                                        # breaker (a FATAL failure opens
                                        # it immediately)
    tpu_serve_breaker_backoff_s: float = 0.5  # base of the breaker's
                                        # bounded exponential backoff:
                                        # how long an open breaker waits
                                        # before letting one half-open
                                        # probe request through
    tpu_serve_canary_rows: int = 64     # pinned probe-set rows the
                                        # canary gate scores on a swap
                                        # candidate (device-vs-host
                                        # parity + finite-output checks)
    tpu_serve_canary_probes: int = 16   # single-row latency probes the
                                        # canary gate times (p99
                                        # recorded in the swap report)
    tpu_serve_canary_p99_ms: float = 0.0  # reject a swap whose canary
                                        # p99 exceeds this; 0 = record
                                        # the p99 but never gate on it
                                        # (CI latency is too noisy to
                                        # gate by default)
    tpu_serve_rollback_watch_s: float = 30.0  # post-swap health-watch
                                        # window: the new live version's
                                        # metrics are monitored this
                                        # long and a regression triggers
                                        # AUTOMATIC rollback to the
                                        # still-resident previous
                                        # version; 0 disables the watch
                                        # (manual rollback still works)
                                        # (LGBM_TPU_SERVE_ROLLBACK_WATCH_S
                                        # env)
    tpu_serve_rollback_error_rate: float = 0.5  # post-swap failed-
                                        # request fraction (over the
                                        # watch window) that triggers
                                        # automatic rollback
    tpu_serve_rollback_degraded: int = 2  # post-swap degraded
                                        # transitions that trigger
                                        # automatic rollback (the new
                                        # version's device path keeps
                                        # dying)
    tpu_serve_rollback_slo_burn: float = 0.0  # post-swap SLO-burn rate
                                        # that triggers automatic
                                        # rollback; 0 = never gate the
                                        # rollback on burn
    tpu_serve_shed_low_frac: float = 0.5  # fraction of the queue-row
                                        # budget low-priority requests
                                        # may fill before being shed
                                        # (overload drops bulk traffic
                                        # first)
                                        # (LGBM_TPU_SERVE_SHED_LOW_FRAC
                                        # env)
    tpu_serve_shed_normal_frac: float = 0.85  # queue-budget fraction for
                                        # normal-priority requests
                                        # (high priority always owns
                                        # the full queue)
                                        # (LGBM_TPU_SERVE_SHED_NORMAL_FRAC
                                        # env)
    tpu_serve_retry_after_s: float = 1.0  # Retry-After header seconds on
                                        # shed (503) responses — when a
                                        # rejected client should come
                                        # back
    tpu_serve_swap_warmup: bool = True  # compile every bucket shape of
                                        # a swap candidate BEFORE the
                                        # atomic flip (the old version
                                        # keeps serving meanwhile), so
                                        # post-flip traffic never pays
                                        # the new forest's XLA compiles
                                        # — the zero-cold-start half of
                                        # zero-downtime; false flips
                                        # immediately after the canary
    tpu_serve_aot: bool = True          # arm the AOT executable store
                                        # when a directory is set: a
                                        # warmed store lets a cold
                                        # process serve request #1 with
                                        # ZERO JIT compiles (serve/
                                        # aot.py); false disarms without
                                        # unsetting the directory
    tpu_serve_aot_dir: str = ""         # AOT executable store directory
                                        # — serialized per-bucket
                                        # executables keyed by forest
                                        # content + backend + jax
                                        # version; empty = store off
                                        # (LGBM_TPU_SERVE_AOT_DIR env
                                        # wins)
    tpu_serve_arena_bytes: int = 0      # device-byte budget for the
                                        # multi-tenant forest arena
                                        # (serve/arena.py): admissions
                                        # past the budget LRU-evict the
                                        # coldest tenant (re-admitted
                                        # transparently on its next
                                        # request); 0 = unbounded
                                        # (LGBM_TPU_SERVE_ARENA_BYTES
                                        # env)

    # ---- Explanation serving (explain/ subsystem) ----
    tpu_explain: bool = True            # arm POST /explain and
                                        # PredictorSession.explain():
                                        # packs the per-node cover counts
                                        # + path metadata on FIRST use
                                        # (predict-only sessions never
                                        # pay the HBM cost); false
                                        # removes the endpoint
                                        # (LGBM_TPU_EXPLAIN env)
    tpu_explain_max_batch: int = 256    # row cap per coalesced device
                                        # TreeSHAP batch — its OWN pow2
                                        # bucket family, compiling at
                                        # most ceil(log2(max_batch))+1
                                        # shapes; smaller than predict's
                                        # because each row costs
                                        # O(leaves x depth^2)
                                        # (LGBM_TPU_EXPLAIN_MAX_BATCH env)
    tpu_explain_max_wait_ms: float = 5.0  # longest the explain
                                        # microbatcher holds the oldest
                                        # queued request while coalescing
                                        # (LGBM_TPU_EXPLAIN_MAX_WAIT_MS
                                        # env)

    # ---- Online learning (online/ subsystem) ----
    tpu_refit_device: bool = True       # leaf-refit path: true = the
                                        # device refit kernel (one
                                        # stacked leaf-index scan + a
                                        # jitted per-iteration segment-
                                        # sum/closed-form step,
                                        # online/refit.py); false = the
                                        # host per-tree bincount loop,
                                        # retained as the differential
                                        # oracle (per-leaf 1e-6 parity
                                        # pinned in tests/test_online.py)
    tpu_online_mode: str = "refit"      # task=online refresh strategy:
                                        # refit = re-estimate the frozen
                                        # forest's leaves over the
                                        # window (decay-mixed), continue
                                        # = boost tpu_online_trees NEW
                                        # trees in the model's own bin
                                        # space (no training-data
                                        # rebinning either way)
    tpu_online_window: int = 50000      # bounded ingest window: the
                                        # freshest labeled rows kept for
                                        # the next refresh; older rows
                                        # fall out (memory-bounded, like
                                        # the serve queue)
                                        # (LGBM_TPU_ONLINE_WINDOW env)
    tpu_online_refit_every: int = 5000  # row cadence: refresh after
                                        # this many newly ingested rows;
                                        # 0 = rows never trigger
                                        # (LGBM_TPU_ONLINE_REFIT_EVERY
                                        # env)
    tpu_online_refit_every_s: float = 0.0  # time cadence in seconds
                                        # (OR-composed with the row
                                        # cadence); a firing with no
                                        # fresh rows is an ingest stall:
                                        # skipped + logged + telemetry-
                                        # stamped, never a stale refit;
                                        # 0 = time never triggers
    tpu_online_trees: int = 10          # boosting rounds added per
                                        # refresh in continue mode
    tpu_online_decay: float = -1.0      # refit decay for the online
                                        # loop (new leaf = decay*old +
                                        # (1-decay)*refit); negative =
                                        # inherit refit_decay_rate
    tpu_online_model: str = "default"   # registry model name the loop
                                        # pushes refreshed versions to
                                        # (POST /models/{name}/swap)
    tpu_online_source: str = ""         # label stream for task=online: a
                                        # JSONL file of {"x": [...],
                                        # "y": <label>} lines ("" falls
                                        # back to data)
    tpu_online_follow: bool = False     # tail the stream for appended
                                        # lines instead of stopping at
                                        # EOF (the feeder-process mode)
    tpu_online_dir: str = ""            # where refreshed model versions
                                        # are written ("" = a fresh temp
                                        # directory)

    # ---- Out-of-core ingestion (ingest/ subsystem) ----
    tpu_ingest: bool = False            # task=train file loading routes
                                        # through the streaming ingest
                                        # subsystem (ingest/): two-pass
                                        # chunked readers (CSV/TSV,
                                        # LibSVM, .npy/.npz), seeded
                                        # reservoir bin-sampling over the
                                        # WHOLE stream, chunk-at-a-time
                                        # binning — the raw [N,F] f64
                                        # matrix is never materialized.
                                        # Bit-identical to the in-RAM
                                        # path given the same sample
                                        # (differential-test pinned)
    tpu_ingest_chunk_rows: int = 65536  # rows per streamed chunk for the
                                        # array/.npy/.npz/LibSVM readers
                                        # — the peak-raw-memory knob
                                        # (text files chunk by bytes via
                                        # the mmap windows).  Chunk size
                                        # never changes the constructed
                                        # dataset (test-pinned)
                                        # (LGBM_TPU_INGEST_CHUNK_ROWS env)
    tpu_ingest_memmap: str = ""         # back the binned matrix with an
                                        # np.memmap file instead of host
                                        # RAM: a directory (per-shard
                                        # X_bin.shardN.npy inside) or a
                                        # file path.  "" keeps the
                                        # matrix in RAM
                                        # (LGBM_TPU_INGEST_MEMMAP env)
    tpu_ingest_shards: int = 0          # row-shard plan: how many
                                        # contiguous shards the stream
                                        # splits into (query-aligned for
                                        # ranking data), each worker
                                        # binning ONLY its own rows.
                                        # 0/1 = no sharding
    tpu_ingest_shard_id: int = -1       # which shard THIS process bins;
                                        # -1 = the recorded process rank
                                        # (parallel/distributed.py)
    tpu_ingest_sample_seed: int = -1    # reservoir sampling seed for
                                        # streamed bin finding; -1 =
                                        # inherit data_random_seed (so
                                        # flipping tpu_ingest keeps the
                                        # sample schedule stable)

    # ---- Drift & quality monitoring (obs/drift.py + serve/quality.py) ----
    tpu_drift: bool = True              # arm serve-side drift monitoring
                                        # when a .quality.json profile
                                        # sits beside the loaded model
                                        # file; off = the session takes
                                        # one is-None branch and nothing
                                        # more (LGBM_TPU_DRIFT env)
    tpu_quality_profile: bool = True    # write the <model>.quality.json
                                        # reference profile (per-feature
                                        # bin occupancy + training
                                        # prediction histogram + train
                                        # AUC baseline) beside every
                                        # saved model that still has its
                                        # training dataset attached
    tpu_drift_sample_rate: float = 0.05  # fraction of served rows whose
                                        # raw features feed the drift
                                        # sketch (deterministic batch-
                                        # granularity sampling); the
                                        # prediction histogram is taken
                                        # on every response regardless
                                        # (LGBM_TPU_DRIFT_SAMPLE_RATE
                                        # env)
    tpu_drift_check_s: float = 30.0     # cadence for scoring the live
                                        # sketch against the reference
                                        # profile (PSI + KS) and
                                        # emitting drift_snapshot events
                                        # (LGBM_TPU_DRIFT_CHECK_S env)
    tpu_drift_min_rows: int = 200       # sketch rows required before a
                                        # cadence firing scores at all —
                                        # tiny samples make PSI scream
                                        # (LGBM_TPU_DRIFT_MIN_ROWS env)
    tpu_drift_psi_warn: float = 0.25    # PSI breach threshold (feature
                                        # max or prediction histogram):
                                        # above it the monitor dumps the
                                        # flight recorder and latches a
                                        # breach for the registry's
                                        # post-swap watch
                                        # (LGBM_TPU_DRIFT_PSI_WARN env)
    tpu_quality_window: int = 512       # labeled rows per rolling
                                        # quality window (windowed AUC /
                                        # NDCG / calibration error from
                                        # the online loop's label
                                        # stream) (LGBM_TPU_QUALITY_WINDOW
                                        # env)
    tpu_quality_drop_warn: float = 0.05  # AUC drop below the profile's
                                        # training baseline that counts
                                        # as a quality breach
                                        # (LGBM_TPU_QUALITY_DROP_WARN
                                        # env)
    tpu_serve_rollback_on_drift: bool = False  # opt-in: a drift/quality
                                        # breach during the post-swap
                                        # health watch triggers rollback
                                        # like an error-rate burn;
                                        # default only annotates the
                                        # watch report
                                        # (LGBM_TPU_SERVE_ROLLBACK_ON_DRIFT
                                        # env)

    # ---- derived (not user-settable) ----
    is_parallel: bool = dataclasses.field(default=False, repr=False)

    # ------------------------------------------------------------------
    @staticmethod
    def str2map(params_str: str) -> Dict[str, str]:
        """Parse a CLI/conf style ``key=value`` string list separated by
        whitespace (reference: Config::Str2Map, config.h:78)."""
        out: Dict[str, str] = {}
        for tok in params_str.split():
            Config.kv2map(out, tok)
        return out

    @staticmethod
    def kv2map(out: Dict[str, str], kv: str) -> None:
        if "=" not in kv:
            if kv.strip():
                log.warning("Unknown token '%s' ignored", kv)
            return
        k, v = kv.split("=", 1)
        k, v = k.strip(), v.strip()
        if k and not k.startswith("#"):
            out[k] = v

    @classmethod
    def from_params(cls, params: Optional[Dict[str, Any]]) -> "Config":
        cfg = cls()
        cfg.update(params or {})
        return cfg

    # ------------------------------------------------------------------
    def update(self, params: Dict[str, Any]) -> None:
        resolved: Dict[str, Any] = {}
        for key, value in params.items():
            canon = _ALIASES.get(key, key)
            if canon in resolved and key != canon:
                continue  # explicit canonical name wins over alias
            resolved[canon] = value
        fields = {f.name: f for f in dataclasses.fields(self)}
        for key, value in resolved.items():
            if key not in fields:
                log.warning("Unknown parameter: %s", key)
                continue
            setattr(self, key, _coerce(fields[key], value))
        self._post_process()

    def _post_process(self) -> None:
        log.set_verbosity(self.verbosity)
        self.objective = parse_objective_alias(self.objective)
        self.boosting = {"gbrt": "gbdt", "random_forest": "rf"}.get(self.boosting, self.boosting)
        self.tree_learner = {
            "serial_tree_learner": "serial", "feature_parallel": "feature",
            "feature_parallel_tree_learner": "feature", "data_parallel": "data",
            "data_parallel_tree_learner": "data", "voting_parallel": "voting",
            "voting_parallel_tree_learner": "voting", "voting_tree_learner": "voting",
        }.get(self.tree_learner, self.tree_learner)
        if self.tree_learner not in ("serial", "feature", "data", "voting"):
            log.fatal(f"Unknown tree learner type {self.tree_learner}")
        if self.device_type not in ("cpu", "tpu", "gpu"):
            log.fatal(f"Unknown device type {self.device_type}")
        if self.device_type == "gpu":
            # The reference's OpenCL device does not exist here; the TPU path is
            # its replacement (reference: src/treelearner/gpu_tree_learner.h).
            log.warning("device_type=gpu mapped to tpu in lightgbm_tpu")
            self.device_type = "tpu"
        self.is_parallel = self.tree_learner != "serial" or self.num_machines > 1
        # consistency checks (reference: Config::CheckParamConflict, config.cpp)
        if self.is_parallel and self.monotone_constraints:
            log.fatal("Cannot use monotone constraints in parallel learning")
        if not (0.0 < self.bagging_fraction <= 1.0):
            log.fatal("bagging_fraction should be in (0.0, 1.0]")
        if not (0.0 < self.feature_fraction <= 1.0):
            log.fatal("feature_fraction should be in (0.0, 1.0]")
        if not (0.0 < self.feature_fraction_bynode <= 1.0):
            log.fatal("feature_fraction_bynode should be in (0.0, 1.0]")
        if self.num_leaves < 2:
            log.fatal("num_leaves should be >= 2")
        if not (1 < self.max_bin <= 65535):
            log.fatal("max_bin should be in (1, 65535]")
        if self.boosting == "goss" and self.top_rate + self.other_rate > 1.0:
            log.fatal("top_rate + other_rate should be <= 1.0 for GOSS")
        if self.objective in ("multiclass", "multiclassova") and self.num_class < 2:
            log.fatal(f"num_class must be >= 2 for objective {self.objective}")
        if self.objective not in ("multiclass", "multiclassova", "none") and self.num_class != 1:
            log.fatal(f"num_class must be 1 for objective {self.objective}")
        if self.boosting == "rf":
            if self.bagging_freq <= 0 or not (0.0 < self.bagging_fraction < 1.0):
                log.fatal("bagging_freq and bagging_fraction (in (0,1)) are required for rf")
        if not (0.0 <= self.tpu_wave_gain_gate <= 1.0):
            log.fatal("tpu_wave_gain_gate should be in [0.0, 1.0]")
        if self.tpu_hist_dtype not in ("2xbf16", "bf16", "highest",
                                       "int16", "int8",
                                       "float32", "bfloat16"):
            log.fatal("tpu_hist_dtype should be 2xbf16, bf16, highest, "
                      "int16 or int8 "
                      "(aliases: float32 -> 2xbf16, bfloat16 -> bf16)")
        if self.tpu_hist_dtype in ("int16", "int8") \
                and self.num_leaves > 32000:
            log.fatal("quantized histogram modes carry leaf ids in the "
                      "int16 vector stream: num_leaves must be <= 32000")
        if self.tpu_wave_capacity < 1:
            log.fatal("tpu_wave_capacity should be >= 1")
        if self.tpu_block_rows < 128 or self.tpu_block_rows % 128 != 0:
            log.fatal("tpu_block_rows should be a positive multiple of 128 "
                      "(TPU lane-tile alignment)")
        # normalize the health-mode synonyms to the canonical "",
        # "monitor", "strict" via the ONE parser in obs/health.py —
        # unknown values are fatal on the parameter path (the env path
        # warns instead: it cannot raise at import time)
        from .obs.health import parse_mode
        self.tpu_health = parse_mode(self.tpu_health, fatal=True)
        if self.tpu_fingerprint_freq < 0:
            log.fatal("tpu_fingerprint_freq should be >= 0")
        if self.tpu_serve_max_batch < 1:
            log.fatal("tpu_serve_max_batch should be >= 1")
        if self.tpu_serve_max_wait_ms < 0:
            log.fatal("tpu_serve_max_wait_ms should be >= 0")
        if self.tpu_serve_queue_depth < self.tpu_serve_max_batch:
            log.fatal("tpu_serve_queue_depth should be >= "
                      "tpu_serve_max_batch")
        if not (0 <= self.tpu_serve_port <= 65535):
            log.fatal("tpu_serve_port should be in [0, 65535]")
        if self.tpu_serve_slo_p99_ms < 0:
            log.fatal("tpu_serve_slo_p99_ms should be >= 0")
        if self.tpu_serve_replicas < 1:
            log.fatal("tpu_serve_replicas should be >= 1")
        if self.tpu_serve_breaker_trip < 1:
            log.fatal("tpu_serve_breaker_trip should be >= 1")
        if self.tpu_serve_canary_rows < 1:
            log.fatal("tpu_serve_canary_rows should be >= 1")
        if not (0.0 <= self.tpu_serve_rollback_error_rate <= 1.0):
            log.fatal("tpu_serve_rollback_error_rate should be in [0, 1]")
        if not (0.0 <= self.tpu_serve_shed_low_frac <= 1.0):
            log.fatal("tpu_serve_shed_low_frac should be in [0, 1]")
        if not (0.0 <= self.tpu_serve_shed_normal_frac <= 1.0):
            log.fatal("tpu_serve_shed_normal_frac should be in [0, 1]")
        if self.tpu_serve_rollback_watch_s < 0:
            log.fatal("tpu_serve_rollback_watch_s should be >= 0")
        if self.tpu_serve_arena_bytes < 0:
            log.fatal("tpu_serve_arena_bytes should be >= 0")
        if self.tpu_explain_max_batch < 1:
            log.fatal("tpu_explain_max_batch should be >= 1")
        if self.tpu_explain_max_wait_ms < 0:
            log.fatal("tpu_explain_max_wait_ms should be >= 0")
        if self.tpu_flight_len < 0:
            log.fatal("tpu_flight_len should be >= 0")
        if not (-1 <= self.tpu_train_metrics_port <= 65535):
            log.fatal("tpu_train_metrics_port should be in [-1, 65535]")
        if self.tpu_straggler_factor <= 1.0:
            log.fatal("tpu_straggler_factor should be > 1")
        if self.tpu_straggler_iters < 0:
            log.fatal("tpu_straggler_iters should be >= 0")
        if self.tpu_on_device_error not in ("abort", "fallback", "retry"):
            log.fatal("tpu_on_device_error should be abort, fallback or "
                      "retry")
        if self.tpu_checkpoint_freq < 0:
            log.fatal("tpu_checkpoint_freq should be >= 0")
        if self.tpu_checkpoint_keep < 1:
            log.fatal("tpu_checkpoint_keep should be >= 1")
        if self.tpu_device_retries < 0:
            log.fatal("tpu_device_retries should be >= 0")
        if self.tpu_wedge_timeout_s < 0:
            log.fatal("tpu_wedge_timeout_s should be >= 0")
        if self.tpu_serve_reprobe_s < 0:
            log.fatal("tpu_serve_reprobe_s should be >= 0")
        if self.tpu_online_mode not in ("refit", "continue"):
            log.fatal("tpu_online_mode should be refit or continue")
        if self.tpu_online_window < 1:
            log.fatal("tpu_online_window should be >= 1")
        if self.tpu_online_refit_every < 0:
            log.fatal("tpu_online_refit_every should be >= 0")
        if self.tpu_online_refit_every_s < 0:
            log.fatal("tpu_online_refit_every_s should be >= 0")
        if self.tpu_online_trees < 1:
            log.fatal("tpu_online_trees should be >= 1")
        if self.tpu_online_decay > 1.0:
            log.fatal("tpu_online_decay should be <= 1 (negative = "
                      "inherit refit_decay_rate)")
        if (self.task == "online" and self.tpu_online_refit_every <= 0
                and self.tpu_online_refit_every_s <= 0):
            log.fatal("task=online needs a refresh cadence: set "
                      "tpu_online_refit_every (rows) and/or "
                      "tpu_online_refit_every_s (seconds)")
        if self.tpu_ingest_chunk_rows < 1:
            log.fatal("tpu_ingest_chunk_rows should be >= 1")
        if self.tpu_ingest_shards < 0:
            log.fatal("tpu_ingest_shards should be >= 0")
        if (self.tpu_ingest_shards > 1
                and self.tpu_ingest_shard_id >= self.tpu_ingest_shards):
            log.fatal("tpu_ingest_shard_id should be < tpu_ingest_shards "
                      "(or -1 for the process rank)")
        if self.tpu_fleet < 0:
            log.fatal("tpu_fleet should be >= 0")
        if self.tpu_fleet_heartbeat_s <= 0:
            log.fatal("tpu_fleet_heartbeat_s should be > 0 (seconds)")
        if self.tpu_fleet_transport not in ("auto", "jax", "host"):
            log.fatal("tpu_fleet_transport should be auto, jax or host")
        if self.tpu_fleet_min_ranks < 1:
            log.fatal("tpu_fleet_min_ranks should be >= 1")
        if self.tpu_fleet_max_recoveries < 0:
            log.fatal("tpu_fleet_max_recoveries should be >= 0")
        if not 0.0 <= self.tpu_drift_sample_rate <= 1.0:
            log.fatal("tpu_drift_sample_rate should be in [0, 1]")
        if self.tpu_drift_check_s <= 0:
            log.fatal("tpu_drift_check_s should be > 0")
        if self.tpu_drift_min_rows < 1:
            log.fatal("tpu_drift_min_rows should be >= 1")
        if self.tpu_drift_psi_warn <= 0:
            log.fatal("tpu_drift_psi_warn should be > 0")
        if self.tpu_quality_window < 1:
            log.fatal("tpu_quality_window should be >= 1")
        if self.tpu_quality_drop_warn <= 0:
            log.fatal("tpu_quality_drop_warn should be > 0")

    # ------------------------------------------------------------------
    def num_model_per_iteration(self) -> int:
        if self.objective in ("multiclass", "multiclassova"):
            return self.num_class
        return 1

    def to_params(self) -> Dict[str, Any]:
        out = {}
        for f in dataclasses.fields(self):
            if f.name == "is_parallel":
                continue
            v = getattr(self, f.name)
            if v != (f.default if f.default is not dataclasses.MISSING else None):
                out[f.name] = v
        return out


def _coerce(fld: dataclasses.Field, value: Any):
    """Coerce a raw parameter value (possibly a string from a conf file) to
    the field's declared type."""
    name = fld.name
    ftype = fld.type if isinstance(fld.type, str) else getattr(fld.type, "__name__", str(fld.type))
    is_list = "List" in ftype
    if is_list:
        if value is None:
            return []
        if isinstance(value, str):
            items = [x for x in value.replace(",", " ").split() if x]
        elif isinstance(value, (set, frozenset)):
            # sets are a documented reference idiom: {'l2', 'l1'}; sort
            # for a deterministic order — numerically when the values are
            # numeric (eval_at={5,10,20} must stay [5,10,20])
            try:
                items = sorted(value, key=float)
            except (TypeError, ValueError):
                items = sorted(value, key=str)
        elif isinstance(value, (list, tuple)):
            items = list(value)
        else:
            items = [value]
        if "int" in ftype:
            return [int(float(x)) for x in items]
        if "float" in ftype:
            return [float(x) for x in items]
        return [str(x) for x in items]
    if "bool" in ftype:
        if isinstance(value, str):
            return value.strip().lower() in ("true", "1", "yes", "+", "t")
        return bool(value)
    if ftype.startswith("int"):
        return int(float(value))
    if ftype.startswith("float"):
        return float(value)
    if name == "valid":  # declared List[str] but handled above
        return value
    return str(value)


def read_config_file(path: str) -> Dict[str, str]:
    """Parse a LightGBM ``train.conf``-style file: one ``key = value`` per
    line, ``#`` comments (reference: Application::LoadParameters)."""
    out: Dict[str, str] = {}
    with open(path, "r") as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out
