"""Public ``Dataset`` / ``Booster`` API
(reference: python-package/lightgbm/basic.py:712,1666).

The reference wraps the C library through ctypes; here ``Dataset`` wraps the
host-side ``BinnedDataset`` construction and ``Booster`` drives the
device-resident boosting engine directly.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .config import Config
from .io.dataset import BinnedDataset
from .utils import log
from .utils.log import LightGBMError


def _is_scipy_sparse(data) -> bool:
    return hasattr(data, "toarray") and hasattr(data, "tocsr")


def _to_matrix(data) -> np.ndarray:
    """Accept numpy arrays, lists, pandas DataFrames, scipy sparse."""
    if hasattr(data, "values") and hasattr(data, "columns"):  # pandas
        return np.ascontiguousarray(data.values, dtype=np.float64)
    if _is_scipy_sparse(data):
        return np.ascontiguousarray(data.toarray(), dtype=np.float64)
    arr = np.asarray(data)
    if arr.ndim != 2:
        raise LightGBMError("Data should be 2-D")
    return np.ascontiguousarray(arr, dtype=np.float64)


def _feature_names_of(data) -> Optional[List[str]]:
    if hasattr(data, "columns"):
        return [str(c) for c in data.columns]
    return None


def _is_pandas_df(data) -> bool:
    return hasattr(data, "columns") and hasattr(data, "dtypes")


def _data_from_pandas(df, categorical_feature="auto",
                      pandas_categorical=None):
    """DataFrame -> (f64 matrix, names, categorical_feature,
    pandas_categorical).  category-dtype columns become their integer
    codes with NaN for missing; at predict/valid time the codes are
    aligned to the TRAIN-time category lists so the same string maps to
    the same code (reference: basic.py:313-354 _data_from_pandas)."""
    import pandas as pd
    cat_cols = [c for c in df.columns
                if isinstance(df[c].dtype, pd.CategoricalDtype)]
    unordered = [c for c in cat_cols if not df[c].cat.ordered]
    if cat_cols:
        df = df.copy()  # one copy covers both mutation passes below
    if pandas_categorical is None:  # train dataset defines the mapping
        pandas_categorical = [list(df[c].cat.categories) for c in cat_cols]
    else:
        if len(cat_cols) != len(pandas_categorical):
            raise LightGBMError("train and valid dataset "
                                "categorical_feature do not match.")
        for c, cats in zip(cat_cols, pandas_categorical):
            if list(df[c].cat.categories) != list(cats):
                df[c] = df[c].cat.set_categories(cats)
    if cat_cols:
        for c in cat_cols:
            codes = df[c].cat.codes.to_numpy().astype(np.float64)
            codes[codes == -1] = np.nan  # unseen/missing -> NaN
            df[c] = codes
    names = [str(c) for c in df.columns]
    if categorical_feature == "auto":
        categorical_feature = [names.index(str(c)) for c in unordered]
    mat = np.ascontiguousarray(df.to_numpy(dtype=np.float64))
    return mat, names, categorical_feature, pandas_categorical


class Dataset:
    """Training/validation dataset with lazy construction
    (reference: basic.py:712-1664)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name="auto", categorical_feature="auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True, silent: bool = False):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params) if params else {}
        self.free_raw_data = free_raw_data
        self._handle: Optional[BinnedDataset] = None
        self.used_indices: Optional[np.ndarray] = None
        self._matrix_cache: Optional[np.ndarray] = None

    # ------------------------------------------------------------------
    def _resolve_categorical(self, names: List[str]) -> List[int]:
        cats = self.categorical_feature
        if cats == "auto" or cats is None:
            cats = self.params.get("categorical_feature", [])
        out = []
        for c in cats or []:
            if isinstance(c, str):
                if c in names:
                    out.append(names.index(c))
            else:
                out.append(int(c))
        return sorted(set(out))

    def construct(self) -> "Dataset":
        """Build the binned representation (reference: _lazy_init,
        basic.py:819)."""
        if self._handle is not None:
            return self
        if self.data is None:
            raise LightGBMError("Cannot construct Dataset: raw data was freed")
        self.pandas_categorical = getattr(self, "pandas_categorical", None)
        if _is_pandas_df(self.data):
            ref_pc = (getattr(self.reference.construct(),
                              "pandas_categorical", None)
                      if self.reference is not None else None)
            mat, names, auto_cat, self.pandas_categorical = \
                _data_from_pandas(self.data, self.categorical_feature,
                                  ref_pc)
            if self.categorical_feature == "auto" and auto_cat:
                # keep "auto" when no category-dtype columns exist so the
                # params['categorical_feature'] fallback still applies
                self.categorical_feature = auto_cat
        elif _is_scipy_sparse(self.data):
            mat = self.data  # stays sparse; from_csr never densifies
            names = None
        else:
            mat = _to_matrix(self.data)
            names = _feature_names_of(self.data)
        if isinstance(self.feature_name, (list, tuple)):
            names = list(self.feature_name)
        if names is None:
            names = [f"Column_{i}" for i in range(mat.shape[1])]
        config = Config.from_params(self.params)
        ref_handle = None
        if self.reference is not None:
            ref_handle = self.reference.construct()._handle
        builder = (BinnedDataset.from_csr if _is_scipy_sparse(mat)
                   else BinnedDataset.from_matrix)
        self._handle = builder(
            mat, config,
            categorical_features=self._resolve_categorical(names),
            feature_names=names, reference=ref_handle)
        if self.label is not None:
            self.set_label(self.label)
        if self.weight is not None:
            self.set_weight(self.weight)
        if self.group is not None:
            self.set_group(self.group)
        if self.init_score is not None:
            self.set_init_score(self.init_score)
        if self.free_raw_data:
            self.data = None
        return self

    # ------------------------------------------------------------------
    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       params=params or self.params,
                       free_raw_data=self.free_raw_data)

    # -- field setters/getters (reference: set_field/get_field) --------
    def set_label(self, label) -> None:
        self.label = label
        if self._handle is not None:
            arr = np.asarray(
                label.values if hasattr(label, "values") else label)
            self._handle.metadata.set_label(arr.ravel())

    def set_weight(self, weight) -> None:
        self.weight = weight
        if self._handle is not None and weight is not None:
            self._handle.metadata.set_weights(np.asarray(weight).ravel())

    def set_group(self, group) -> None:
        self.group = group
        if self._handle is not None and group is not None:
            self._handle.metadata.set_query(np.asarray(group).ravel())

    def set_init_score(self, init_score) -> None:
        self.init_score = init_score
        if self._handle is not None and init_score is not None:
            self._handle.metadata.set_init_score(np.asarray(init_score).ravel())

    def get_label(self):
        if self._handle is not None and self._handle.metadata.label is not None:
            return self._handle.metadata.label
        return self.label

    def get_weight(self):
        return self.weight

    def get_group(self):
        return self.group

    def get_init_score(self):
        return self.init_score

    def get_data(self):
        return self.data

    def num_data(self) -> int:
        if self._handle is not None:
            return self._handle.num_data
        if _is_scipy_sparse(self.data):
            return self.data.shape[0]
        return _to_matrix(self.data).shape[0]

    def num_feature(self) -> int:
        if self._handle is not None:
            return self._handle.num_total_features
        if _is_scipy_sparse(self.data):
            return self.data.shape[1]
        return _to_matrix(self.data).shape[1]

    def get_feature_name(self) -> List[str]:
        self.construct()
        return list(self._handle.feature_names)

    def subset(self, used_indices: Sequence[int], params=None) -> "Dataset":
        """Row-subset view constructed in this dataset's bin space."""
        self.construct()
        if self.data is None:
            raise LightGBMError("Cannot subset: raw data was freed; "
                                "use free_raw_data=False")
        idx = np.asarray(used_indices)
        if self._matrix_cache is None:
            # sparse raw data row-slices sparsely — densifying a wide
            # sparse matrix here would defeat the no-densify CSR path
            self._matrix_cache = (self.data.tocsr()
                                  if _is_scipy_sparse(self.data)
                                  else _to_matrix(self.data))
        sub = Dataset(self._matrix_cache[idx], reference=self,
                      params=params or self.params,
                      free_raw_data=self.free_raw_data)
        if self.label is not None:
            sub.label = np.asarray(self.label)[idx]
        if self.weight is not None:
            sub.weight = np.asarray(self.weight)[idx]
        if self.init_score is not None:
            sub.init_score = np.asarray(self.init_score)[idx]
        if self.group is not None:
            # group sizes of the selected rows: count consecutive query ids
            sizes = np.asarray(self.group).ravel()
            qid = np.repeat(np.arange(len(sizes)), sizes)[idx]
            _, counts = np.unique(qid, return_counts=True)
            sub.group = counts
        sub.used_indices = idx
        return sub

    def save_binary(self, filename: str) -> "Dataset":
        """Serialize the constructed dataset (numpy archive rather than the
        reference's custom binary format; reference: dataset.h:416)."""
        self.construct()
        from .io.dataset_io import save_dataset
        save_dataset(self._handle, filename)
        return self


def _same_bin_mappers(a: BinnedDataset, b: BinnedDataset) -> bool:
    """True when two constructed datasets share bin mappings (reference:
    Dataset::CheckAlign semantics for validation data)."""
    if a.bin_mappers is b.bin_mappers:
        return True
    if len(a.bin_mappers) != len(b.bin_mappers):
        return False
    for ma, mb in zip(a.bin_mappers, b.bin_mappers):
        if (ma.num_bin != mb.num_bin or ma.bin_type != mb.bin_type
                or ma.missing_type != mb.missing_type
                or not np.array_equal(ma.bin_upper_bound, mb.bin_upper_bound)
                or ma.bin_2_categorical != mb.bin_2_categorical):
            return False
    return True


class Booster:
    """Trained model handle + training driver (reference: basic.py:1666+)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None, silent: bool = False):
        self.params = dict(params) if params else {}
        self.best_iteration = -1
        self.best_score: Dict = {}
        self._train_data_name = "training"
        self.train_set = None
        self.valid_sets: List[Dataset] = []
        self._gbdt = None

        if train_set is not None:
            if not isinstance(train_set, Dataset):
                raise TypeError(f"Training data should be Dataset instance, "
                                f"met {type(train_set).__name__}")
            self._init_train(train_set)
        elif model_file is not None:
            from .io.model_io import load_model_file
            self._gbdt, self.config = load_model_file(model_file)
            self.pandas_categorical = getattr(self._gbdt,
                                              "pandas_categorical", None)
        elif model_str is not None:
            from .io.model_io import load_model_string
            self._gbdt, self.config = load_model_string(model_str)
            self.pandas_categorical = getattr(self._gbdt,
                                              "pandas_categorical", None)
        else:
            raise TypeError("Need at least one training dataset or model "
                            "file or model string to create Booster instance")

    # ------------------------------------------------------------------
    def _init_train(self, train_set: Dataset) -> None:
        from .boosting import create_boosting
        from .metric import create_metrics
        from .objective import create_objective

        self.config = Config.from_params(self.params)
        train_set.params = {**train_set.params, **self.params}
        train_set.construct()
        self.train_set = train_set
        objective = create_objective(self.config)
        metrics = create_metrics(self.config)
        self._gbdt = create_boosting(self.config)
        self._gbdt.init(self.config, train_set._handle, objective, metrics)
        self.pandas_categorical = getattr(train_set, "pandas_categorical",
                                          None)

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        if not isinstance(data, Dataset):
            raise TypeError(f"Validation data should be Dataset instance, "
                            f"met {type(data).__name__}")
        # A valid set must be binned with the TRAINING set's mappers —
        # trees are replayed in bin space, so mismatched mappers silently
        # corrupt validation metrics (reference fails loudly:
        # 'Cannot add validation data, since it has different bin mappers
        # with training data', gbdt.cpp ResetTrainingData analog).
        if data._handle is None:
            if self.train_set is not None:
                data.reference = self.train_set
            data.construct()
        elif (self.train_set is not None and self.train_set._handle is not None
              and not _same_bin_mappers(data._handle,
                                        self.train_set._handle)):
            raise LightGBMError(
                "Cannot add validation data, since it has different bin "
                "mappers with training data; construct it with "
                "reference=train_set")
        self._gbdt.add_valid(data._handle, name)
        self.valid_sets.append(data)
        return self

    # ------------------------------------------------------------------
    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration; True = no further splits possible
        (reference: basic.py:2050, c_api LGBM_BoosterUpdateOneIter)."""
        if train_set is not None and train_set is not self.train_set:
            raise LightGBMError("Resetting the training set is not supported; "
                                "create a new Booster instead")
        if fobj is None:
            return self._gbdt.train_one_iter()
        grad, hess = fobj(self._raw_train_score(), self.train_set)
        return self._gbdt.train_one_iter(np.asarray(grad), np.asarray(hess))

    def _raw_train_score(self) -> np.ndarray:
        s = np.asarray(self._gbdt._train_score, dtype=np.float64)
        return s[:, 0] if self._gbdt.num_tpi == 1 else s

    def rollback_one_iter(self) -> "Booster":
        self._gbdt.rollback_one_iter()
        return self

    def refit(self, data, label, decay_rate: float = 0.9,
              **kwargs) -> "Booster":
        """Refit this model's tree structures to new data: leaf outputs
        become ``decay_rate * old + (1 - decay_rate) * new`` (reference:
        basic.py:2547 Booster.refit -> GBDT::RefitTree gbdt.cpp:298-321)."""
        import copy
        if self._gbdt is None or self._gbdt.num_trees == 0:
            raise LightGBMError("Cannot refit an empty model")
        if getattr(self._gbdt, "objective", None) is None:
            raise LightGBMError("Cannot refit due to null objective function.")
        params = dict(self.params or {})
        params["refit_decay_rate"] = decay_rate
        params.update(kwargs)
        # file-loaded boosters carry no params: seed the objective from
        # the model's minimal config, or the refit trainer would compute
        # REGRESSION gradients for a binary/multiclass forest
        if "objective" not in params and self.config is not None:
            params["objective"] = self.config.objective
            if self.config.num_class > 1:
                params["num_class"] = self.config.num_class
        new_set = Dataset(data, label=label, params=params)
        nb = Booster(params=params, train_set=new_set)
        nb._gbdt.load_initial_models(
            [copy.deepcopy(t) for t in self._gbdt.models],
            replay_scores=False)  # refit rebuilds scores from scratch
        nb._gbdt.refit_models(decay_rate)
        return nb

    def current_iteration(self) -> int:
        return self._gbdt.current_iteration()

    def num_trees(self) -> int:
        return self._gbdt.num_trees

    def num_model_per_iteration(self) -> int:
        return self._gbdt.num_tpi

    # ------------------------------------------------------------------
    def eval_train(self, feval=None) -> List:
        return [e for e in self._eval_all(feval)
                if e[0] == self._train_data_name]

    def eval_valid(self, feval=None) -> List:
        return [e for e in self._eval_all(feval, include_train=False)
                if e[0] != self._train_data_name]

    def eval(self, data=None, name=None, feval=None) -> List:
        if data is None:
            return self._eval_all(feval)
        if data is self.train_set:
            return self.eval_train(feval)
        for i, vds in enumerate(self.valid_sets):
            if data is vds:
                want = self._gbdt.valid_names[i]
                return [e for e in self._eval_all(feval) if e[0] == want]
        raise LightGBMError("Can only evaluate the training set or a dataset "
                            "previously attached with add_valid")

    def _eval_all(self, feval=None, include_train: bool = True) -> List:
        out = []
        for ds_name, mname, value, hib in self._gbdt.eval_results(
                include_train=include_train):
            if ds_name == "training":
                ds_name = self._train_data_name
            out.append((ds_name, mname, value, hib))
        if feval is not None:
            def run_feval(score, dataset, tag):
                # custom metrics receive TRANSFORMED predictions, like the
                # reference (feval(self.__inner_predict(i), data) where
                # GetPredict applies the objective's ConvertOutput)
                obj = self._gbdt.objective
                preds = np.asarray(obj.convert_output(score)) \
                    if obj is not None else score
                res = feval(preds, dataset)
                if res is None:
                    return
                entries = res if isinstance(res, list) else [res]
                for (n, v, hb) in entries:
                    out.append((tag, n, v, hb))
            if include_train:
                run_feval(self._raw_train_score(), self.train_set,
                          self._train_data_name)
            for i, vds in enumerate(self.valid_sets):
                s = np.asarray(self._gbdt._valid_scores[i], dtype=np.float64)
                s = s[:, 0] if self._gbdt.num_tpi == 1 else s
                run_feval(s, vds, self._gbdt.valid_names[i])
        return out

    # ------------------------------------------------------------------
    def predict(self, data, num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, start_iteration: int = 0,
                **kwargs) -> np.ndarray:
        if _is_pandas_df(data) and getattr(self, "pandas_categorical",
                                           None) is not None:
            mat, _, _, _ = _data_from_pandas(
                data, categorical_feature=None,
                pandas_categorical=self.pandas_categorical)
        elif _is_scipy_sparse(data):
            if data.shape[1] > self.num_feature():
                # reject BEFORE the block-wise densify below — a too-wide
                # matrix means the caller's feature space is not the
                # model's (the reference C API fails the same way:
                # 'The number of features in data ... is not the same as
                # it was in training data')
                raise LightGBMError(
                    f"The number of features in data ({data.shape[1]}) is "
                    f"not the same as it was in training data "
                    f"({self.num_feature()})")
            if data.shape[1] < self.num_feature():
                # LibSVM-style input sizes by the max feature index
                # PRESENT; pad implicit-zero columns up to the model's
                # feature count (the reference pads the same way)
                import scipy.sparse as sp
                pad = sp.csr_matrix((data.shape[0],
                                     self.num_feature() - data.shape[1]))
                data = sp.hstack([data.tocsr(), pad], format="csr")
            # block-wise densify, ~128MB of dense cells per block: bounded
            # memory on wide sparse inputs (the reference predicts sparse
            # rows natively, predictor.hpp:140-180; row blocks are the
            # dense-core analog)
            block = max(256, (1 << 24) // max(data.shape[1], 1))
            if data.shape[0] > block:
                csr = data.tocsr()
                blocks = [
                    self.predict(csr[i:i + block],
                                 num_iteration=num_iteration,
                                 raw_score=raw_score, pred_leaf=pred_leaf,
                                 pred_contrib=pred_contrib,
                                 start_iteration=start_iteration, **kwargs)
                    for i in range(0, csr.shape[0], block)]
                return np.concatenate(blocks, axis=0)
            mat = _to_matrix(data)
        else:
            mat = _to_matrix(data)
        # sparse input was padded to the model width above (LibSVM-style
        # narrower matrices); anything else must match exactly — the
        # reference C API raises the same error both directions, and a
        # narrower dense matrix would otherwise die in an IndexError
        # deep inside binning
        if mat.shape[1] != self.num_feature():
            raise LightGBMError(
                f"The number of features in data ({mat.shape[1]}) is not "
                f"the same as it was in training data "
                f"({self.num_feature()})")
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        if pred_leaf:
            return self._gbdt.predict_leaf(mat, num_iteration, start_iteration)
        if pred_contrib:
            # routes heavy inputs through the batched device TreeSHAP
            # kernel (explain/) when a device is available; small inputs
            # and count-less models stay on the host oracle (core/shap)
            return self._gbdt.predict_contrib(mat, num_iteration,
                                              start_iteration)
        return self._gbdt.predict(mat, num_iteration, raw_score,
                                  start_iteration)

    # ------------------------------------------------------------------
    def save_model(self, filename: str, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> "Booster":
        with open(filename, "w") as fh:
            fh.write(self.model_to_string(num_iteration, start_iteration))
        # quality-profile sidecar (obs/drift.py): boosters that still
        # hold their training dataset persist the reference
        # distribution beside the model so serving can arm drift
        # monitoring; the model text format itself stays untouched
        # (reference-compatible).  Never lets profiling fail a save.
        cfg = getattr(self._gbdt, "config", None) if self._gbdt else None
        if (self._gbdt is not None
                and getattr(self._gbdt, "train_ds", None) is not None
                and (cfg is None
                     or getattr(cfg, "tpu_quality_profile", True))):
            from .obs.drift import profile_path
            try:
                prof = self._gbdt.quality_profile()
                if prof is not None:
                    prof.save(profile_path(filename))
            except Exception as exc:  # noqa: BLE001
                log.warning("quality profile sidecar skipped: %s", exc)
        return self

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0) -> str:
        from .io.model_io import model_to_string
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        txt = model_to_string(self._gbdt, num_iteration, start_iteration)
        pc = getattr(self, "pandas_categorical", None)
        if pc is not None:
            # appended like the reference python package so string/file
            # round-trips keep the category->code mapping
            # (reference: basic.py:367 _dump_pandas_categorical); omitted
            # when absent to stay byte-identical with reference CLI files
            import json as _json

            from .compat import json_default_with_numpy
            txt += ("\npandas_categorical:"
                    + _json.dumps(pc, default=json_default_with_numpy)
                    + "\n")
        return txt

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0) -> Dict[str, Any]:
        """Model as a nested dict, the reference's JSON dump structure
        (reference: GBDT::DumpModel, gbdt_model_text.cpp:20-85; python
        Booster.dump_model, basic.py:2243)."""
        from .io.model_json import dump_model
        if num_iteration is None:
            num_iteration = self.best_iteration if self.best_iteration > 0 else -1
        return dump_model(self._gbdt, num_iteration, start_iteration)

    def model_to_if_else(self, num_iteration: Optional[int] = None) -> str:
        """Standalone C scoring code for the forest (reference:
        GBDT::ModelToIfElse, gbdt_model_text.cpp:88-270 — the CLI
        ``task=convert_model`` output)."""
        from .io.model_json import model_to_if_else
        return model_to_if_else(self._gbdt, num_iteration)

    def feature_importance(self, importance_type: str = "split",
                           iteration=None) -> np.ndarray:
        return self._gbdt.feature_importance(
            importance_type, num_iteration=-1 if iteration is None
            else int(iteration))

    def feature_name(self) -> List[str]:
        if self._gbdt.train_ds is not None:
            return list(self._gbdt.train_ds.feature_names)
        return list(getattr(self._gbdt, "feature_names", []))

    def num_feature(self) -> int:
        if self._gbdt.train_ds is not None:
            return self._gbdt.train_ds.num_total_features
        return len(self.feature_name())

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        self.params.update(params)
        self.config.update(params)
        if self._gbdt is not None:
            # file-loaded boosters start with config=None; adopting the
            # updated Booster config is what lets prediction-time knobs
            # (pred_early_stop*) reach them
            self._gbdt.config = self.config
            if self._gbdt.train_ds is not None:
                self._gbdt.shrinkage_rate = float(self.config.learning_rate)
        return self

    def free_dataset(self) -> "Booster":
        self.train_set = None
        return self

    # ------------------------------------------------------------------
    # pickling / copying: serialize through the model text, like the
    # reference Booster's __getstate__ (reference: basic.py:1875-1904 —
    # the handle cannot cross processes; the model string can). The
    # unpickled booster is prediction-ready; training state is not
    # carried (same as the reference unless free_raw_data=False).
    def __getstate__(self) -> Dict[str, Any]:
        state = {"params": self.params,
                 "best_iteration": self.best_iteration,
                 "best_score": self.best_score,
                 "model_str": self.model_to_string(num_iteration=-1)}
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        from .io.model_io import load_model_string
        self.params = state.get("params", {})
        self.best_iteration = state.get("best_iteration", -1)
        self.best_score = state.get("best_score", {})
        self._train_data_name = "training"
        self.train_set = None
        self.valid_sets = []
        self._gbdt, self.config = load_model_string(state["model_str"])
        self.pandas_categorical = getattr(self._gbdt, "pandas_categorical",
                                          None)

    def __copy__(self) -> "Booster":
        return self.__deepcopy__(None)

    def __deepcopy__(self, memo) -> "Booster":
        new = Booster(model_str=self.model_to_string(num_iteration=-1))
        new.params = dict(self.params)
        new.best_iteration = self.best_iteration
        return new

    def get_split_value_histogram(self, feature, bins=None,
                                  xgboost_style: bool = False):
        """Histogram of split threshold values used for ``feature`` across
        the forest (reference: basic.py:2583 Booster.
        get_split_value_histogram)."""
        if isinstance(feature, str):
            feature = self.feature_name().index(feature)
        values = []
        for tree in self._gbdt.models:
            nn = max(tree.num_leaves - 1, 0)
            for i in range(nn):
                if (int(tree.split_feature[i]) == feature
                        and not tree.is_categorical(i)):
                    values.append(float(tree.threshold[i]))
        values = np.asarray(values, np.float64)
        if bins is None or (isinstance(bins, int) and bins > len(values)):
            bins = max(len(values), 1)
        hist, edges = np.histogram(values, bins=bins)
        if not xgboost_style:
            return hist, edges
        import pandas as pd
        mask = hist != 0
        return pd.DataFrame({"SplitValue": edges[1:][mask],
                             "Count": hist[mask]})

    def trees_to_dataframe(self):
        """One row per node/leaf of every tree (reference: basic.py:2757
        Booster.trees_to_dataframe)."""
        import pandas as pd
        names = self.feature_name()
        rows = []

        def walk(tree, ti, node, depth, parent):
            if node >= 0:  # internal
                idx = f"{ti}-S{node}"
                f = int(tree.split_feature[node])
                rows.append(dict(
                    tree_index=ti, node_depth=depth, node_index=idx,
                    left_child=_child_name(tree, ti, tree.left_child[node]),
                    right_child=_child_name(tree, ti, tree.right_child[node]),
                    parent_index=parent,
                    split_feature=names[f] if f < len(names) else str(f),
                    split_gain=float(tree.split_gain[node]),
                    threshold=float(tree.threshold[node]),
                    decision_type="==" if tree.is_categorical(node)
                    else "<=",
                    missing_direction="left"
                    if (tree.decision_type[node] & 2) else "right",
                    value=float(tree.internal_value[node]),
                    weight=float(tree.internal_weight[node]),
                    count=int(tree.internal_count[node])))
                walk(tree, ti, int(tree.left_child[node]), depth + 1, idx)
                walk(tree, ti, int(tree.right_child[node]), depth + 1, idx)
            else:
                leaf = ~node
                rows.append(dict(
                    tree_index=ti, node_depth=depth,
                    node_index=f"{ti}-L{leaf}", left_child=None,
                    right_child=None, parent_index=parent,
                    split_feature=None, split_gain=None, threshold=None,
                    decision_type=None, missing_direction=None,
                    value=float(tree.leaf_value[leaf]),
                    weight=float(tree.leaf_weight[leaf]),
                    count=int(tree.leaf_count[leaf])))

        def _child_name(tree, ti, child):
            return f"{ti}-S{child}" if child >= 0 else f"{ti}-L{~child}"

        for ti, tree in enumerate(self._gbdt.models):
            walk(tree, ti, 0 if tree.num_leaves > 1 else ~0, 1, None)
        return pd.DataFrame(rows)

    def free_network(self) -> "Booster":
        from .parallel.distributed import shutdown
        shutdown()  # tears down jax.distributed AND resets NETWORK
        return self

    def set_network(self, machines, local_listen_port: int = 12400,
                    listen_time_out: int = 120, num_machines: int = 1) -> "Booster":
        """Record the machine topology; like the reference, the network
        itself comes up when a Booster binds to training data — here via
        ``parallel.distributed.init_distributed`` (jax.distributed) instead
        of the reference's TCP linkers (reference: basic.py set_network ->
        Network::Init, network.cpp:24-74)."""
        from .parallel import mesh as _mesh
        from .parallel.distributed import parse_machine_list
        if not isinstance(machines, str):
            machines = ",".join(str(m) for m in machines)
        hosts = parse_machine_list(machines, default_port=local_listen_port)
        _mesh.NETWORK.update(machines=",".join(hosts),
                             num_machines=int(num_machines),
                             local_listen_port=int(local_listen_port),
                             time_out=int(listen_time_out))
        return self
