"""Public ``Dataset`` / ``Booster`` API (reference: python-package/lightgbm/basic.py).

Placeholder — filled in as the training engine lands.
"""
from __future__ import annotations


class Dataset:  # pragma: no cover - placeholder
    def __init__(self, *a, **kw):
        raise NotImplementedError("Dataset lands with the training engine")


class Booster:  # pragma: no cover - placeholder
    def __init__(self, *a, **kw):
        raise NotImplementedError("Booster lands with the training engine")
