"""Plotting utilities
(reference: python-package/lightgbm/plotting.py — same public signatures;
matplotlib-based, graphviz optional for tree digraphs)."""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .basic import Booster
from .utils.log import LightGBMError


def _check_not_tuple_of_2_elements(obj, obj_name: str) -> None:
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a tuple of 2 elements.")


def _to_booster(booster) -> Booster:
    from .sklearn import LGBMModel
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be Booster or LGBMModel.")


def plot_importance(booster, ax=None, height: float = 0.2, xlim=None,
                    ylim=None, title="Feature importance",
                    xlabel="Feature importance", ylabel="Features",
                    importance_type="split", max_num_features=None,
                    ignore_zero=True, figsize=None, dpi=None, grid=True,
                    precision=3, **kwargs):
    """(reference: plotting.py:23-137 plot_importance)."""
    import matplotlib.pyplot as plt

    bst = _to_booster(booster)
    importance = np.asarray(bst.feature_importance(importance_type))
    names = bst.feature_name()
    if not len(importance):
        raise ValueError("Booster's feature_importance is empty.")

    tuples: List[Tuple[str, float]] = sorted(
        zip(names, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [t for t in tuples if t[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("No features with non-zero importance.")
    labels, values = zip(*tuples)

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y,
                f"{x:.{precision}f}" if importance_type == "gain" else str(int(x)),
                va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, max(values) * 1.1)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (-1, len(values))
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim=None, ylim=None, title="Metric during training",
                xlabel="Iterations", ylabel="auto", figsize=None, dpi=None,
                grid=True):
    """(reference: plotting.py:140-260 plot_metric)."""
    import matplotlib.pyplot as plt

    if isinstance(booster, Booster):
        raise TypeError("booster must be dict or LGBMModel; pass "
                        "evals_result from train() or a fitted sklearn "
                        "estimator.")
    from .sklearn import LGBMModel
    if isinstance(booster, LGBMModel):
        eval_results = booster.evals_result_
    elif isinstance(booster, dict):
        eval_results = booster
    else:
        raise TypeError("booster must be dict or LGBMModel.")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    names = dataset_names or list(eval_results.keys())
    first = eval_results[names[0]]
    if metric is None:
        metric = next(iter(first))
    num_iters = 0
    for name in names:
        if metric not in eval_results[name]:
            continue
        vals = eval_results[name][metric]
        num_iters = max(num_iters, len(vals))
        ax.plot(range(len(vals)), vals, label=name)
    ax.legend(loc="best")
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, num_iters)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if ylabel == "auto":
        ylabel = metric
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8, xlim=None, ylim=None,
                               title="Split value histogram for feature with "
                                     "@index/name@ @feature@",
                               xlabel="Feature split value", ylabel="Count",
                               figsize=None, dpi=None, grid=True, **kwargs):
    """(reference: plotting.py:263-366)."""
    import matplotlib.pyplot as plt

    bst = _to_booster(booster)
    if isinstance(feature, str) and feature not in bst.feature_name():
        raise ValueError(f"feature {feature!r} not found")
    hist, bin_edges = bst.get_split_value_histogram(
        feature, bins="auto" if bins is None else bins)
    if hist.sum() == 0:
        raise ValueError("Cannot plot split value histogram, the feature "
                         "was never used for splitting.")
    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    width = width_coef * (bin_edges[1] - bin_edges[0])
    centers = (bin_edges[:-1] + bin_edges[1:]) / 2.0
    ax.bar(centers, hist, width=width, **kwargs)
    if title is not None:
        title = title.replace("@index/name@",
                              "name" if isinstance(feature, str) else "index")
        title = title.replace("@feature@", str(feature))
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _tree_to_digraph(tree, feature_names, precision: int = 3, **kwargs):
    import graphviz
    graph = graphviz.Digraph(**kwargs)

    def node_name(i, leaf):
        return f"leaf{i}" if leaf else f"split{i}"

    def add(i, leaf, parent=None, decision=None):
        if leaf:
            label = f"leaf {i}: {tree.leaf_value[i]:.{precision}f}"
            graph.node(node_name(i, True), label=label)
        else:
            f = int(tree.split_feature[i])
            fname = (feature_names[f] if feature_names
                     and f < len(feature_names) else f"Column_{f}")
            if tree.is_categorical(i):
                label = f"{fname} in categories"
            else:
                label = f"{fname} <= {tree.threshold[i]:.{precision}f}"
            graph.node(node_name(i, False), label=label, shape="rectangle")
            for child, dec in ((int(tree.left_child[i]), "yes"),
                               (int(tree.right_child[i]), "no")):
                if child >= 0:
                    add(child, False, node_name(i, False), dec)
                else:
                    add(~child, True, node_name(i, False), dec)
        if parent is not None:
            graph.edge(parent, node_name(i, leaf), decision)

    if tree.num_leaves <= 1:
        add(0, True)
    else:
        add(0, False)
    return graph


def create_tree_digraph(booster, tree_index: int = 0, show_info=None,
                        precision: int = 3, **kwargs):
    """(reference: plotting.py:473-540)."""
    bst = _to_booster(booster)
    models = list(bst._gbdt.models)
    if not 0 <= tree_index < len(models):
        raise IndexError("tree_index is out of range.")
    return _tree_to_digraph(models[tree_index], bst.feature_name(),
                            precision, **kwargs)


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None, dpi=None,
              show_info=None, precision: int = 3, **kwargs):
    """(reference: plotting.py:369-470) — renders the graphviz digraph into
    a matplotlib axes (needs the graphviz binary)."""
    import matplotlib.image as mpimg
    import matplotlib.pyplot as plt

    graph = create_tree_digraph(booster, tree_index=tree_index,
                                precision=precision, **kwargs)
    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    import io as _io
    s = _io.BytesIO(graph.pipe(format="png"))
    img = mpimg.imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
