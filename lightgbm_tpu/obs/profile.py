"""Profile mode: per-kernel cost attribution against the analytical roofline.

The telemetry layer (``core``) records *what* happened per iteration; this
module explains *why it is slow*.  With the gate on (``LGBM_TPU_PROFILE=1``
or the ``tpu_profile`` parameter) every profiled compiled program — the
jitted units the trainer dispatches, each named after the ``lgbm/*`` scope
it wraps — is:

- **sync-bracketed**: ``block_until_ready`` after every call, so the
  measured time is device compute, not enqueue (this deliberately breaks
  the training loop's async pipelining — profile mode is for attribution
  runs, never for benchmark numbers);
- **cost-analyzed**: FLOPs and bytes-accessed come from XLA's own
  ``lowered.compile().cost_analysis()``, cached per input signature;
- **roofline-scored**: achieved time is compared against
  ``max(flops/peak_flops, bytes/peak_bw)`` for the local device (peaks
  from the table below, overridable via env), and a ``kernel_profile``
  event carries the fraction — ``docs/ROOFLINE.md``'s hand-written model,
  machine-checked on every run.

Everything is OFF-path when disabled: ``wrap`` returns its argument
unchanged, so the hot loop sees zero new code.  Events only reach disk
when a telemetry sink is configured (``core.event`` gates); without one,
the per-kernel aggregates still accumulate and surface in
``obs.digest()`` (which ``bench.py`` embeds).
"""
from __future__ import annotations

import os
import sys
import time
from typing import Dict, Optional, Tuple

from ..utils import log
from . import core

# (device_kind substring, peak FLOP/s, peak HBM bytes/s).  Matmul peaks are
# the bf16 numbers — the histogram kernels run bf16/f32 MXU passes and the
# roofline model in docs/ROOFLINE.md uses the same convention.  First match
# wins; the CPU fallback is a deliberately rough single-core estimate (the
# CPU path exists for smoke-testing the machinery, not for CPU rooflines).
DEVICE_PEAKS = (
    ("v6", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5", 394e12, 820e9),       # v5e (docs/ROOFLINE.md's chip)
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
    ("cpu", 100e9, 20e9),
)

_env = os.environ.get("LGBM_TPU_PROFILE", "")
_on = _env not in ("", "0", "false")

_agg: Dict[str, dict] = {}   # kernel name -> aggregate record
_ca_warned = set()


def profile_enabled() -> bool:
    """True when profile mode is on (env LGBM_TPU_PROFILE or enable)."""
    return _on


_announced = False


def enable_profile(on: bool = True) -> None:
    """Flip the PROCESS-WIDE profile gate (same scope as the telemetry
    sink: ``tpu_profile`` on one Booster leaves it on for every later
    Booster until ``enable_profile(False)``).  Takes effect for boosters
    built AFTER the flip — instrumentation is decided when the jitted
    closures are wrapped at Booster init, not per call."""
    global _on, _announced
    _on = bool(on)
    core._set_profile_active(_on)
    if _on and not _announced:
        _announced = True
        log.info("profile mode ON for the rest of the process: every "
                 "phase/kernel is sync-bracketed (async dispatch "
                 "disabled) — do not read throughput numbers from this "
                 "run; obs.enable_profile(False) turns it off")


_unknown_kind_warned = set()


def device_peaks(device=None) -> Tuple[float, float]:
    """(peak FLOP/s, peak HBM bytes/s) for ``device`` (default: local
    device 0).  ``LGBM_TPU_PEAK_FLOPS`` / ``LGBM_TPU_PEAK_BW`` override
    the table (each independently) — set them when profiling a chip the
    table mispredicts; an unrecognized device_kind warns once and uses
    the conservative CPU-class fallback."""
    env_f = os.environ.get("LGBM_TPU_PEAK_FLOPS", "")
    env_b = os.environ.get("LGBM_TPU_PEAK_BW", "")
    kind = "cpu"
    jx = sys.modules.get("jax")
    if jx is not None:
        try:
            d = device if device is not None else jx.devices()[0]
            kind = str(d.device_kind).lower()
        except Exception:  # noqa: BLE001 — backend not up yet
            pass
    base = None
    for sub, fl, bw in DEVICE_PEAKS:
        if sub in kind:
            base = (fl, bw)
            break
    if base is None:
        if kind not in _unknown_kind_warned:
            _unknown_kind_warned.add(kind)
            log.warning("device_kind %r not in the peak table; roofline "
                        "fractions use CPU-class fallback peaks — set "
                        "LGBM_TPU_PEAK_FLOPS / LGBM_TPU_PEAK_BW for real "
                        "numbers", kind)
        base = (100e9, 20e9)
    return (float(env_f) if env_f else base[0],
            float(env_b) if env_b else base[1])


def device_kind() -> str:
    jx = sys.modules.get("jax")
    if jx is None:
        return "unknown"
    try:
        return str(jx.devices()[0].device_kind)
    except Exception:  # noqa: BLE001
        return "unknown"


def roofline_seconds(flops: float, nbytes: float,
                     peaks: Optional[Tuple[float, float]] = None) -> float:
    """Analytical floor time: the slower of the compute and memory legs."""
    pf, pb = peaks if peaks is not None else device_peaks()
    return max(flops / pf if pf else 0.0, nbytes / pb if pb else 0.0)


def cost_analysis_dict(compiled) -> dict:
    """Normalize ``compiled.cost_analysis()`` across jax versions (a dict
    in newer jax, a one-element list of dicts in 0.4.x)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def extract_cost(ca: dict) -> Tuple[float, float]:
    """(flops, bytes accessed) from an XLA cost-analysis dict."""
    return (float(ca.get("flops", 0.0) or 0.0),
            float(ca.get("bytes accessed", 0.0) or 0.0))


def _sig(args, kwargs):
    import jax
    leaves, _ = jax.tree_util.tree_flatten((args, kwargs))
    out = []
    for x in leaves:
        shp = getattr(x, "shape", None)
        if shp is not None:
            out.append((tuple(shp), str(getattr(x, "dtype", ""))))
        else:
            out.append(repr(x))
    return tuple(out)


def record_kernel(name: str, flops: float, nbytes: float, achieved_s: float,
                  phase: str = None, **extra) -> None:
    """Fold one kernel execution into the aggregates + emit its
    ``kernel_profile`` event.  Also the entry point for ANALYTICAL
    attributions (kernels fused inside a larger program whose work is
    known from the model, e.g. the wave kernel's rows-histogrammed count —
    pass ``source="analytical"``).  ``phase`` overrides the phase
    attribution for callers emitting outside the phase timer that did the
    work (the per-iteration analytical records)."""
    rf = roofline_seconds(flops, nbytes)
    frac = rf / achieved_s if achieved_s > 0 else 0.0
    a = _agg.get(name)
    if a is None:
        a = _agg[name] = {"calls": 0, "achieved_s": 0.0, "flops": 0.0,
                          "bytes": 0.0, "roofline_s": 0.0, "best_frac": 0.0}
    a["calls"] += 1
    a["achieved_s"] += achieved_s
    a["flops"] += flops
    a["bytes"] += nbytes
    a["roofline_s"] += rf
    a["best_frac"] = max(a["best_frac"], frac)
    core.event("kernel_profile", kernel=name,
               phase=phase if phase is not None else core.current_phase(),
               flops=flops, bytes=nbytes, achieved_s=round(achieved_s, 6),
               roofline_s=round(rf, 9), roofline_frac=round(frac, 6),
               device=device_kind(), **extra)


class _Profiled:
    """Sync-bracketing, cost-analyzing wrapper around one jitted callable.

    The cost analysis is cached per input signature (shapes/dtypes/static
    values), so steady-state calls pay one time read + one sync — exactly
    the bracketing profile mode promises."""

    __slots__ = ("name", "fn", "_costs")

    def __init__(self, name: str, fn):
        self.name = name
        self.fn = fn
        self._costs: Dict[tuple, Tuple[float, float]] = {}

    def __call__(self, *args, **kwargs):
        if not _on:
            return self.fn(*args, **kwargs)
        import jax
        key = _sig(args, kwargs)
        cost = self._costs.get(key)
        if cost is None:
            try:
                ca = cost_analysis_dict(
                    self.fn.lower(*args, **kwargs).compile())
                cost = extract_cost(ca)
            except Exception as exc:  # noqa: BLE001 — AOT API varies
                if self.name not in _ca_warned:
                    _ca_warned.add(self.name)
                    log.warning("cost_analysis unavailable for %s (%s); "
                                "profiling time only", self.name, exc)
                cost = (0.0, 0.0)
            self._costs[key] = cost
            # warm the jit dispatch cache: the AOT lower().compile()
            # above does NOT populate it, so without this untimed call
            # the first recorded achieved_s would be dominated by
            # trace+compile and poison the roofline aggregates (the fn
            # is pure; the duplicated device work is profile-mode cost)
            jax.block_until_ready(self.fn(*args, **kwargs))
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        jax.block_until_ready(out)
        record_kernel(self.name, cost[0], cost[1],
                      time.perf_counter() - t0)
        return out


def wrap(name: str, fn):
    """Instrument a jitted callable under ``lgbm/<name>``-style naming.
    Identity when profiling is off — the disabled path costs nothing.

    When the xprof plane is armed, the retrace watcher composes outside
    the profiled wrapper (the wrapper still needs the raw ``lower()``),
    so every ``wrap`` point gets retrace attribution for free."""
    if fn is None:
        return fn
    from . import xprof  # lazy: avoids import work on the off path
    if isinstance(fn, xprof._Watched):  # already fully wrapped
        return fn
    if _on and not isinstance(fn, _Profiled):
        fn = _Profiled(name, fn)
    return xprof.watch_jit(name, fn)


def profile_digest() -> dict:
    """Per-kernel aggregates for ``obs.digest()`` / bench embedding."""
    out = {}
    for name, a in _agg.items():
        ach = a["achieved_s"]
        out[name] = {
            "calls": a["calls"],
            "achieved_s": round(ach, 6),
            "flops": a["flops"],
            "bytes": a["bytes"],
            "roofline_s": round(a["roofline_s"], 9),
            "roofline_frac": round(a["roofline_s"] / ach, 6) if ach else 0.0,
            "best_frac": round(a["best_frac"], 6),
        }
    return out


def reset_profile() -> None:
    _agg.clear()


core._register_reset(reset_profile)
if _on:
    core._set_profile_active(True)
