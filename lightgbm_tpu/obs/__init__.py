"""Observability subsystem: structured telemetry, phase timers, JAX
instrumentation (see ``core`` for the event/counter API, ``trace`` for
the recompile hook, ``profile`` for kernel cost attribution, ``memory``
for the HBM census, ``report`` for JSONL merging).

Quick start::

    LGBM_TPU_TELEMETRY=/tmp/telem python train.py
    python tools/telemetry_report.py /tmp/telem

or programmatically ``obs.enable("/tmp/telem")`` / the ``tpu_telemetry``
parameter.  ``LGBM_TPU_TIMETAG=1`` keeps the plain phase-time report.
``LGBM_TPU_PROFILE=1`` (or ``tpu_profile``) adds the sync-bracketed
profile mode: per-kernel ``kernel_profile`` events with cost-analysis
FLOPs/bytes and roofline fractions, plus ``memory_census`` snapshots.
``LGBM_TPU_HEALTH=monitor|strict`` (or ``tpu_health``) arms the
training-health sentinels (``health``): per-iteration numerics guards,
model-state fingerprints, and the cross-rank divergence audit.
``LGBM_TPU_TRACE=1`` (or ``tpu_trace``) turns on the span layer
(``spans``): request/iteration trace events one schema wide, exported to
Perfetto by ``tools/trace_export.py``; ``LGBM_TPU_FLIGHT=<n>`` (or
``tpu_flight_len``) sizes the flight recorder ring dumped as
``FLIGHT_rN.json`` on degradations and health aborts.
``LGBM_TPU_XPROF=1`` (or ``tpu_xprof``) arms the measured-roofline
plane (``xprof``): a windowed ``jax.profiler`` capture around a few
mid-train iterations, parsed and attributed per ``lgbm/*`` scope into
``kernel_measured`` events, plus compile walls / cache traffic /
retrace attribution as ``compile`` events.
"""
from .board import TrainBoard
from .board import active as board_active
from .board import current as train_board
from .core import (TIMETAG_ENABLED, add, count, counter_value,
                   counters_snapshot, current_phase, digest, disable,
                   enable, enabled, event, gauge, phase, phase_delta,
                   phase_snapshot, record_collective,
                   record_collective_host, report, reset, sink_path, sync,
                   tracing_enabled)
from .drift import (DriftMonitor, DriftSketch, QualityProfile,
                    accumulate_occupancy, bin_features, coarsen,
                    compute_occupancy, init_occupancy, ks, profile_path,
                    psi)
from .health import (TrainingHealthError, check_gradients, check_score,
                     check_tree, divergence_audit, enable_health,
                     health_enabled, health_mode, model_fingerprint)
from .memory import (audit as memory_audit, expect_released, memory_digest,
                     peak_bytes)
from .memory import snapshot as memory_snapshot
from .profile import (device_peaks, enable_profile, profile_digest,
                      profile_enabled, record_kernel, roofline_seconds)
from .profile import wrap as profile_wrap
from .spans import (Span, begin_span, current_context, emit_span,
                    enable_flight, enable_trace, end_span, flight_dump,
                    flight_enabled, flight_len, flight_len_from_env,
                    flight_snapshot, new_span_id, new_trace_id, span,
                    span_record_enabled, trace_enabled)
from .ranks import RankAggregator, Reconciler, StragglerDetector, skew_table
from .trace import compile_count, compile_seconds, install_recompile_hook
from .xprof import (WindowedCapture, attribute, compile_digest,
                    install_compile_observer, maybe_window,
                    measured_rooflines, parse_trace_dir, record_measured,
                    resolve_trace_dir, resolve_window, trace_files,
                    train_context, watch_jit, xprof_digest)

__all__ = [
    "TIMETAG_ENABLED", "add", "count", "counter_value",
    "counters_snapshot", "current_phase", "digest", "disable", "enable",
    "enabled", "event", "gauge", "phase", "phase_delta", "phase_snapshot",
    "record_collective", "record_collective_host", "report", "reset",
    "sink_path", "sync", "tracing_enabled",
    "DriftMonitor", "DriftSketch", "QualityProfile",
    "accumulate_occupancy", "bin_features", "coarsen",
    "compute_occupancy", "init_occupancy", "ks", "profile_path", "psi",
    "compile_count", "compile_seconds", "install_recompile_hook",
    "device_peaks", "enable_profile", "profile_digest", "profile_enabled",
    "profile_wrap", "record_kernel", "roofline_seconds",
    "memory_audit", "memory_digest", "memory_snapshot", "expect_released",
    "peak_bytes",
    "TrainingHealthError", "check_gradients", "check_score", "check_tree",
    "divergence_audit", "enable_health", "health_enabled", "health_mode",
    "model_fingerprint",
    "Span", "begin_span", "current_context", "emit_span", "enable_flight",
    "enable_trace", "end_span", "flight_dump", "flight_enabled",
    "flight_len", "flight_len_from_env", "flight_snapshot", "new_span_id",
    "new_trace_id", "span", "span_record_enabled", "trace_enabled",
    "TrainBoard", "board_active", "train_board",
    "RankAggregator", "Reconciler", "StragglerDetector", "skew_table",
    "WindowedCapture", "attribute", "compile_digest",
    "install_compile_observer", "maybe_window", "measured_rooflines",
    "parse_trace_dir", "record_measured", "resolve_trace_dir",
    "resolve_window", "trace_files", "train_context", "watch_jit",
    "xprof_digest",
]
