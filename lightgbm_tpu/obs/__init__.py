"""Observability subsystem: structured telemetry, phase timers, JAX
instrumentation (see ``core`` for the event/counter API, ``trace`` for
the recompile hook, ``report`` for JSONL merging).

Quick start::

    LGBM_TPU_TELEMETRY=/tmp/telem python train.py
    python tools/telemetry_report.py /tmp/telem

or programmatically ``obs.enable("/tmp/telem")`` / the ``tpu_telemetry``
parameter.  ``LGBM_TPU_TIMETAG=1`` keeps the plain phase-time report.
"""
from .core import (TIMETAG_ENABLED, add, count, counter_value,
                   counters_snapshot, current_phase, digest, disable,
                   enable, enabled, event, gauge, phase, phase_delta,
                   phase_snapshot, record_collective,
                   record_collective_host, report, reset, sink_path, sync,
                   tracing_enabled)
from .trace import compile_count, compile_seconds, install_recompile_hook

__all__ = [
    "TIMETAG_ENABLED", "add", "count", "counter_value",
    "counters_snapshot", "current_phase", "digest", "disable", "enable",
    "enabled", "event", "gauge", "phase", "phase_delta", "phase_snapshot",
    "record_collective", "record_collective_host", "report", "reset",
    "sink_path", "sync", "tracing_enabled",
    "compile_count", "compile_seconds", "install_recompile_hook",
]
