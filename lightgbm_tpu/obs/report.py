"""Merge telemetry JSONL files into per-phase / per-iteration summaries.

Library backing for ``tools/telemetry_report.py`` (and for tests): pure
stdlib, no jax import, so the report tool starts instantly even on a box
without an accelerator runtime.
"""
from __future__ import annotations

import glob
import json
import os
import re
from collections import defaultdict
from typing import List


def telemetry_files(path: str) -> List[str]:
    """Resolve ``path`` (a telemetry dir, a ``.jsonl`` file, or a glob)
    to the sorted list of per-process JSONL files.  A ``base.jsonl``
    argument also picks up the ``base.{i}.jsonl`` siblings non-zero
    ranks write in file-sink mode (obs/core.py sink_path)."""
    if os.path.isdir(path):
        return sorted(glob.glob(os.path.join(path, "telemetry.*.jsonl")))
    if path.endswith(".jsonl"):
        sibs = glob.glob(path[:-len(".jsonl")] + ".*.jsonl")
        out = {f for f in sibs + [path] if os.path.isfile(f)}
        return sorted(out)
    return sorted(glob.glob(path))


def load_events(path: str) -> List[dict]:
    """Parse every record from the file set; corrupt lines are counted,
    not fatal (a crashed run may truncate its last record).  Each event
    gains ``_proc`` (from the ``telemetry.{i}.jsonl`` name, else 0)."""
    events = []
    bad = 0
    for fname in telemetry_files(path):
        m = re.search(r"\.(\d+)\.jsonl$", os.path.basename(fname))
        proc = int(m.group(1)) if m else 0
        with open(fname) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    bad += 1
                    continue
                rec["_proc"] = proc
                events.append(rec)
    if bad:
        events.append({"event": "_parse_errors", "count": bad, "_proc": -1})
    return events


def summarize(events: List[dict]) -> dict:
    """Machine-readable digest of a merged event stream.

    Per-iteration rows come from process 0 (iteration records are
    emitted by every process and are near-identical — metrics/timings of
    replicated training); counters are summed across processes' final
    ``summary`` events (collective bytes et al. are per-process).
    """
    procs = sorted({e["_proc"] for e in events if e["_proc"] >= 0})
    iters0 = [e for e in events if e.get("event") == "iteration"
              and e["_proc"] == (procs[0] if procs else 0)]
    iters0.sort(key=lambda e: e.get("iteration", 0))

    phase_s = defaultdict(float)
    phase_calls = defaultdict(int)
    per_iteration = []
    for e in iters0:
        for k, v in (e.get("phase_s") or {}).items():
            phase_s[k] += float(v)
        per_iteration.append({
            "iteration": e.get("iteration"),
            "iter_s": e.get("iter_s"),
            "leaves": e.get("leaves"),
            "waves": e.get("waves"),
            "recompiles": e.get("recompiles"),
            "phase_s": e.get("phase_s") or {},
            "metrics": e.get("metrics") or {},
            "cum_row_iters_per_s": e.get("cum_row_iters_per_s"),
        })

    counters = defaultdict(float)
    summaries = [e for e in events if e.get("event") == "summary"]
    sum_phase = defaultdict(float)
    for e in summaries:
        for k, v in (e.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                counters[k] += v
        for k, v in (e.get("phase_s") or {}).items():
            sum_phase[k] += float(v)
        for k, v in (e.get("phase_calls") or {}).items():
            phase_calls[k] += int(v)
    # the atexit summaries carry authoritative totals including phases
    # outside the iteration loop (binning, predict); per-iteration deltas
    # are the fallback for live/crashed runs with no summary yet
    if sum_phase:
        phase_s = sum_phase
    # live runs (no atexit summary yet): fall back to per-event counters
    if not summaries:
        for e in events:
            if e.get("event") == "collective":
                kind = e.get("kind", "?")
                tag = "traced_" if e.get("traced") else ""
                counters[f"collective/{kind}/{tag}calls"] += 1
                counters[f"collective/{kind}/{tag}bytes"] += e.get("bytes", 0)

    last = per_iteration[-1] if per_iteration else {}
    return {
        "processes": procs,
        "iterations": len(per_iteration),
        "per_iteration": per_iteration,
        "phase_s": {k: round(v, 4) for k, v in sorted(phase_s.items())},
        "phase_calls": dict(sorted(phase_calls.items())),
        "counters": {k: (int(v) if float(v).is_integer() else round(v, 4))
                     for k, v in sorted(counters.items())},
        "metrics_last": last.get("metrics", {}),
        "cum_row_iters_per_s": last.get("cum_row_iters_per_s"),
        "parse_errors": sum(e.get("count", 0) for e in events
                            if e.get("event") == "_parse_errors"),
    }


def render(digest: dict) -> str:
    """Human-readable table for the digest."""
    out = []
    out.append(f"processes: {len(digest['processes'])}  "
               f"iterations: {digest['iterations']}")
    if digest["phase_s"]:
        total = sum(digest["phase_s"].values()) or 1.0
        calls = digest.get("phase_calls") or {}
        out.append("")
        out.append(f"{'phase':<28}{'seconds':>10}{'share':>8}{'calls':>8}")
        for name, s in sorted(digest["phase_s"].items(),
                              key=lambda kv: -kv[1]):
            c = calls.get(name)
            out.append(f"{name:<28}{s:>10.3f}{100.0 * s / total:>7.1f}%"
                       f"{c if c is not None else '-':>8}")
    rows = digest["per_iteration"]
    if rows:
        out.append("")
        out.append(f"{'iter':>5}{'iter_s':>9}{'leaves':>10}{'waves':>7}"
                   f"{'recomp':>7}  metrics")
        for r in rows:
            leaves = r.get("leaves")
            leaves_s = ",".join(str(x) for x in leaves) if leaves else "-"
            metr = " ".join(f"{k}={v:.6g}"
                            for k, v in (r.get("metrics") or {}).items())
            waves = r.get("waves")
            out.append(f"{r.get('iteration', '?'):>5}"
                       f"{(r.get('iter_s') or 0.0):>9.3f}"
                       f"{leaves_s:>10}"
                       f"{'-' if waves in (None, -1) else waves:>7}"
                       f"{r.get('recompiles') if r.get('recompiles') is not None else '-':>7}"
                       f"  {metr}")
        if digest.get("cum_row_iters_per_s"):
            out.append(f"cumulative row-iterations/s: "
                       f"{digest['cum_row_iters_per_s']:,}")
    if digest["counters"]:
        out.append("")
        out.append("counters:")
        for k, v in digest["counters"].items():
            out.append(f"  {k:<40} {v}")
    if digest.get("parse_errors"):
        out.append(f"\n(parse errors skipped: {digest['parse_errors']})")
    return "\n".join(out)
