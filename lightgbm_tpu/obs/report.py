"""Merge telemetry JSONL files into per-phase / per-iteration summaries.

Pure stdlib, no jax import, so the report starts instantly even on a
box without an accelerator runtime.  Also the CLI::

    python -m lightgbm_tpu.obs.report <path> [--json]

``<path>`` is a telemetry dir (merges every
``telemetry.{process_index}.jsonl`` in it), one ``.jsonl`` file, or a
glob.  Default output is the human-readable table; ``--json`` prints
the machine digest (the same shape bench.py embeds as its
``telemetry`` field).  ``tools/telemetry_report.py`` remains as a thin
shim over this entry point.
"""
from __future__ import annotations

import glob
import json
import math
import os
import re
from collections import defaultdict
from typing import List


def telemetry_files(path: str) -> List[str]:
    """Resolve ``path`` (a telemetry dir, a ``.jsonl`` file, or a glob)
    to the sorted list of per-process JSONL files.  A ``base.jsonl``
    argument also picks up the ``base.{i}.jsonl`` siblings non-zero
    ranks write in file-sink mode (obs/core.py sink_path)."""
    if os.path.isdir(path):
        return sorted(glob.glob(os.path.join(path, "telemetry.*.jsonl")))
    if path.endswith(".jsonl"):
        sibs = glob.glob(path[:-len(".jsonl")] + ".*.jsonl")
        out = {f for f in sibs + [path] if os.path.isfile(f)}
        return sorted(out)
    return sorted(glob.glob(path))


def load_events(path: str) -> List[dict]:
    """Parse every record from the file set; corrupt lines are counted,
    not fatal (a crashed run may truncate its last record).  Each event
    gains ``_proc`` (from the ``telemetry.{i}.jsonl`` name, else 0)."""
    events = []
    bad = 0
    for fname in telemetry_files(path):
        m = re.search(r"\.(\d+)\.jsonl$", os.path.basename(fname))
        proc = int(m.group(1)) if m else 0
        with open(fname) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    bad += 1
                    continue
                rec["_proc"] = proc
                events.append(rec)
    if bad:
        events.append({"event": "_parse_errors", "count": bad, "_proc": -1})
    return events


def summarize(events: List[dict]) -> dict:
    """Machine-readable digest of a merged event stream.

    Per-iteration rows come from process 0 (iteration records are
    emitted by every process and are near-identical — metrics/timings of
    replicated training); counters are summed across processes' final
    ``summary`` events (collective bytes et al. are per-process).
    Multi-process runs additionally get a cross-host phase-skew table
    (the straggler report: a phase whose wall time diverges across
    processes is where the collective waits pile up), and profile-mode
    runs get per-kernel roofline aggregates + the memory-census peak.
    """
    procs = sorted({e["_proc"] for e in events if e["_proc"] >= 0})
    iters0 = [e for e in events if e.get("event") == "iteration"
              and e["_proc"] == (procs[0] if procs else 0)]
    iters0.sort(key=lambda e: e.get("iteration", 0))

    phase_s = defaultdict(float)
    phase_calls = defaultdict(int)
    per_iteration = []
    for e in iters0:
        for k, v in (e.get("phase_s") or {}).items():
            phase_s[k] += float(v)
        row = {
            "iteration": e.get("iteration"),
            "iter_s": e.get("iter_s"),
            "leaves": e.get("leaves"),
            "waves": e.get("waves"),
            "recompiles": e.get("recompiles"),
            "phase_s": e.get("phase_s") or {},
            "metrics": e.get("metrics") or {},
            "cum_row_iters_per_s": e.get("cum_row_iters_per_s"),
        }
        for k in ("hist_mode", "wave_capacity", "fused_sibling",
                  "fused_grad", "overlap", "overlap_frac",
                  "grad_hbm_bytes_saved"):
            if e.get(k) is not None:
                row[k] = e[k]
        per_iteration.append(row)

    counters = defaultdict(float)
    summaries = [e for e in events if e.get("event") == "summary"]
    sum_phase = defaultdict(float)
    proc_phase = defaultdict(dict)   # proc -> {phase: seconds}
    for e in summaries:
        for k, v in (e.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                counters[k] += v
        for k, v in (e.get("phase_s") or {}).items():
            sum_phase[k] += float(v)
            proc_phase[e["_proc"]][k] = float(v)
        for k, v in (e.get("phase_calls") or {}).items():
            phase_calls[k] += int(v)
    # the atexit summaries carry authoritative totals including phases
    # outside the iteration loop (binning, predict); per-iteration deltas
    # are the fallback for live/crashed runs with no summary yet
    if sum_phase:
        phase_s = sum_phase
    # live runs (no atexit summary yet): fall back to per-event counters
    if not summaries:
        for e in events:
            if e.get("event") == "collective":
                kind = e.get("kind", "?")
                tag = "traced_" if e.get("traced") else ""
                counters[f"collective/{kind}/{tag}calls"] += 1
                counters[f"collective/{kind}/{tag}bytes"] += e.get("bytes", 0)

    # waves-per-tree: kernel launches per grown tree, the CPU-measurable
    # wave-scheduling efficiency figure (ISSUE 8 — packed lane pairs cut
    # it ~1.5x on deep trees); trees that failed to grow don't count
    waves_sum = trees_sum = 0
    for e in iters0:
        w = e.get("waves")
        if isinstance(w, (int, float)) and w >= 0:
            grown = sum(1 for x in (e.get("leaves") or [])
                        if isinstance(x, (int, float)) and x > 1)
            if grown:
                waves_sum += w
                trees_sum += grown

    last = per_iteration[-1] if per_iteration else {}
    wave_pipeline = {}
    if trees_sum:
        wave_pipeline["waves_per_tree"] = round(waves_sum / trees_sum, 3)
        wave_pipeline["waves_total"] = int(waves_sum)
        wave_pipeline["trees_grown"] = int(trees_sum)
    for k in ("hist_mode", "wave_capacity", "fused_sibling",
              "fused_grad", "overlap", "overlap_frac",
              "grad_hbm_bytes_saved"):
        if last.get(k) is not None:
            wave_pipeline[k] = last[k]
    out = {
        "processes": procs,
        "iterations": len(per_iteration),
        "per_iteration": per_iteration,
        "phase_s": {k: round(v, 4) for k, v in sorted(phase_s.items())},
        "phase_calls": dict(sorted(phase_calls.items())),
        "counters": {k: (int(v) if float(v).is_integer() else round(v, 4))
                     for k, v in sorted(counters.items())},
        "metrics_last": last.get("metrics", {}),
        "cum_row_iters_per_s": last.get("cum_row_iters_per_s"),
        "parse_errors": sum(e.get("count", 0) for e in events
                            if e.get("event") == "_parse_errors"),
    }
    if wave_pipeline:
        out["wave_pipeline"] = wave_pipeline
    skew = phase_skew(proc_phase)
    if skew:
        out["phase_skew"] = skew
    kernels = kernel_summary(events)
    if kernels:
        out["kernels"] = kernels
    xp = xprof_summary(events)
    if xp:
        out["xprof"] = xp
    comp = compile_summary(events)
    if comp:
        out["compile"] = comp
    mem = memory_summary(events)
    if mem:
        out["memory"] = mem
    health = health_summary(events)
    if health:
        out["health"] = health
    serve = serve_summary(events)
    if serve:
        out["serve"] = serve
    trace = trace_summary(events)
    if trace:
        out["trace"] = trace
    robust = robust_summary(events)
    if robust:
        out["robust"] = robust
    online = online_summary(events)
    if online:
        out["online"] = online
    ing = ingest_summary(events)
    if ing:
        out["ingest"] = ing
    drift = drift_summary(events)
    if drift:
        out["drift"] = drift
    recon = reconciliation_summary(events)
    if recon:
        out["reconciliation"] = recon
    stragglers = [{k: e.get(k) for k in ("rank", "phase", "iteration",
                                         "ratio", "median_s", "rank_s",
                                         "consecutive")}
                  for e in events if e.get("event") == "straggler"]
    if stragglers:
        out["stragglers"] = stragglers
    return out


def reconciliation_summary(events: List[dict]) -> dict:
    """Aggregate ``reconciliation`` events per cost-model unit: scored
    iterations, mean/last measured-over-modeled ratio, and the worst
    ratio with its iteration — the post-hoc companion of the live
    board's reconciliation row (a unit whose mean ratio drifts far
    above 1 is where docs/ROOFLINE.md's model is optimistic on this
    backend)."""
    per_unit: dict = {}
    for e in events:
        if e.get("event") != "reconciliation":
            continue
        for unit, u in (e.get("units") or {}).items():
            ratio = u.get("ratio")
            if ratio is None:
                continue
            agg = per_unit.setdefault(unit, {
                "iterations": 0, "ratio_sum": 0.0, "last_ratio": None,
                "worst_ratio": None, "worst_iteration": None})
            agg["iterations"] += 1
            agg["ratio_sum"] += float(ratio)
            agg["last_ratio"] = float(ratio)
            agg["last_measured_s"] = u.get("measured_s")
            agg["last_modeled_s"] = u.get("modeled_s")
            if (agg["worst_ratio"] is None
                    or float(ratio) > agg["worst_ratio"]):
                agg["worst_ratio"] = float(ratio)
                agg["worst_iteration"] = e.get("iteration")
    out = {}
    for unit, agg in per_unit.items():
        n = agg.pop("iterations")
        s = agg.pop("ratio_sum")
        out[unit] = dict(iterations=n, mean_ratio=round(s / n, 4),
                         **{k: (round(v, 4)
                                if isinstance(v, float) else v)
                            for k, v in agg.items()})
    return out


def phase_skew(proc_phase: dict) -> dict:
    """Cross-host straggler table from per-process phase totals: for each
    phase seen by >1 process, the min/max seconds and the spread as a
    fraction of the mean.  A phase with high spread_frac is where the
    slow host makes everyone else wait at the next collective
    (reference: the Network::Allreduce barrier in
    data_parallel_tree_learner.cpp)."""
    if len(proc_phase) < 2:
        return {}
    names = set()
    for d in proc_phase.values():
        names.update(d)
    out = {}
    for name in sorted(names):
        vals = [d[name] for d in proc_phase.values() if name in d]
        if len(vals) < 2:
            continue
        mean = sum(vals) / len(vals)
        out[name] = {
            "min_s": round(min(vals), 4),
            "max_s": round(max(vals), 4),
            "spread_s": round(max(vals) - min(vals), 4),
            "spread_frac": round((max(vals) - min(vals)) / mean, 4)
            if mean else 0.0,
        }
    return out


def kernel_summary(events: List[dict]) -> dict:
    """Aggregate ``kernel_profile`` events per kernel: call count, total
    achieved seconds, summed analytical roofline seconds, and the
    roofline fraction (roofline/achieved — 1.0 means running AT the
    analytical floor)."""
    agg = {}
    for e in events:
        if e.get("event") != "kernel_profile":
            continue
        k = e.get("kernel", "?")
        a = agg.setdefault(k, {"calls": 0, "achieved_s": 0.0,
                               "roofline_s": 0.0, "flops": 0.0,
                               "bytes": 0.0})
        a["calls"] += 1
        a["achieved_s"] += float(e.get("achieved_s", 0.0) or 0.0)
        a["roofline_s"] += float(e.get("roofline_s", 0.0) or 0.0)
        a["flops"] += float(e.get("flops", 0.0) or 0.0)
        a["bytes"] += float(e.get("bytes", 0.0) or 0.0)
    for a in agg.values():
        ach = a["achieved_s"]
        a["achieved_s"] = round(ach, 6)
        a["roofline_s"] = round(a["roofline_s"], 9)
        a["roofline_frac"] = round(a["roofline_s"] / ach, 6) if ach else 0.0
    return dict(sorted(agg.items()))


def xprof_summary(events: List[dict]) -> dict:
    """Aggregate ``kernel_measured`` events (obs/xprof.py) per kernel:
    attributed op count, trace-measured ms, and — for scopes with an
    analytic model — the cost-model ms, roofline fraction and
    HBM/MXU boundedness.  Unattributed residual rows keep their device
    label so multi-device windows stay distinguishable.  This is the
    MEASURED column of docs/ROOFLINE.md; ``kernel_summary`` above is
    the host-sync-bracketed estimate from profile mode."""
    agg: dict = {}
    window = 0.0
    for e in events:
        if e.get("event") != "kernel_measured":
            continue
        key = e.get("kernel", "?")
        if key == "unattributed" and e.get("device"):
            key = "unattributed(%s)" % e["device"]
        a = agg.setdefault(key, {"ops": 0, "measured_ms": 0.0})
        a["ops"] += int(e.get("ops", 0) or 0)
        a["measured_ms"] += float(e.get("measured_ms", 0.0) or 0.0)
        for f in ("model_ms", "roofline_frac", "bound",
                  "occupancy", "model"):
            if e.get(f) is not None:
                a[f] = e[f]
        window = max(window, float(e.get("window_ms", 0.0) or 0.0))
    if not agg:
        return {}
    for a in agg.values():
        a["measured_ms"] = round(a["measured_ms"], 4)
    return {"window_ms": round(window, 3),
            "kernels": dict(sorted(agg.items()))}


def compile_summary(events: List[dict]) -> dict:
    """Fold ``compile`` events (obs/xprof.py) into the compile-plane
    digest: backend-compile count + wall attributed per jit, persistent
    compile-cache hit/miss traffic, and retraces with the argument
    signatures that forced them."""
    out = {"compiles": 0, "wall_s": 0.0, "by_jit": {},
           "cache_hits": 0, "cache_misses": 0, "retraces": 0}
    retrace_jits: dict = {}
    seen = False
    for e in events:
        if e.get("event") != "compile":
            continue
        seen = True
        kind = e.get("kind")
        if kind == "backend_compile":
            out["compiles"] += 1
            w = float(e.get("wall_s", 0.0) or 0.0)
            out["wall_s"] += w
            ent = out["by_jit"].setdefault(
                e.get("jit") or "<top>", {"count": 0, "wall_s": 0.0})
            ent["count"] += 1
            ent["wall_s"] += w
        elif kind == "cache_hit":
            out["cache_hits"] += 1
        elif kind == "cache_miss":
            out["cache_misses"] += 1
        elif kind == "retrace":
            out["retraces"] += 1
            jit = e.get("jit") or "?"
            lst = retrace_jits.setdefault(jit, [])
            for c in (e.get("changed") or [])[:4]:
                if c not in lst:
                    lst.append(c)
    if not seen:
        return {}
    out["wall_s"] = round(out["wall_s"], 4)
    for ent in out["by_jit"].values():
        ent["wall_s"] = round(ent["wall_s"], 4)
    out["by_jit"] = dict(sorted(out["by_jit"].items()))
    if retrace_jits:
        out["retrace_jits"] = dict(sorted(retrace_jits.items()))
    return out


def memory_summary(events: List[dict]) -> dict:
    """Fold ``memory_census`` + ``donation_audit`` events into the census
    digest: run peak, last per-buffer attribution, audit survivors."""
    peak = 0
    peak_phase = ""
    last_buffers = {}
    survivors = []
    n = 0
    for e in events:
        if e.get("event") == "memory_census":
            n += 1
            basis = max(int(e.get("peak_bytes", 0) or 0),
                        int(e.get("device_peak_bytes", 0) or 0),
                        int(e.get("live_bytes", 0) or 0))
            if basis > peak:
                peak = basis
                peak_phase = e.get("phase", "")
            if e.get("buffers"):
                last_buffers = e["buffers"]
        elif e.get("event") == "donation_audit":
            survivors.extend(e.get("survivors") or [])
    if not n:
        return {}
    out = {"peak_bytes": peak, "peak_phase": peak_phase, "snapshots": n,
           "buffers_last": last_buffers}
    if survivors:
        out["audit_survivors"] = sorted(set(survivors))
    return out


def health_summary(events: List[dict]) -> dict:
    """Fold ``health``/``fingerprint``/``divergence`` events (obs/health)
    into one digest section: failure count + first failure's attribution,
    fingerprint coverage, and the divergence audit's verdict.  Empty when
    the run had no health instrumentation."""
    fails = [e for e in events
             if e.get("event") == "health" and not e.get("ok", True)]
    fps = [e for e in events if e.get("event") == "fingerprint"]
    div = [e for e in events if e.get("event") == "divergence"]
    if not (fails or fps or div):
        return {}
    out = {
        "failures": len(fails),
        "fingerprints": len(fps),
        "divergence_checks": len(div),
        "divergence_failures": sum(1 for e in div
                                   if not e.get("ok", True)),
    }
    if fails:
        f = fails[0]
        out["first_failure"] = {k: f.get(k) for k in
                                ("check", "phase", "iteration", "detail")}
    if fps:
        out["last_fingerprint"] = {"iteration": fps[-1].get("iteration"),
                                   "digest": fps[-1].get("digest")}
    return out


def percentile(sorted_vals: List[float], p: float):
    """Nearest-rank percentile (rank ceil(p*n), 1-indexed) over a
    pre-sorted list (stdlib only).  THE latency-percentile definition
    for the serving stack: the digest here, the session's ``stats()``
    /health endpoint, and the serve bench all share it so p50/p99 can
    never silently diverge."""
    if not sorted_vals:
        return None
    n = len(sorted_vals)
    i = min(max(math.ceil(p * n) - 1, 0), n - 1)
    return round(sorted_vals[i], 3)


def serve_summary(events: List[dict]) -> dict:
    """Fold ``serve_*`` events (serve/session.py) into the serving
    digest: request latency percentiles, batch occupancy / pad waste,
    overloads, deadline misses, and whether the session degraded to the
    host predictor.  Empty when the run served nothing."""
    reqs = [e for e in events if e.get("event") == "serve_request"]
    batches = [e for e in events if e.get("event") == "serve_batch"]
    overloads = sum(1 for e in events if e.get("event") == "serve_overload")
    degraded = [e for e in events if e.get("event") == "serve_degraded"]
    if not (reqs or batches):
        return {}
    lat = sorted(float(e.get("total_ms", 0.0) or 0.0)
                 for e in reqs if e.get("ok", True))
    rows = sum(int(e.get("rows", 0) or 0) for e in batches)
    padded = sum(int(e.get("padded", 0) or 0) for e in batches)
    out = {
        "requests": len(reqs),
        "ok": sum(1 for e in reqs if e.get("ok", True)),
        "deadline_missed": sum(1 for e in reqs
                               if e.get("reason") == "deadline"),
        "overloads": overloads,
        "batches": len(batches),
        "rows": rows,
        "padded_rows": padded,
        "occupancy": round(rows / padded, 4) if padded else None,
        "pad_waste_rows": max(padded - rows, 0),
        "p50_ms": percentile(lat, 0.50),
        "p99_ms": percentile(lat, 0.99),
        "max_queue_rows": max((int(e.get("queue_rows", 0) or 0)
                               for e in batches), default=0),
        "degraded": bool(degraded),
    }
    if degraded:
        out["degraded_error"] = degraded[0].get("error")
    # fleet lifecycle (serve/registry.py): swaps / canary verdicts /
    # rollbacks / failovers beside the request numbers they governed
    swaps = [e for e in events if e.get("event") == "serve_swap"]
    rollbacks = [e for e in events if e.get("event") == "serve_rollback"]
    failovers = [e for e in events if e.get("event") == "serve_failover"]
    if swaps or rollbacks or failovers:
        out["fleet"] = {
            # initial deploys (add_model stamps initial=True) are not
            # hot-swaps — the registry's swaps counter and
            # tpu_serve_swaps_total exclude them, so the digest must too
            "swaps": sum(1 for e in swaps
                         if e.get("ok") and not e.get("initial")),
            "deploys": sum(1 for e in swaps
                           if e.get("ok") and e.get("initial")),
            "swaps_rejected": sum(1 for e in swaps if not e.get("ok")),
            "rollbacks": len(rollbacks),
            "failovers": len(failovers),
        }
        if rollbacks:
            out["fleet"]["last_rollback"] = {
                "model": rollbacks[-1].get("model"),
                "reason": rollbacks[-1].get("reason")}
    shed = [e for e in events if e.get("event") == "serve_overload"
            and e.get("priority")]
    if shed:
        by_class = defaultdict(int)
        for e in shed:
            by_class[e.get("priority", "?")] += 1
        out["shed_by_priority"] = dict(sorted(by_class.items()))
    xreqs = [e for e in events if e.get("event") == "explain_request"]
    xbatches = [e for e in events if e.get("event") == "explain_batch"]
    if xreqs or xbatches:
        xlat = sorted(float(e.get("total_ms", 0.0) or 0.0)
                      for e in xreqs if e.get("ok", True))
        xrows = sum(int(e.get("rows", 0) or 0) for e in xbatches)
        xpad = sum(int(e.get("padded", 0) or 0) for e in xbatches)
        out["explain"] = {
            "requests": len(xreqs),
            "ok": sum(1 for e in xreqs if e.get("ok", True)),
            "deadline_missed": sum(1 for e in xreqs
                                   if e.get("reason") == "deadline"),
            "batches": len(xbatches),
            "rows": xrows,
            "padded_rows": xpad,
            "occupancy": round(xrows / xpad, 4) if xpad else None,
            "p50_ms": percentile(xlat, 0.50),
            "p99_ms": percentile(xlat, 0.99),
        }
    return out


def robust_summary(events: List[dict]) -> dict:
    """Fold the fault-tolerance events (robust/: ``checkpoint`` /
    ``restore`` / ``retry`` / ``fault_injected`` / ``device_stall`` /
    ``serve_recovered``) into one recovery digest: how often the run
    checkpointed, what it recovered from, and what was injected.  Empty
    when the run saw no recovery activity."""
    cps = [e for e in events if e.get("event") == "checkpoint"]
    rst = [e for e in events if e.get("event") == "restore"]
    rets = [e for e in events if e.get("event") == "retry"]
    inj = [e for e in events if e.get("event") == "fault_injected"]
    stalls = [e for e in events if e.get("event") == "device_stall"]
    recov = [e for e in events if e.get("event") == "serve_recovered"]
    if not (cps or rst or rets or inj or stalls or recov):
        return {}
    by_point = defaultdict(lambda: {"retries": 0, "transient": 0,
                                    "fatal": 0})
    for e in rets:
        p = by_point[e.get("point", "?")]
        p["retries"] += 1
        p[e.get("classify", "fatal")] = p.get(e.get("classify", "fatal"),
                                              0) + 1
    out = {
        "checkpoints": len(cps),
        "restores": len(rst),
        "retries": len(rets),
        "faults_injected": len(inj),
        "stalls": len(stalls),
        "serve_recoveries": len(recov),
    }
    if by_point:
        out["retries_by_point"] = {k: dict(v)
                                   for k, v in sorted(by_point.items())}
    if inj:
        pts = defaultdict(int)
        for e in inj:
            pts[e.get("point", "?")] += 1
        out["faults_by_point"] = dict(sorted(pts.items()))
    if cps:
        last = cps[-1]
        out["last_checkpoint"] = {"iteration": last.get("iteration"),
                                  "reason": last.get("reason"),
                                  "path": last.get("path")}
    if rst:
        out["resumed_from_iteration"] = rst[-1].get("iteration")
    return out


def online_summary(events: List[dict]) -> dict:
    """Fold the online-learning events (``refit`` from
    boosting/gbdt.py's leaf re-estimation, ``online_refresh`` from
    online/loop.py's cadence firings) into one closed-loop digest: how
    many refreshed versions were produced/pushed, what was rejected or
    skipped, and what the refits cost.  Empty when the run neither
    refit nor ran the online loop."""
    refits = [e for e in events if e.get("event") == "refit"]
    refreshes = [e for e in events if e.get("event") == "online_refresh"]
    if not (refits or refreshes):
        return {}
    out = {
        "refits": len(refits),
        "refreshes": len(refreshes),
        "refreshes_ok": sum(1 for e in refreshes if e.get("ok")),
        "refreshes_failed": sum(1 for e in refreshes
                                if not e.get("ok", True)
                                and not e.get("skipped")),
        "refreshes_skipped": sum(1 for e in refreshes if e.get("skipped")),
        "rows_refreshed": sum(int(e.get("rows", 0) or 0)
                              for e in refreshes if e.get("ok")),
    }
    if refits:
        last = refits[-1]
        out["refit_rows"] = sum(int(e.get("rows", 0) or 0) for e in refits)
        out["refit_wall_s"] = round(sum(float(e.get("wall_s", 0.0) or 0.0)
                                        for e in refits), 4)
        out["last_refit"] = {k: last.get(k) for k in
                             ("trees", "rows", "decay", "mode")}
    if refreshes:
        lat = sorted(float(e.get("ms", 0.0) or 0.0)
                     for e in refreshes if e.get("ok"))
        out["refresh_p50_ms"] = percentile(lat, 0.50)
        versions = [int(e.get("version", 0) or 0) for e in refreshes
                    if e.get("ok")]
        if versions:
            out["last_version"] = max(versions)
        skips = defaultdict(int)
        for e in refreshes:
            if e.get("skipped"):
                skips[str(e["skipped"])] += 1
        if skips:
            out["skipped_by_reason"] = dict(sorted(skips.items()))
    return out


def ingest_summary(events: List[dict]) -> dict:
    """Fold the streaming-ingestion events (``ingest_chunk`` per
    streamed chunk, ``ingest_summary`` per constructed dataset —
    ingest/stream.py) into one digest section: rows/chunks/throughput
    of the LAST ingestion plus totals across the run.  Empty when
    nothing streamed."""
    chunks = [e for e in events if e.get("event") == "ingest_chunk"]
    sums = [e for e in events if e.get("event") == "ingest_summary"]
    if not (chunks or sums):
        return {}
    out = {
        "ingestions": len(sums),
        "chunk_events": len(chunks),
        "rows_total": sum(int(e.get("rows", 0) or 0) for e in sums),
    }
    if sums:
        last = sums[-1]
        out["last"] = {k: last.get(k) for k in
                       ("rows", "local_rows", "chunks", "sample_rows",
                        "shards", "shard_id", "memmap", "wall_s",
                        "rows_per_s", "source", "digest")
                       if last.get(k) is not None}
        out["rows_per_s"] = last.get("rows_per_s")
    return out


def drift_summary(events: List[dict]) -> dict:
    """Fold the drift/quality plane (``drift_snapshot`` cadence checks
    from obs/drift.py's serve-side monitor, ``quality_window`` rolling
    evaluations from serve/quality.py) into one digest section: score
    trajectory extremes, breach counts, and the last window per model.
    Empty when the run monitored nothing."""
    snaps = [e for e in events if e.get("event") == "drift_snapshot"]
    wins = [e for e in events if e.get("event") == "quality_window"]
    if not (snaps or wins):
        return {}
    out = {"snapshots": len(snaps), "quality_windows": len(wins),
           "drift_breaches": sum(1 for e in snaps if e.get("breach")),
           "quality_breaches": sum(1 for e in wins if e.get("breach"))}
    if snaps:
        last = snaps[-1]
        out["psi_max"] = round(max(float(e.get("psi_max", 0.0) or 0.0)
                                   for e in snaps), 6)
        out["pred_psi_max"] = round(
            max(float(e.get("pred_psi", 0.0) or 0.0) for e in snaps), 6)
        out["last_snapshot"] = {k: last.get(k) for k in
                                ("model", "version", "feat_rows",
                                 "pred_rows", "psi_max", "pred_psi",
                                 "worst_feature", "breach")}
    if wins:
        last = wins[-1]
        deltas = [float(e["auc_delta"]) for e in wins
                  if e.get("auc_delta") is not None]
        if deltas:
            out["auc_delta_max"] = round(max(deltas), 6)
        out["last_window"] = {k: last.get(k) for k in
                              ("model", "version", "rows", "auc",
                               "auc_delta", "cal_err", "ndcg", "breach")
                              if last.get(k) is not None}
    return out


def trace_summary(events: List[dict]) -> dict:
    """Fold ``span`` events (obs/spans.py) into the trace digest:
    span/trace counts and per-name call/duration aggregates.  Empty when
    the run traced nothing.  ``tools/trace_export.py`` turns the same
    events into a Perfetto-loadable timeline."""
    spans = [e for e in events if e.get("event") == "span"]
    if not spans:
        return {}
    by_name = {}
    traces = set()
    for e in spans:
        traces.add(e.get("trace_id"))
        a = by_name.setdefault(e.get("name", "?"),
                               {"calls": 0, "total_ms": 0.0})
        a["calls"] += 1
        a["total_ms"] += float(e.get("dur_ms", 0.0) or 0.0)
    for a in by_name.values():
        a["total_ms"] = round(a["total_ms"], 3)
    return {"spans": len(spans), "traces": len(traces),
            "by_name": dict(sorted(by_name.items()))}


# ---------------------------------------------------------------------------
# Event schemas — the CI smoke validates profile-mode streams against these
# ---------------------------------------------------------------------------

_NUM = (int, float)
EVENT_SCHEMAS = {
    # event name -> {field: (types..., required)}
    # per-iteration training record (boosting/gbdt.py).  Nullable fields
    # (waves, kernel_rows, partition_passes — None off the wave path) are
    # deliberately NOT listed: the validator type-checks listed fields
    # only, and a null would fail the int check on legitimate streams.
    "iteration": {
        "iteration": (int, True),
        "iter_s": (_NUM, True),
        "leaves": (list, False),
        "metrics": (dict, False),
        "phase_s": (dict, False),
        "recompiles": (int, False),
        "partition_batched": (bool, False),
        "cum_row_iters_per_s": (_NUM, False),
        # wave-pipeline mode stamps (ISSUE 8): emitted only on the wave
        # path, never null
        "hist_mode": (str, False),
        "wave_capacity": (int, False),
        "fused_sibling": (bool, False),
        # quantized/fused/overlap pipeline stamps (ISSUE 11):
        # fused_grad + grad_hbm_bytes_saved ride every iteration (the
        # fused pass applies on the XLA path too); overlap/overlap_frac
        # only on the wave path
        "fused_grad": (bool, False),
        "grad_hbm_bytes_saved": (_NUM, False),
        "overlap": (bool, False),
        "overlap_frac": (_NUM, False),
    },
    "kernel_profile": {
        "kernel": (str, True),
        "phase": (str, False),
        "flops": (_NUM, True),
        "bytes": (_NUM, True),
        "achieved_s": (_NUM, True),
        "roofline_s": (_NUM, True),
        "roofline_frac": (_NUM, True),
        "device": (str, True),
    },
    # measured-roofline rows (obs/xprof.py): trace-attributed device-op
    # time per lgbm/* scope joined against the analytic cost models.
    # Model fields (flops/bytes/model_ms/roofline_frac/bound/model) are
    # present only for scopes an analytic model covers; 'unattributed'
    # residual rows carry measured fields only.
    "kernel_measured": {
        "kernel": (str, True),
        "measured_ms": (_NUM, True),
        "window_ms": (_NUM, True),
        "ops": (int, True),
        "source": (str, True),
        "device": (str, False),
        "occupancy": (_NUM, False),
        "flops": (_NUM, False),
        "bytes": (_NUM, False),
        "model": (str, False),
        "model_ms": (_NUM, False),
        "roofline_frac": (_NUM, False),
        "bound": (str, False),
    },
    # compile-plane events (obs/xprof.py): kind is backend_compile
    # (per-jit wall), cache_hit / cache_miss (persistent compile
    # cache), or retrace (with the argument-signature diff that
    # forced it)
    "compile": {
        "kind": (str, True),
        "jit": (str, False),
        "wall_s": (_NUM, False),
        "changed": (list, False),
        "signatures": (int, False),
    },
    "memory_census": {
        "phase": (str, True),
        "buffers": (dict, True),
        "live_bytes": (int, True),
        "live_count": (int, True),
        "unattributed_bytes": (int, True),
        "peak_bytes": (int, True),
    },
    "donation_audit": {
        "phase": (str, True),
        "survivors": (list, True),
    },
    # training-health sentinels (obs/health.py)
    "health": {
        "check": (str, True),
        "phase": (str, True),
        "iteration": (int, True),
        "mode": (str, True),
        "ok": (bool, True),
        "detail": (dict, False),
    },
    "fingerprint": {
        "iteration": (int, True),
        "digest": (str, True),
        "stats": (list, True),
        "trees": (int, False),
    },
    "divergence": {
        "iteration": (int, True),
        "ok": (bool, True),
        "ranks": (int, True),
        "digests": (list, True),
        "spread": (list, False),
    },
    # serving engine (serve/session.py)
    "serve_request": {
        "rows": (int, True),
        "total_ms": (_NUM, True),
        "ok": (bool, True),
        "reason": (str, False),
    },
    "serve_batch": {
        "rows": (int, True),
        "padded": (int, True),
        "requests": (int, True),
        "queue_rows": (int, True),
        "exec_ms": (_NUM, True),
        "degraded": (bool, True),
    },
    "serve_degraded": {
        "error": (str, True),
        "plane": (str, False),   # absent = predict, "explain" = TreeSHAP
    },
    # explanation serving (serve/session.py explain path + explain/)
    "explain_request": {
        "rows": (int, True),
        "total_ms": (_NUM, True),
        "ok": (bool, True),
        "reason": (str, False),
    },
    "explain_batch": {
        "rows": (int, True),
        "padded": (int, True),
        "requests": (int, True),
        "queue_rows": (int, True),
        "exec_ms": (_NUM, True),
        "degraded": (bool, True),
    },
    "serve_overload": {
        "rows": (int, True),
        "queue_rows": (int, True),
        "priority": (str, False),   # shedding class of the rejected
                                    # request (low sheds first)
    },
    # serving fleet (serve/registry.py + serve/router.py)
    "serve_swap": {
        "model": (str, True),
        "ok": (bool, True),
        "from_version": (int, False),
        "to_version": (int, True),
        "ms": (_NUM, False),
        "initial": (bool, False),
    },
    "serve_canary": {
        "model": (str, True),
        "version": (int, True),
        "ok": (bool, True),
        "checks": (dict, True),
        "p99_ms": (_NUM, False),
    },
    "serve_rollback": {
        "model": (str, True),
        "from_version": (int, True),
        "to_version": (int, True),
        "reason": (str, True),
    },
    "serve_failover": {
        "replica": (int, True),
        "classify": (str, True),
        "breaker": (str, True),
        "error": (str, False),
    },
    "serve_drain": {
        "replica": (int, True),
        "draining": (bool, True),
    },
    # zero-cold-start plane (serve/aot.py): a present-but-untrusted
    # store entry fell back to a JIT compile — the loud part of the
    # "never crash" contract
    "aot_fallback": {
        "kind": (str, True),
        "entry": (str, True),
        "reason": (str, True),
    },
    "serve_replica_restart": {
        "replica": (int, True),
        "boot_ms": (_NUM, True),
        "boot_compiles": (int, True),
        "aot": (bool, True),
    },
    # multi-tenant arena plane (serve/arena.py): residency transitions
    "arena_admit": {
        "model": (str, True),
        "tenants": (int, True),
        "resident": (int, True),
        "bytes": (int, True),
        "readmit": (bool, True),
    },
    "arena_evict": {
        "model": (str, True),
        "reason": (str, True),
        "bytes": (int, False),
    },
    "arena_repack": {
        "generation": (int, True),
        "tenants": (int, True),
        "trees": (int, True),
        "bytes": (int, True),
        "ms": (_NUM, True),
    },
    "arena_swap": {
        "model": (str, True),
        "ok": (bool, True),
        "version": (int, False),
        "generation": (int, False),
        "error": (str, False),
    },
    # trace plane (obs/spans.py) + the HTTP access log (serve/server.py)
    "span": {
        "name": (str, True),
        "trace_id": (str, True),
        "span_id": (str, True),
        "parent_id": (str, False),
        "dur_ms": (_NUM, True),
        "attrs": (dict, False),
    },
    "serve_access": {
        "method": (str, True),
        "path": (str, True),
        "status": (int, True),
        "latency_ms": (_NUM, True),
        "trace_id": (str, True),
    },
    # fault tolerance (robust/checkpoint.py + robust/watchdog.py +
    # robust/faults.py)
    "checkpoint": {
        "iteration": (int, True),
        "path": (str, True),
        "bytes": (int, False),
        "ms": (_NUM, False),
        "reason": (str, False),
    },
    "restore": {
        "iteration": (int, True),
        "path": (str, True),
    },
    "retry": {
        "point": (str, True),
        "attempt": (int, True),
        "classify": (str, True),
        "action": (str, True),
        "error": (str, False),
        "delay_ms": (_NUM, False),
        "iteration": (int, False),
    },
    "fault_injected": {
        "point": (str, True),
        "action": (str, True),
        "call": (int, True),
        "iteration": (int, False),
    },
    "device_stall": {
        "point": (str, True),
        "elapsed_s": (_NUM, True),
        "deadline_s": (_NUM, True),
        "iteration": (int, False),
    },
    "serve_probe": {
        "ok": (bool, True),
        "error": (str, False),
        "plane": (str, False),
    },
    "serve_recovered": {
        "plane": (str, False),
    },
    # online learning (boosting/gbdt.py refit_models + online/loop.py)
    "refit": {
        "trees": (int, True),
        "rows": (int, True),
        "decay": (_NUM, True),
        "wall_s": (_NUM, True),
        "mode": (str, True),       # device (the jitted kernel) | host
                                   # (the retained bincount oracle)
        "iterations": (int, False),
    },
    "online_refresh": {
        "mode": (str, True),       # refit | continue
        "ok": (bool, True),
        "rows": (int, False),
        "ms": (_NUM, False),
        "version": (int, False),   # successful pushes only
        "skipped": (str, False),   # e.g. "ingest_stall" — the cadence
                                   # fired but no fresh rows arrived
        "error": (str, False),
    },
    # streaming ingestion (ingest/stream.py)
    "ingest_chunk": {
        "pass": (int, True),       # 1 = count/sample, 2 = binarize
        "chunk": (int, True),
        "rows": (int, True),
        "stream_row0": (int, True),
    },
    "ingest_summary": {
        "rows": (int, True),       # whole-stream rows
        "local_rows": (int, True),  # this shard's binned rows
        "chunks": (int, True),
        "sample_rows": (int, True),
        "shards": (int, True),
        "shard_id": (int, True),
        "memmap": (bool, True),
        "wall_s": (_NUM, True),
        "rows_per_s": (_NUM, True),
        "source": (str, True),
        "digest": (str, False),    # dataset content digest (recorded
                                   # when telemetry/flight is armed —
                                   # crash-resume re-streams must match)
    },
    # drift/quality plane (obs/drift.py + serve/quality.py)
    "drift_snapshot": {
        "model": (str, True),
        "version": (int, True),
        "feat_rows": (int, True),   # sampled feature rows in the sketch
        "pred_rows": (int, True),   # scored responses in the sketch
        "psi_max": (_NUM, True),    # worst per-feature PSI vs reference
        "psi_mean": (_NUM, True),
        "ks_max": (_NUM, True),
        "pred_psi": (_NUM, True),   # prediction-histogram PSI
        "pred_ks": (_NUM, True),
        "worst_feature": (str, True),
        "breach": (bool, True),
    },
    "quality_window": {
        "model": (str, True),
        "version": (int, True),     # served version the window scored
        "rows": (int, True),
        "auc": (_NUM, False),       # absent for single-class windows
        "auc_ref": (_NUM, False),   # training AUC from the profile
        "auc_delta": (_NUM, False),  # ref - live (positive = worse)
        "cal_err": (_NUM, False),
        "ndcg": (_NUM, False),
        "breach": (bool, True),
    },
    # live introspection plane (obs/ranks.py, ISSUE 17)
    "straggler": {
        "rank": (int, True),        # the offending process index
        "phase": (str, True),       # which phase lagged (ranks.PHASES)
        "iteration": (int, True),
        "ratio": (_NUM, True),      # rank wall over fleet median
        "median_s": (_NUM, True),   # per-iteration fleet median wall
        "rank_s": (_NUM, True),     # per-iteration offender wall
        "consecutive": (int, True),  # iterations the streak lasted
        "breach": (bool, False),
    },
    "reconciliation": {
        "iteration": (int, True),
        "units": (dict, True),      # unit -> {measured_s, modeled_s,
                                    #          ratio}
    },
}


def validate_events(events: List[dict], kinds=None) -> List[str]:
    """Schema-check every event whose name is in ``EVENT_SCHEMAS`` (or in
    ``kinds`` when given).  Returns human-readable problem strings —
    empty means the stream is well-formed.  Pure structural validation;
    semantic checks (nonzero FLOPs etc.) belong to the caller."""
    problems = []
    for i, e in enumerate(events):
        name = e.get("event")
        if name not in EVENT_SCHEMAS or (kinds and name not in kinds):
            continue
        for field, (types, required) in EVENT_SCHEMAS[name].items():
            if field not in e:
                if required:
                    problems.append(f"event {i} ({name}): missing {field!r}")
                continue
            v = e[field]
            types_t = types if isinstance(types, tuple) else (types,)
            # bool is an int subclass; only fields that SAY bool take one
            bad = (bool not in types_t if isinstance(v, bool)
                   else not isinstance(v, types))
            if bad:
                problems.append(
                    f"event {i} ({name}): {field!r} has type "
                    f"{type(v).__name__}, wanted {types}")
    return problems


def render(digest: dict) -> str:
    """Human-readable table for the digest."""
    out = []
    out.append(f"processes: {len(digest['processes'])}  "
               f"iterations: {digest['iterations']}")
    if digest["phase_s"]:
        total = sum(digest["phase_s"].values()) or 1.0
        calls = digest.get("phase_calls") or {}
        out.append("")
        out.append(f"{'phase':<28}{'seconds':>10}{'share':>8}{'calls':>8}")
        for name, s in sorted(digest["phase_s"].items(),
                              key=lambda kv: -kv[1]):
            c = calls.get(name)
            out.append(f"{name:<28}{s:>10.3f}{100.0 * s / total:>7.1f}%"
                       f"{c if c is not None else '-':>8}")
    rows = digest["per_iteration"]
    if rows:
        out.append("")
        out.append(f"{'iter':>5}{'iter_s':>9}{'leaves':>10}{'waves':>7}"
                   f"{'recomp':>7}  metrics")
        for r in rows:
            leaves = r.get("leaves")
            leaves_s = ",".join(str(x) for x in leaves) if leaves else "-"
            metr = " ".join(f"{k}={v:.6g}"
                            for k, v in (r.get("metrics") or {}).items())
            waves = r.get("waves")
            out.append(f"{r.get('iteration', '?'):>5}"
                       f"{(r.get('iter_s') or 0.0):>9.3f}"
                       f"{leaves_s:>10}"
                       f"{'-' if waves in (None, -1) else waves:>7}"
                       f"{r.get('recompiles') if r.get('recompiles') is not None else '-':>7}"
                       f"  {metr}")
        if digest.get("cum_row_iters_per_s"):
            out.append(f"cumulative row-iterations/s: "
                       f"{digest['cum_row_iters_per_s']:,}")
    if digest.get("wave_pipeline"):
        w = digest["wave_pipeline"]
        parts = []
        if w.get("waves_per_tree") is not None:
            parts.append(f"{w['waves_per_tree']} waves/tree "
                         f"({w['waves_total']} waves / "
                         f"{w['trees_grown']} trees)")
        if w.get("hist_mode"):
            parts.append(f"hist_mode={w['hist_mode']}")
        if w.get("wave_capacity") is not None:
            parts.append(f"capacity={w['wave_capacity']}")
        if w.get("fused_sibling") is not None:
            parts.append(f"fused_sibling={'on' if w['fused_sibling'] else 'off'}")
        if w.get("fused_grad") is not None:
            parts.append(f"fused_grad={'on' if w['fused_grad'] else 'off'}")
        if w.get("overlap") is not None:
            txt = "on" if w["overlap"] else "off"
            if w.get("overlap_frac") is not None:
                txt += f" ({w['overlap_frac']:.0%} of waves)"
            parts.append(f"overlap={txt}")
        if w.get("grad_hbm_bytes_saved"):
            parts.append(
                f"grad_hbm_saved={w['grad_hbm_bytes_saved'] / 1e6:.1f}MB/it")
        out.append("")
        out.append("wave pipeline: " + ", ".join(parts))
    if digest.get("phase_skew"):
        out.append("")
        out.append(f"{'phase skew (cross-process)':<28}{'min_s':>9}"
                   f"{'max_s':>9}{'spread':>9}{'frac':>7}")
        for name, s in sorted(digest["phase_skew"].items(),
                              key=lambda kv: -kv[1]["spread_frac"]):
            out.append(f"{name:<28}{s['min_s']:>9.3f}{s['max_s']:>9.3f}"
                       f"{s['spread_s']:>9.3f}{s['spread_frac']:>6.1%}")
    if digest.get("kernels"):
        out.append("")
        out.append(f"{'kernel':<28}{'calls':>6}{'achieved':>10}"
                   f"{'roofline':>10}{'frac':>8}")
        for name, k in sorted(digest["kernels"].items(),
                              key=lambda kv: -kv[1]["achieved_s"]):
            out.append(f"{name:<28}{k['calls']:>6}"
                       f"{k['achieved_s']:>9.3f}s"
                       f"{k['roofline_s']:>9.4f}s"
                       f"{k['roofline_frac']:>8.4f}")
    if digest.get("xprof"):
        xp = digest["xprof"]
        out.append("")
        out.append(f"measured roofline (xprof window "
                   f"{xp.get('window_ms', 0):.1f} ms):")
        out.append(f"{'kernel':<28}{'ops':>6}{'measured':>11}"
                   f"{'model':>11}{'frac':>8}{'bound':>7}")
        for name, k in sorted(xp.get("kernels", {}).items(),
                              key=lambda kv: -kv[1]["measured_ms"]):
            model_ms = k.get("model_ms")
            frac = k.get("roofline_frac")
            out.append(
                f"{name:<28}{k['ops']:>6}"
                f"{k['measured_ms']:>9.3f}ms"
                + (f"{model_ms:>9.3f}ms" if model_ms is not None
                   else f"{'—':>11}")
                + (f"{frac:>8.4f}" if frac is not None else f"{'—':>8}")
                + f"{k.get('bound', '—'):>7}")
    if digest.get("compile"):
        c = digest["compile"]
        out.append("")
        out.append(f"compile plane: {c['compiles']} backend compile(s) "
                   f"({c['wall_s']:.2f} s), cache {c['cache_hits']} hit(s) "
                   f"/ {c['cache_misses']} miss(es), "
                   f"{c['retraces']} retrace(s)")
        for jit, ent in sorted((c.get("by_jit") or {}).items(),
                               key=lambda kv: -kv[1]["wall_s"]):
            out.append(f"  {jit:<26} {ent['count']:>4} compile(s)"
                       f"{ent['wall_s']:>9.3f}s")
        for jit, changed in (c.get("retrace_jits") or {}).items():
            out.append(f"  retrace {jit}: {'; '.join(changed[:3])}")
    if digest.get("memory"):
        m = digest["memory"]
        out.append("")
        out.append(f"memory census: peak {m['peak_bytes']:,} bytes "
                   f"(phase {m.get('peak_phase', '?')!r}, "
                   f"{m.get('snapshots', 0)} snapshots)")
        for name, b in sorted((m.get("buffers_last") or {}).items(),
                              key=lambda kv: -kv[1]):
            out.append(f"  {name:<26} {b:>14,}")
        if m.get("audit_survivors"):
            out.append(f"  RELEASE-AUDIT SURVIVORS: "
                       f"{', '.join(m['audit_survivors'])}")
    if digest.get("health"):
        h = digest["health"]
        out.append("")
        verdict = ("DIVERGED" if h.get("divergence_failures")
                   else "FAILED" if h.get("failures") else "healthy")
        out.append(f"training health: {verdict} — {h['failures']} check "
                   f"failure(s), {h['fingerprints']} fingerprint(s), "
                   f"{h['divergence_checks']} divergence audit(s)")
        if h.get("first_failure"):
            f = h["first_failure"]
            out.append(f"  first failure: {f.get('check')} at iteration "
                       f"{f.get('iteration')} in phase {f.get('phase')!r} "
                       f"{f.get('detail')}")
        if h.get("last_fingerprint"):
            lf = h["last_fingerprint"]
            out.append(f"  last fingerprint: iteration "
                       f"{lf.get('iteration')} digest {lf.get('digest')}")
    if digest.get("serve"):
        s = digest["serve"]
        out.append("")
        verdict = "DEGRADED (host fallback)" if s.get("degraded") else "ok"
        out.append(f"serving: {verdict} — {s['requests']} request(s), "
                   f"{s['batches']} batch(es), "
                   f"p50 {s.get('p50_ms')}ms p99 {s.get('p99_ms')}ms")
        if s.get("padded_rows"):
            out.append(f"  batch occupancy {s.get('occupancy'):.1%} "
                       f"({s['rows']:,} rows / {s['padded_rows']:,} padded, "
                       f"{s['pad_waste_rows']:,} pad-waste rows), "
                       f"queue peak {s.get('max_queue_rows', 0)} rows")
        if s.get("overloads") or s.get("deadline_missed"):
            out.append(f"  overloads {s.get('overloads', 0)}, deadline "
                       f"misses {s.get('deadline_missed', 0)}")
        if s.get("explain"):
            x = s["explain"]
            occ = x.get("occupancy")
            out.append(f"  explain: {x['requests']} request(s), "
                       f"{x['batches']} batch(es), "
                       f"p50 {x.get('p50_ms')}ms p99 {x.get('p99_ms')}ms"
                       + (f", occupancy {occ:.1%}" if occ else "")
                       + (f", deadline misses {x['deadline_missed']}"
                          if x.get("deadline_missed") else ""))
        if s.get("fleet"):
            f = s["fleet"]
            line = (f"  fleet: {f['swaps']} swap(s), "
                    f"{f['swaps_rejected']} rejected by canary, "
                    f"{f['rollbacks']} rollback(s), "
                    f"{f['failovers']} replica failover(s)")
            if f.get("last_rollback"):
                lr = f["last_rollback"]
                line += (f" — last rollback: {lr.get('model')} "
                         f"({lr.get('reason')})")
            out.append(line)
        if s.get("shed_by_priority"):
            out.append("  shed by priority: " + ", ".join(
                f"{k}={v}" for k, v in s["shed_by_priority"].items()))
    if digest.get("robust"):
        r = digest["robust"]
        out.append("")
        out.append(f"recovery: {r['checkpoints']} checkpoint(s), "
                   f"{r['restores']} restore(s), {r['retries']} device "
                   f"retr{'y' if r['retries'] == 1 else 'ies'}, "
                   f"{r['stalls']} stall(s), {r['serve_recoveries']} "
                   f"serve recover(ies), {r['faults_injected']} injected "
                   f"fault(s)")
        if r.get("resumed_from_iteration") is not None:
            out.append(f"  resumed from iteration "
                       f"{r['resumed_from_iteration']}")
        if r.get("last_checkpoint"):
            lc = r["last_checkpoint"]
            out.append(f"  last checkpoint: iteration {lc.get('iteration')}"
                       f" ({lc.get('reason')})")
        for point, v in (r.get("retries_by_point") or {}).items():
            out.append(f"  retries at {point:<20} {v.get('retries', 0)} "
                       f"({v.get('transient', 0)} transient, "
                       f"{v.get('fatal', 0)} fatal)")
    if digest.get("online"):
        o = digest["online"]
        out.append("")
        line = (f"online loop: {o.get('refreshes_ok', 0)} refresh(es) "
                f"pushed, {o.get('refreshes_failed', 0)} failed, "
                f"{o.get('refreshes_skipped', 0)} skipped, "
                f"{o['refits']} refit(s)")
        if o.get("last_version"):
            line += f" — live at v{o['last_version']}"
        out.append(line)
        if o.get("last_refit"):
            lr = o["last_refit"]
            out.append(f"  last refit: {lr.get('trees')} tree(s) over "
                       f"{lr.get('rows')} row(s), decay "
                       f"{lr.get('decay')}, {lr.get('mode')} path "
                       f"({o.get('refit_wall_s', 0)}s total)")
        if o.get("skipped_by_reason"):
            out.append("  skipped: " + ", ".join(
                f"{k}={v}" for k, v in o["skipped_by_reason"].items()))
    if digest.get("ingest"):
        g = digest["ingest"]
        out.append("")
        last = g.get("last") or {}
        line = (f"ingest: {g.get('ingestions', 0)} ingestion(s), "
                f"{g.get('rows_total', 0):,} row(s) streamed")
        if last.get("rows_per_s"):
            line += f" — last at {last['rows_per_s']:,.0f} rows/s"
        if last.get("shards", 1) and last.get("shards", 1) > 1:
            line += (f", shard {last.get('shard_id')}/"
                     f"{last.get('shards')} "
                     f"({last.get('local_rows'):,} local rows)")
        if last.get("memmap"):
            line += ", memmap-backed"
        out.append(line)
        if last.get("digest"):
            out.append(f"  dataset digest {last['digest']}")
    if digest.get("drift"):
        d = digest["drift"]
        out.append("")
        verdict = ("BREACHED" if (d.get("drift_breaches")
                                  or d.get("quality_breaches"))
                   else "quiet")
        out.append(f"drift/quality: {verdict} — {d['snapshots']} "
                   f"snapshot(s) ({d.get('drift_breaches', 0)} drift "
                   f"breach(es)), {d['quality_windows']} quality "
                   f"window(s) ({d.get('quality_breaches', 0)} quality "
                   f"breach(es))")
        if d.get("last_snapshot"):
            ls = d["last_snapshot"]
            out.append(f"  last snapshot: {ls.get('model')} "
                       f"v{ls.get('version')} psi_max "
                       f"{ls.get('psi_max')} pred_psi "
                       f"{ls.get('pred_psi')} "
                       f"(worst {ls.get('worst_feature') or '-'}, "
                       f"{ls.get('feat_rows')}/{ls.get('pred_rows')} "
                       f"feat/pred rows)")
        if d.get("last_window"):
            lw = d["last_window"]
            parts = [f"{lw.get('rows')} row(s)"]
            if lw.get("auc") is not None:
                parts.append(f"auc {lw['auc']}")
            if lw.get("auc_delta") is not None:
                parts.append(f"delta {lw['auc_delta']}")
            if lw.get("cal_err") is not None:
                parts.append(f"cal_err {lw['cal_err']}")
            if lw.get("ndcg") is not None:
                parts.append(f"ndcg {lw['ndcg']}")
            out.append(f"  last window: {lw.get('model')} "
                       f"v{lw.get('version')} " + ", ".join(parts))
    if digest.get("stragglers"):
        out.append("")
        out.append(f"{'straggler breaches':<28}{'rank':>6}{'iter':>7}"
                   f"{'ratio':>8}{'median_s':>10}{'rank_s':>10}")
        for s in digest["stragglers"]:
            out.append(f"{(s.get('phase') or '?'):<28}"
                       f"{(s.get('rank') if s.get('rank') is not None else '?'):>6}"
                       f"{(s.get('iteration') if s.get('iteration') is not None else '?'):>7}"
                       f"{(s.get('ratio') or 0.0):>8.2f}"
                       f"{(s.get('median_s') or 0.0):>10.4f}"
                       f"{(s.get('rank_s') or 0.0):>10.4f}")
    if digest.get("reconciliation"):
        out.append("")
        out.append(f"{'reconciliation (meas/model)':<28}{'iters':>6}"
                   f"{'mean':>8}{'last':>8}{'worst':>8}{'@iter':>7}")
        for unit, u in sorted(digest["reconciliation"].items(),
                              key=lambda kv: -(kv[1]["mean_ratio"] or 0)):
            worst_it = u.get("worst_iteration")
            out.append(f"{unit:<28}{u['iterations']:>6}"
                       f"{u['mean_ratio']:>8.2f}"
                       f"{(u.get('last_ratio') or 0.0):>8.2f}"
                       f"{(u.get('worst_ratio') or 0.0):>8.2f}"
                       f"{(worst_it if worst_it is not None else '-'):>7}")
    if digest.get("trace"):
        t = digest["trace"]
        out.append("")
        out.append(f"trace plane: {t['spans']} span(s) across "
                   f"{t['traces']} trace(s) — export with "
                   f"tools/trace_export.py")
        for name, a in sorted(t["by_name"].items(),
                              key=lambda kv: -kv[1]["total_ms"])[:8]:
            out.append(f"  {name:<28} {a['calls']:>6} calls "
                       f"{a['total_ms']:>10.1f} ms")
    if digest["counters"]:
        out.append("")
        out.append("counters:")
        for k, v in digest["counters"].items():
            out.append(f"  {k:<40} {v}")
    if digest.get("parse_errors"):
        out.append(f"\n(parse errors skipped: {digest['parse_errors']})")
    return "\n".join(out)


def main(argv=None) -> int:
    """CLI entry: ``python -m lightgbm_tpu.obs.report <path> [--json]``
    (folded in from the old tools/telemetry_report.py stub)."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.obs.report",
        description="Summarize lightgbm_tpu telemetry JSONL files")
    ap.add_argument("path", help="telemetry dir, one .jsonl file, or a "
                                 "glob")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable digest instead of "
                         "the table")
    args = ap.parse_args(argv)

    files = telemetry_files(args.path)
    if not files:
        print(f"no telemetry files under {args.path!r}", file=sys.stderr)
        return 1
    digest = summarize(load_events(args.path))
    if args.json:
        print(json.dumps(digest))
    else:
        print(f"merged {len(files)} file(s)")
        print(render(digest))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
