"""JAX-native instrumentation: the recompile counter.

XLA recompilation is the classic silent TPU-performance killer — a shape
or static-argument change retraces the whole grower (~40-60s, see the
_JIT_CACHE note in boosting/gbdt.py) and nothing in the training loop
says so.  ``jax.monitoring`` publishes a duration event per backend
compile; hooking it gives an exact process-wide compile counter without
wrapping every jitted closure.  ``boosting/gbdt.py`` snapshots the
counter around each iteration and warns when a steady-state iteration
triggered a retrace.

The hook is installed by :func:`.core.enable` (so the telemetry-off path
never imports jax from here) and is global + permanent once installed:
listeners can't be unregistered without clearing everyone's, and an idle
listener costs a few Python calls per compile — compiles are rare.
"""
from __future__ import annotations

from . import core

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_installed = False


def install_recompile_hook() -> bool:
    """Register the compile listener (idempotent).  False when
    jax.monitoring is unavailable or the registration API changed."""
    global _installed
    if _installed:
        return True
    try:
        import jax.monitoring as monitoring
    except Exception:  # noqa: BLE001
        return False

    def _on_duration(name, secs, **kw):
        if name == _COMPILE_EVENT:
            # straight into the accumulators, bypassing core.count's
            # enabled() gate: the listener outlives disable()/enable()
            # cycles and compile counts are cheap to keep
            core._counters["jax/compiles"] += 1
            core._counters["jax/compile_s"] += float(secs)

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # noqa: BLE001
        return False
    _installed = True
    return True


def compile_count() -> int:
    """Backend compiles observed since the hook was installed."""
    return int(core._counters.get("jax/compiles", 0))


def compile_seconds() -> float:
    return float(core._counters.get("jax/compile_s", 0.0))
