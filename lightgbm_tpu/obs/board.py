"""Live training introspection board: the train-side ``/metrics``
exporter (ISSUE 17).

``engine.train`` arms a :class:`TrainBoard` alongside the telemetry
sink when ``tpu_train_metrics_port`` (or ``LGBM_TPU_TRAIN_METRICS``)
asks for one — same threaded-``http.server`` pattern as
``serve/server.py``, one daemon thread, zero cost on the training
thread beyond the per-event note (the <5% off-path guard covers it).
Endpoints:

- ``GET /metrics`` — Prometheus text: iteration, cumulative
  ``row_iters/s`` + live ``vs_baseline``, per-phase wall fractions,
  checkpoint age, watchdog retry/stall state (scrapeable via the
  provider hook ``set_provider("watchdog", guard.snapshot)``), health
  failures, recompile count, collective bytes, the live per-rank skew
  table and the last reconciliation row.
- ``GET /progress`` — JSON: iteration/total, EMA-smoothed ETA, last-K
  iteration records, ``vs_baseline`` projection from BASELINE.json.
  The ETA survives resume-from-checkpoint: ``start_round`` (the
  restored offset engine.train already tracks) anchors the
  completed-this-run count, so the rate is measured over THIS run's
  iterations, never wall-clock-since-boot.
- ``GET /debug/flight`` — the flight-recorder ring, same shape as the
  serving endpoint.

The board sees events through ``core._set_board_hook`` — the same
one-None-check forward the flight ring uses — so it works with or
without a JSONL sink, and arming it flips ``core.tracing_enabled()``
so the phase timers it renders actually accumulate.

On multi-process runs each rank binds ``port + rank`` (port 0 keeps
every rank ephemeral) and rank 0 additionally renders the fleet skew
table that ``obs/ranks.py`` maintains from the piggybacked stats
exchange.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..utils import log
from . import core, spans, xprof

# bench.py REF_ROW_ITERS_PER_SEC (HIGGS 10.5M rows x 500 iters / 238.5s
# reference GPU wall) — the fallback denominator while BASELINE.json
# "published" stays empty
_REF_ROW_ITERS_PER_S = 10_500_000 * 500 / 238.5

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def baseline_row_iters_per_s() -> float:
    """The live ``vs_baseline`` denominator: BASELINE.json's published
    row_iters/s when one exists, else the bench.py reference constant."""
    try:
        with open(os.path.join(_REPO, "BASELINE.json")) as fh:
            pub = (json.load(fh) or {}).get("published") or {}
        for key in ("row_iters_per_s", "value"):
            v = pub.get(key)
            if v:
                return float(v)
    except (OSError, ValueError, TypeError):
        pass
    return _REF_ROW_ITERS_PER_S


def _fmt(v) -> str:
    """Prometheus sample formatting (serve/metrics.py conventions)."""
    if v is None:
        return "0"
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _head(out: list, name: str, kind: str, help_: str) -> None:
    out.append(f"# HELP {name} {help_}")
    out.append(f"# TYPE {name} {kind}")


class _BoardServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    board = None  # set by TrainBoard.start


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: A002 — silence stderr
        pass

    def _reply(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — http.server API
        board = self.server.board
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._reply(200, board.metrics_text().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/progress":
                self._reply(200, json.dumps(
                    board.progress(), default=core._json_default).encode(),
                    "application/json")
            elif path == "/debug/flight":
                self._reply(200, json.dumps(
                    {"enabled": spans.flight_enabled(),
                     "ring_len": spans.flight_len(),
                     "events": spans.flight_snapshot()},
                    default=core._json_default).encode(),
                    "application/json")
            else:
                self._reply(404, b'{"error": "not found"}',
                            "application/json")
        except (BrokenPipeError, ConnectionResetError):
            pass


class TrainBoard:
    """The exporter: event-fed accumulators + the HTTP thread."""

    def __init__(self, total_rounds: int, start_round: int = 0,
                 port: int = 0, host: str = "127.0.0.1", last_k: int = 32):
        self.total_rounds = int(total_rounds)
        self.start_round = int(start_round)
        self._host = host
        self._port_req = int(port)
        self.port = None
        self._lock = threading.Lock()
        self._t0 = time.time()
        self._iteration = None
        self._completed = 0          # iterations finished THIS run
        self._ema_iter_s = None
        self._last_iter_s = None
        self._row_iters_per_s = 0.0
        self._phase_cum = {}
        self._recent = deque(maxlen=max(int(last_k), 1))
        self._ckpt_t = None
        self._ckpt_iter = None
        self._ckpt_count = 0
        self._restores = 0
        self._retries = 0
        self._stalls = 0
        self._health_failures = 0
        self._stragglers = deque(maxlen=8)
        self._straggler_count = 0
        self._reconciliation = None
        self._providers = {}
        self._baseline = baseline_row_iters_per_s()
        self.hook_s = 0.0            # train-thread seconds spent in notes
        self._server = None
        self._thread = None

    # ------------------------------------------------------------------
    # event intake (train thread)
    # ------------------------------------------------------------------

    def _note(self, name: str, fields: dict) -> None:
        t0 = time.perf_counter()
        try:
            self._dispatch(name, fields)
        except Exception:  # noqa: BLE001 — the exporter never fails train
            pass
        finally:
            self.hook_s += time.perf_counter() - t0

    def _dispatch(self, name: str, fields: dict) -> None:
        if name == "iteration":
            with self._lock:
                self._iteration = int(fields.get("iteration", 0))
                it_s = float(fields.get("iter_s", 0.0) or 0.0)
                self._last_iter_s = it_s
                # EMA over THIS run's iterations only (alpha 0.3): a
                # resumed run's ETA reflects the live rate, not the
                # restored offset's wall clock
                self._ema_iter_s = (it_s if self._ema_iter_s is None
                                    else 0.7 * self._ema_iter_s
                                    + 0.3 * it_s)
                self._completed += 1
                rps = fields.get("cum_row_iters_per_s")
                if rps:
                    self._row_iters_per_s = float(rps)
                for p, s in (fields.get("phase_s") or {}).items():
                    self._phase_cum[p] = \
                        self._phase_cum.get(p, 0.0) + float(s or 0.0)
                self._recent.append({
                    "iteration": self._iteration,
                    "iter_s": round(it_s, 6),
                    "metrics": fields.get("metrics") or {},
                    "recompiles": int(fields.get("recompiles", 0) or 0),
                    "cum_row_iters_per_s": self._row_iters_per_s,
                    "t": round(time.time(), 3),
                })
        elif name == "checkpoint":
            with self._lock:
                self._ckpt_t = time.time()
                self._ckpt_iter = fields.get("iteration")
                self._ckpt_count += 1
        elif name == "restore":
            with self._lock:
                self._restores += 1
        elif name == "retry":
            with self._lock:
                self._retries += 1
        elif name == "device_stall":
            with self._lock:
                self._stalls += 1
        elif name == "health":
            if not fields.get("ok", True):
                with self._lock:
                    self._health_failures += 1
        elif name == "straggler":
            with self._lock:
                self._stragglers.append(dict(fields))
                self._straggler_count += 1
        elif name == "reconciliation":
            with self._lock:
                self._reconciliation = {
                    "iteration": fields.get("iteration"),
                    "units": fields.get("units") or {}}

    def set_provider(self, name: str, fn) -> None:
        """Register a snapshot callable rendered on scrape (e.g. the
        engine's DeviceGuard: ``set_provider("watchdog",
        guard.snapshot)``)."""
        self._providers[name] = fn

    def _provider(self, name: str) -> dict:
        fn = self._providers.get(name)
        if fn is None:
            return {}
        try:
            return dict(fn() or {})
        except Exception:  # noqa: BLE001 — scrape must not raise
            return {}

    # ------------------------------------------------------------------
    # renderers (HTTP thread)
    # ------------------------------------------------------------------

    def _eta_s(self) -> Optional[float]:
        if self._ema_iter_s is None or self._iteration is None:
            return None
        remaining = max(self.total_rounds - (self._iteration + 1), 0)
        return self._ema_iter_s * remaining

    def progress(self) -> dict:
        with self._lock:
            eta = self._eta_s()
            rps = self._row_iters_per_s
            out = {
                "iteration": self._iteration,
                "total_rounds": self.total_rounds,
                "start_round": self.start_round,
                "completed": self._completed,
                "frac": (round((self._iteration + 1) / self.total_rounds,
                               4)
                         if self._iteration is not None
                         and self.total_rounds else None),
                "eta_s": round(eta, 3) if eta is not None else None,
                "ema_iter_s": (round(self._ema_iter_s, 6)
                               if self._ema_iter_s is not None else None),
                "uptime_s": round(time.time() - self._t0, 3),
                "row_iters_per_s": rps,
                "vs_baseline": (round(rps / self._baseline, 4)
                                if rps else None),
                "recent": list(self._recent),
                "checkpoint": {
                    "count": self._ckpt_count,
                    "iteration": self._ckpt_iter,
                    "age_s": (round(time.time() - self._ckpt_t, 3)
                              if self._ckpt_t else None)},
                "restores": self._restores,
                "stragglers": list(self._stragglers),
                "reconciliation": self._reconciliation,
            }
        wd = self._provider("watchdog")
        if wd:
            out["watchdog"] = wd
        fl = self._provider("fleet")
        if fl:
            out["fleet"] = fl
        hub = self._provider("fleet_hub")
        if hub:
            out["fleet_hub"] = hub
        return out

    def metrics_text(self) -> str:
        from . import ranks
        with self._lock:
            it = (self._iteration if self._iteration is not None
                  else self.start_round - 1)
            eta = self._eta_s()
            phase_cum = dict(self._phase_cum)
            rps = self._row_iters_per_s
            last_straggler = (self._stragglers[-1]
                             if self._stragglers else None)
            recon = self._reconciliation
            vals = (self._completed, self._ema_iter_s, self._ckpt_count,
                    self._ckpt_t, self._restores, self._retries,
                    self._stalls, self._health_failures,
                    self._straggler_count)
        (completed, ema, ckpts, ckpt_t, restores, retries, stalls,
         health_fail, stragglers) = vals
        out = []
        _head(out, "tpu_train_uptime_seconds", "gauge",
              "Seconds since the exporter was armed.")
        out.append("tpu_train_uptime_seconds "
                   + _fmt(round(time.time() - self._t0, 3)))
        _head(out, "tpu_train_iteration", "gauge",
              "Last completed boosting iteration (global numbering; "
              "resumes continue from the restored offset).")
        out.append("tpu_train_iteration " + _fmt(it))
        _head(out, "tpu_train_total_rounds", "gauge",
              "Configured num_boost_round for this run.")
        out.append("tpu_train_total_rounds " + _fmt(self.total_rounds))
        _head(out, "tpu_train_start_round", "gauge",
              "Iteration the run started/resumed at.")
        out.append("tpu_train_start_round " + _fmt(self.start_round))
        _head(out, "tpu_train_completed_iterations", "counter",
              "Iterations finished by THIS process lifetime.")
        out.append("tpu_train_completed_iterations " + _fmt(completed))
        _head(out, "tpu_train_iter_seconds", "gauge",
              "EMA-smoothed per-iteration wall seconds.")
        out.append("tpu_train_iter_seconds " + _fmt(ema))
        _head(out, "tpu_train_eta_seconds", "gauge",
              "Smoothed remaining-wall estimate (0 until the first "
              "iteration lands).")
        out.append("tpu_train_eta_seconds "
                   + _fmt(round(eta, 3) if eta is not None else None))
        _head(out, "tpu_train_row_iters_per_s", "gauge",
              "Cumulative row-iterations per second (bench.py's unit).")
        out.append("tpu_train_row_iters_per_s " + _fmt(rps))
        _head(out, "tpu_train_vs_baseline", "gauge",
              "Live row_iters/s over the BASELINE.json reference.")
        out.append("tpu_train_vs_baseline "
                   + _fmt(round(rps / self._baseline, 4) if rps else None))
        total_phase = sum(phase_cum.values())
        _head(out, "tpu_train_phase_seconds", "counter",
              "Cumulative wall seconds per training phase.")
        for p in sorted(phase_cum):
            out.append('tpu_train_phase_seconds{phase="%s"} %s'
                       % (p, _fmt(round(phase_cum[p], 6))))
        _head(out, "tpu_train_phase_frac", "gauge",
              "Fraction of phase-accounted wall per phase.")
        for p in sorted(phase_cum):
            frac = phase_cum[p] / total_phase if total_phase else 0.0
            out.append('tpu_train_phase_frac{phase="%s"} %s'
                       % (p, _fmt(round(frac, 4))))
        _head(out, "tpu_train_checkpoints_total", "counter",
              "Checkpoints written this run.")
        out.append("tpu_train_checkpoints_total " + _fmt(ckpts))
        _head(out, "tpu_train_checkpoint_age_seconds", "gauge",
              "Seconds since the last checkpoint write (0 before any).")
        out.append("tpu_train_checkpoint_age_seconds "
                   + _fmt(round(time.time() - ckpt_t, 3)
                          if ckpt_t else None))
        _head(out, "tpu_train_restores_total", "counter",
              "Checkpoint restores observed.")
        out.append("tpu_train_restores_total " + _fmt(restores))
        _head(out, "tpu_train_retries_total", "counter",
              "Watchdog retry events observed.")
        out.append("tpu_train_retries_total " + _fmt(retries))
        _head(out, "tpu_train_stalls_total", "counter",
              "Device-stall events observed.")
        out.append("tpu_train_stalls_total " + _fmt(stalls))
        _head(out, "tpu_train_health_failures_total", "counter",
              "Failed health checks observed.")
        out.append("tpu_train_health_failures_total " + _fmt(health_fail))
        _head(out, "tpu_train_recompiles_total", "counter",
              "XLA compilations this process (jax/compiles counter).")
        out.append("tpu_train_recompiles_total "
                   + _fmt(core.counter_value("jax/compiles")))
        _head(out, "tpu_train_compile_seconds_total", "counter",
              "Seconds spent in XLA compilation this process.")
        out.append("tpu_train_compile_seconds_total "
                   + _fmt(round(core.counter_value("jax/compile_s"), 3)))
        _head(out, "tpu_train_compile_cache_hits_total", "counter",
              "Persistent compile-cache hits this process.")
        out.append("tpu_train_compile_cache_hits_total "
                   + _fmt(core.counter_value("jax/compile_cache_hits")))
        _head(out, "tpu_train_compile_cache_misses_total", "counter",
              "Persistent compile-cache misses this process.")
        out.append("tpu_train_compile_cache_misses_total "
                   + _fmt(core.counter_value("jax/compile_cache_misses")))
        _head(out, "tpu_train_retraces_total", "counter",
              "Jit retraces attributed to an argument-signature change.")
        out.append("tpu_train_retraces_total "
                   + _fmt(core.counter_value("jax/retraces")))
        comp = xprof.compile_digest()
        if comp.get("by_jit"):
            _head(out, "tpu_train_compile_wall_seconds", "counter",
                  "Backend-compile wall seconds attributed per jit "
                  "(dispatching phase).")
            for jit, ent in sorted(comp["by_jit"].items()):
                out.append('tpu_train_compile_wall_seconds{jit="%s"} %s'
                           % (jit, _fmt(ent.get("wall_s"))))
        coll = [(k, v) for k, v in core.counters_snapshot().items()
                if k.startswith("collective/") and k.endswith("bytes")]
        _head(out, "tpu_train_collective_bytes_total", "counter",
              "Bytes moved per collective kind (traced_* = in-jit).")
        for k, v in sorted(coll):
            kind = k[len("collective/"):-len("/bytes")] \
                if k.endswith("/bytes") else \
                k[len("collective/"):-len("/traced_bytes")] + "/traced"
            out.append('tpu_train_collective_bytes_total{kind="%s"} %s'
                       % (kind, _fmt(v)))
        wd = self._provider("watchdog")
        if wd:
            _head(out, "tpu_train_watchdog_active", "gauge",
                  "1 when the device watchdog (or fault harness) is "
                  "armed.")
            out.append("tpu_train_watchdog_active "
                       + _fmt(wd.get("active")))
            _head(out, "tpu_train_watchdog_retries", "gauge",
                  "Retries the in-process watchdog has burned.")
            out.append("tpu_train_watchdog_retries "
                       + _fmt(wd.get("retry_count")))
            _head(out, "tpu_train_watchdog_stalls", "gauge",
                  "Stalls the in-process watchdog has stamped.")
            out.append("tpu_train_watchdog_stalls "
                       + _fmt(wd.get("stall_count")))
            _head(out, "tpu_train_watchdog_deadline_seconds", "gauge",
                  "Current per-call watchdog deadline.")
            out.append("tpu_train_watchdog_deadline_seconds "
                       + _fmt(wd.get("deadline_s")))
        fl = self._provider("fleet")
        if fl:
            _head(out, "tpu_train_fleet_world_size", "gauge",
                  "Live ranks in the elastic training fleet.")
            out.append("tpu_train_fleet_world_size "
                       + _fmt(fl.get("world")))
            _head(out, "tpu_train_fleet_rank", "gauge",
                  "This process's current shard rank (member id as "
                  "label — stable across resizes).")
            out.append('tpu_train_fleet_rank{member="%s"} %s'
                       % (fl.get("member"), _fmt(fl.get("rank"))))
            _head(out, "tpu_train_fleet_epoch", "gauge",
                  "Fleet epoch (bumped by every resize).")
            out.append("tpu_train_fleet_epoch " + _fmt(fl.get("epoch")))
            _head(out, "tpu_train_fleet_dead_ranks", "gauge",
                  "Members classified dead since launch.")
            out.append("tpu_train_fleet_dead_ranks "
                       + _fmt(len(fl.get("dead") or ())))
            _head(out, "tpu_train_fleet_recoveries_total", "counter",
                  "Elastic recoveries (rollback + resize) this rank "
                  "has run.")
            out.append("tpu_train_fleet_recoveries_total "
                       + _fmt(fl.get("recoveries")))
            _head(out, "tpu_train_fleet_pending_join", "gauge",
                  "Healed ranks parked at the hub awaiting a resize.")
            out.append("tpu_train_fleet_pending_join "
                       + _fmt(fl.get("pending_join")))
            members = fl.get("members") or {}
            if members:
                _head(out, "tpu_train_fleet_member_age_seconds", "gauge",
                      "Seconds since each live member's last heartbeat "
                      "(coordinator view).")
                for m in sorted(members):
                    out.append(
                        'tpu_train_fleet_member_age_seconds{member="%s",'
                        'shard="%s"} %s'
                        % (m, members[m].get("shard"),
                           _fmt(members[m].get("age_s"))))
        _head(out, "tpu_train_stragglers_total", "counter",
              "Straggler breaches detected (rank 0 only).")
        out.append("tpu_train_stragglers_total " + _fmt(stragglers))
        if last_straggler is not None:
            _head(out, "tpu_train_straggler_ratio", "gauge",
                  "Last straggler breach: rank wall over fleet median.")
            out.append(
                'tpu_train_straggler_ratio{rank="%s",phase="%s"} %s'
                % (last_straggler.get("rank"),
                   last_straggler.get("phase"),
                   _fmt(last_straggler.get("ratio"))))
        skew = ranks.skew_table()
        if skew.get("ranks"):
            _head(out, "tpu_train_phase_skew_seconds", "gauge",
                  "Per-rank per-iteration phase wall from the last "
                  "stats exchange.")
            for r in sorted(skew["ranks"]):
                for p, s in sorted(skew["ranks"][r].items()):
                    out.append(
                        'tpu_train_phase_skew_seconds{rank="%s",'
                        'phase="%s"} %s' % (r, p, _fmt(s)))
        if recon and recon.get("units"):
            _head(out, "tpu_train_reconciliation_ratio", "gauge",
                  "Measured over modeled phase seconds per cost-model "
                  "unit (last scored iteration).")
            for unit, u in sorted(recon["units"].items()):
                out.append(
                    'tpu_train_reconciliation_ratio{unit="%s"} %s'
                    % (unit, _fmt(u.get("ratio"))))
        _head(out, "tpu_train_flight_enabled", "gauge",
              "1 when the flight recorder ring is armed.")
        out.append("tpu_train_flight_enabled "
                   + _fmt(spans.flight_enabled()))
        return "\n".join(out) + "\n"

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> "TrainBoard":
        global _BOARD
        self._server = _BoardServer((self._host, self._port_req),
                                    _Handler)
        self._server.board = self
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="lgbm-train-board",
            daemon=True)
        self._thread.start()
        core._set_board_hook(self._note)
        from .trace import install_recompile_hook
        install_recompile_hook()
        # compile-plane gauges (cache hits/misses, per-jit walls) need
        # the jax.monitoring listeners live for the board's lifetime
        xprof.install_compile_observer()
        if not spans.flight_enabled():
            # the board's /debug/flight and the straggler dump both
            # want a ring; arm the default size unless the env says no
            spans.enable_flight(spans.flight_len_from_env(256))
        _BOARD = self
        return self

    def stop(self) -> None:
        global _BOARD
        core._set_board_hook(None)
        if _BOARD is self:
            _BOARD = None
        if self._server is not None:
            try:
                self._server.shutdown()
                self._server.server_close()
            except OSError:
                pass
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"


_BOARD: Optional[TrainBoard] = None


def active() -> bool:
    """True when a TrainBoard exporter is armed in this process."""
    return _BOARD is not None


def current() -> Optional[TrainBoard]:
    return _BOARD


def resolve_port(config) -> Optional[int]:
    """The exporter port for this run, or None for off.  The env var
    wins over the config knob: ``LGBM_TPU_TRAIN_METRICS=<port>`` arms
    it (0 = ephemeral), ``off``/``false``/``-1`` disarms; unset falls
    through to ``tpu_train_metrics_port`` (-1 default = off)."""
    env = os.environ.get("LGBM_TPU_TRAIN_METRICS")
    if env is not None and env.strip():
        v = env.strip().lower()
        if v in ("off", "false", "no", "none"):
            return None
        try:
            p = int(v)
        except ValueError:
            log.warning("LGBM_TPU_TRAIN_METRICS=%r is not a port; "
                        "train metrics exporter stays off", env)
            return None
        return p if p >= 0 else None
    p = int(getattr(config, "tpu_train_metrics_port", -1) or -1)
    return p if p >= 0 else None


def maybe_start(config, total_rounds: int,
                start_round: int = 0) -> Optional[TrainBoard]:
    """Arm the exporter when configured (engine.train's hook).  A fixed
    port is offset by the process index so every rank of a multi-host
    run exports locally without colliding; bind failures log and
    continue — introspection never kills a train run."""
    port = resolve_port(config)
    if port is None:
        return None
    if port > 0:
        port += core._process_index()
    board = TrainBoard(total_rounds, start_round=start_round, port=port)
    try:
        board.start()
    except OSError as exc:
        log.warning("train metrics exporter failed to bind port %d "
                    "(%s); continuing without it", port, exc)
        return None
    log.info("train metrics exporter: %s/metrics", board.url)
    return board
