"""Measured roofline plane: profiler capture, trace parsing, attribution.

Closes the loop from a captured ``jax.profiler`` trace to the four
analytic cost models.  Four pieces, all CPU-smokeable:

1. **Windowed capture** — :func:`maybe_window` arms a
   :class:`WindowedCapture` around ``tpu_xprof_iters`` mid-train
   iterations (skipping the warmup/compile iteration) when
   ``tpu_xprof`` / ``LGBM_TPU_XPROF`` is set.  ``engine.train`` and
   ``bench.py`` drive it with one ``step()`` per completed iteration;
   the trace lands under the telemetry sink (``<sink>/xprof``) so one
   artifact dir carries both event stream and profile.

2. **Stdlib trace parsing** — :func:`parse_trace_dir` reads the
   ``*.trace.json.gz`` Chrome-trace stream the profiler emits (gzip +
   json only, no tensorboard/tsl import) and never raises on empty,
   truncated, or gzip-corrupt artifacts: failures land in the result's
   ``errors`` list so callers can triage instead of crash.

3. **Attribution + measured roofline** — :func:`attribute` buckets
   complete-event durations by the ``lgbm/*`` scopes the codebase
   already stamps (``core.phase`` TraceAnnotations on the host track,
   ``named_scope`` metadata in device-op names/args on TPU) plus an
   ``unattributed`` residual per device track.  ``measured_rooflines``
   joins the buckets against ``wave_kernel_cost`` / ``partition_cost``
   / ``rank_pair_cost`` / ``shap_cost`` into ``kernel_measured`` rows
   (achieved ms vs model ms, roofline fraction, HBM-vs-MXU bound) that
   the digest, report, Reconciler, bench_history and prof_kernels all
   consume.

4. **Compile observability** — :func:`install_compile_observer` hooks
   ``jax.monitoring`` for per-jit backend-compile walls and persistent
   compile-cache hits/misses, and :func:`watch_jit` (composed into
   ``profile.wrap``) attributes retraces to the argument whose
   signature changed.  Everything surfaces as ``compile`` events,
   board gauges, and :func:`compile_digest`.
"""
from __future__ import annotations

import glob
import gzip
import json
import logging
import os
import re
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import core

log = logging.getLogger("lightgbm_tpu.obs.xprof")

__all__ = [
    "WindowedCapture",
    "attribute",
    "compile_digest",
    "install_compile_observer",
    "maybe_window",
    "measured_rooflines",
    "parse_trace_dir",
    "record_measured",
    "reset_xprof",
    "resolve_trace_dir",
    "resolve_window",
    "trace_files",
    "train_context",
    "watch_jit",
    "xprof_digest",
]

# ---------------------------------------------------------------------------
# trace parsing (stdlib only)
# ---------------------------------------------------------------------------

# scopes stamped by core.phase / profile.wrap / named_scope throughout
# the codebase; anything matching is attributable
_SCOPE_RE = re.compile(r"lgbm/[A-Za-z0-9_.\-]+")

# device-op events whose name is executor plumbing, not kernel work —
# they overlap the real op events and would double-count the residual
_INFRA_RE = re.compile(r"::")


def trace_files(path: str) -> List[str]:
    """All Chrome-trace artifacts under *path* (recursive).

    ``jax.profiler`` writes ``plugins/profile/<ts>/<host>.trace.json.gz``;
    plain ``.trace.json`` is accepted too for hand-built fixtures.
    """
    if not path or not os.path.isdir(path):
        return []
    out = []
    for pat in ("*.trace.json.gz", "*.trace.json"):
        out.extend(glob.glob(os.path.join(path, "**", pat), recursive=True))
    return sorted(set(out))


def _load_trace(path: str) -> Dict[str, Any]:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as fh:
        doc = json.loads(fh.read().decode("utf-8", "replace"))
    if not isinstance(doc, dict):
        raise ValueError("trace root is not an object")
    return doc


def _is_device_track(proc: str, thread: str) -> bool:
    """True when a (process, thread) pair carries real device-op events.

    TPU/GPU traces give ops their own ``/device:...`` processes; CPU
    traces run the XLA thunk executor on host threads whose names carry
    the ``XLA`` client marker.  The plain ``python`` thread is host-side
    profiler noise (every interpreted call) and is never a device track.
    """
    if "/device:" in proc or proc.startswith("/tpu") or proc.startswith("/gpu"):
        return True
    return "xla" in thread.lower()


def parse_trace_dir(path: str) -> Dict[str, Any]:
    """Parse every trace artifact under *path* into one flat op list.

    Never raises for bad artifacts: empty dirs, truncated gzip streams
    and corrupt json all produce an explicit empty result with the
    per-file failure recorded in ``errors``.

    Returns ``{"dir", "files", "parsed", "errors", "ops", "tracks",
    "window_us"}``.  ``ops`` holds only the SCOPED events — each
    ``{"name", "scope", "device", "thread", "dur_us", "ts"}`` with
    ``device`` the process/track label for device tracks and ``""``
    for host annotation events.  Unscoped device-op work is aggregated
    on the fly into ``tracks`` (``{track: {ops, busy_us,
    unattributed_us}}``) — a CPU while-loop can emit 10^5..10^6 tiny
    thunk events per iteration and materializing them all would cost
    hundreds of MB.
    """
    files = trace_files(path)
    out: Dict[str, Any] = {
        "dir": path, "files": len(files), "parsed": 0,
        "errors": [], "ops": [], "tracks": {}, "window_us": 0.0,
    }
    for f in files:
        try:
            doc = _load_trace(f)
        except (OSError, EOFError, ValueError) as exc:
            out["errors"].append(
                "%s: %s" % (os.path.basename(f), type(exc).__name__))
            continue
        events = doc.get("traceEvents")
        if not isinstance(events, list):
            out["errors"].append(
                "%s: no traceEvents list" % os.path.basename(f))
            continue
        out["parsed"] += 1
        _fold_events(events, out)
    return out


def _fold_events(events: Sequence[Any], out: Dict[str, Any]) -> None:
    procs: Dict[Any, str] = {}
    threads: Dict[Tuple[Any, Any], str] = {}
    for e in events:  # metadata pass: pid/tid -> names
        if not isinstance(e, dict) or e.get("ph") != "M":
            continue
        args = e.get("args") or {}
        if e.get("name") == "process_name":
            procs[e.get("pid")] = str(args.get("name", ""))
        elif e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = str(args.get("name", ""))

    t_lo, t_hi = None, None
    tracks = out["tracks"]
    for e in events:
        if not isinstance(e, dict) or e.get("ph") != "X":
            continue
        try:
            ts = float(e.get("ts", 0.0))
            dur = float(e.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        if dur < 0:
            continue
        t_lo = ts if t_lo is None else min(t_lo, ts)
        t_hi = ts + dur if t_hi is None else max(t_hi, ts + dur)
        name = str(e.get("name", ""))
        proc = procs.get(e.get("pid"), "")
        thread = threads.get((e.get("pid"), e.get("tid")), "")
        device = _is_device_track(proc, thread)
        scope = _scope_of(name, e.get("args") if device else None)
        if device and not _INFRA_RE.search(name):
            track = proc or "device"
            t = tracks.get(track)
            if t is None:
                t = tracks[track] = {"ops": 0, "busy_us": 0.0,
                                     "unattributed_us": 0.0}
            t["ops"] += 1
            t["busy_us"] += dur
            if scope is None:
                t["unattributed_us"] += dur
        if scope is None:
            continue  # unscoped: host interpreter noise / aggregated above
        out["ops"].append({
            "name": name[:160],
            "scope": scope,
            "device": (proc or "device") if device else "",
            "thread": thread,
            "dur_us": dur,
            "ts": ts,
        })
    if t_lo is not None:
        out["window_us"] = max(out["window_us"], t_hi - t_lo)


def _scope_of(name: str, args: Any) -> Optional[str]:
    if name.startswith("lgbm/"):
        # host TraceAnnotations carry the full phase name verbatim
        # ("lgbm/tree growth" — spaces allowed); device-op paths are
        # slash-separated identifiers ("lgbm/wave_hist/fusion.3") whose
        # first component is the scope
        if " " in name:
            return name
        m = _SCOPE_RE.match(name)
        return m.group(0) if m else name
    m = _SCOPE_RE.search(name)
    if m:
        return m.group(0)
    if isinstance(args, dict):
        # TPU device ops carry the named_scope path in metadata args
        # (long_name / tf_op); scan values only on device tracks
        for v in args.values():
            if isinstance(v, str):
                m = _SCOPE_RE.search(v)
                if m:
                    return m.group(0)
    return None


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------

def attribute(parsed: Dict[str, Any]) -> Dict[str, Any]:
    """Bucket parsed op durations by ``lgbm/*`` scope, per device.

    Returns ``{"window_ms", "kernels": {scope: {ops, measured_ms,
    devices}}, "devices": {track: {ops, busy_ms, unattributed_ms}},
    "errors", "files", "parsed"}``.  The ``unattributed`` residual only
    accumulates on device tracks — host annotation spans either match a
    scope or are interpreter noise, never missing kernel work.
    """
    kernels: Dict[str, Dict[str, Any]] = {}
    for op in parsed.get("ops", ()):
        k = kernels.setdefault(
            op["scope"], {"ops": 0, "measured_ms": 0.0, "devices": set()})
        k["ops"] += 1
        k["measured_ms"] += op["dur_us"] / 1e3
        k["devices"].add(op["device"] or "host")
    for k in kernels.values():
        k["devices"] = sorted(k["devices"])
        k["measured_ms"] = round(k["measured_ms"], 4)
    devices = {
        track: {"ops": int(t["ops"]),
                "busy_ms": round(t["busy_us"] / 1e3, 4),
                "unattributed_ms": round(t["unattributed_us"] / 1e3, 4)}
        for track, t in parsed.get("tracks", {}).items()}
    return {
        "window_ms": round(parsed.get("window_us", 0.0) / 1e3, 4),
        "kernels": kernels,
        "devices": devices,
        "errors": list(parsed.get("errors", ())),
        "files": parsed.get("files", 0),
        "parsed": parsed.get("parsed", 0),
    }


# ---------------------------------------------------------------------------
# measured roofline: join attribution against the analytic cost models
# ---------------------------------------------------------------------------

# scope -> cost-model family.  The hist scopes all describe one full
# histogram pass over the binned matrix; partition scopes move every row
# once per split wave; grad is the objective (rank_pair when lambdarank
# query sizes are in the context); shap is the explainer sweep.
_HIST_SCOPES = frozenset((
    "lgbm/pallas_hist", "lgbm/pallas_hist_wave", "lgbm/wave_hist",
    "lgbm/hist_onehot", "lgbm/hist_scatter", "lgbm/hist_wave_xla",
    "lgbm/grow", "lgbm/grow_apply_fused",
))
_PART_SCOPES = frozenset((
    "lgbm/wave_partition", "lgbm/partition", "lgbm/grow_apply",
    "lgbm/apply_leaf", "lgbm/wave_split_phase",
))


def train_context(booster: Any = None, **extra: Any) -> Dict[str, Any]:
    """Cost-model context for :func:`measured_rooflines`.

    Pulls dataset shape and wave-pipeline state off a live ``Booster``
    when given; ``extra`` overrides/extends (``iters`` — the number of
    captured iterations — always comes from the capture window).
    """
    ctx: Dict[str, Any] = {}
    gbdt = getattr(booster, "_gbdt", None)
    if gbdt is not None:
        ds = getattr(gbdt, "train_ds", None)
        if ds is not None:
            ctx["rows"] = int(getattr(ds, "num_data", 0) or 0)
            ctx["features"] = int(getattr(ds, "num_features", 0) or 0)
        cfg = getattr(gbdt, "config", None)
        if cfg is not None:
            ctx["bins"] = int(getattr(cfg, "max_bin", 255) or 255)
            ctx["leaves"] = int(getattr(cfg, "num_leaves", 31) or 31)
        wi = getattr(gbdt, "_wave_info", None) or {}
        if wi.get("hist_mode"):
            ctx["mode"] = wi["hist_mode"]
        if wi.get("fused_sibling") is not None:
            ctx["fused"] = bool(wi["fused_sibling"])
    ctx.update({k: v for k, v in extra.items() if v is not None})
    return ctx


def _model_cost(scope: str, ctx: Dict[str, Any]
                ) -> Optional[Tuple[float, float, str]]:
    """(flops, nbytes, model-name) for *scope* over the window, or None.

    Costs are per full pass and scaled by ``ctx["iters"]`` (captured
    iterations); scopes with no analytic model stay measured-only rows.
    """
    if not ctx:
        return None
    iters = max(int(ctx.get("iters", 1) or 1), 1)
    N = int(ctx.get("rows", 0) or 0)
    F = int(ctx.get("features", 0) or 0)
    B = int(ctx.get("bins", 255) or 255)
    try:
        if scope in _HIST_SCOPES and N and F:
            from ..ops.pallas_hist import wave_kernel_cost
            flops, nbytes = wave_kernel_cost(
                N, F, B, mode=str(ctx.get("mode") or "2xbf16"),
                packed=bool(ctx.get("packed", False)),
                fused=bool(ctx.get("fused", False)))
            return flops * iters, nbytes * iters, "wave_kernel"
        if scope in _PART_SCOPES and N:
            from ..core.splitter import partition_cost
            splits = max(int(ctx.get("leaves", 31) or 31) - 1, 1)
            flops, nbytes = partition_cost(N, splits=splits, batched=True)
            return flops * iters, nbytes * iters, "partition"
        if scope == "lgbm/grad" and ctx.get("query_sizes"):
            from ..ops.rank import rank_pair_cost
            sizes = list(ctx["query_sizes"])
            flops, nbytes = rank_pair_cost(
                sizes, int(ctx.get("chunk_elems", 1 << 20)))
            return flops * iters, nbytes * iters, "rank_pair"
        if scope == "lgbm/forest_shap" and ctx.get("shap"):
            from ..ops.treeshap import shap_cost
            flops, nbytes = shap_cost(**ctx["shap"])
            return float(flops), float(nbytes), "shap"
    except Exception as exc:  # a bad context must not kill the report
        log.debug("cost model for %s failed: %s", scope, exc)
    return None


def measured_rooflines(attrib: Dict[str, Any],
                       context: Optional[Dict[str, Any]] = None
                       ) -> List[Dict[str, Any]]:
    """Join attributed kernels against the analytic cost models.

    One row per attributed scope (plus one ``unattributed`` row per
    device track with residual time), shaped for the ``kernel_measured``
    event schema: achieved ms vs roofline-model ms, roofline fraction
    (model/achieved, 1.0 = running at the roofline) and whether the
    model says the kernel is MXU- or HBM-bound.
    """
    context = context or {}
    window_ms = float(attrib.get("window_ms", 0.0) or 0.0)
    rows: List[Dict[str, Any]] = []
    for scope in sorted(attrib.get("kernels", ())):
        k = attrib["kernels"][scope]
        measured_ms = float(k["measured_ms"])
        row: Dict[str, Any] = {
            "kernel": scope,
            "ops": int(k["ops"]),
            "measured_ms": round(measured_ms, 4),
            "window_ms": window_ms,
            "source": "xprof",
            "device": ",".join(k.get("devices", ())) or "host",
        }
        if window_ms > 0:
            row["occupancy"] = round(measured_ms / window_ms, 4)
        cost = _model_cost(scope, context)
        if cost is not None and measured_ms > 0:
            flops, nbytes, model = cost
            try:
                from .profile import device_peaks, roofline_seconds
                pf, pb = device_peaks()
                model_ms = roofline_seconds(flops, nbytes) * 1e3
            except Exception:
                model_ms, pf, pb = 0.0, 0.0, 0.0
            if model_ms > 0:
                row.update({
                    "flops": float(flops), "bytes": float(nbytes),
                    "model": model,
                    "model_ms": round(model_ms, 4),
                    "roofline_frac": round(model_ms / measured_ms, 4),
                    "bound": ("mxu" if pf and pb
                              and flops / pf >= nbytes / pb else "hbm"),
                })
        rows.append(row)
    for dev in sorted(attrib.get("devices", ())):
        d = attrib["devices"][dev]
        if d.get("unattributed_ms", 0.0) <= 0:
            continue
        row = {
            "kernel": "unattributed",
            "ops": int(d["ops"]),
            "measured_ms": round(float(d["unattributed_ms"]), 4),
            "window_ms": window_ms,
            "source": "xprof",
            "device": dev,
        }
        if window_ms > 0:
            row["occupancy"] = round(row["measured_ms"] / window_ms, 4)
        rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# module state: digest + event emission
# ---------------------------------------------------------------------------

def _fresh_state() -> Dict[str, Any]:
    return {"kernels": {}, "window_ms": 0.0, "devices": {},
            "trace_dir": "", "errors": [], "files": 0, "parsed": 0}


_state = _fresh_state()


def record_measured(attrib: Dict[str, Any],
                    context: Optional[Dict[str, Any]] = None,
                    trace_dir: str = "") -> List[Dict[str, Any]]:
    """Emit ``kernel_measured`` events + fold into the xprof digest."""
    rows = measured_rooflines(attrib, context)
    _state["window_ms"] = float(attrib.get("window_ms", 0.0) or 0.0)
    _state["devices"] = {
        d: dict(v) for d, v in attrib.get("devices", {}).items()}
    _state["trace_dir"] = trace_dir or str(attrib.get("dir", ""))
    _state["errors"] = list(attrib.get("errors", ()))
    _state["files"] = int(attrib.get("files", 0) or 0)
    _state["parsed"] = int(attrib.get("parsed", 0) or 0)
    for row in rows:
        key = row["kernel"]
        if key == "unattributed" and row.get("device"):
            key = "unattributed(%s)" % row["device"]
        _state["kernels"][key] = {
            f: row[f] for f in (
                "ops", "measured_ms", "model_ms", "roofline_frac",
                "bound", "occupancy", "model") if f in row}
        core.event("kernel_measured", **row)
    return rows


def xprof_digest() -> Dict[str, Any]:
    """Measured-roofline block for ``core.digest()`` (``{}`` when idle)."""
    if not _state["kernels"] and not _state["errors"]:
        return {}
    out = {
        "window_ms": round(_state["window_ms"], 3),
        "trace_files": _state["files"],
        "trace_parsed": _state["parsed"],
        "kernels": {k: dict(v) for k, v in sorted(_state["kernels"].items())},
    }
    if _state["errors"]:
        out["errors"] = list(_state["errors"])
    if _state["trace_dir"]:
        out["trace_dir"] = _state["trace_dir"]
    return out


# ---------------------------------------------------------------------------
# compile observability
# ---------------------------------------------------------------------------

def _fresh_compile() -> Dict[str, Any]:
    return {"count": 0, "wall_s": 0.0, "by_jit": {},
            "cache_hits": 0, "cache_misses": 0, "retraces": 0}


_compile = _fresh_compile()
_observer_on = False

_CACHE_EVENTS = {
    "/jax/compilation_cache/cache_hits": "cache_hits",
    "/jax/compilation_cache/cache_misses": "cache_misses",
}


def _on_compile_duration(event: str, duration: float, **_kw: Any) -> None:
    if event != "/jax/core/compile/backend_compile_duration":
        return
    # compiles fire under the phase timer of the jit that dispatched
    # them, so the current phase IS the per-jit attribution
    jit = core.current_phase() or "<top>"
    _compile["count"] += 1
    _compile["wall_s"] += float(duration)
    ent = _compile["by_jit"].setdefault(jit, {"count": 0, "wall_s": 0.0})
    ent["count"] += 1
    ent["wall_s"] += float(duration)
    core.event("compile", kind="backend_compile", jit=jit,
               wall_s=round(float(duration), 4))


def _on_cache_event(event: str, **_kw: Any) -> None:
    key = _CACHE_EVENTS.get(event)
    if key is None:
        return
    _compile[key] += 1
    # direct counter bump (trace.py pattern): cache traffic must be
    # countable even when no sink/board armed yet at fire time
    core._counters["jax/compile_%s" % key] += 1.0
    core.event("compile", kind=key[:-1])  # cache_hit / cache_miss


def install_compile_observer() -> bool:
    """Hook ``jax.monitoring`` for compile walls + cache traffic.

    Idempotent; returns False when jax.monitoring is unavailable.
    """
    global _observer_on
    if _observer_on:
        return True
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_compile_duration)
        monitoring.register_event_listener(_on_cache_event)
    except Exception as exc:
        log.debug("compile observer unavailable: %s", exc)
        return False
    _observer_on = True
    return True


def compile_digest() -> Dict[str, Any]:
    """Compile-plane block for ``core.digest()`` (``{}`` when idle)."""
    c = _compile
    if not (c["count"] or c["cache_hits"] or c["cache_misses"]
            or c["retraces"]):
        return {}
    return {
        "compiles": c["count"],
        "wall_s": round(c["wall_s"], 4),
        "by_jit": {k: {"count": v["count"], "wall_s": round(v["wall_s"], 4)}
                   for k, v in sorted(c["by_jit"].items())},
        "cache_hits": c["cache_hits"],
        "cache_misses": c["cache_misses"],
        "retraces": c["retraces"],
    }


# --- retrace attribution ----------------------------------------------------

def _arg_sig(args: Tuple[Any, ...], kwargs: Dict[str, Any]
             ) -> Tuple[Tuple[str, str], ...]:
    """Flat (label, "shape dtype"/repr) signature of a call's leaves."""
    sig: List[Tuple[str, str]] = []

    def leaf(label: str, v: Any) -> None:
        shape = getattr(v, "shape", None)
        dtype = getattr(v, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((label, "%s %s" % (tuple(shape), dtype)))
        elif isinstance(v, (list, tuple)):
            for i, item in enumerate(v):
                leaf("%s[%d]" % (label, i), item)
        elif isinstance(v, dict):
            for k in sorted(v, key=str):
                leaf("%s[%r]" % (label, k), v[k])
        else:
            sig.append((label, type(v).__name__))

    for i, a in enumerate(args):
        leaf("arg%d" % i, a)
    for k in sorted(kwargs):
        leaf(k, kwargs[k])
    return tuple(sig)


def _sig_diff(old: Tuple[Tuple[str, str], ...],
              new: Tuple[Tuple[str, str], ...]) -> List[str]:
    prev = dict(old)
    cur = dict(new)
    changed = []
    for label in sorted(set(prev) | set(cur)):
        a, b = prev.get(label, "<absent>"), cur.get(label, "<absent>")
        if a != b:
            changed.append("%s: %s -> %s" % (label, a, b))
    return changed or ["call structure changed"]


# true while any WindowedCapture is tracing — _Watched wrappers stamp
# their jit's TraceAnnotation only inside the window
_capturing = [False]


class _Watched:
    """Retrace watcher: flags per-jit argument-signature changes.

    A signature change after the first call is exactly the condition
    under which ``jax.jit`` retraces — the diff names the argument that
    forced it, which is the attribution direction 3's AOT work needs.
    """

    def __init__(self, name: str, fn: Callable):
        self._name = name
        self._fn = fn
        self._last: Optional[Tuple[Tuple[str, str], ...]] = None
        self._sigs: set = set()

    def __getattr__(self, item: str) -> Any:  # lower(), trace(), ...
        return getattr(self._fn, item)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        try:
            sig = _arg_sig(args, kwargs)
        except Exception:
            sig = None
        if sig is not None:
            if self._last is not None and sig != self._last \
                    and sig not in self._sigs:
                _compile["retraces"] += 1
                core._counters["jax/retraces"] += 1.0
                changed = _sig_diff(self._last, sig)
                core.event("compile", kind="retrace", jit=self._name,
                           changed=changed[:8],
                           signatures=len(self._sigs) + 1)
                log.info("retrace %s: %s", self._name,
                         "; ".join(changed[:3]))
            self._sigs.add(sig)
            self._last = sig
        if _capturing[0]:
            # stamp the dispatch span so the trace attributes this jit
            # unit even on backends where named_scope metadata is lost
            # (CPU thunks) — the host-side annotation IS the scope
            import jax
            with jax.profiler.TraceAnnotation(self._name):
                return self._fn(*args, **kwargs)
        return self._fn(*args, **kwargs)


def watch_jit(name: str, fn: Optional[Callable]) -> Optional[Callable]:
    """Wrap *fn* with retrace attribution when the xprof plane is armed.

    Identity when disarmed or already wrapped — safe to compose into
    ``profile.wrap`` unconditionally.
    """
    if fn is None or not _armed() or isinstance(fn, _Watched):
        return fn
    return _Watched(name, fn)


# ---------------------------------------------------------------------------
# windowed capture
# ---------------------------------------------------------------------------

def _start_session() -> Any:
    """Open a profiler session with the Python-call tracer OFF.

    The default ``jax.profiler.start_trace`` traces every interpreter
    call; a GBDT iteration does enough host work that the capture
    drowns in ``$builtins`` frames and ``stop_trace`` spends minutes
    serializing them.  The XLA session API takes ProfileOptions, so
    drop to it when available (falls back to the public API).

    Caveat that survives either way: on the CPU backend the thunk
    executor emits one TraceMe per HLO op *per while-loop iteration*,
    so capture volume scales with row count — keep CPU windows on
    small shapes (the smoke uses ~500 rows).  TPU device tracing does
    not have this pathology.
    """
    try:
        from jax._src.lib import xla_client
        opts = xla_client.profiler.ProfileOptions()
        opts.python_tracer_level = 0
        return xla_client.profiler.ProfilerSession(opts)
    except Exception:
        import jax
        jax.profiler.start_trace(_PUBLIC_TRACE_DIR[0])
        return None


def _stop_session(session: Any, out_dir: str) -> None:
    if session is not None:
        session.export(session.stop(), out_dir)
    else:
        import jax
        jax.profiler.stop_trace()


# fallback public-API path needs the dir at start time; stashed by
# WindowedCapture._start just before _start_session runs
_PUBLIC_TRACE_DIR = [""]


class WindowedCapture:
    """Arms ``jax.profiler`` around a few mid-train iterations.

    Drive with one :meth:`step` per *completed* training iteration: the
    first ``skip`` iterations (warmup + compile) pass through, then the
    trace starts, runs ``iters`` iterations, syncs, stops, and ingests
    itself (parse → attribute → ``kernel_measured`` events).  ``close``
    in a ``finally`` handles windows the loop never finished.

    Off the capture window each ``step`` is a couple of integer
    compares; ``hook_s`` accounts that cost so smokes can pin it.
    """

    def __init__(self, out_dir: str, iters: int = 3, skip: int = 1,
                 context: Optional[Dict[str, Any]] = None,
                 sync: Optional[Callable[[], Any]] = None):
        self.out_dir = out_dir
        self.iters = max(int(iters), 1)
        self.skip = max(int(skip), 0)
        self.context = dict(context or {})
        self.context.setdefault("iters", self.iters)
        self._sync = sync
        self._session = None
        self._seen = 0
        self._active = False
        self._done = False
        self.hook_s = 0.0
        self.rows: List[Dict[str, Any]] = []
        self.attrib: Optional[Dict[str, Any]] = None
        self.error = ""

    @property
    def active(self) -> bool:
        return self._active

    @property
    def done(self) -> bool:
        return self._done

    def step(self) -> None:
        """Call once after each completed training iteration."""
        if self._done:
            return
        t0 = time.perf_counter()
        self._seen += 1
        if not self._active:
            if self._seen > self.skip:
                self._start()
            self.hook_s += time.perf_counter() - t0
            return
        if self._seen >= self.skip + 1 + self.iters:
            self._finish()
        # while active the capture cost is deliberate, not hook overhead

    def close(self) -> None:
        """Finish an incomplete window (call from ``finally``)."""
        if self._active:
            self._finish()
        self._done = True

    # -- internals ----------------------------------------------------------

    def _start(self) -> None:
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            _PUBLIC_TRACE_DIR[0] = self.out_dir
            self._session = _start_session()
        except Exception as exc:  # already tracing / no backend
            self.error = "start_trace: %s" % exc
            log.warning("xprof capture failed to start: %s", exc)
            self._done = True
            return
        self._active = True
        _capturing[0] = True
        log.info("xprof window open: %d iters -> %s", self.iters,
                 self.out_dir)

    def _finish(self) -> None:
        self._active = False
        self._done = True
        _capturing[0] = False
        try:
            if self._sync is not None:
                self._sync()
        except Exception:
            pass
        try:
            _stop_session(self._session, self.out_dir)
        except Exception as exc:
            self.error = "stop_trace: %s" % exc
            log.warning("xprof capture failed to stop: %s", exc)
            return
        self._ingest()

    def _ingest(self) -> None:
        parsed = parse_trace_dir(self.out_dir)
        self.attrib = attribute(parsed)
        self.rows = record_measured(self.attrib, self.context,
                                    trace_dir=self.out_dir)
        try:
            # the Reconciler scores the same rows: per-kernel trace
            # truth over model, beside its coarse phase-wall units
            from .ranks import Reconciler
            units = Reconciler().score_measured(self.rows)
            if units:
                core.event("reconciliation", iteration=int(self._seen),
                           units=units, source="xprof")
        except Exception:
            pass
        if parsed["files"] and not parsed["parsed"]:
            self.error = "unparseable trace: %s" % "; ".join(
                parsed["errors"][:3])
            log.warning("xprof window %s", self.error)
            return
        n_kern = sum(1 for r in self.rows if r["kernel"] != "unattributed")
        log.info("xprof window closed: %d files, %d lgbm kernels, "
                 "window %.1f ms", parsed["parsed"], n_kern,
                 self.attrib["window_ms"])


# ---------------------------------------------------------------------------
# arming: env / config resolution
# ---------------------------------------------------------------------------

_FALSY = ("", "0", "false", "off", "no")


def _armed(config: Any = None) -> bool:
    return resolve_window(config) > 0


def resolve_window(config: Any = None) -> int:
    """Captured-iteration count, or 0 when the plane is off.

    ``LGBM_TPU_XPROF`` wins over config: ``1``/``true`` arms with
    ``tpu_xprof_iters`` (default 3), a number > 1 sets the window
    directly, falsy strings disarm even when ``tpu_xprof`` is set.
    """
    cfg_iters = int(getattr(config, "tpu_xprof_iters", 0) or 0) or 3
    env = os.environ.get("LGBM_TPU_XPROF", "").strip().lower()
    if env:
        if env in _FALSY[1:]:
            return 0
        if env in ("1", "true", "on", "yes"):
            return cfg_iters
        try:
            return max(int(env), 1)
        except ValueError:
            return cfg_iters
    if getattr(config, "tpu_xprof", False):
        return cfg_iters
    return 0


def resolve_trace_dir(config: Any = None) -> str:
    """Capture dir: env > telemetry sink sibling > tempdir."""
    env = os.environ.get("LGBM_TPU_XPROF_DIR", "")
    if env:
        return env
    sink = core._path or str(getattr(config, "tpu_telemetry", "") or "")
    if sink:
        if sink.endswith(".jsonl"):
            return sink[:-len(".jsonl")] + "_xprof"
        return os.path.join(sink, "xprof")
    import tempfile
    return tempfile.mkdtemp(prefix="lgbm_xprof_")


def maybe_window(config: Any = None,
                 context: Optional[Dict[str, Any]] = None,
                 sync: Optional[Callable[[], Any]] = None,
                 skip: int = 1) -> Optional[WindowedCapture]:
    """Arm a capture window when ``tpu_xprof``/``LGBM_TPU_XPROF`` says so.

    Also installs the compile observer — capture runs want compile
    walls and cache traffic in the same digest.  Returns None when off.
    """
    iters = resolve_window(config)
    if iters <= 0:
        return None
    install_compile_observer()
    return WindowedCapture(resolve_trace_dir(config), iters=iters,
                           skip=skip, context=context, sync=sync)


# ---------------------------------------------------------------------------
# reset + env-arming
# ---------------------------------------------------------------------------

def reset_xprof() -> None:
    global _state, _compile
    _state = _fresh_state()
    _compile = _fresh_compile()


core._register_reset(reset_xprof)

if os.environ.get("LGBM_TPU_XPROF", "").strip().lower() not in _FALSY:
    install_compile_observer()
