"""Structured training telemetry: events, counters, gauges, phase timers.

This is the always-available observability layer the TIMETAG accumulators
(``utils/timetag.py``, now a façade over this module) grew into.  Two
independent gates:

- ``LGBM_TPU_TIMETAG=1`` — phase wall-time accumulation + atexit report,
  exactly the reference's compiled-in TIMETAG behavior (reference:
  src/treelearner/serial_tree_learner.cpp:21-60).
- ``LGBM_TPU_TELEMETRY=<path>`` (or the ``tpu_telemetry`` parameter, or
  :func:`enable`) — a structured JSONL event stream.  ``<path>`` is a
  directory (files ``telemetry.{process_index}.jsonl`` inside it) or a
  ``*.jsonl`` file (non-zero ranks insert ``.{process_index}`` before the
  extension), so multi-host runs never interleave writers.

Because JAX dispatch is asynchronous, a phase that launches device work
must synchronize before its timer stops or it only measures enqueue time.
``sync(x)`` blocks on ``x`` ONLY while either gate is on, so the training
loop keeps its async pipelining in normal runs (the overlap matters: see
the lag-1 stop note in boosting/gbdt.py).  When both gates are off every
entry point here is a dict lookup + early return — the hot path pays a
few attribute accesses per phase, nothing else.

Events are one JSON object per line, each carrying ``event`` (name) and
``t`` (unix seconds); ``tools/telemetry_report.py`` merges the per-process
files back into per-phase / per-iteration summaries.
"""
from __future__ import annotations

import atexit
import json
import math
import os
import sys
import time
from collections import defaultdict
from typing import Optional

from ..utils import log

TIMETAG_ENABLED = os.environ.get("LGBM_TPU_TIMETAG", "") not in ("", "0",
                                                                 "false")

_acc = defaultdict(float)       # phase name -> accumulated seconds
_cnt = defaultdict(int)         # phase name -> completed enter/exit pairs
_counters = defaultdict(float)  # counter name -> value (monotonic)
_gauges = {}                    # gauge name -> last value

_path: Optional[str] = None     # configured sink (dir or *.jsonl file)
_fh = None                      # lazily-opened per-process file handle
_cur_phase = ""                 # innermost active phase (collective attr.)
_atexit_on = False
_write_warned = False
_profile_active = False         # set by obs.profile (avoids import cycle)
_spans_active = False           # set by obs.spans (trace mode)
_span_phase_hook = None         # obs.spans phase->span promotion hook
_flight_hook = None             # obs.spans flight-recorder event forward
_board_hook = None              # obs.board live-exporter event forward
_mem_probe = None               # obs.memory per-phase-exit hook
_reset_hooks = []               # submodule state cleared by reset()


def _set_spans_active(on: bool, phase_hook=None) -> None:
    """Trace mode flips this so phase timers run (and become spans) even
    without a telemetry sink (obs/spans.py owns the gate; core can't
    import it — spans imports core)."""
    global _spans_active, _span_phase_hook
    _spans_active = bool(on)
    _span_phase_hook = phase_hook
    if on:
        _ensure_atexit()


def _set_flight_hook(hook) -> None:
    """obs/spans.py installs this so operational events reach the flight
    ring even with no sink configured (one None check when disarmed)."""
    global _flight_hook
    _flight_hook = hook


def _set_board_hook(hook) -> None:
    """obs/board.py installs this so the live train exporter sees every
    event (and phase timers accumulate) even with no sink configured —
    same reasoning as the flight hook: core can't import board."""
    global _board_hook
    _board_hook = hook
    if hook is not None:
        _ensure_atexit()


def _set_profile_active(on: bool) -> None:
    """Profile mode flips this so phase timers sync-bracket device work
    even without a telemetry sink (obs/profile.py owns the gate; core
    can't import it — profile imports core)."""
    global _profile_active, _mem_probe
    _profile_active = bool(on)
    if on:
        from .memory import phase_probe
        _mem_probe = phase_probe
        _ensure_atexit()
    else:
        _mem_probe = None


def _register_reset(hook) -> None:
    _reset_hooks.append(hook)


def enabled() -> bool:
    """True when a telemetry sink is configured (events will be written)."""
    return _path is not None


def tracing_enabled() -> bool:
    """True when phase timers accumulate and :func:`sync` blocks."""
    return (TIMETAG_ENABLED or _path is not None or _profile_active
            or _spans_active or _board_hook is not None)


def enable(path: str) -> None:
    """Point the JSONL sink at ``path`` (directory, or a ``*.jsonl`` file).

    Idempotent for the same path; switching paths closes the old sink.
    Also installs the recompile counter (see :mod:`.trace`).
    """
    global _path
    if not path:
        return
    if _path is not None and _path != path:
        _close_sink()
    _path = path
    _ensure_atexit()
    from .trace import install_recompile_hook
    install_recompile_hook()


def disable() -> None:
    """Close the sink and stop writing events (accumulators are kept —
    use :func:`reset` to clear them)."""
    global _path
    _close_sink()
    _path = None


def _close_sink() -> None:
    global _fh
    if _fh is not None:
        try:
            _fh.close()
        except OSError:
            pass
        _fh = None


def _process_index() -> int:
    """This process's rank for the per-process file name.  Resolved
    without initializing a backend on the single-host path (mirrors
    parallel.distributed._runtime_active's reasoning).  Before
    jax.distributed comes up, fall back to the launcher-provided rank
    (same resolution order as parallel.distributed.process_id) so early
    events — dataset construction precedes the in-engine bootstrap —
    land in the right per-process file from the first write."""
    jx = sys.modules.get("jax")
    if jx is not None:
        state = None
        try:
            # the ONE guarded access point for the private API (see its
            # docstring + the loud contract test); lazy so the telemetry
            # layer never imports jax machinery itself
            from ..parallel.distributed import jax_distributed_state
            state = jax_distributed_state()
        except Exception:  # noqa: BLE001
            pass
        if state is not None:
            if state.client is not None:
                try:
                    return int(jx.process_index())
                except Exception:  # noqa: BLE001
                    pass
        else:
            # private API moved: best effort via the public probe
            try:
                return int(jx.process_index())
            except Exception:  # noqa: BLE001
                pass
    for var in ("JAX_PROCESS_ID", "LGBM_TPU_RANK"):
        v = os.environ.get(var, "")
        if v:
            try:
                return int(v)
            except ValueError:
                pass
    try:
        from ..parallel import mesh as _mesh
        r = _mesh.NETWORK.get("rank")
        if r:
            return int(r)
    except Exception:  # noqa: BLE001
        pass
    return 0


def _sink_target(pidx: int) -> str:
    if _path.endswith(".jsonl"):
        if pidx:
            return f"{_path[:-len('.jsonl')]}.{pidx}.jsonl"
        return _path
    return os.path.join(_path, f"telemetry.{pidx}.jsonl")


def sink_path() -> Optional[str]:
    """The resolved per-process file this process writes (None when
    disabled).  Resolves (and creates directories) without opening."""
    if _path is None:
        return None
    return _sink_target(_process_index())


_fh_idx = None  # process index the open handle was resolved with


def _open_sink():
    global _fh, _fh_idx
    idx = _process_index()
    if _fh is not None and idx != _fh_idx:
        # the rank became known after the sink opened (jax.distributed
        # initialized mid-run): move subsequent writes to the right
        # per-process file; the handful of pre-init events stay behind
        # in the old file, flagged by the marker below
        old_target = _sink_target(_fh_idx)
        _close_sink()
        _fh_idx = None
        fh = _open_sink()
        fh.write(json.dumps(
            {"event": "sink_reattached", "t": round(time.time(), 6),
             "early_events_in": os.path.basename(old_target)},
            separators=(",", ":")) + "\n")
        return fh
    if _fh is None:
        _fh_idx = idx
        target = sink_path()
        d = os.path.dirname(target)
        if d:
            os.makedirs(d, exist_ok=True)
        # line-buffered: every event lands on disk at its newline, so a
        # crash mid-run loses at most the record being written
        _fh = open(target, "a", buffering=1)
    return _fh


def _json_default(o):
    try:
        return o.item()  # numpy / jax scalars
    except Exception:  # noqa: BLE001
        return repr(o)


def event(name: str, **fields) -> None:
    """Append one structured record to the JSONL sink (no-op when
    disabled).  Keep field values JSON-representable; numpy scalars are
    unwrapped automatically."""
    if _flight_hook is not None:
        _flight_hook(name, fields)
    if _board_hook is not None:
        _board_hook(name, fields)
    if _path is None:
        return
    rec = {"event": name, "t": round(time.time(), 6)}
    rec.update(fields)
    write_record(rec)


def write_record(rec: dict) -> None:
    """Low-level sink append for a pre-built record (obs/spans.py's span
    records carry their own ``name``/``t`` fields, which the keyword
    surface of :func:`event` cannot express).  No-op when disabled."""
    global _write_warned
    if _path is None:
        return
    try:
        _open_sink().write(
            json.dumps(rec, separators=(",", ":"), default=_json_default)
            + "\n")
    except (OSError, TypeError, ValueError) as exc:
        if not _write_warned:
            _write_warned = True
            log.warning("telemetry write failed (%s); further write "
                        "errors are silenced", exc)


def count(name: str, n=1) -> None:
    """Bump a monotonic counter (no-op when disabled)."""
    if _path is not None or _board_hook is not None:
        _counters[name] += n


def gauge(name: str, value) -> None:
    """Record the latest value of a gauge (no-op when disabled)."""
    if _path is not None or _board_hook is not None:
        _gauges[name] = value


def counter_value(name: str) -> float:
    return _counters.get(name, 0)


def counters_snapshot() -> dict:
    """Counters + gauges as one JSON-friendly dict."""
    out = {}
    for k, v in _counters.items():
        fv = float(v)
        out[k] = int(fv) if fv.is_integer() else round(fv, 6)
    out.update(_gauges)
    return out


# ---------------------------------------------------------------------------
# Phase timers (the TIMETAG accumulators) + XLA-profile annotation
# ---------------------------------------------------------------------------

def _trace_annotation(name: str):
    """A jax.profiler.TraceAnnotation so captured XLA profiles carry our
    phase names (``lgbm/<phase>``); None when telemetry is off or jax is
    not imported yet (never import jax from the telemetry layer)."""
    if _path is None:
        return None
    jx = sys.modules.get("jax")
    if jx is None:
        return None
    try:
        return jx.profiler.TraceAnnotation("lgbm/" + name)
    except Exception:  # noqa: BLE001
        return None


class phase:
    """Context manager accumulating wall time under ``name`` when tracing
    is enabled (exported as ``utils.timetag.timetag``)."""

    __slots__ = ("name", "t0", "_t0w", "_on", "_prev", "_ta")

    def __init__(self, name: str):
        self.name = name
        self._on = False

    def __enter__(self):
        if tracing_enabled():
            global _cur_phase
            self._on = True
            self._prev = _cur_phase
            _cur_phase = self.name
            self._ta = _trace_annotation(self.name)
            if self._ta is not None:
                self._ta.__enter__()
            # trace mode promotes this timer to a span (obs/spans.py);
            # the span schema wants a wall-clock start
            self._t0w = time.time() if _span_phase_hook is not None else None
            self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type=None, exc_value=None, tb=None):
        if self._on:
            global _cur_phase
            dur = time.perf_counter() - self.t0
            _acc[self.name] += dur
            _cnt[self.name] += 1
            _cur_phase = self._prev
            if self._ta is not None:
                self._ta.__exit__(exc_type, exc_value, tb)
            if _span_phase_hook is not None and self._t0w is not None:
                _span_phase_hook(self.name, self._t0w, dur)
            if _mem_probe is not None:
                # profile mode: per-phase live-byte peak (obs/memory.py)
                _mem_probe(self.name)
            self._on = False
        return False


def current_phase() -> str:
    return _cur_phase


def sync(x):
    """Block on a jax value only when tracing — keeps async dispatch
    intact in normal runs. Returns ``x``."""
    if x is not None and tracing_enabled():
        import jax

        jax.block_until_ready(x)
    return x


def add(name: str, seconds: float) -> None:
    """Manual accumulation for phases timed externally."""
    if tracing_enabled():
        _acc[name] += seconds
        _cnt[name] += 1


def phase_snapshot() -> dict:
    """Current per-phase accumulated seconds (copy)."""
    return dict(_acc)


def phase_delta(snapshot: dict) -> dict:
    """Per-phase seconds accumulated since ``snapshot`` (only phases that
    moved)."""
    out = {}
    for name, total in _acc.items():
        d = total - snapshot.get(name, 0.0)
        if d > 0.0:
            out[name] = round(d, 6)
    return out


def reset() -> None:
    _acc.clear()
    _cnt.clear()
    _counters.clear()
    _gauges.clear()
    for hook in _reset_hooks:
        hook()


def digest() -> dict:
    """Machine-readable run summary: phase totals/call counts + counter
    snapshot (+ per-kernel rooflines and the memory-census peak when
    profile mode ran).  Embedded in bench.py's JSON line and in the
    atexit ``summary`` event."""
    d = {
        "phase_s": {k: round(v, 4) for k, v in _acc.items()},
        "phase_calls": dict(_cnt),
        "counters": counters_snapshot(),
    }
    from .memory import memory_digest
    from .profile import profile_digest
    from .xprof import compile_digest, xprof_digest
    kernels = profile_digest()
    if kernels:
        d["kernels"] = kernels
    mem = memory_digest()
    if mem:
        d["memory"] = mem
    xp = xprof_digest()
    if xp:
        d["xprof"] = xp
    comp = compile_digest()
    if comp:
        d["compile"] = comp
    return d


def report() -> None:
    """Print accumulated phase times (reference prints at GBDT/learner
    destructors, gbdt.cpp:46-56) and any counters."""
    if _acc:
        total = sum(_acc.values())
        log.info("TIMETAG phase times:")
        for name, t in sorted(_acc.items(), key=lambda kv: -kv[1]):
            log.info("  %-24s %8.3f s  (%d calls, %4.1f%%)",
                     name, t, _cnt[name], 100.0 * t / total if total else 0.0)
    if _counters:
        log.info("telemetry counters:")
        for name, v in sorted(_counters.items()):
            fv = float(v)
            log.info("  %-32s %s", name,
                     int(fv) if fv.is_integer() else round(fv, 4))


# ---------------------------------------------------------------------------
# Collective-traffic accounting (parallel/mesh.py, parallel/distributed.py)
# ---------------------------------------------------------------------------

def record_collective(kind: str, x) -> None:
    """Account an in-``jit`` collective (psum/all_gather) at TRACE time.

    Inside compiled code the per-execution call can't be observed from
    Python, but tracing sees every collective op with its exact payload
    shape — so these are bytes/calls PER COMPILED PROGRAM EXECUTION
    (counter suffix ``traced_*``); multiply by the grower's execution
    count for total traffic.  Attributed to the phase active when tracing
    ran (tracing happens under the first call's phase timer).
    """
    if _path is None:
        return
    try:
        nbytes = int(math.prod(x.shape)) * int(x.dtype.itemsize)
        shape = list(x.shape)
    except Exception:  # noqa: BLE001 — exotic aval; count the call anyway
        nbytes, shape = 0, None
    _counters[f"collective/{kind}/traced_calls"] += 1
    _counters[f"collective/{kind}/traced_bytes"] += nbytes
    event("collective", kind=kind, bytes=nbytes, shape=shape,
          phase=_cur_phase, traced=True)


def record_collective_host(kind: str, nbytes: int) -> None:
    """Account a host-driven collective (multihost_utils gathers) with its
    ACTUAL runtime byte count."""
    if _path is None:
        return
    _counters[f"collective/{kind}/calls"] += 1
    _counters[f"collective/{kind}/bytes"] += int(nbytes)
    event("collective", kind=kind, bytes=int(nbytes), phase=_cur_phase,
          traced=False)


# ---------------------------------------------------------------------------
# Process lifecycle
# ---------------------------------------------------------------------------

def _at_exit() -> None:
    if _path is not None:
        event("summary", **digest())
        _close_sink()
    if TIMETAG_ENABLED:
        report()


def _ensure_atexit() -> None:
    global _atexit_on
    if not _atexit_on:
        atexit.register(_at_exit)
        _atexit_on = True


if TIMETAG_ENABLED:
    _ensure_atexit()

_env_sink = os.environ.get("LGBM_TPU_TELEMETRY", "")
if _env_sink and _env_sink != "0":
    enable(_env_sink)
