"""Training-health sentinels: numerics guards, model fingerprints, and
the cross-rank divergence audit.

The telemetry layer (``core``) records what happened; profile mode
(``profile``) explains why it is slow; this module certifies the run was
NUMERICALLY TRUSTWORTHY — the missing piece that turns a rare TPU lease
window into a committable datapoint instead of a number that might hide
silent NaNs or cross-process model drift.

Three check families, all gated on one process-wide mode switch
(``LGBM_TPU_HEALTH`` env var or the ``tpu_health`` parameter):

- **gradient/hessian guards** (:func:`check_gradients`, tapped by
  ``objective/base.py health_tap``, the GOSS amplifier, and the custom-
  gradient path): non-finite values are counted on device in one small
  jitted reduction and attributed to the phase + iteration (+ objective
  and first bad row);
- **split/histogram guards** (:func:`check_tree`, reducing
  ``core/splitter.py tree_health_stats``): non-finite split gains or
  leaf values are attributed to the node and feature; leaf-count /
  leaf-weight conservation against the root catches corrupted histogram
  totals end to end;
- **model-state fingerprints** (:func:`model_fingerprint`): a cheap
  device reduction of the score vector + the iteration's tree arrays,
  hashed into a digest and emitted as a ``fingerprint`` event.  Under
  multi-process training :func:`divergence_audit` gathers every rank's
  fingerprint stats (``parallel/distributed.py rank_allgather_stats``,
  the min/max-over-the-hash comparison with which-rank attribution) and
  RAISES on mismatch — replicated state that drifted is unrecoverable,
  so the audit aborts in monitor mode too.

Modes: ``""`` (off — every entry point is one boolean check, the <5%
off-path overhead guard holds), ``monitor`` (check + warn + ``health``
events into the telemetry stream), ``strict`` (abort with a
:class:`TrainingHealthError` naming the phase/iteration and, for split
checks, the node/feature).  Checks synchronize the device once per
guarded quantity per iteration — health mode trades the training loop's
async pipelining for certainty, the same contract as profile mode.

Multi-process note: this engine's distributed design REPLICATES scores,
gradients, and trees on every rank (rows are sharded only inside the
grower's collectives — parallel/mesh.py), so a numerics failure is seen
by every rank in the same iteration and a strict abort fires everywhere
at once rather than wedging peers at the next collective.  The one
state that CAN silently drift per-rank is exactly what the fingerprint
audit compares — and a divergence aborts all ranks symmetrically, since
every rank evaluates the same gathered stats.
"""
from __future__ import annotations

import hashlib
import os
from typing import Optional

import numpy as np

from ..utils import log
from ..utils.log import LightGBMError
from . import core


class TrainingHealthError(LightGBMError):
    """A health sentinel tripped (strict mode, or any divergence)."""


MODE_OFF, MODE_MONITOR, MODE_STRICT = "", "monitor", "strict"

# conservation tolerances (check_tree): counts ride the f32 histogram
# count channel and are exact below ~2^24 rows/leaf; weights accumulate
# through parent-minus-child chains in f32 over (2x)bf16 histogram terms
_COUNT_ATOL = 0.5
_WEIGHT_RTOL = 5e-2

_mode = MODE_OFF
_jit = {}              # cached jitted reductions (never cleared: tiny)
_gather_override = None  # test hook: callable(stats) -> [R, n] array


def parse_mode(value, fatal: bool = False) -> str:
    """The ONE health-mode parser (config.py's ``tpu_health`` validation
    routes here too, so the synonym lists cannot drift).  ``fatal=True``
    rejects unknown values (the parameter path); the env path cannot
    raise at import time, so an unknown value arms 'monitor' with an
    explicit downgrade warning — NOT the 'strict' the user may have
    meant."""
    v = str(value).strip().lower()
    if v in ("", "0", "false", "off", "no", "none"):
        return MODE_OFF
    if v in ("strict", "abort"):
        return MODE_STRICT
    if v in ("1", "true", "on", "yes", "monitor", "warn"):
        return MODE_MONITOR
    if fatal:
        log.fatal("tpu_health should be off, monitor or strict "
                  f"(got {value!r})")
    log.warning("unknown LGBM_TPU_HEALTH value %r; arming 'monitor' "
                "(NOT 'strict') — fix the value if you wanted aborts",
                value)
    return MODE_MONITOR


def enable_health(mode="monitor") -> None:
    """Flip the PROCESS-WIDE health gate (same scope as the telemetry
    sink / profile gate): ``""``/``0`` off, ``monitor``/``1`` check and
    report, ``strict`` check and abort."""
    global _mode
    _mode = parse_mode(mode)


def health_mode() -> str:
    return _mode


def health_enabled() -> bool:
    return bool(_mode)


def _fail(check: str, msg: str, *, phase: str, iteration: int,
          detail: dict) -> bool:
    core.count("health/failures")
    core.event("health", check=check, phase=phase, iteration=iteration,
               ok=False, mode=_mode, detail=detail)
    if _mode == MODE_STRICT:
        _dump_flight("training_health")
        raise TrainingHealthError(msg)
    log.warning("HEALTH: %s", msg)
    return False


def _dump_flight(reason: str) -> None:
    """Before a health abort, persist the flight ring — the last N
    iteration/health events ARE the post-mortem for the abort."""
    from .spans import flight_dump, flight_enabled
    if flight_enabled():
        flight_dump(reason)


# ---------------------------------------------------------------------------
# Numerics sentinels
# ---------------------------------------------------------------------------

def _grad_stats_fn():
    fn = _jit.get("grad")
    if fn is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def fn(g, h):
            bg = ~jnp.isfinite(g.reshape(-1))
            bh = ~jnp.isfinite(h.reshape(-1))
            return jnp.stack([jnp.sum(bg), jnp.sum(bh),
                              jnp.argmax(bg), jnp.argmax(bh)]
                             ).astype(jnp.int32)
        _jit["grad"] = fn
    return fn


def check_gradients(g, h, *, phase: str, iteration: int,
                    objective: Optional[str] = None) -> bool:
    """Finite-check gradients/hessians; True when healthy (or off)."""
    if not _mode:
        return True
    core.count("health/checks")
    s = np.asarray(_grad_stats_fn()(g, h))
    if s[0] == 0 and s[1] == 0:
        return True
    # the argmax is over the flattened [N, K] buffer: map it back to a
    # (row, class) pair so multiclass attribution points at a real row
    shape = tuple(g.shape)
    k = shape[1] if len(shape) == 2 else 1
    flat = int(s[2] if s[0] else s[3])
    detail = {"nonfinite_grad": int(s[0]), "nonfinite_hess": int(s[1]),
              "first_bad_row": flat // k,
              "size": int(np.prod(shape))}
    if k > 1:
        detail["first_bad_class"] = flat % k
    if objective:
        detail["objective"] = objective
    msg = (f"non-finite gradients/hessians at iteration {iteration} in "
           f"phase '{phase}'"
           + (f" (objective={objective})" if objective else "")
           + f": {int(s[0])} bad gradient and {int(s[1])} bad hessian "
           f"value(s), first at row {detail['first_bad_row']}"
           + (f" class {flat % k}" if k > 1 else ""))
    return _fail("gradients", msg, phase=phase, iteration=iteration,
                 detail=detail)


def check_score(score, *, phase: str, iteration: int) -> bool:
    """Finite-check a score/prediction buffer (DART renormalization
    patches scores outside the guarded gradient path)."""
    if not _mode:
        return True
    core.count("health/checks")
    fn = _jit.get("score")
    if fn is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def fn(s):
            bad = ~jnp.isfinite(s.reshape(-1))
            return jnp.stack([jnp.sum(bad), jnp.argmax(bad)]
                             ).astype(jnp.int32)
        _jit["score"] = fn
    s = np.asarray(fn(score))
    if s[0] == 0:
        return True
    shape = tuple(score.shape)
    k = shape[1] if len(shape) == 2 else 1
    row = int(s[1]) // k
    msg = (f"non-finite score values at iteration {iteration} in phase "
           f"'{phase}': {int(s[0])} bad value(s), first at row {row}")
    return _fail("score", msg, phase=phase, iteration=iteration,
                 detail={"nonfinite": int(s[0]), "first_bad_row": row})


def check_tree(arrs, *, phase: str, iteration: int, class_id: int = 0
               ) -> bool:
    """Split-gain finiteness + histogram-total conservation for one grown
    tree (``core/splitter.py tree_health_stats``); True when healthy.

    Attribution: a non-finite gain names the node and its split feature;
    a conservation breach reports the leaf-sum vs root totals.  Constant
    trees (num_leaves <= 1 — including the lag-path's zeroed dead trees)
    carry no invariants and pass.
    """
    if not _mode:
        return True
    core.count("health/checks")
    fn = _jit.get("tree")
    if fn is None:
        import jax

        from ..core.splitter import tree_health_stats
        fn = _jit["tree"] = jax.jit(tree_health_stats)
    s = np.asarray(fn(arrs), np.float64)
    (n_bad_gain, n_bad_val, n_bad_w, first_node, first_feat,
     leaf_cnt, root_cnt, leaf_w, root_w, nl) = s
    if nl <= 1:
        return True
    base = {"class_id": class_id, "num_leaves": int(nl)}
    if n_bad_gain or n_bad_val or n_bad_w:
        detail = dict(base, nonfinite_gain=int(n_bad_gain),
                      nonfinite_value=int(n_bad_val),
                      nonfinite_weight=int(n_bad_w))
        if n_bad_gain:
            # first_node/first_feat come from argmax over the bad-gain
            # mask — meaningful ONLY when a gain actually went bad
            detail["node"] = int(first_node)
            detail["feature"] = int(first_feat)
        msg = (f"non-finite tree state at iteration {iteration} in phase "
               f"'{phase}' (class {class_id}): {int(n_bad_gain)} bad split "
               f"gain(s), {int(n_bad_val)} bad value(s), {int(n_bad_w)} "
               f"bad weight(s)"
               + (f"; first bad gain at node {int(first_node)} "
                  f"(feature {int(first_feat)})" if n_bad_gain else ""))
        return _fail("tree", msg, phase=phase, iteration=iteration,
                     detail=detail)
    cnt_bad = abs(leaf_cnt - root_cnt) > max(_COUNT_ATOL, 1e-6 * root_cnt)
    w_bad = abs(leaf_w - root_w) > _WEIGHT_RTOL * max(abs(root_w), 1e-6)
    if cnt_bad or w_bad:
        detail = dict(base, leaf_count_sum=leaf_cnt, root_count=root_cnt,
                      leaf_weight_sum=leaf_w, root_weight=root_w)
        msg = (f"histogram-total conservation breach at iteration "
               f"{iteration} in phase '{phase}' (class {class_id}): "
               f"leaves sum to count={leaf_cnt:g}/weight={leaf_w:g} but "
               f"the root histogrammed count={root_cnt:g}/"
               f"weight={root_w:g}")
        return _fail("conservation", msg, phase=phase, iteration=iteration,
                     detail=detail)
    return True


# ---------------------------------------------------------------------------
# Model-state fingerprints + cross-rank divergence audit
# ---------------------------------------------------------------------------

def _fp_fns():
    fns = _jit.get("fp")
    if fns is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def score_fp(s):
            f = s.reshape(-1).astype(jnp.float32)
            return jnp.stack([jnp.sum(f), jnp.sum(f * f),
                              jnp.min(f), jnp.max(f)])

        @jax.jit
        def tree_fp(t):
            return jnp.stack([
                jnp.sum(t.leaf_value), jnp.sum(jnp.abs(t.leaf_value)),
                jnp.sum(t.threshold_bin.astype(jnp.float32)),
                jnp.sum(t.split_feature.astype(jnp.float32)),
                t.num_leaves.astype(jnp.float32)])
        fns = _jit["fp"] = (score_fp, tree_fp)
    return fns


def model_fingerprint(score, trees=(), *, iteration: int) -> Optional[dict]:
    """Cheap per-iteration fingerprint of the model state: device
    reductions of the score vector and the iteration's tree arrays,
    combined into an f64 stats vector + a blake2b digest.  Emits a
    ``fingerprint`` event; returns ``{"iteration", "stats", "digest"}``
    (None when health is off).

    Identical replicated training MUST produce identical stats on every
    rank (the reductions are deterministic for identical inputs on the
    same backend) — that property is what :func:`divergence_audit`
    compares.
    """
    if not _mode:
        return None
    score_fp, tree_fp = _fp_fns()
    parts = [np.asarray(score_fp(score), np.float64)]
    for t in trees:
        parts.append(np.asarray(tree_fp(t), np.float64))
    stats = np.concatenate(parts) if parts else np.zeros(0)
    digest = hashlib.blake2b(stats.astype("<f8").tobytes(),
                             digest_size=8).hexdigest()
    core.event("fingerprint", iteration=iteration, digest=digest,
               stats=[float(x) for x in stats], trees=len(trees))
    return {"iteration": iteration, "stats": stats, "digest": digest}


def _digest_of(vec: np.ndarray) -> str:
    return hashlib.blake2b(np.asarray(vec, np.float64).astype("<f8")
                           .tobytes(), digest_size=8).hexdigest()


def divergence_audit(stats: np.ndarray, *, iteration: int) -> bool:
    """Compare this rank's fingerprint stats against every other rank's
    (no-op off multi-process).  Emits a ``divergence`` event with the
    per-stat min/max spread and per-rank digests; RAISES
    :class:`TrainingHealthError` on mismatch in EVERY mode — ranks whose
    replicated model state drifted cannot produce a meaningful run, so
    monitoring it is aborting it.
    """
    if not _mode:
        return True
    stats = np.asarray(stats, np.float64)
    if _gather_override is not None:
        gathered = np.asarray(_gather_override(stats), np.float64)
    else:
        from ..parallel.distributed import rank_allgather_stats
        gathered = rank_allgather_stats(stats)
    if gathered is None or gathered.shape[0] <= 1:
        return True
    core.count("health/divergence_checks")
    digests = [_digest_of(gathered[r]) for r in range(gathered.shape[0])]
    spread = gathered.max(axis=0) - gathered.min(axis=0)
    ok = len(set(digests)) == 1
    core.event("divergence", iteration=iteration, ok=ok,
               ranks=gathered.shape[0], digests=digests,
               spread=[float(x) for x in spread])
    if ok:
        return True
    core.count("health/failures")
    # blame the MINORITY: ranks whose digest differs from the modal one
    # (digests [A, A, B] names rank 2, not rank 0); with no majority —
    # every rank distinct — all ranks are suspects
    counts = {}
    for d in digests:
        counts[d] = counts.get(d, 0) + 1
    modal, modal_n = max(counts.items(), key=lambda kv: kv[1])
    bad = ([r for r, d in enumerate(digests) if d != modal]
           if modal_n > 1 else list(range(len(digests))))
    worst = int(np.argmax(spread))
    msg = (f"cross-rank model divergence at iteration {iteration}: "
           f"rank(s) {bad} disagree with the majority fingerprint "
           f"(digests {digests}); worst stat index {worst} spreads "
           f"{spread[worst]:g} across ranks")
    _dump_flight("divergence")
    raise TrainingHealthError(msg)


_env_mode = os.environ.get("LGBM_TPU_HEALTH", "")
if _env_mode:
    enable_health(_env_mode)
