"""HBM memory census: live-byte attribution, peak tracking, release audit.

Training's device footprint is a handful of logical buffers — the binned
matrix (feature-major resident copy + row-major twin), grad/hess vectors,
the per-leaf histogram stack, tier-gather scratch, train/valid scores,
and the stacked forest for device prediction.  ``snapshot`` attributes
``jax.live_arrays()`` bytes to whichever of those the caller names,
reports the unattributed remainder, folds in ``device.memory_stats()``
where the backend provides it (TPU does; CPU returns None and the
live-array sum stands in), and tracks the peak across the run.

The release audit is the donation check: a caller registers a buffer it
expects a phase to CONSUME (donated into a jit, or simply dropped when
the new value lands) via ``expect_released``; ``audit`` then warns when
the buffer survived — an extra reference pinning HBM that the schedule
believed was free.

All entry points no-op unless telemetry or profile mode is on; events
additionally need a telemetry sink (``core.event`` gates), but peak
tracking works sink-less so ``bench.py`` can embed the figure from
``obs.digest()`` alone.
"""
from __future__ import annotations

import sys
import weakref
from typing import Dict, List, Optional, Tuple

from ..utils import log
from . import core

_peak_bytes = 0
_peak_phase = ""
_phase_peaks: Dict[str, int] = {}   # phase name -> max live bytes at exit
_expected: List[tuple] = []         # (name, weakref, registered-phase)
_warned_survivors = set()
_snapshots = 0


def _active() -> bool:
    from . import profile
    return core.enabled() or profile.profile_enabled()


def _tree_bytes(buf) -> int:
    """Total nbytes across a buffer pytree (arrays, tuples of arrays)."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(buf):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


def _device_stats() -> Tuple[Optional[int], Optional[int]]:
    """(bytes_in_use, peak_bytes_in_use) summed over local devices, or
    (None, None) when the backend has no allocator stats (CPU)."""
    jx = sys.modules.get("jax")
    if jx is None:
        return None, None
    in_use = peak = None
    try:
        for d in jx.local_devices():
            st = d.memory_stats()
            if not st:
                continue
            in_use = (in_use or 0) + int(st.get("bytes_in_use", 0))
            peak = (peak or 0) + int(st.get("peak_bytes_in_use",
                                            st.get("bytes_in_use", 0)))
    except Exception:  # noqa: BLE001 — stats are best-effort everywhere
        return None, None
    return in_use, peak


def _live_total() -> Tuple[int, int]:
    """(total bytes, array count) over ``jax.live_arrays()``."""
    jx = sys.modules.get("jax")
    if jx is None:
        return 0, 0
    try:
        live = jx.live_arrays()
    except Exception:  # noqa: BLE001
        return 0, 0
    return sum(int(getattr(a, "nbytes", 0)) for a in live), len(live)


def _note_peak(nbytes: int, phase: str) -> None:
    global _peak_bytes, _peak_phase
    if nbytes > _peak_bytes:
        _peak_bytes = nbytes
        _peak_phase = phase


def snapshot(phase: str, buffers: Optional[dict] = None) -> Optional[dict]:
    """One census point: attribute live bytes to the named logical
    buffers, record device allocator stats, update the peak, and emit a
    ``memory_census`` event.  Returns the record (None when inactive)."""
    global _snapshots
    if not _active():
        return None
    import jax
    attributed = {}
    seen = set()  # logical names may alias one device array; count once
    for name, buf in (buffers or {}).items():
        if buf is None:
            continue
        nb = 0
        for leaf in jax.tree_util.tree_leaves(buf):
            b = getattr(leaf, "nbytes", None)
            if b is not None and id(leaf) not in seen:
                seen.add(id(leaf))
                nb += int(b)
        if nb:
            attributed[name] = nb
    live_bytes, live_count = _live_total()
    dev_in_use, dev_peak = _device_stats()
    basis = dev_in_use if dev_in_use is not None else live_bytes
    _note_peak(max(basis, dev_peak or 0), phase)
    _snapshots += 1
    rec = {
        "phase": phase,
        "buffers": attributed,
        "live_bytes": live_bytes,
        "live_count": live_count,
        "unattributed_bytes": max(live_bytes - sum(attributed.values()), 0),
        "peak_bytes": _peak_bytes,
    }
    if dev_in_use is not None:
        rec["device_bytes_in_use"] = dev_in_use
        rec["device_peak_bytes"] = dev_peak
    core.event("memory_census", **rec)
    return rec


def phase_probe(phase: str) -> None:
    """Lightweight per-phase-exit hook (installed by ``core.phase`` while
    profile mode is on): tracks per-phase live-byte peaks without the
    full attribution/event cost of ``snapshot``."""
    live_bytes, _ = _live_total()
    dev_in_use, dev_peak = _device_stats()
    basis = dev_in_use if dev_in_use is not None else live_bytes
    if basis > _phase_peaks.get(phase, 0):
        _phase_peaks[phase] = basis
    _note_peak(max(basis, dev_peak or 0), phase)


def expect_released(name: str, arr) -> None:
    """Register ``arr`` as a buffer the current phase should consume —
    the next ``audit`` warns if it is still alive (neither garbage
    collected nor donation-deleted).

    Re-registering a name REPLACES the pending entry: a stop path that
    returns before its audit leaves a stale registration behind, and a
    later run (another booster in the same process) must not report that
    earlier, legitimately-alive buffer as its own leak."""
    if not _active() or arr is None:
        return
    try:
        ref = weakref.ref(arr)
    except TypeError:
        return
    _expected[:] = [e for e in _expected if e[0] != name]
    _expected.append((name, ref, core.current_phase()))


def audit(phase: str = "") -> List[str]:
    """Check every registered release expectation; returns the survivor
    names.  Survivors warn once per buffer name and emit a
    ``donation_audit`` event — an extra reference is pinning HBM the
    schedule expected back."""
    if not _expected:
        return []
    survivors = []
    for name, ref, reg_phase in _expected:
        a = ref()
        if a is None:
            continue
        deleted = False
        try:
            deleted = bool(a.is_deleted())
        except Exception:  # noqa: BLE001
            pass
        if not deleted:
            survivors.append(name)
            if name not in _warned_survivors:
                _warned_survivors.add(name)
                log.warning(
                    "memory census: buffer %r (%s bytes, registered in "
                    "phase %r) survived phase %r — an extra reference is "
                    "pinning HBM that was expected to be released",
                    name, _tree_bytes(a), reg_phase, phase)
    _expected.clear()
    if survivors:
        core.event("donation_audit", phase=phase, survivors=survivors,
                   survived=True)
    return survivors


def peak_bytes() -> int:
    """Peak observed device bytes (allocator peak where available, else
    the live-array sum) across all snapshots/probes so far."""
    return _peak_bytes


def memory_digest() -> dict:
    """Census summary for ``obs.digest()`` (empty when nothing probed)."""
    if not _snapshots and not _phase_peaks:
        return {}
    out = {"peak_bytes": _peak_bytes, "peak_phase": _peak_phase,
           "snapshots": _snapshots}
    if _phase_peaks:
        out["phase_peak_bytes"] = dict(sorted(_phase_peaks.items()))
    return out


def reset_memory() -> None:
    global _peak_bytes, _peak_phase, _snapshots
    _peak_bytes = 0
    _peak_phase = ""
    _snapshots = 0
    _phase_peaks.clear()
    _expected.clear()
    _warned_survivors.clear()


core._register_reset(reset_memory)
