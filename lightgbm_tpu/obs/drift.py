"""Model-quality & drift plane: reference profiles + streaming sketches.

The fourth obs plane beside telemetry (core), profile, health and trace:
it watches WHAT a served model predicts, not how fast.  Three pieces:

- ``QualityProfile`` — the reference distribution captured at train /
  ingest time: per-feature bin-occupancy histograms (free — the binned
  ``X_bin`` matrix already exists; streaming ingestion accumulates them
  during pass 2) plus the training-set raw-prediction histogram and a
  label-quality baseline (train AUC when labels are binary).  Persisted
  beside the model as ``<model>.quality.json`` and carried through the
  serving registry with the model it describes.

- ``DriftSketch`` — the serve-side accumulator: fixed buckets taken
  from the profile (so reference and live histograms share one bin
  space by construction), integer bumps under a single lock, mergeable
  across replicas bit-exactly (integer adds commute) exactly like
  ``ServeMetrics``.  Feature rows are sampled at
  ``tpu_drift_sample_rate`` with a deterministic batch-granularity
  accumulator; the prediction histogram is cheap enough to take every
  response.

- ``DriftMonitor`` — profile + sketch + cadence: every
  ``tpu_drift_check_s`` it scores the sketch against the profile with
  PSI and KS, emits a ``drift_snapshot`` telemetry event, and on a
  ``tpu_drift_psi_warn`` breach dumps the flight recorder and latches a
  breach record the registry's post-swap health watch reads (default
  non-gating; ``tpu_serve_rollback_on_drift`` opts into rollback).

Bin-space consistency is the load-bearing design point: the profile
stores each numerical feature's searchable upper bounds
(``bin_upper_bound[:n_search-1]``) and NaN bin, and ``bin_features``
replicates ``BinMapper.value_to_bin``'s exact numerics
(io/binning.py) from those — so a live raw request row lands in the
same bin the training row did, and PSI measures traffic shift, never
binning skew.  Categorical features are excluded from feature drift
(their live values need the category dictionary, not thresholds).

Pure numpy + stdlib — no jax import, safe for serve hot paths and
report tooling alike.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import List, Optional

import numpy as np

from . import core
from .spans import flight_dump

PROFILE_SUFFIX = ".quality.json"

# prediction-histogram resolution: quantile edges of the training raw
# scores (equal-mass buckets make PSI sensitive at the distribution's
# bulk, where a shifted traffic mix actually moves mass)
PRED_BUCKETS = 32

# floor for PSI's log ratio — standard epsilon smoothing so an empty
# bucket on either side contributes a large-but-finite term
_PSI_EPS = 1e-6

# feature PSI/KS are scored on this many equal-reference-mass groups of
# the fine bins (decile-style), not the raw ~255-bin histograms: a
# sparse live sample leaves fine bins empty, and epsilon smoothing
# would read each empty bin as a large shift term — coarsening keeps
# PSI a traffic-shift signal at serve-realistic sample sizes
FEAT_PSI_BUCKETS = 16

# consecutive breach snapshots before a second flight dump (the monitor
# has its own cooldown beside the session's storm cooldown)
_DUMP_COOLDOWN_S = 60.0

# the monitor's hot path only APPENDS batch references; histogramming
# runs when this many rows are pending (or a cadence check / status
# read forces it) so the numpy fixed cost amortizes over many batches
_PEND_FLUSH_ROWS = 512


def profile_path(model_path: str) -> str:
    """Sidecar path convention: the profile lives beside the model file
    it describes, so registry deploys pick it up with no extra plumbing."""
    return str(model_path) + PROFILE_SUFFIX


def _knob(config, name: str, cast, default):
    """Config attr with LGBM_TPU_<NAME> env override (the leading
    ``tpu_`` of the param name folds into the prefix) — the serve-stack
    convention (serve/session.py _env_num)."""
    stem = name[4:] if name.startswith("tpu_") else name
    v = os.environ.get("LGBM_TPU_" + stem.upper())
    if v is not None:
        if cast is bool:  # bool("0") is True — parse the usual spellings
            return v.strip().lower() not in ("", "0", "false", "no", "off")
        try:
            return cast(v)
        except ValueError:
            pass
    return cast(getattr(config, name, default) if config is not None
                else default)


# ---------------------------------------------------------------------------
# distribution distances
# ---------------------------------------------------------------------------

def psi(p_counts, q_counts) -> float:
    """Population Stability Index between two aligned histograms:
    sum((p-q) * ln(p/q)) over normalized bucket masses, epsilon-smoothed.
    Rule of thumb: <0.1 stable, 0.1-0.25 moderate shift, >0.25 major."""
    p = np.asarray(p_counts, np.float64)
    q = np.asarray(q_counts, np.float64)
    ps, qs = p.sum(), q.sum()
    if ps <= 0 or qs <= 0:
        return 0.0
    p = np.maximum(p / ps, _PSI_EPS)
    q = np.maximum(q / qs, _PSI_EPS)
    return float(np.sum((p - q) * np.log(p / q)))


def coarsen(ref_counts, live_counts, buckets: int = FEAT_PSI_BUCKETS):
    """Regroup two aligned fine-bin histograms into ``buckets``
    contiguous groups of roughly equal REFERENCE mass (cuts come from
    the reference CDF, so both histograms regroup identically).  The
    fine bin space stays the sketch's storage format; scoring happens
    here, on the coarse view."""
    ref = np.asarray(ref_counts, np.float64)
    live = np.asarray(live_counts, np.float64)
    if len(ref) <= buckets:
        return ref, live
    total = ref.sum()
    if total <= 0:
        idx = np.linspace(0, len(ref), buckets + 1).astype(np.int64)
    else:
        cdf = np.cumsum(ref)
        targets = total * np.arange(1, buckets) / buckets
        cuts = np.searchsorted(cdf, targets, side="left") + 1
        idx = np.concatenate([[0], cuts, [len(ref)]])
    idx = np.unique(np.clip(idx, 0, len(ref)))
    if idx[-1] != len(ref):
        idx = np.append(idx, len(ref))
    return (np.add.reduceat(ref, idx[:-1]),
            np.add.reduceat(live, idx[:-1]))


def ks(p_counts, q_counts) -> float:
    """Kolmogorov-Smirnov statistic on aligned histograms: the max CDF
    gap.  Complements PSI — KS catches a concentrated shift PSI's
    log-ratio smears across buckets."""
    p = np.asarray(p_counts, np.float64)
    q = np.asarray(q_counts, np.float64)
    ps, qs = p.sum(), q.sum()
    if ps <= 0 or qs <= 0:
        return 0.0
    return float(np.max(np.abs(np.cumsum(p / ps) - np.cumsum(q / qs))))


# ---------------------------------------------------------------------------
# reference-profile capture (train / ingest side)
# ---------------------------------------------------------------------------

def init_occupancy(ds) -> List[np.ndarray]:
    """One int64 count vector per used (inner) feature, sized by the
    feature's BinMapper — the accumulator ``accumulate_occupancy``
    fills.  Streaming ingestion allocates this before pass 2."""
    return [np.zeros(ds.inner_to_mapper(i).num_bin, np.int64)
            for i in range(ds.num_features)]


def accumulate_occupancy(ds, acc: List[np.ndarray], row0: int,
                         nrows: int) -> None:
    """Fold rows ``[row0, row0+nrows)`` of the already-binned ``X_bin``
    into ``acc``.  With EFB the physical column is decoded back to
    feature bins (inverse of io/bundling.py encode_column): a value in
    this member's ``[offset, offset+num_bin)`` range is the member's
    bin + offset, anything else (bin 0 = all-default, or another
    member's range) reads as the member's default bin.  Bundle
    conflicts make this an approximation bounded by the EFB conflict
    budget — the same bound training itself accepts."""
    if nrows <= 0 or ds.X_bin is None:
        return
    X = ds.X_bin[row0:row0 + nrows]
    bundle = ds.bundle
    for i in range(ds.num_features):
        nb = len(acc[i])
        if bundle is not None:
            col = X[:, int(bundle.feat2phys[i])].astype(np.int64)
            if bundle.needs_fix[i]:
                off = int(bundle.feat_offset[i])
                db = int(ds.inner_to_mapper(i).default_bin)
                fb = np.where((col >= off) & (col < off + nb),
                              col - off, db)
            else:
                fb = col
        else:
            fb = X[:, i].astype(np.int64)
        acc[i] += np.bincount(fb, minlength=nb)[:nb]


def compute_occupancy(ds, chunk_rows: int = 65536) -> List[np.ndarray]:
    """Whole-dataset bin occupancy, chunked so a memmap-backed ``X_bin``
    streams instead of materializing."""
    acc = init_occupancy(ds)
    for row0 in range(0, int(ds.num_data), chunk_rows):
        accumulate_occupancy(ds, acc, row0,
                             min(chunk_rows, int(ds.num_data) - row0))
    return acc


def _pred_histogram(scores: np.ndarray):
    """Equal-mass histogram of raw scores with TIE-ROBUST edges: cuts
    fall at midpoints BETWEEN distinct adjacent score values, never on a
    value itself.  GBDT margins are heavily discrete (leaf-value sums),
    and training-time accumulated scores differ from serve-time
    recomputed ones by float noise — an edge sitting exactly on a tie
    clump would flip the whole clump across buckets for a 1e-7
    difference and read as drift.  Counts use the same
    ``searchsorted(side='left')`` the sketch uses."""
    s = np.asarray(scores, np.float64).ravel()
    s = s[np.isfinite(s)]
    if s.size == 0:
        return [], [0]
    u, uc = np.unique(s, return_counts=True)
    if len(u) < 2:
        return [], [int(s.size)]
    cum = np.cumsum(uc)
    targets = s.size * np.arange(1, PRED_BUCKETS) / PRED_BUCKETS
    cut = np.searchsorted(cum, targets, side="left")
    cut = np.unique(np.clip(cut, 0, len(u) - 2))
    edges = (u[cut] + u[cut + 1]) / 2.0
    counts = np.bincount(np.searchsorted(edges, s, side="left"),
                         minlength=len(edges) + 1)
    return [float(x) for x in edges], [int(x) for x in counts]


def _binary_auc(scores: np.ndarray, label: np.ndarray) -> Optional[float]:
    """Compact tie-aware ROC AUC for binary labels (the quality
    baseline; metric/basic.py AUCMetric is the full weighted version —
    this is the unweighted rank statistic, stdlib-cheap)."""
    y = np.asarray(label, np.float64).ravel()
    s = np.asarray(scores, np.float64).ravel()
    mask = np.isfinite(s) & np.isfinite(y)
    y, s = y[mask], s[mask]
    pos = y > 0
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if n_pos == 0 or n_neg == 0:
        return None
    # rank with tie midpoints
    order = np.argsort(s, kind="mergesort")
    ranks = np.empty(len(s), np.float64)
    ranks[order] = np.arange(1, len(s) + 1, dtype=np.float64)
    sv = s[order]
    # average ranks over tie runs
    start = 0
    for i in range(1, len(sv) + 1):
        if i == len(sv) or sv[i] != sv[start]:
            if i - start > 1:
                ranks[order[start:i]] = 0.5 * (start + 1 + i)
            start = i
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0)
                 / (n_pos * n_neg))


class QualityProfile:
    """The persisted reference distribution — see module docstring.

    ``features``: list of per-inner-feature records
    ``{feature, name, categorical, num_bin, edges, nan_bin, counts}``
    where ``edges`` are the searchable upper bounds replicating
    ``value_to_bin`` and ``nan_bin`` is the NaN destination bin (-1
    when the feature has no NaN bin).  ``pred``:
    ``{edges, counts, mean, std}`` of the training raw margin.
    ``meta``: rows / train_auc / created timestamp.
    """

    FORMAT_VERSION = 1

    def __init__(self, features: List[dict], pred: dict, meta: dict):
        self.features = features
        self.pred = pred
        self.meta = meta

    # -- capture ------------------------------------------------------
    @classmethod
    def from_training(cls, ds, raw_score=None, label=None,
                      occupancy: Optional[List[np.ndarray]] = None
                      ) -> "QualityProfile":
        """Build the profile from a constructed ``BinnedDataset`` plus
        (optionally) the training raw scores.  ``occupancy`` short-cuts
        the X_bin scan when ingestion already accumulated it
        (``ds.quality_occupancy`` from ingest/stream.py pass 2)."""
        from ..io.binning import BIN_NUMERICAL, MISSING_NAN
        if occupancy is None:
            occupancy = getattr(ds, "quality_occupancy", None)
        if occupancy is None:
            occupancy = compute_occupancy(ds)
        features = []
        for i in range(ds.num_features):
            m = ds.inner_to_mapper(i)
            orig = int(ds.real_feature_idx[i])
            rec = {
                "feature": orig,
                "name": (ds.feature_names[orig]
                         if orig < len(ds.feature_names)
                         else f"Column_{orig}"),
                "categorical": m.bin_type != BIN_NUMERICAL,
                "num_bin": int(m.num_bin),
                "counts": [int(x) for x in occupancy[i]],
            }
            if m.bin_type == BIN_NUMERICAL:
                n_search = m.num_bin - (1 if m.missing_type == MISSING_NAN
                                        else 0)
                rec["edges"] = [float(x)
                                for x in m.bin_upper_bound[:n_search - 1]]
                rec["nan_bin"] = (m.num_bin - 1
                                  if m.missing_type == MISSING_NAN else -1)
            features.append(rec)

        pred = {"edges": [], "counts": [0], "mean": None, "std": None}
        meta = {"rows": int(ds.num_data),
                "num_features": int(ds.num_features),
                "train_auc": None,
                "created_unix": round(time.time(), 3)}
        if raw_score is not None:
            s = np.asarray(raw_score, np.float64)
            s = s[:, 0] if s.ndim == 2 else s.ravel()
            edges, counts = _pred_histogram(s)
            fin = s[np.isfinite(s)]
            pred = {"edges": edges, "counts": counts,
                    "mean": float(fin.mean()) if fin.size else None,
                    "std": float(fin.std()) if fin.size else None}
            if label is not None:
                lab = np.asarray(label, np.float64).ravel()
                if lab.size == s.size and set(np.unique(lab)) <= {0.0, 1.0}:
                    meta["train_auc"] = _binary_auc(s, lab)
        return cls(features, pred, meta)

    # -- persistence --------------------------------------------------
    def to_dict(self) -> dict:
        return {"format_version": self.FORMAT_VERSION,
                "features": self.features, "pred": self.pred,
                "meta": self.meta}

    @classmethod
    def from_dict(cls, d: dict) -> "QualityProfile":
        return cls(list(d.get("features") or []),
                   dict(d.get("pred") or {"edges": [], "counts": [0]}),
                   dict(d.get("meta") or {}))

    def save(self, path: str) -> str:
        with open(path, "w") as fh:
            json.dump(self.to_dict(), fh)
        return path

    @classmethod
    def load(cls, path: str) -> "QualityProfile":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))

    # -- serve-side binning ------------------------------------------
    def numeric_records(self) -> List[dict]:
        return [r for r in self.features if not r.get("categorical")]


def bin_features(X, records: List[dict]) -> List[np.ndarray]:
    """Raw request rows -> per-record feature bins, replicating
    ``BinMapper.value_to_bin``'s numerical path exactly (NaN masked to
    0.0 for the search, ``searchsorted(edges, v, side='left')``, then
    NaN routed to the profile's ``nan_bin`` when one exists)."""
    X = np.asarray(X, np.float64)
    out = []
    for rec in records:
        v = X[:, int(rec["feature"])]
        nan = np.isnan(v)
        vv = np.where(nan, 0.0, v)
        b = np.searchsorted(np.asarray(rec["edges"], np.float64), vv,
                            side="left")
        nb = int(rec["nan_bin"])
        if nb >= 0:
            b = np.where(nan, nb, b)
        out.append(b.astype(np.int64))
    return out


# ---------------------------------------------------------------------------
# serve-side streaming sketch
# ---------------------------------------------------------------------------

class DriftSketch:
    """Fixed-bucket live histograms in the profile's bin space.

    Buckets are fixed at construction (from the profile), updates are
    integer bumps under one lock, and ``merge`` is elementwise integer
    addition — so merging per-replica sketches equals the
    single-accumulator oracle bit-exactly regardless of interleaving,
    the ``ServeMetrics`` contract."""

    def __init__(self, profile: QualityProfile):
        self.records = profile.numeric_records()
        self._nbins = [int(r["num_bin"]) for r in self.records]
        self.feat_counts = [np.zeros(nb, np.int64) for nb in self._nbins]
        self.pred_edges = np.asarray(profile.pred.get("edges") or [],
                                     np.float64)
        self.pred_counts = np.zeros(len(self.pred_edges) + 1, np.int64)
        self.feat_rows = 0
        self.pred_rows = 0
        self._lock = threading.Lock()

    def observe_features(self, X) -> int:
        """Bin a sampled batch of raw rows and bump the counts.  The
        binning runs OUTSIDE the lock; only the adds hold it."""
        X = np.asarray(X, np.float64)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.shape[0] == 0 or not self.records:
            return 0
        bins = bin_features(X, self.records)
        adds = [np.bincount(np.clip(b, 0, nb - 1), minlength=nb)[:nb]
                for b, nb in zip(bins, self._nbins)]
        with self._lock:
            for c, a in zip(self.feat_counts, adds):
                c += a
            self.feat_rows += int(X.shape[0])
        return int(X.shape[0])

    def observe_preds(self, scores) -> int:
        s = np.asarray(scores, np.float64).ravel()
        if s.size == 0:
            return 0
        if self.pred_edges.size:
            b = np.searchsorted(self.pred_edges, s, side="left")
        else:
            b = np.zeros(s.size, np.int64)
        add = np.bincount(b, minlength=len(self.pred_counts))
        add = add[:len(self.pred_counts)]
        with self._lock:
            self.pred_counts += add
            self.pred_rows += int(s.size)
        return int(s.size)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "feat_rows": int(self.feat_rows),
                "pred_rows": int(self.pred_rows),
                "feat_counts": [c.copy() for c in self.feat_counts],
                "pred_counts": self.pred_counts.copy(),
            }

    def merge(self, other: "DriftSketch") -> None:
        """Fold another replica's sketch into this one (bit-exact:
        integer adds commute and associate)."""
        snap = other.snapshot()
        with self._lock:
            for c, a in zip(self.feat_counts, snap["feat_counts"]):
                c += a
            self.pred_counts += snap["pred_counts"]
            self.feat_rows += snap["feat_rows"]
            self.pred_rows += snap["pred_rows"]


# ---------------------------------------------------------------------------
# the monitor: profile + sketch + cadence + breach latch
# ---------------------------------------------------------------------------

class DriftMonitor:
    """One per served model version (built by the replica router and
    shared across its replica sessions, like ``ServeMetrics``)."""

    def __init__(self, profile: QualityProfile, config=None, *,
                 source: str = ""):
        self.profile = profile
        self.sketch = DriftSketch(profile)
        self.source = source
        self.sample_rate = _knob(config, "tpu_drift_sample_rate",
                                 float, 0.05)
        self.check_s = _knob(config, "tpu_drift_check_s", float, 30.0)
        self.min_rows = _knob(config, "tpu_drift_min_rows", int, 200)
        self.psi_warn = _knob(config, "tpu_drift_psi_warn", float, 0.25)
        # fleet identity — stamped by the router like session identity
        self.model_name = "default"
        self.model_version = 0
        self.scores: Optional[dict] = None
        self.breach: Optional[dict] = None
        self.breach_count = 0
        self.checks = 0
        self._acc = 0.0              # deterministic sampling accumulator
        self._pend_s: list = []      # score batches awaiting histogram
        self._pend_X: list = []      # sampled feature batches awaiting bin
        self._pend_rows = 0
        self._paused = False         # canary gate: synthetic probes
        self._last_check_t = time.monotonic()
        self._last_dump_t = -math.inf
        self._lock = threading.Lock()

    # -- construction -------------------------------------------------
    @classmethod
    def maybe_load(cls, model, config=None) -> Optional["DriftMonitor"]:
        """Arm drift monitoring when (a) the knob is on, (b) the model
        came from a file path, and (c) the ``.quality.json`` sidecar is
        beside it.  Anything else -> None (the session's hot path takes
        one ``is None`` branch and nothing more)."""
        if not _knob(config, "tpu_drift", bool, True):
            return None
        if not isinstance(model, str):
            return None
        path = profile_path(model)
        if not os.path.isfile(path):
            return None
        try:
            return cls(QualityProfile.load(path), config, source=path)
        except (ValueError, OSError) as exc:  # corrupt sidecar: serve on
            from ..utils import log
            log.warning("drift: failed to load %s (%s) — monitoring off",
                        path, exc)
            return None

    # -- hot path -----------------------------------------------------
    def observe(self, raw_rows, raw_scores) -> None:
        """Called once per executed serve batch with the raw feature
        rows and the raw margin scores.  Prediction histogram every
        response; feature rows through the deterministic
        batch-granularity sampler (credit accrues at ``sample_rate``
        per row; a batch is taken when the credit covers it — at rate
        1.0 every batch).  ``raw_rows`` may be one [n, P] array, a list
        of per-request arrays (concatenated only when the sampler takes
        the batch — the skipped-batch cost is a size sum), or None.

        This path only COPIES and APPENDS: the histogramming happens in
        ``flush`` every ``_PEND_FLUSH_ROWS`` pending rows (or when a
        cadence check / status read forces it), so the per-batch serve
        cost is a couple of small allocations, not a numpy call chain.
        The copies decouple the sketch from callers that mutate their
        result arrays after the fact."""
        if self._paused:
            return
        n = 0
        if isinstance(raw_rows, (list, tuple)):
            for r in raw_rows:
                if r is not None:
                    n += len(r)
        elif raw_rows is not None:
            n = len(raw_rows)
        take = False
        if n and self.sample_rate > 0.0:
            self._acc += n * self.sample_rate
            if self._acc >= n:
                self._acc -= n
                take = True
        if raw_scores is None and not take:
            return
        # copies, made outside the lock: the buffer must not see a
        # caller mutating its result/input arrays after this returns
        s = np.array(raw_scores, np.float64) \
            if raw_scores is not None else None
        if take:
            if isinstance(raw_rows, (list, tuple)):
                X = [np.array(r) for r in raw_rows if r is not None]
            else:
                X = np.array(raw_rows)
        with self._lock:
            if s is not None:
                self._pend_s.append(s)
                self._pend_rows += n or s.size
            if take:
                self._pend_X.append(X)
                self._pend_rows += n
            due = self._pend_rows >= _PEND_FLUSH_ROWS
        if due:
            self.flush()

    def flush(self) -> None:
        """Drain the pending batch buffers into the sketch.  The swap is
        atomic under the lock; shaping/concatenation/binning all run
        outside it.  Integer adds commute, so flush order across
        threads never changes the resulting counts."""
        with self._lock:
            if not self._pend_s and not self._pend_X:
                return
            ps, px = self._pend_s, self._pend_X
            self._pend_s, self._pend_X = [], []
            self._pend_rows = 0
            # capture the sketch with the buffers: a concurrent
            # reset_window swaps ``self.sketch``, and these rows belong
            # to the window they were observed in, not the fresh one
            sketch = self.sketch
        scores = []
        for s in ps:
            s = s[:, 0] if s.ndim == 2 else s.ravel()
            if s.size:
                scores.append(s)
        if scores:
            sketch.observe_preds(
                np.concatenate(scores) if len(scores) > 1 else scores[0])
        rows = []
        for batch in px:
            if isinstance(batch, (list, tuple)):
                rows.extend(np.asarray(r) for r in batch if r is not None)
            else:
                rows.append(np.asarray(batch))
        rows = [r.reshape(1, -1) if r.ndim == 1 else r for r in rows]
        rows = [r for r in rows if r.ndim == 2 and r.shape[0]]
        if rows:
            sketch.observe_features(
                np.concatenate(rows, axis=0) if len(rows) > 1 else rows[0])

    def pause(self) -> None:
        """Stop observing/checking: the canary gate pushes synthetic
        probe traffic through the real predict path, and those rows
        must neither seed the sketch nor trip a (cooldown-consuming)
        breach dump before the version has served a single real row."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def reset_window(self) -> None:
        """Drop the live window: pending buffers, sketch counts, the
        last scores, and any latched breach.  The registry calls this
        when a version goes live (or is restored by a rollback) so the
        serving episode is scored from an empty window."""
        with self._lock:
            self._pend_s, self._pend_X = [], []
            self._pend_rows = 0
            self._acc = 0.0
            self.sketch = DriftSketch(self.profile)
            self.scores = None
            self.breach = None
            self._last_check_t = time.monotonic()

    # -- cadence ------------------------------------------------------
    def compute_scores(self, snap: Optional[dict] = None) -> dict:
        """Score the sketch against the profile: per-feature PSI/KS
        (numerical features only), prediction PSI/KS, and the
        aggregates the breach gate reads."""
        snap = snap or self.sketch.snapshot()
        per_feature = []
        for rec, live in zip(self.sketch.records, snap["feat_counts"]):
            rc, lc = coarsen(rec["counts"], live)
            per_feature.append({
                "feature": rec["feature"], "name": rec["name"],
                "psi": round(psi(rc, lc), 6),
                "ks": round(ks(rc, lc), 6),
            })
        feat_psi = [f["psi"] for f in per_feature]
        pred_ref = np.asarray(self.profile.pred.get("counts") or [0],
                              np.float64)
        if len(pred_ref) == len(snap["pred_counts"]):
            # same equal-reference-mass regrouping the features get: a
            # small live sample over the 32 fine buckets reads ~0.5 PSI
            # of pure noise (several near-empty buckets), and an early
            # cadence check must not breach on that
            prc, plc = coarsen(pred_ref, snap["pred_counts"])
            p_psi, p_ks = psi(prc, plc), ks(prc, plc)
        else:
            p_psi = p_ks = 0.0
        worst = max(per_feature, key=lambda f: f["psi"], default=None)
        return {
            "feat_rows": snap["feat_rows"],
            "pred_rows": snap["pred_rows"],
            "psi_max": round(max(feat_psi), 6) if feat_psi else 0.0,
            "psi_mean": round(float(np.mean(feat_psi)), 6)
            if feat_psi else 0.0,
            "ks_max": round(max((f["ks"] for f in per_feature),
                                default=0.0), 6),
            "pred_psi": round(p_psi, 6),
            "pred_ks": round(p_ks, 6),
            "worst_feature": (worst["name"] if worst else None),
            "per_feature": per_feature,
        }

    def maybe_check(self, now: Optional[float] = None,
                    force: bool = False) -> Optional[dict]:
        """Cadence gate: score + emit + breach-check when due.  Returns
        the fresh scores dict, or None when not due / not enough rows.
        Cheap when idle — one monotonic read and a compare."""
        if self._paused:
            return None
        now = time.monotonic() if now is None else now
        with self._lock:
            if not force and now - self._last_check_t < self.check_s:
                return None
            self._last_check_t = now
        self.flush()
        snap = self.sketch.snapshot()
        if not force and snap["feat_rows"] < self.min_rows \
                and snap["pred_rows"] < self.min_rows:
            return None
        scores = self.compute_scores(snap)
        self.checks += 1
        breach_kinds = []
        if scores["feat_rows"] >= self.min_rows \
                and scores["psi_max"] > self.psi_warn:
            breach_kinds.append("feature_psi")
        if scores["pred_rows"] >= self.min_rows \
                and scores["pred_psi"] > self.psi_warn:
            breach_kinds.append("pred_psi")
        breached = bool(breach_kinds)
        core.event("drift_snapshot",
                   model=self.model_name,
                   version=int(self.model_version),
                   feat_rows=int(scores["feat_rows"]),
                   pred_rows=int(scores["pred_rows"]),
                   psi_max=scores["psi_max"],
                   psi_mean=scores["psi_mean"],
                   ks_max=scores["ks_max"],
                   pred_psi=scores["pred_psi"],
                   pred_ks=scores["pred_ks"],
                   worst_feature=scores["worst_feature"] or "",
                   breach=breached)
        if breached:
            self.breach_count += 1
            self.breach = {
                "kinds": breach_kinds,
                "psi_max": scores["psi_max"],
                "pred_psi": scores["pred_psi"],
                "threshold": self.psi_warn,
                "worst_feature": scores["worst_feature"],
                "at_unix": round(time.time(), 3),
            }
            if now - self._last_dump_t >= _DUMP_COOLDOWN_S:
                self._last_dump_t = now
                flight_dump(f"drift_psi:{self.model_name}",
                            extra={"drift": {k: v for k, v in
                                             scores.items()
                                             if k != "per_feature"},
                                   "breach": self.breach})
        else:
            self.breach = None
        self.scores = scores
        return scores

    # -- introspection ------------------------------------------------
    def status(self) -> dict:
        """The ``GET /drift`` / ``stats()`` view: thresholds, live row
        counts, last scores, breach latch."""
        self.flush()
        snap = self.sketch.snapshot()
        out = {
            "armed": True,
            "model": self.model_name,
            "version": int(self.model_version),
            "source": self.source,
            "sample_rate": self.sample_rate,
            "check_s": self.check_s,
            "min_rows": self.min_rows,
            "psi_warn": self.psi_warn,
            "feat_rows": snap["feat_rows"],
            "pred_rows": snap["pred_rows"],
            "checks": self.checks,
            "breaches": self.breach_count,
            "breach": self.breach,
            "reference_rows": self.profile.meta.get("rows"),
            "train_auc": self.profile.meta.get("train_auc"),
        }
        if self.scores is not None:
            out["scores"] = {k: v for k, v in self.scores.items()
                             if k != "per_feature"}
        return out
