"""Tracing spans + the flight recorder — layer 0 of the trace plane.

One span schema shared by the serving engine and the training loop, so a
serving request and a training iteration render on the same timeline
(``tools/trace_export.py`` converts the merged JSONL to Chrome
trace-event / Perfetto JSON).  A completed span is one ``span`` event:

    {"event": "span", "t": <start, unix s>, "dur_ms": ...,
     "name": "serve/queue_wait", "trace_id": ..., "span_id": ...,
     "parent_id": ..., "attrs": {...}}

``trace_id`` groups every span of one request (minted at the HTTP edge,
honoring an incoming ``X-Request-Id``) or of one training run;
``parent_id`` links children to the request's root span.  Two gates:

- **trace mode** (``tpu_trace`` / ``LGBM_TPU_TRACE``, :func:`enable_trace`)
  writes span events to the telemetry sink and promotes every
  ``obs.phase`` timer to a span (so training phases trace for free).
  Like profile mode it sync-brackets phases — attribution, not benching.
- **the flight recorder** (``tpu_flight_len`` / ``LGBM_TPU_FLIGHT``,
  :func:`enable_flight`) keeps a bounded in-memory ring of the last N
  spans and operational events (health/degradation/overload/iteration)
  with NO sink required — :func:`flight_dump` writes it as
  ``FLIGHT_rN.json`` on a degradation flip, an overload storm, a
  ``TrainingHealthError``, or on demand via ``GET /debug/flight``.

With both gates off every entry point is one attribute check — the same
off-path contract as the rest of ``obs`` (guarded by the overhead tests).
"""
from __future__ import annotations

import glob
import itertools
import json
import os
import re
import threading
import time
import uuid
from collections import deque
from typing import Optional

from ..utils import log
from . import core

_ID_BAD = re.compile(r"[^A-Za-z0-9._:-]")

# ids are (per-process random base) + (atomic counter): unique without
# paying uuid4's ~25us urandom syscall on every span (the hot path emits
# several spans per serving request)
_ID_BASE = uuid.uuid4().hex[:8]
_ID_SEQ = itertools.count(1)

_trace_on = False
_flight: Optional[deque] = None
_flight_lock = threading.Lock()
_tls = threading.local()

# events (besides spans) worth keeping in the post-mortem ring: the
# operational record of the moments before a flip
_FLIGHT_EVENTS = frozenset((
    "health", "divergence", "fingerprint", "train_stop", "iteration",
    "serve_degraded", "serve_overload", "serve_batch", "serve_request",
    "serve_access", "serve_start", "serve_stop",
    # explanation serving (serve/session.py explain path): the TreeSHAP
    # batch history belongs in a serving post-mortem exactly like the
    # predict batches beside it
    "explain_request", "explain_batch",
    # fault tolerance (robust/): the recovery record is exactly what a
    # wedge post-mortem needs in the ring
    "checkpoint", "restore", "retry", "fault_injected", "device_stall",
    "serve_probe", "serve_recovered",
    # serving fleet (serve/registry.py + serve/router.py): the swap /
    # rollback / failover lifecycle IS the post-mortem when a model push
    # bounces
    "serve_swap", "serve_canary", "serve_rollback", "serve_failover",
    "serve_drain",
    # online learning (online/loop.py + refit_models): a bounced or
    # skipped refresh is the first thing a stale-model post-mortem
    # needs beside the swap/canary records it produced
    "online_refresh", "refit",
    # streaming ingestion (ingest/stream.py): the per-dataset summary —
    # rows, shard, digest — is what a crash-mid-ingest or corrupt-chunk
    # post-mortem needs first (per-chunk records stay telemetry-only:
    # a 10^8-row stream would flush the whole ring with them)
    "ingest_summary",
    # drift & quality plane (obs/drift.py + serve/quality.py): the
    # score trail leading up to a breach is exactly what the breach's
    # own flight dump must contain
    "drift_snapshot", "quality_window",
    # live introspection plane (obs/ranks.py): the straggler breach
    # belongs in the ring it triggers a dump of (reconciliation stays
    # telemetry-only: one record per iteration would crowd the ring the
    # way per-chunk ingest records would)
    "straggler",
    # zero-cold-start plane (serve/aot.py + serve/arena.py +
    # router.restart_replica): a store entry silently re-paying JIT, a
    # tenant bouncing in and out of residency, or a replica reboot are
    # exactly the moments-before a cold-start or capacity post-mortem
    # replays
    "aot_fallback", "serve_replica_restart", "arena_admit",
    "arena_evict", "arena_repack", "arena_swap",
))


# ---------------------------------------------------------------------------
# identifiers + context
# ---------------------------------------------------------------------------

def new_trace_id(seed=None) -> str:
    """Mint a trace id; a non-empty ``seed`` (e.g. an incoming
    ``X-Request-Id`` header) is sanitized and used verbatim so the
    caller's correlation id survives into every span."""
    if seed:
        s = _ID_BAD.sub("_", str(seed).strip())[:64]
        if s:
            return s
    return f"{_ID_BASE}{next(_ID_SEQ):x}"


def new_span_id() -> str:
    return f"s{next(_ID_SEQ):x}"


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_context():
    """(trace_id, span_id) of the innermost active span on this thread,
    or (None, None)."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else (None, None)


# ---------------------------------------------------------------------------
# gates
# ---------------------------------------------------------------------------

def trace_enabled() -> bool:
    """True when span events stream to the telemetry sink and phase
    timers are promoted to spans."""
    return _trace_on


def span_record_enabled() -> bool:
    """True when spans are recorded anywhere (sink and/or flight ring) —
    the one check the serving hot path pays when both gates are off."""
    return _trace_on or _flight is not None


def enable_trace(on: bool = True) -> None:
    """Flip the PROCESS-WIDE trace gate (same scope as profile mode).
    Also arms the phase->span hook so ``obs.phase`` timers emit spans."""
    global _trace_on
    _trace_on = bool(on)
    core._set_spans_active(_trace_on, _on_phase if _trace_on else None)


def enable_flight(n: int) -> None:
    """Arm the flight ring with the last ``n`` records (0 disables).
    Idempotent for the same length; re-arming with a new length keeps
    the newest records that fit."""
    global _flight
    n = int(n)
    with _flight_lock:
        if n <= 0:
            _flight = None
        elif _flight is None or _flight.maxlen != n:
            old = list(_flight) if _flight is not None else []
            _flight = deque(old[-n:], maxlen=n)
    core._set_flight_hook(_flight_event_hook if n > 0 else None)


def flight_len_from_env(fallback) -> int:
    """THE parser for ``LGBM_TPU_FLIGHT`` (module init, serve sessions,
    and the trainer all route here so the disable synonyms cannot
    drift): unset -> ``fallback``; 0/false/off/no -> 0; else int."""
    v = os.environ.get("LGBM_TPU_FLIGHT", "").strip()
    if not v:
        return int(fallback)
    if v.lower() in ("0", "false", "off", "no"):
        return 0
    try:
        return int(v)
    except ValueError:
        log.warning("ignoring non-numeric LGBM_TPU_FLIGHT=%r", v)
        return int(fallback)


def flight_enabled() -> bool:
    return _flight is not None


def flight_len() -> int:
    return _flight.maxlen if _flight is not None else 0


def _flight_reset() -> None:
    with _flight_lock:
        if _flight is not None:
            _flight.clear()


core._register_reset(_flight_reset)


# ---------------------------------------------------------------------------
# span emission
# ---------------------------------------------------------------------------

def emit_span(name: str, t0: float, dur_ms: float, trace_id: str,
              span_id: Optional[str] = None, parent_id: Optional[str] = None,
              attrs: Optional[dict] = None) -> Optional[str]:
    """Record one completed span (explicit timing — the serving path
    measures its own phases).  Returns the span id, or None when both
    gates are off (the record went nowhere)."""
    if not (_trace_on or _flight is not None):
        return None
    rec = {"event": "span", "t": round(t0, 6), "name": name,
           "trace_id": trace_id, "span_id": span_id or new_span_id(),
           "dur_ms": round(float(dur_ms), 3)}
    if parent_id:
        rec["parent_id"] = parent_id
    if attrs:
        rec["attrs"] = attrs
    if _flight is not None:
        with _flight_lock:
            if _flight is not None:
                _flight.append(rec)
    if _trace_on:
        core.write_record(rec)
    return rec["span_id"]


class Span:
    """An in-flight span (see :func:`begin_span`)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "t0", "_tp0", "_pushed", "_done")

    def __init__(self, name, trace_id, span_id, parent_id, attrs, pushed):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self.t0 = time.time()
        self._tp0 = time.perf_counter()
        self._pushed = pushed
        self._done = False


def begin_span(name: str, trace_id: Optional[str] = None,
               parent_id: Optional[str] = None, push: bool = True,
               **attrs) -> Optional[Span]:
    """Open a span; ``push=True`` makes it the thread's current context
    so nested spans (and trace-mode phase timers) parent to it.  Returns
    None when recording is off — :func:`end_span` accepts None."""
    if not (_trace_on or _flight is not None):
        return None
    cur_trace, cur_span = current_context()
    if trace_id is None:
        trace_id = cur_trace or new_trace_id()
    if parent_id is None:
        parent_id = cur_span
    sp = Span(name, trace_id, new_span_id(), parent_id, attrs or None, push)
    if push:
        _stack().append((trace_id, sp.span_id))
    return sp


def end_span(sp: Optional[Span], **attrs) -> None:
    """Close a span opened by :func:`begin_span` (idempotent, None-safe)."""
    if sp is None or sp._done:
        return
    sp._done = True
    if sp._pushed:
        st = _stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i][1] == sp.span_id:
                del st[i:]
                break
    a = dict(sp.attrs or {})
    a.update(attrs)
    emit_span(sp.name, sp.t0, (time.perf_counter() - sp._tp0) * 1e3,
              sp.trace_id, span_id=sp.span_id, parent_id=sp.parent_id,
              attrs=a or None)


class span:
    """Context-manager sugar over :func:`begin_span`/:func:`end_span`."""

    __slots__ = ("_args", "_kw", "_sp")

    def __init__(self, name, trace_id=None, parent_id=None, **attrs):
        self._args = (name, trace_id, parent_id)
        self._kw = attrs

    def __enter__(self):
        name, trace_id, parent_id = self._args
        self._sp = begin_span(name, trace_id=trace_id, parent_id=parent_id,
                              **self._kw)
        return self._sp

    def __exit__(self, *exc):
        end_span(self._sp)
        return False


def _on_phase(name: str, t0_wall: float, dur_s: float) -> None:
    """core.phase exit hook (trace mode only): every phase timer becomes
    a span under the thread's current trace context, so the training
    loop's existing ``timetag`` phases trace with zero new call sites."""
    trace_id, parent = current_context()
    if trace_id is None:
        trace_id = f"proc-{os.getpid()}"
    emit_span("phase/" + name, t0_wall, dur_s * 1e3, trace_id,
              parent_id=parent)


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

# introspection endpoints are scraped continuously (Prometheus, LB
# health probes); their access lines must not evict the request spans /
# batch history the post-mortem ring exists to keep
_SCRAPE_PATHS = frozenset(("", "/", "/health", "/metrics", "/stats",
                           "/debug/flight"))


def _flight_event_hook(name: str, fields: dict) -> None:
    """core.event forward: operational events enter the ring even when
    no telemetry sink is configured (spans are appended by emit_span
    directly, so they are deliberately absent from the allowlist)."""
    if name not in _FLIGHT_EVENTS:
        return
    if name == "serve_access" and fields.get("path") in _SCRAPE_PATHS:
        return
    rec = {"event": name, "t": round(time.time(), 6)}
    rec.update(fields)
    if _flight is not None:
        with _flight_lock:
            if _flight is not None:
                _flight.append(rec)


def flight_snapshot() -> list:
    """Copy of the ring, oldest first (empty when disabled)."""
    with _flight_lock:
        return list(_flight) if _flight is not None else []


def _next_flight_round(out_dir: str) -> int:
    n = 0
    for f in glob.glob(os.path.join(out_dir, "FLIGHT_r*.json")):
        m = re.search(r"FLIGHT_r(\d+)\.json$", os.path.basename(f))
        if m:
            n = max(n, int(m.group(1)))
    return n + 1


def flight_dump(reason: str, out_dir: Optional[str] = None,
                extra: Optional[dict] = None) -> Optional[str]:
    """Write the ring as ``FLIGHT_rN.json`` (next free N) and return the
    path; None when the ring is disarmed or the write failed.  The dump
    is the post-mortem artifact: its last events are the moments before
    whatever tripped ``reason``."""
    events = flight_snapshot()
    if _flight is None:
        return None
    if not out_dir:
        out_dir = os.environ.get("LGBM_TPU_FLIGHT_DIR", "")
    if not out_dir:
        # prefer the telemetry sink's directory so the post-mortem lands
        # next to the event stream it complements; cwd is the fallback
        sink = core.sink_path()
        out_dir = (os.path.dirname(sink) or os.getcwd()) if sink \
            else os.getcwd()
    try:
        os.makedirs(out_dir, exist_ok=True)
        n = _next_flight_round(out_dir)
        path = os.path.join(out_dir, f"FLIGHT_r{n:02d}.json")
        rec = {"kind": "flight", "reason": reason,
               "t": round(time.time(), 6), "ring_len": flight_len(),
               "events": events}
        if extra:
            rec.update(extra)
        with open(path, "w") as fh:
            json.dump(rec, fh, indent=1, default=core._json_default)
        log.warning("flight recorder: dumped %d event(s) to %s (%s)",
                    len(events), path, reason)
        return path
    except OSError as exc:
        log.warning("flight recorder: dump failed (%s)", exc)
        return None


_env_trace = os.environ.get("LGBM_TPU_TRACE", "")
if _env_trace not in ("", "0", "false"):
    enable_trace()
if os.environ.get("LGBM_TPU_FLIGHT", ""):
    enable_flight(flight_len_from_env(256))
