"""Per-rank training aggregation: live straggler detection + the
measured-vs-model reconciliation scorer (ISSUE 17).

The reference keeps its Network layer introspectable per rank; this
module is the JAX-graft analog for the training loop.  Three pieces:

- :class:`StragglerDetector` — pure streak logic over a
  ``[num_ranks, num_phases]`` per-iteration wall matrix: a rank whose
  phase wall exceeds the fleet median by ``tpu_straggler_factor`` for
  ``tpu_straggler_iters`` consecutive iterations is a straggler.
- :class:`RankAggregator` — accumulates this rank's per-iteration phase
  deltas and, on the fingerprint cadence, exchanges the window sums over
  the existing host collectives (``parallel/distributed.
  train_stats_exchange`` — piggybacked, so no new sync points).  Rank 0
  runs the detector, emits the ``straggler`` event (rank + phase + skew
  ratio stamped) and dumps the flight recorder — direction 2's "lost
  host" as telemetry instead of a silent stall.
- :class:`Reconciler` — scores each iteration's measured phase times
  against the analytic cost models (``wave_kernel_cost``,
  ``partition_cost``, ``rank_pair_cost``) into a ``reconciliation``
  event, so a TPU window self-attributes where docs/ROOFLINE.md's model
  is wrong without a manual ``prof_kernels`` session.

Everything here is host-side and allocation-light: the per-iteration
work is a few float adds; the exchange rides an already-scheduled
collective.  obs/board.py renders the live skew table and the last
reconciliation row on ``/metrics``.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from . import core

# the phases the straggler detector watches: hist/split wall lives in
# "tree growth", gradient work in "boosting (grad/hess)" — the two
# device-bound legs a wedged or slow host shows up in first (the valid
# scoring leg is optional per run, so skew there is config, not fault)
PHASES = ("boosting (grad/hess)", "tree growth")

# below this per-iteration median wall (seconds) a phase is noise — a
# 2x ratio over microseconds is measurement jitter, not a straggler
_MIN_MEDIAN_S = 1e-4


def _median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if not n:
        return 0.0
    m = n // 2
    return s[m] if n % 2 else 0.5 * (s[m - 1] + s[m])


class StragglerDetector:
    """Streak logic over per-rank, per-phase iteration walls.

    ``update(means, window_iters, iteration)`` takes the fleet's
    per-iteration mean wall matrix for the window just exchanged
    (``means[rank][phase_idx]`` seconds) plus how many iterations the
    window covered, and returns the breaches that *crossed* the
    consecutive-iterations threshold on this update (each streak emits
    once; recovery resets it so a relapse emits again).
    """

    def __init__(self, factor: float, iters: int,
                 phases: Sequence[str] = PHASES):
        self.factor = float(factor)
        self.iters = max(int(iters), 1)
        self.phases = tuple(phases)
        self._streak: Dict[tuple, int] = {}   # (rank, phase) -> iters
        self._emitted: set = set()            # streaks already reported

    def update(self, means: Sequence[Sequence[float]], window_iters: int,
               iteration: int) -> List[dict]:
        breaches = []
        window_iters = max(int(window_iters), 1)
        for pi, phase in enumerate(self.phases):
            col = [float(row[pi]) for row in means]
            med = _median(col)
            if med < _MIN_MEDIAN_S:
                for r in range(len(col)):
                    self._streak.pop((r, phase), None)
                    self._emitted.discard((r, phase))
                continue
            for r, wall in enumerate(col):
                key = (r, phase)
                if wall > self.factor * med:
                    streak = self._streak.get(key, 0) + window_iters
                    self._streak[key] = streak
                    if streak >= self.iters and key not in self._emitted:
                        self._emitted.add(key)
                        breaches.append({
                            "rank": r,
                            "phase": phase,
                            "iteration": int(iteration),
                            "ratio": round(wall / med, 4),
                            "median_s": round(med, 6),
                            "rank_s": round(wall, 6),
                            "consecutive": int(streak),
                            "breach": True,
                        })
                else:
                    self._streak.pop(key, None)
                    self._emitted.discard(key)
        return breaches


# live skew table for the board: the last exchanged per-rank,
# per-iteration phase walls — written by the train thread on each
# exchange, read by the exporter's HTTP thread
_skew_lock = threading.Lock()
_skew: dict = {}


def skew_table() -> dict:
    """Last exchanged skew snapshot: ``{"iteration": n, "window_iters":
    k, "ranks": {rank: {phase: per_iter_s}}, "stragglers": [...]}`` —
    empty before the first multi-process exchange."""
    with _skew_lock:
        return dict(_skew)


def _reset_skew() -> None:
    with _skew_lock:
        _skew.clear()


core._register_reset(_reset_skew)


class RankAggregator:
    """Accumulate this rank's phase walls; exchange + detect on the
    fingerprint cadence.  Single-process runs cost one branch per tick
    (``train_stats_exchange`` returns None before any collective)."""

    def __init__(self, factor: float = 2.0, iters: int = 3,
                 phases: Sequence[str] = PHASES):
        self.phases = tuple(phases)
        self.detector = StragglerDetector(factor, iters, self.phases)
        self._win = [0.0] * len(self.phases)
        self._win_iters = 0

    def accumulate(self, phase_s: dict) -> None:
        """Fold one iteration's phase deltas into the open window."""
        for i, p in enumerate(self.phases):
            self._win[i] += float(phase_s.get(p, 0.0) or 0.0)
        self._win_iters += 1

    def exchange(self, iteration: int) -> Optional[List[dict]]:
        """Exchange the open window across ranks (non-blocking w.r.t.
        extra sync points: rides the fingerprint tick, which already
        synchronizes).  Returns the breaches rank 0 detected, None when
        single-process or the window is empty."""
        if not self._win_iters:
            return None
        vec = list(self._win) + [float(self._win_iters)]
        self._win = [0.0] * len(self.phases)
        self._win_iters = 0
        from ..parallel.distributed import train_stats_exchange
        mat = train_stats_exchange(vec)
        if mat is None:
            return None
        rows = [[float(v) for v in row] for row in mat]
        means = [[w / max(row[-1], 1.0) for w in row[:-1]] for row in rows]
        window_iters = int(max(r[-1] for r in rows))
        table = {r: {p: round(means[r][pi], 6)
                     for pi, p in enumerate(self.phases)}
                 for r in range(len(means))}
        breaches = self.detector.update(means, window_iters, iteration)
        with _skew_lock:
            _skew.clear()
            _skew.update(iteration=int(iteration),
                         window_iters=window_iters, ranks=table,
                         stragglers=list(breaches))
        if core._process_index() != 0:
            return breaches
        for b in breaches:
            core.event("straggler", **b)
            from . import spans
            if spans.flight_enabled():
                spans.flight_dump(
                    f"straggler:rank{b['rank']}",
                    extra={"straggler": b, "skew": table})
        return breaches


class Reconciler:
    """Score one iteration's measured phase walls against the analytic
    cost models — the ``reconciliation`` event's ``units`` map, where
    each unit carries ``measured_s`` / ``modeled_s`` / ``ratio``
    (measured over modeled: >> 1 means the roofline model is
    optimistic for that unit on this backend).  All inputs are
    best-effort: a unit whose model inputs are missing is skipped, not
    guessed."""

    def __init__(self):
        self._peaks = None

    def _roofline(self, flops: float, nbytes: float) -> float:
        from .profile import device_peaks, roofline_seconds
        if self._peaks is None:
            self._peaks = device_peaks()
        return roofline_seconds(flops, nbytes, self._peaks)

    @staticmethod
    def _unit(measured: float, modeled: float) -> Optional[dict]:
        if modeled <= 0 or measured < 0:
            return None
        return {"measured_s": round(measured, 6),
                "modeled_s": round(modeled, 6),
                "ratio": round(measured / modeled, 4)}

    def score(self, *, phase_s: dict, iter_s: float, N: int,
              kern_rows=None, waves=None, wave_cost_args=None,
              splits: int = 0, part_batched: bool = False,
              rank_sizes=None) -> Optional[dict]:
        units = {}
        growth = float(phase_s.get("tree growth", iter_s) or 0.0)
        modeled_growth = 0.0
        if kern_rows and kern_rows > 0 and wave_cost_args:
            try:
                from ..ops.pallas_hist import wave_kernel_cost
                Fk, Bk, mode, packed_k, fused_k = wave_cost_args
                flops, nbytes = wave_kernel_cost(
                    kern_rows, Fk, Bk, mode, waves=waves or 1,
                    packed=packed_k, fused=fused_k)
                modeled = self._roofline(flops, nbytes)
                modeled_growth += modeled
                u = self._unit(growth, modeled)
                if u:
                    units["wave_kernel"] = u
            except Exception:  # noqa: BLE001 — scoring must not fail train
                pass
        if splits > 0:
            try:
                from ..core.splitter import partition_cost
                pflops, pbytes = partition_cost(
                    int(N), splits=int(splits), batched=bool(part_batched),
                    waves=int(waves or 1))
                modeled = self._roofline(pflops, pbytes)
                modeled_growth += modeled
                u = self._unit(growth, modeled)
                if u:
                    units["partition"] = u
            except Exception:  # noqa: BLE001
                pass
        if modeled_growth > 0:
            # the combined growth-phase verdict: measured wall over the
            # SUM of the in-phase unit models — the single number the
            # digest's reconciliation table leads with
            u = self._unit(growth, modeled_growth)
            if u:
                units["tree_growth"] = u
        if rank_sizes is not None and len(rank_sizes):
            try:
                from ..ops.rank import rank_pair_cost
                rflops, rbytes = rank_pair_cost(rank_sizes)
                boosting = float(
                    phase_s.get("boosting (grad/hess)", 0.0) or 0.0)
                u = self._unit(boosting, self._roofline(rflops, rbytes))
                if u:
                    units["rank_pair"] = u
            except Exception:  # noqa: BLE001
                pass
        return units or None

    def score_shap(self, measured_s: float, *, N: int, T: int, L: int,
                   P: int, F: int, K: int = 1) -> Optional[dict]:
        """Score a TreeSHAP contribution pass against ``ops/treeshap.
        shap_cost`` — the explain plane's unit of the reconciliation
        table (emitted from the trainer's ``pred_contrib`` path, where
        the batched scan is host-bracketed)."""
        try:
            from ..ops.treeshap import shap_cost
            flops, nbytes = shap_cost(N, T, L, P, F, K)
            return self._unit(float(measured_s),
                              self._roofline(flops, nbytes))
        except Exception:  # noqa: BLE001 — scoring must not fail predict
            return None

    def score_measured(self, rows) -> Optional[dict]:
        """Fold ``kernel_measured`` rows (obs/xprof.py) into the same
        ``units`` shape ``score`` emits — one unit per trace-attributed
        kernel that carries a model join.  Where ``score`` ratios a
        coarse host phase wall against the models, this ratios the
        per-kernel trace truth: the two agreeing is the cost model
        validated end to end; diverging, the phase wall is hiding
        dispatch gaps or unattributed work."""
        units = {}
        for row in rows or ():
            model_ms = row.get("model_ms")
            if not model_ms:
                continue
            u = self._unit(float(row.get("measured_ms", 0.0)) / 1e3,
                           float(model_ms) / 1e3)
            if u:
                units[row.get("kernel", "?")] = u
        return units or None
