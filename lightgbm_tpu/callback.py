"""Training callbacks.

Implements the same public contract as the reference callback bus
(reference: python-package/lightgbm/callback.py — ``CallbackEnv`` fields,
``EarlyStopException``, the four factory functions, ``order`` /
``before_iteration`` attributes) but as callable classes holding explicit
state objects rather than closures over mutable cells.

An evaluation entry is the tuple ``(dataset_name, metric_name, value,
higher_is_better)`` — cv adds a fifth stdv element.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple


class EarlyStopException(Exception):
    """Signals the training loop to stop at ``best_iteration``."""

    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


@dataclass
class CallbackEnv:
    """Snapshot passed to every callback once per iteration."""
    model: Any
    params: Dict[str, Any]
    iteration: int
    begin_iteration: int
    end_iteration: int
    evaluation_result_list: List[Tuple]


def _entry_to_str(entry: Tuple, show_stdv: bool = True) -> str:
    name, metric, value = entry[0], entry[1], entry[2]
    if len(entry) == 5 and show_stdv:
        return f"{name}'s {metric}: {value:g} + {entry[4]:g}"
    if len(entry) not in (4, 5):
        raise ValueError(f"Wrong metric value: {entry!r}")
    return f"{name}'s {metric}: {value:g}"


def _results_to_str(entries: List[Tuple], show_stdv: bool = True) -> str:
    return "\t".join(_entry_to_str(e, show_stdv) for e in entries)


class _LogEvaluation:
    """Logs the evaluation line every ``period`` iterations."""

    order = 10
    before_iteration = False
    # display-only: checkpoint resume (engine.train) replays the recorded
    # eval history through stateful callbacks; re-printing it would be
    # noise
    skip_on_resume = True

    def __init__(self, period: int, show_stdv: bool):
        self.period = period
        self.show_stdv = show_stdv

    def __call__(self, env: CallbackEnv) -> None:
        from .utils import log
        it = env.iteration + 1
        if self.period > 0 and env.evaluation_result_list and it % self.period == 0:
            log.info("[%d]\t%s", it,
                     _results_to_str(env.evaluation_result_list, self.show_stdv))


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """Create a callback that logs evaluation results every ``period`` iters."""
    return _LogEvaluation(period, show_stdv)


class _RecordEvaluation:
    """Appends each metric value into a nested ``{data: {metric: [...]}}`` dict."""

    order = 20
    before_iteration = False

    def __init__(self, store: Dict):
        if not isinstance(store, dict):
            raise TypeError("eval_result should be a dictionary")
        store.clear()
        self.store = store

    def __call__(self, env: CallbackEnv) -> None:
        for entry in env.evaluation_result_list:
            data_name, metric_name, value = entry[0], entry[1], entry[2]
            self.store.setdefault(data_name, {}).setdefault(metric_name, []).append(value)
            if len(entry) == 5:
                self.store[data_name].setdefault(f"{metric_name}-stdv", []).append(entry[4])


def record_evaluation(eval_result: Dict) -> Callable:
    """Create a callback recording evaluation history into ``eval_result``."""
    return _RecordEvaluation(eval_result)


class _ResetParameter:
    """Applies per-iteration parameter schedules (lists or callables)."""

    order = 10
    before_iteration = True

    def __init__(self, schedules: Dict[str, Any]):
        self.schedules = schedules

    def _value_at(self, key: str, value, step: int, total: int):
        if isinstance(value, list):
            if len(value) != total:
                raise ValueError(
                    f"Length of list {key!r} has to equal to 'num_boost_round'.")
            return value[step]
        if callable(value):
            return value(step)
        raise ValueError(f"Schedule for {key!r} must be a list or a callable")

    def __call__(self, env: CallbackEnv) -> None:
        step = env.iteration - env.begin_iteration
        total = env.end_iteration - env.begin_iteration
        changed = {}
        for key, sched in self.schedules.items():
            new = self._value_at(key, sched, step, total)
            if env.params.get(key) != new:
                changed[key] = new
        if changed:
            if env.model is not None:
                env.model.reset_parameter(changed)
            env.params.update(changed)


def reset_parameter(**kwargs) -> Callable:
    """Create a callback that resets parameters on a schedule each iteration."""
    return _ResetParameter(kwargs)


@dataclass
class _MetricState:
    """Best-so-far tracking for one (dataset, metric) pair."""
    higher_is_better: bool
    best_score: float = None  # type: ignore[assignment]
    best_iter: int = 0
    best_results: Optional[List[Tuple]] = field(default=None)

    def update(self, score: float, iteration: int, results: List[Tuple]) -> bool:
        better = (self.best_results is None
                  or (score > self.best_score if self.higher_is_better
                      else score < self.best_score))
        if better:
            self.best_score = score
            self.best_iter = iteration
            self.best_results = results
        return better


class _EarlyStopping:
    """Stops training when no validation metric improves for N rounds.

    Train-set entries never trigger a stop (they almost always improve);
    they only participate in the mandatory final-iteration report, matching
    the reference behavior including the cv ``cv_agg``/train special case.
    """

    order = 30
    before_iteration = False

    def __init__(self, stopping_rounds: int, first_metric_only: bool, verbose: bool):
        self.stopping_rounds = stopping_rounds
        self.first_metric_only = first_metric_only
        self.verbose = verbose
        self.states: Optional[List[_MetricState]] = None
        self.enabled = True
        self.first_metric = ""

    # -- helpers -------------------------------------------------------
    def _setup(self, env: CallbackEnv) -> None:
        from .utils import log
        boosting = next((env.params[k] for k in ("boosting", "boosting_type", "boost")
                         if k in env.params), "gbdt")
        self.enabled = boosting != "dart"
        if not self.enabled:
            log.warning("Early stopping is not available in dart mode")
            return
        if not env.evaluation_result_list:
            raise ValueError("For early stopping, at least one dataset and eval "
                             "metric is required for evaluation")
        if self.verbose:
            log.info("Training until validation scores don't improve for %d rounds",
                     self.stopping_rounds)
        self.first_metric = self._metric_key(env.evaluation_result_list[0])
        self.states = [_MetricState(higher_is_better=bool(e[3]))
                       for e in env.evaluation_result_list]

    @staticmethod
    def _metric_key(entry: Tuple) -> str:
        return entry[1].split(" ")[-1]

    def _is_train_entry(self, env: CallbackEnv, entry: Tuple) -> bool:
        if entry[0] == "cv_agg":
            return entry[1].split(" ")[0] == "train"
        train_name = getattr(env.model, "_train_data_name", "training")
        return entry[0] == train_name

    def _report_and_stop(self, state: _MetricState, reason: str) -> None:
        from .utils import log
        if self.verbose:
            log.info("%s, best iteration is:\n[%d]\t%s", reason,
                     state.best_iter + 1, _results_to_str(state.best_results))
            if self.first_metric_only:
                log.info("Evaluated only: %s", self.first_metric)
        raise EarlyStopException(state.best_iter, state.best_results)

    # -- main ----------------------------------------------------------
    def __call__(self, env: CallbackEnv) -> None:
        if self.states is None:
            self._setup(env)
        if not self.enabled:
            return
        last_iter = env.iteration == env.end_iteration - 1
        for state, entry in zip(self.states, env.evaluation_result_list):
            state.update(float(entry[2]), env.iteration, env.evaluation_result_list)
            if self.first_metric_only and self.first_metric != self._metric_key(entry):
                continue
            if self._is_train_entry(env, entry):
                if last_iter:
                    self._report_and_stop(state, "Did not meet early stopping")
                continue
            if env.iteration - state.best_iter >= self.stopping_rounds:
                self._report_and_stop(state, "Early stopping")
            if last_iter:
                self._report_and_stop(state, "Did not meet early stopping")


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    """Create a callback that stops training when no validation metric has
    improved for ``stopping_rounds`` consecutive rounds."""
    return _EarlyStopping(stopping_rounds, first_metric_only, verbose)
