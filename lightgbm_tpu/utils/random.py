"""Deterministic RNG for sampling decisions.

Plays the role of the reference's lightweight ``Random`` helper
(reference: include/LightGBM/utils/random.h) whose seeds drive bagging,
feature-fraction and EFB shuffling. We do not reproduce the reference's LCG
bit-for-bit; we only guarantee determinism for a given seed, which is the
property the framework (and its tests) rely on. Host-side sampling uses
NumPy's PCG64; device-side sampling (bagging under jit) uses
``jax.random`` keys derived from the same seeds.
"""
from __future__ import annotations

import numpy as np


class Random:
    def __init__(self, seed: int = 0):
        self._gen = np.random.Generator(np.random.PCG64(seed))

    def next_int(self, lo: int, hi: int) -> int:
        """Uniform int in [lo, hi)."""
        return int(self._gen.integers(lo, hi))

    def next_float(self) -> float:
        return float(self._gen.random())

    def sample(self, n: int, k: int) -> np.ndarray:
        """k distinct indices from range(n), sorted ascending.

        Mirrors the contract of the reference ``Random::Sample`` (used for
        feature_fraction and bin-sample selection).
        """
        k = min(k, n)
        if k <= 0:
            return np.empty(0, dtype=np.int32)
        idx = self._gen.choice(n, size=k, replace=False)
        idx.sort()
        return idx.astype(np.int32)

    def permutation(self, n: int) -> np.ndarray:
        return self._gen.permutation(n).astype(np.int32)
