"""Persistent XLA compilation-cache wiring.

Every training process pays the grower compile (4.4 s headline / 9.9 s
rank leg at the BENCH_r05 shapes) even though the compiled program is
byte-identical run to run — pure overhead on every bench round and every
restart.  JAX ships a content-addressed persistent cache; this module is
the ONE switch that turns it on for this package, from either surface:

- the ``tpu_compile_cache_dir`` parameter (``engine.train`` / any
  ``Booster`` construction), or
- the ``LGBM_TPU_COMPILE_CACHE`` environment variable (``bench.py``,
  CLI, anything that cannot pass params).

``enable_compile_cache`` is idempotent and must run BEFORE the first
``jit`` compilation it should capture; later calls with the same
directory are no-ops.  ``compile_cache_info`` reports the directory in
effect and whether it was WARM (held entries) when enabled — bench.py
embeds both so a recorded compile_s figure says which kind of compile it
measured.
"""
from __future__ import annotations

import os
from typing import Optional

from . import log

_state = {"dir": None, "warm": None}


def _entry_count(path: str) -> int:
    try:
        return sum(len(fs) for _, _, fs in os.walk(path))
    except OSError:
        return 0


def enable_compile_cache(path: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``path`` (falling back
    to ``$LGBM_TPU_COMPILE_CACHE``; no-op when neither is set).

    Returns the cache directory in effect, or None when the cache stays
    off or JAX refused the configuration (logged, never raised — a cache
    failure must not cost a training run)."""
    p = path or os.environ.get("LGBM_TPU_COMPILE_CACHE", "")
    if not p:
        return _state["dir"]
    p = os.path.abspath(os.path.expanduser(str(p)))
    if _state["dir"] == p:
        return p
    warm = _entry_count(p) > 0
    try:
        os.makedirs(p, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", p)
        # cache EVERYTHING: the default minimums (1s compile, 4KB entry)
        # would skip the many small helper jits around the grower
        for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                          ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(knob, val)
            except Exception:  # noqa: BLE001 — knob absent on this jax
                pass
        # jax initializes the cache backend lazily at the FIRST compile
        # and then ignores config changes; if anything compiled before
        # this call (warm process, earlier Booster), the no-dir decision
        # is already frozen — reset so the new directory takes effect
        try:
            from jax.experimental.compilation_cache.compilation_cache import \
                reset_cache
            reset_cache()
        except Exception:  # noqa: BLE001 — moved/absent on this jax
            pass
    except Exception as exc:  # noqa: BLE001
        log.warning("persistent compilation cache disabled (%s: %s)",
                    type(exc).__name__, exc)
        return None
    _state["dir"] = p
    _state["warm"] = warm
    log.info("persistent XLA compilation cache at %s (%s)", p,
             "warm" if warm else "cold")
    return p


def compile_cache_info() -> dict:
    """{"dir": path-or-None, "warm": bool-or-None} as of enable time."""
    return dict(_state)


# ---------------------------------------------------------------------
# executable-store plumbing (serve/aot.py)
#
# The XLA cache above still pays trace + lowering + a cache probe per
# bucket shape on every boot.  The serving AOT store (serve/aot.py)
# goes one step further — whole serialized EXECUTABLES, loaded without
# touching the compiler at all — and shares this module's on-disk
# hygiene: durable atomic writes and warm/cold introspection.
# ---------------------------------------------------------------------

def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` via a same-directory temp file +
    ``os.replace`` so a concurrent reader (another serving process
    loading the store) sees either the old entry or the complete new
    one, never a torn write.  Raises on failure — callers decide how
    loud a store write failure is."""
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    finally:
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass


def store_entries(path: Optional[str], suffix: str = ".aot") -> list:
    """Entry filenames under an executable-store directory (sorted;
    empty for a missing/unreadable dir — a cold store, not an error)."""
    if not path:
        return []
    try:
        return sorted(f for f in os.listdir(path) if f.endswith(suffix))
    except OSError:
        return []
