"""Per-phase wall-time tracing — the TIMETAG analog.

The reference compiles in ``std::chrono`` phase accumulators under
``#ifdef TIMETAG`` and prints them at teardown (reference:
src/treelearner/serial_tree_learner.cpp:21-60, src/boosting/gbdt.cpp:30-56).
Here the switch is the ``LGBM_TPU_TIMETAG`` environment variable (set to
``1``) — a Python-level gate instead of a rebuild.

This module is now a thin façade over :mod:`lightgbm_tpu.obs` — the same
accumulators feed both the atexit TIMETAG report and the structured
telemetry stream (``LGBM_TPU_TELEMETRY``), so the two gates share one
source of truth.  The public surface is unchanged:

    with timetag("tree growth"):
        tree, leaf_id = grow(...)
        sync(leaf_id)

``sync(x)`` blocks on ``x`` ONLY while tracing (either gate) is enabled,
so the training loop keeps its async pipelining when tracing is off (the
overlap matters: see the lag-1 stop note in boosting/gbdt.py).
Accumulated times print at process exit and via :func:`report`.
"""
from __future__ import annotations

from ..obs.core import (TIMETAG_ENABLED as ENABLED, add, phase as timetag,
                        report, reset, sync)

__all__ = ["ENABLED", "timetag", "sync", "add", "reset", "report"]
