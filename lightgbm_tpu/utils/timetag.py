"""Per-phase wall-time tracing — the TIMETAG analog.

The reference compiles in ``std::chrono`` phase accumulators under
``#ifdef TIMETAG`` and prints them at teardown (reference:
src/treelearner/serial_tree_learner.cpp:21-60, src/boosting/gbdt.cpp:30-56).
Here the switch is the ``LGBM_TPU_TIMETAG`` environment variable (set to
``1``) — a Python-level gate instead of a rebuild.

Because JAX dispatch is asynchronous, a phase that launches device work
must synchronize before its timer stops or it only measures enqueue time.
``sync(x)`` blocks on ``x`` ONLY while tracing is enabled, so the
training loop keeps its async pipelining when tracing is off (the
overlap matters: see the lag-1 stop note in boosting/gbdt.py).

Usage::

    with timetag("tree growth"):
        tree, leaf_id = grow(...)
        sync(leaf_id)

Accumulated times print at process exit and via :func:`report`.
"""
from __future__ import annotations

import atexit
import os
import time
from collections import defaultdict

from . import log

ENABLED = os.environ.get("LGBM_TPU_TIMETAG", "") not in ("", "0", "false")

_acc = defaultdict(float)
_cnt = defaultdict(int)


class timetag:
    """Context manager accumulating wall time under ``name`` when enabled."""

    __slots__ = ("name", "t0")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        if ENABLED:
            self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if ENABLED:
            _acc[self.name] += time.perf_counter() - self.t0
            _cnt[self.name] += 1
        return False


def sync(x):
    """Block on a jax value only when tracing — keeps async dispatch
    intact in normal runs. Returns ``x``."""
    if ENABLED and x is not None:
        import jax

        jax.block_until_ready(x)
    return x


def add(name: str, seconds: float) -> None:
    """Manual accumulation for phases timed externally."""
    if ENABLED:
        _acc[name] += seconds
        _cnt[name] += 1


def reset() -> None:
    _acc.clear()
    _cnt.clear()


def report() -> None:
    """Print accumulated phase times (reference prints at GBDT/learner
    destructors, gbdt.cpp:46-56)."""
    if not _acc:
        return
    total = sum(_acc.values())
    log.info("TIMETAG phase times:")
    for name, t in sorted(_acc.items(), key=lambda kv: -kv[1]):
        log.info("  %-24s %8.3f s  (%d calls, %4.1f%%)",
                 name, t, _cnt[name], 100.0 * t / total if total else 0.0)


if ENABLED:
    atexit.register(report)
