"""Logging for lightgbm_tpu.

TPU-native rebuild of the reference's static ``Log`` class
(reference: include/LightGBM/utils/log.h:21-108): four levels gated by a
global verbosity, with ``fatal`` raising instead of ``abort()``-ing so the
Python API surfaces errors as exceptions (like the C API's error string path).
"""
from __future__ import annotations

import sys

FATAL = -1
WARNING = 0
INFO = 1
DEBUG = 2

_level = INFO


def set_verbosity(verbosity: int) -> None:
    """Map the LightGBM ``verbosity`` parameter onto a log level.

    <0 → fatal only, 0 → warnings, 1 → info, >=2 → debug.
    """
    global _level
    if verbosity < 0:
        _level = FATAL
    elif verbosity == 0:
        _level = WARNING
    elif verbosity == 1:
        _level = INFO
    else:
        _level = DEBUG


def get_verbosity() -> int:
    return _level


class LightGBMError(Exception):
    """Raised on fatal errors (the rebuild's analog of Log::Fatal)."""


def debug(msg: str, *args) -> None:
    if _level >= DEBUG:
        _emit("Debug", msg % args if args else msg)


def info(msg: str, *args) -> None:
    if _level >= INFO:
        _emit("Info", msg % args if args else msg)


def warning(msg: str, *args) -> None:
    if _level >= WARNING:
        _emit("Warning", msg % args if args else msg)


def fatal(msg: str, *args) -> None:
    raise LightGBMError(msg % args if args else msg)


def _emit(tag: str, msg: str) -> None:
    sys.stderr.write(f"[LightGBM-TPU] [{tag}] {msg}\n")
    sys.stderr.flush()


def check(cond: bool, msg: str = "check failed") -> None:
    if not cond:
        fatal(msg)
