from . import log
from .random import Random

__all__ = ["log", "Random"]
