"""CLI application driver: ``python -m lightgbm_tpu task=train conf=...``.

The analog of the reference CLI (reference: src/main.cpp,
src/application/application.cpp:48-81 task dispatch, :198-218 Train with
snapshots, :221-247 Predict).  Arguments are ``key=value`` pairs; a
``config=FILE`` pair loads a LightGBM .conf file, with command-line pairs
taking precedence (reference: config.cpp Config::Set ordering).
"""
from __future__ import annotations

import os
import sys
from typing import Dict, List

import numpy as np

from .basic import Booster, Dataset
from .config import Config, read_config_file
from .engine import train as train_api
from .io.text_loader import load_text
from .utils import log


def _parse_args(argv: List[str]) -> Dict[str, str]:
    params: Dict[str, str] = {}
    conf_file = None
    for arg in argv:
        k, eq, v = arg.partition("=")
        if not eq:
            log.fatal(f"Unknown argument {arg!r}; expected key=value")
        k = k.strip()
        if k in ("config", "config_file", "conf"):
            conf_file = v.strip()
        else:
            params[k] = v.strip()
    if conf_file:
        file_params = read_config_file(conf_file)
        for k, v in file_params.items():
            params.setdefault(k, v)  # CLI pairs win
    return params


def _dataset_from_file(path: str, cfg: Config, params: Dict,
                       reference=None, initscore_path: str = "") -> Dataset:
    if getattr(cfg, "tpu_ingest", False):
        from .io.text_loader import _ParseError
        try:
            return _dataset_ingest(path, cfg, params, reference,
                                   initscore_path)
        except _ParseError as exc:
            log.warning("tpu_ingest streaming needs the strict native "
                        "parser for text input (%s); falling back to "
                        "in-memory loading", exc)
    if getattr(cfg, "two_round", False):
        from .io.text_loader import _ParseError
        try:
            return _dataset_two_round(path, cfg, params, reference,
                                      initscore_path)
        except _ParseError as exc:
            log.warning("two_round streaming needs the strict native "
                        "parser (%s); falling back to in-memory loading",
                        exc)
    X, label, weight, group, names = load_text(path, cfg)
    init_score = _load_init_scores(path, initscore_path)
    ds = Dataset(X, label=label, weight=weight, group=group,
                 init_score=init_score,
                 feature_name=names, params=dict(params),
                 reference=reference)
    return ds


def _load_init_scores(path: str, initscore_path: str = ""):
    """Init scores: explicit initscore_filename, else the <data>.init
    sidecar (reference: Metadata::LoadInitialScore, metadata.cpp — ".init"
    suffix convention).  Multiclass files are N rows x K cols; the trainer
    consumes class-major flat layout (gbdt init reshapes (K, N))."""
    if initscore_path and not os.path.exists(initscore_path):
        log.fatal(f"Initial score file {initscore_path} does not exist")
    for cand in ([initscore_path] if initscore_path else []) + [path + ".init"]:
        if cand and os.path.exists(cand):
            arr = np.loadtxt(cand, dtype=np.float64)
            init_score = (arr.T.ravel() if arr.ndim == 2 else arr.ravel())
            log.info("Loaded %d init scores from %s", len(init_score), cand)
            return init_score
    return None


def _resolve_cli_categoricals(cfg: Config):
    """categorical_feature spec string -> list of ints / names (the
    name-based entries resolve against kept feature names downstream)."""
    cats = []
    spec = str(getattr(cfg, "categorical_feature", "") or "")
    for tok in spec.replace("name:", "").split(","):
        tok = tok.strip()
        if not tok:
            continue
        cats.append(int(tok) if tok.isdigit() else tok)
    return cats


def _dataset_ingest(path: str, cfg: Config, params: Dict,
                    reference=None, initscore_path: str = "") -> Dataset:
    """tpu_ingest=true file loading: two-pass streaming ingestion
    (ingest/stream.py) — chunked readers, reservoir bin sampling,
    chunk-at-a-time binning, optional memmap-backed bin matrix and
    row-shard plans; the raw matrix is never materialized."""
    from .ingest.stream import ingest_file

    ref_handle = (reference.construct()._handle
                  if reference is not None else None)
    handle, label, weight, group, names = ingest_file(
        path, cfg, categorical_features=_resolve_cli_categoricals(cfg),
        reference=ref_handle)
    ds = Dataset(None, params=dict(params), feature_name=names,
                 reference=reference)
    ds._handle = handle
    if label is not None:
        ds.label = label
    if weight is not None:
        ds.weight = weight
    if group is not None:
        ds.group = group
    init_score = _load_init_scores(path, initscore_path)
    if init_score is not None:
        # init-score files are whole-stream ([N_global * K] class-major
        # flat); a sharded load keeps only its own rows of each class
        lo, hi = getattr(handle, "ingest_row_range",
                         (0, handle.num_data))
        n_global = getattr(handle, "ingest_num_rows", handle.num_data)
        if len(init_score) % n_global != 0:
            log.fatal(f"init score length {len(init_score)} is not a "
                      f"multiple of the data rows ({n_global})")
        if handle.num_data != n_global:
            k = len(init_score) // n_global
            init_score = np.ascontiguousarray(
                init_score.reshape(k, n_global)[:, lo:hi]).ravel()
        ds.set_init_score(init_score)
    return ds


def _dataset_two_round(path: str, cfg: Config, params: Dict,
                       reference=None, initscore_path: str = "") -> Dataset:
    """two_round=true file loading: stream the file twice instead of
    materializing the raw matrix (reference: config.h two_round,
    dataset_loader.cpp:807-827)."""
    from .io.text_loader import load_text_two_round

    ref_handle = (reference.construct()._handle
                  if reference is not None else None)
    handle, label, weight, group, names = load_text_two_round(
        path, cfg, categorical_features=_resolve_cli_categoricals(cfg),
        reference=ref_handle)
    ds = Dataset(None, params=dict(params), feature_name=names,
                 reference=reference)
    ds._handle = handle
    if label is not None:
        ds.set_label(label)
    if weight is not None:
        ds.set_weight(weight)
    if group is not None:
        ds.set_group(group)
    init_score = _load_init_scores(path, initscore_path)
    if init_score is not None:
        ds.set_init_score(init_score)
    return ds


def run_train(cfg: Config, params: Dict) -> None:
    train_set = _dataset_from_file(
        cfg.data, cfg, params,
        initscore_path=getattr(cfg, "initscore_filename", ""))
    valid_sets, valid_names = [], []
    for i, vpath in enumerate(cfg.valid):
        vinit = (cfg.valid_data_initscores[i]
                 if i < len(getattr(cfg, "valid_data_initscores", []))
                 else "")
        valid_sets.append(_dataset_from_file(vpath, cfg, params,
                                             reference=train_set,
                                             initscore_path=vinit))
        valid_names.append(f"valid_{i + 1}" if len(cfg.valid) > 1 else "valid")

    from . import callback
    cbs = []
    if cfg.metric_freq > 0 and (valid_sets or cfg.is_provide_training_metric):
        cbs.append(callback.print_evaluation(period=cfg.metric_freq))
    if cfg.snapshot_freq > 0:
        # reference: gbdt.cpp:290-294 — save <output_model>.snapshot_iter_N
        def snapshot_cb(env):
            it = env.iteration + 1
            if it % cfg.snapshot_freq == 0:
                out = f"{cfg.output_model}.snapshot_iter_{it}"
                env.model.save_model(out)
                log.info("Saved snapshot to %s", out)
        snapshot_cb.order = 100
        # a checkpoint resume replays the eval history through the
        # callbacks; rewriting old snapshot files during the replay
        # would be wasted IO
        snapshot_cb.skip_on_resume = True
        cbs.append(snapshot_cb)

    if cfg.is_provide_training_metric:
        valid_sets = [train_set] + valid_sets
        valid_names = ["training"] + valid_names

    if getattr(cfg, "tpu_checkpoint_dir", ""):
        log.info("fault tolerance armed: checkpoints every %d iteration(s) "
                 "to %s (resume is automatic on restart)",
                 cfg.tpu_checkpoint_freq, cfg.tpu_checkpoint_dir)

    init_model = cfg.input_model or None
    bst = train_api(params, train_set,
                    num_boost_round=int(cfg.num_iterations),
                    valid_sets=valid_sets or None,
                    valid_names=valid_names or None,
                    init_model=init_model,
                    early_stopping_rounds=(cfg.early_stopping_round
                                           if cfg.early_stopping_round > 0
                                           else None),
                    verbose_eval=False,
                    callbacks=cbs)
    bst.save_model(cfg.output_model)
    log.info("Finished training; model saved to %s", cfg.output_model)


def run_predict(cfg: Config, params: Dict) -> None:
    if not cfg.input_model:
        log.fatal("task=predict needs input_model (alias: model_file)")
    # self-contained: only input_model + data are needed — the model file
    # carries objective/num_class, no training config required
    bst = Booster(model_file=cfg.input_model)
    # prediction-time knobs (pred_early_stop*) come from the CLI config,
    # not the minimal config parsed from the model
    bst.reset_parameter({"pred_early_stop": cfg.pred_early_stop,
                         "pred_early_stop_freq": cfg.pred_early_stop_freq,
                         "pred_early_stop_margin": cfg.pred_early_stop_margin})
    X, _, _, _, _ = load_text(cfg.data, cfg)
    num_it = cfg.num_iteration_predict if cfg.num_iteration_predict > 0 else None
    from .boosting.gbdt import PredictorBase
    K = bst.num_model_per_iteration()
    n_iters = bst.num_trees() // max(K, 1)
    window = min(num_it, n_iters) if num_it else n_iters
    # LGBM_TPU_PREDICT_MIN_WORK forces the routing either way (0 = every
    # predict through the serving session; huge = always the host loop)
    # — an ops escape hatch that also makes the session branch testable
    try:
        min_work = int(os.environ.get("LGBM_TPU_PREDICT_MIN_WORK", "")
                       or PredictorBase._DEVICE_PREDICT_MIN_WORK)
    except ValueError:
        min_work = PredictorBase._DEVICE_PREDICT_MIN_WORK
    work = X.shape[0] * window * K
    if cfg.predict_leaf_index or cfg.predict_contrib:
        pred = bst.predict(X, num_iteration=num_it,
                           raw_score=bool(cfg.predict_raw_score),
                           pred_leaf=bool(cfg.predict_leaf_index),
                           pred_contrib=bool(cfg.predict_contrib))
    elif work >= min_work:
        # heavy value predictions route through the serving session: the
        # model is packed once into the device-resident forest (bin
        # space rebuilt from the model itself, no training data needed)
        # and scored in bounded pow2 buckets — the same engine
        # task=serve runs behind HTTP.  Small inputs keep the host loop
        # (same dispatch-overhead heuristic Booster.predict applies).
        from .serve import PredictorSession
        with PredictorSession(bst, config=bst.config,
                              num_iteration=num_it) as sess:
            pred = sess.predict(X, raw_score=bool(cfg.predict_raw_score))
    else:
        pred = bst.predict(X, num_iteration=num_it,
                           raw_score=bool(cfg.predict_raw_score))
    pred = np.atleast_1d(pred)
    fmt = "%d" if pred.dtype.kind in "iu" else "%.18g"
    np.savetxt(cfg.output_result, pred, fmt=fmt, delimiter="\t")
    log.info("Finished prediction; results saved to %s", cfg.output_result)


def run_serve(cfg: Config, params: Dict) -> None:
    """task=serve: pack input_model into a replicated, registry-managed
    fleet and serve it over HTTP until interrupted (serve/server.py:
    POST /predict /explain, POST /models/{name}/swap|rollback for
    zero-downtime model pushes, GET /health /metrics /stats /models).
    The model registers as ``default``; ``tpu_serve_replicas`` sessions
    serve it behind the failover router."""
    if not cfg.input_model:
        log.fatal("task=serve needs input_model (alias: model_file)")
    from .serve import ForestArena, ModelRegistry, PredictServer
    reg = ModelRegistry(config=cfg)
    reg.add_model("default", cfg.input_model)
    # multi-tenant arena rides the same fleet surface: POST
    # /models/{name}/swap with {"arena": true} admits a tenant into the
    # shared pack, /predict routes by model= name
    reg.attach_arena(ForestArena(config=cfg))
    router = reg.resolve(None).router
    n = router.warmup()
    log.info("serve: %d replica(s) warmed %d bucket shapes "
             "(max_batch=%d); arena attached (budget %s)",
             len(router.replicas), n, router.max_batch,
             reg.arena.budget_bytes or "unbounded")
    PredictServer(reg, host=cfg.tpu_serve_host,
                  port=cfg.tpu_serve_port).serve_forever()


def run_convert_model(cfg: Config, params: Dict) -> None:
    """task=convert_model: model file -> standalone if-else scoring code
    (reference: Application::ConvertModel, application.cpp:233-241)."""
    if not cfg.input_model:
        log.fatal("task=convert_model needs input_model")
    bst = Booster(model_file=cfg.input_model)
    code = bst.model_to_if_else()
    with open(cfg.convert_model, "w") as fh:
        fh.write(code)
    log.info("Finished converting model; code saved to %s", cfg.convert_model)


def run_refit(cfg: Config, params: Dict) -> None:
    if not cfg.input_model:
        log.fatal("task=refit needs input_model")
    bst = Booster(model_file=cfg.input_model)
    X, label, _, _, _ = load_text(cfg.data, cfg)
    new_bst = bst.refit(X, label, decay_rate=cfg.refit_decay_rate)
    new_bst.save_model(cfg.output_model)
    log.info("Finished refit; model saved to %s", cfg.output_model)


def main(argv=None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    params = _parse_args(argv)
    cfg = Config.from_params(params)
    if cfg.tpu_telemetry:
        # enable before any data loads so dataset-construction phases
        # (bin finding, binarize) land in the telemetry too — the param
        # analog of setting LGBM_TPU_TELEMETRY before the process starts
        from . import obs
        obs.enable(cfg.tpu_telemetry)
    task = cfg.task
    if task == "train":
        # tpu_fleet=N: this invocation becomes the GANG LAUNCHER — it
        # spawns N `python -m lightgbm_tpu.fleet` worker ranks, watches
        # them, heals lost ones, and exits with the fleet's verdict
        # (fleet/launch.py; a spawned rank re-enters main() with
        # LGBM_TPU_FLEET_RANK set and falls through to run_train only
        # on the jax transport)
        from .fleet.launch import launch_fleet, should_gang_launch
        if should_gang_launch(cfg):
            res = launch_fleet(cfg, params)
            raise SystemExit(0 if res["ok"] else (res["rc"] or 1))
        run_train(cfg, params)
    elif task in ("predict", "prediction", "test"):
        run_predict(cfg, params)
    elif task == "serve":
        run_serve(cfg, params)
    elif task == "online":
        # closed-loop learning service (online/loop.py): serve
        # input_model behind the registry fleet AND consume the labeled
        # stream back into refreshed versions via canary-gated swaps
        from .online import run_online
        run_online(cfg, params)
    elif task == "refit":
        run_refit(cfg, params)
    elif task == "convert_model":
        run_convert_model(cfg, params)
    else:
        log.fatal(f"Unknown task {task!r} (supported: train, predict, "
                  "serve, online, convert_model, refit)")
