"""``PredictorSession``: pack a model once, serve it device-resident.

The trainer's device predict path (boosting/gbdt.py) is tied to live
training state; the session is the serving-side replacement — the
reference's dedicated ``Predictor`` (src/application/predictor.hpp)
rebuilt for TPU batch traversal:

- the model (a ``Booster``, a bare ``GBDT``/``LoadedGBDT``, or a model
  file path) is packed ONCE into a stacked bin-space ``ForestArrays``
  plus a model-derived ``DeviceMeta`` (serve/packing.py — no train_ds);
- ``predict(X)`` is the synchronous path (chunks internally to the
  batch cap); ``submit(X) -> ticket`` / ``result(ticket)`` the async
  one, coalesced by the dynamic microbatcher (serve/batcher.py);
- ``explain(X)`` / ``submit_explain(X)`` are the SHAP-contribution
  twins: the batched device TreeSHAP kernel (explain/) behind its own
  microbatcher and pow2 bucket family (``tpu_explain_max_batch`` /
  ``tpu_explain_max_wait_ms``), packed lazily on first use so
  predict-only sessions never pay the path-metadata HBM cost;
- every device call pads its rows to the next power-of-two bucket, so
  the jitted forest scan compiles at most ``ceil(log2(max_batch)) + 1``
  shapes — the obs recompile counter (obs/trace.py) verifies the bound;
- if the device backend dies mid-flight the session degrades to the
  host numpy predictor (per-tree value-space traversal) instead of
  failing requests; ``stats()['degraded']`` and the ``serve_degraded``
  event record it, and the HTTP /health endpoint reports it.

Telemetry (when a sink is configured): ``serve_request`` per request
(rows, total_ms, ok), ``serve_batch`` per device batch (rows, padded,
bucket, queue_rows, exec_ms), ``serve_overload`` / ``serve_degraded``
on the respective transitions.  ``obs/report.py serve_summary`` folds
them into the serving digest (p50/p99, occupancy, pad waste).
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import InvalidStateError
from typing import Optional

import numpy as np

from .. import obs
from ..config import Config
from ..robust import faults
from ..utils import log
from .batcher import (DeadlineExceeded, MicroBatcher, Request,
                      ServeOverloadError, normalize_priority)
from .metrics import ServeMetrics
from .packing import ServeBinSpace

_LAT_RESERVOIR = 8192  # latency samples kept for the p50/p99 estimate

# an overload STORM (>= _STORM_N rejects inside _STORM_WINDOW_S) dumps
# the flight ring once per _STORM_COOLDOWN_S — the post-mortem for "why
# did the queue blow up", rate-limited so a sustained storm writes one
# artifact, not thousands
_STORM_N = 16
_STORM_WINDOW_S = 5.0
_STORM_COOLDOWN_S = 60.0


def _safe_resolve(future, result=None, error=None) -> None:
    """Resolve a request future, tolerating the overload-cancellation
    race: a submit that overloaded cancels its already-queued chunks,
    and cancel() can land between any done() check and the set_* call —
    an InvalidStateError here must not poison the rest of the batch."""
    try:
        if error is not None:
            future.set_exception(error)
        else:
            future.set_result(result)
    except InvalidStateError:
        pass


def _env_num(name: str, cast, fallback):
    v = os.environ.get(name, "")
    if v:
        try:
            return cast(v)
        except ValueError:
            log.warning("ignoring non-numeric %s=%r", name, v)
    return fallback


class Ticket:
    """Handle for an async submission (one or more batcher requests —
    oversize submissions are chunked to the batch cap).  ``kind`` is
    ``"predict"`` or ``"explain"`` — it picks the result conversion and
    which accounting stream (latency histogram, events) the ticket's
    outcome lands in."""

    __slots__ = ("parts", "rows", "raw_score", "t0", "counted", "kind",
                 "priority")

    def __init__(self, parts, rows: int, raw_score: bool,
                 kind: str = "predict", priority: str = "normal"):
        self.parts = parts          # [(future, n_rows), ...]
        self.rows = rows
        self.raw_score = raw_score
        self.t0 = time.perf_counter()
        self.counted = False        # request-level stats recorded once
        self.kind = kind
        self.priority = priority


class PredictorSession:
    """Device-resident inference over one packed model window."""

    def __init__(self, model, config=None, num_iteration: Optional[int] = None,
                 start_iteration: int = 0, max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 metrics: Optional[ServeMetrics] = None,
                 device=None, drift="auto"):
        gbdt = model
        # fleet identity (serve/router.py + serve/registry.py stamp
        # these): which model/version/replica this session serves, and an
        # optional pinned device (per-device replicas on a multi-chip
        # host; None = the backend default)
        self.model_name: Optional[str] = None
        self.model_version: Optional[int] = None
        self.replica_id: Optional[str] = None
        self._device = device
        if isinstance(model, str):
            from ..io.model_io import load_model_file
            gbdt, loaded_cfg = load_model_file(model)
            if config is None:
                config = loaded_cfg
        elif hasattr(model, "_gbdt"):  # a basic.Booster
            gbdt = model._gbdt
            if config is None:
                config = getattr(model, "config", None)
        if config is None:
            config = getattr(gbdt, "config", None) or Config()
        elif isinstance(config, dict):
            config = Config.from_params(config)
        self.config = config
        self.gbdt = gbdt
        self.objective = getattr(gbdt, "objective", None)
        K = self.num_tpi = int(gbdt.num_tpi)

        start, stop = gbdt._iter_window(num_iteration, start_iteration)
        trees = list(gbdt.models)[start * K:stop * K]
        if not trees:
            raise ValueError("cannot serve an empty model")
        self._trees = trees
        self.num_trees = len(trees)
        # rf-style averaged forests divide the summed raw score by the
        # iteration window (io/model_io.py LoadedGBDT.predict_raw)
        self.average_factor = (float(max(stop - start, 1))
                               if getattr(gbdt, "average_output", False)
                               else 0.0)
        if gbdt.train_ds is not None:
            F = int(gbdt.train_ds.num_total_features)
        else:
            F = int(getattr(gbdt, "num_features", 0)
                    or len(getattr(gbdt, "feature_names", []) or []))
        if F <= 0:
            raise ValueError("model declares no feature space to bin into")
        self.num_features = F

        self.max_batch = int(max_batch if max_batch is not None else _env_num(
            "LGBM_TPU_SERVE_MAX_BATCH", int,
            getattr(config, "tpu_serve_max_batch", 1024)))
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.max_wait_ms = float(
            max_wait_ms if max_wait_ms is not None else _env_num(
                "LGBM_TPU_SERVE_MAX_WAIT_MS", float,
                getattr(config, "tpu_serve_max_wait_ms", 2.0)))
        self.queue_depth = int(
            queue_depth if queue_depth is not None else _env_num(
                "LGBM_TPU_SERVE_QUEUE_DEPTH", int,
                getattr(config, "tpu_serve_queue_depth", 8192)))
        # ---- explanation serving (explain/ TreeSHAP) -----------------
        env_x = os.environ.get("LGBM_TPU_EXPLAIN", "").strip().lower()
        self.explain_enabled = (env_x not in ("0", "false", "off")
                                if env_x
                                else bool(getattr(config, "tpu_explain",
                                                  True)))
        self.explain_max_batch = max(int(_env_num(
            "LGBM_TPU_EXPLAIN_MAX_BATCH", int,
            getattr(config, "tpu_explain_max_batch", 256))), 1)
        self.explain_max_wait_ms = max(float(_env_num(
            "LGBM_TPU_EXPLAIN_MAX_WAIT_MS", float,
            getattr(config, "tpu_explain_max_wait_ms", 5.0))), 0.0)
        # packed lazily on first explain()/submit_explain(): the path
        # metadata + its batcher cost host time and HBM a predict-only
        # session must not pay
        self._explain = None
        self._explain_lock = threading.Lock()
        self._explain_buckets: set = set()
        self._explain_batches = 0
        self._explain_rows = 0
        self._explain_padded = 0
        self._n_explain = 0
        self._n_explain_ok = 0
        self._n_explain_deadline = 0
        self._xlat_ms: list = []
        # the explain plane degrades apart from predict's: the TreeSHAP
        # kernel's [N, L, P] working set can fail (HBM OOM) while 1-row
        # predicts still succeed, so a shared flag would let the predict
        # reprobe re-arm a kernel that is still broken — a sustained
        # degrade/recover oscillation routing predict to the host path
        self._explain_degraded = False
        self._last_explain_probe = 0.0

        # ---- pack once: bin space + stacked forest + jitted scan ------
        self.space = ServeBinSpace(trees, F)
        class_ids = np.asarray([i % K for i in range(len(trees))], np.int32)
        self.forest = self.space.pack(trees, class_ids)
        from ..core.forest import forest_predict_fn
        early_stop = (gbdt._early_stop_spec()
                      if hasattr(gbdt, "_early_stop_spec") else None)
        fn = forest_predict_fn(self.space.meta, K, early_stop)
        self._raw_fn = fn  # unwrapped jit fn — the AOT lower/compile unit
        if obs.profile_enabled():
            fn = obs.profile_wrap("lgbm/forest_predict", fn)
        self._device_fn = fn

        # ---- AOT executable store (serve/aot.py): zero-compile boot --
        # entries present for this exact forest/bin-space/backend load as
        # ready-to-call executables keyed per bucket; a warmed store
        # makes request #1 pay zero JIT compiles.  Load failures fall
        # back to the jit path above, loudly (aot_fallback event).
        from .aot import AOTStore, resolve_aot_dir
        self._aot_fns: dict = {}
        self._aot_x_fns: dict = {}
        self._aot = None
        aot_dir = resolve_aot_dir(config)
        if aot_dir:
            self._aot = AOTStore(aot_dir)
            # the executable bakes forest + meta in as constants: hash
            # CONTENT, not shapes (see serve/aot.py key schema)
            self._aot_digest = AOTStore._digest_tree(
                (self.forest, self.space.meta))
            self._aot_extra = (f"K={K}|F={F}|es={early_stop!r}"
                               f"|dev={self._device}")
            for b in self._bucket_sweep(self.max_batch):
                st, afn = self._aot.load("predict", self._aot.key(
                    "predict", b, self._aot_digest, self._aot_extra))
                if st == "hit":
                    self._aot_fns[b] = afn

        # ---- serving state -------------------------------------------
        self._degraded = False
        self._closed = False
        self._lock = threading.Lock()
        self._lat_ms: list = []
        self._n_req = 0
        self._n_ok = 0
        self._n_deadline = 0
        self._n_overload = 0
        self._batches = 0
        self._real_rows = 0
        self._padded_rows = 0
        self._buckets: set = set()
        # ---- observability: live metrics + trace plane ---------------
        self._t_start = time.time()
        obs.install_recompile_hook()
        self._compiles0 = obs.compile_count()
        self.slo_p99_ms = float(_env_num(
            "LGBM_TPU_SERVE_SLO_P99_MS", float,
            getattr(config, "tpu_serve_slo_p99_ms", 250.0)))
        # replicas of one model version share ONE ServeMetrics (the
        # router passes it in) so the fleet's latency histogram and
        # shed counters aggregate without a merge step
        self.metrics = (metrics if metrics is not None
                        else ServeMetrics(slo_p99_ms=self.slo_p99_ms))
        # ---- drift monitoring (obs/drift.py) -------------------------
        # "auto" arms only for a file-loaded model with a .quality.json
        # sidecar beside it (and tpu_drift on); the router passes a
        # shared DriftMonitor instead so one sketch covers every
        # replica of a version, like ServeMetrics above.  Unarmed, the
        # hot path pays exactly one is-None branch.
        if drift == "auto":
            from ..obs.drift import DriftMonitor
            self._drift = DriftMonitor.maybe_load(model, config)
        else:
            self._drift = drift or None
        # probe-and-recover: while degraded, re-try the device every
        # reprobe_s seconds so a transient backend error is not a
        # one-way latch (0 disables — the pre-ISSUE-7 behavior)
        self.reprobe_s = float(_env_num(
            "LGBM_TPU_SERVE_REPROBE_S", float,
            getattr(config, "tpu_serve_reprobe_s", 30.0)))
        self._last_probe = 0.0
        if getattr(config, "tpu_trace", False):
            obs.enable_trace()
        if not obs.flight_enabled():
            obs.enable_flight(obs.flight_len_from_env(
                getattr(config, "tpu_flight_len", 256)))
        self._overload_times: deque = deque(maxlen=_STORM_N)
        self._last_flight_dump = None  # monotonic() of the last dump
        # priority shedding (serve/batcher.py): per-class queue budgets
        # so overload drops low-priority bulk traffic before interactive
        # requests
        self._shed_fracs = {
            "low": float(_env_num(
                "LGBM_TPU_SERVE_SHED_LOW_FRAC", float,
                getattr(config, "tpu_serve_shed_low_frac", 0.5))),
            "normal": float(_env_num(
                "LGBM_TPU_SERVE_SHED_NORMAL_FRAC", float,
                getattr(config, "tpu_serve_shed_normal_frac", 0.85))),
        }
        self._batcher = MicroBatcher(
            self._execute_batch, max_batch=self.max_batch,
            max_wait_s=self.max_wait_ms / 1e3,
            max_queue_rows=self.queue_depth,
            shed_fracs=self._shed_fracs)
        if obs.enabled():
            obs.event("serve_start", trees=self.num_trees, num_class=K,
                      num_features=F, max_batch=self.max_batch,
                      max_wait_ms=self.max_wait_ms,
                      queue_depth=self.queue_depth)

    # ------------------------------------------------------------------
    @staticmethod
    def _bucket_sweep(cap: int):
        """Every bucket size ``_bucket`` can produce, ascending: the
        pow2 ladder clamped at the batch cap (a non-power-of-two cap IS
        the top bucket)."""
        b = 1
        while True:
            size = min(b, cap)
            yield size
            if size >= cap:
                return
            b *= 2

    def warmup(self) -> int:
        """Pre-compile every bucket shape up to the batch cap so the
        first real request never pays a compile.  The probe is clamped
        to the cap — a non-power-of-two ``max_batch`` IS the top bucket
        (``_bucket`` clamps the same way), so warmup compiles exactly
        the shapes real traffic can produce.  With the AOT store armed,
        buckets it did not already hold are lowered/compiled ONCE here,
        registered for dispatch, and persisted — the next process boots
        with zero compiles.  Returns the bucket count."""
        n = 0
        for size in self._bucket_sweep(self.max_batch):
            if self._aot is not None and size not in self._aot_fns:
                self._aot_export(size)
            self._run_device(np.zeros((size, self.num_features), np.int32))
            n += 1
        return n

    def _aot_export(self, size: int) -> None:
        """Lower + compile the predict fn for one bucket and persist it
        (serve/aot.py).  The compiled executable also joins the dispatch
        table so this process pays the compile exactly once.  Failure is
        logged and costs the next boot a compile, never a request."""
        import jax
        import jax.numpy as jnp
        try:
            bins = jnp.asarray(
                np.zeros((size, self.num_features), np.int32))
            if self._device is not None:
                with jax.default_device(self._device):
                    comp = self._raw_fn.lower(self.forest, bins).compile()
            else:
                comp = self._raw_fn.lower(self.forest, bins).compile()
            self._aot_fns[size] = comp
            self._aot.save("predict", self._aot.key(
                "predict", size, self._aot_digest, self._aot_extra),
                comp, note={"bucket": size, "model": self.model_name})
        except Exception as exc:  # noqa: BLE001 — store is best-effort
            log.warning("AOT export failed for bucket %d (%s: %s)",
                        size, type(exc).__name__, exc)

    def _bucket(self, n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return min(b, self.max_batch)

    def _run_device(self, bins: np.ndarray, span_ctx=None):
        """Pad to the pow2 bucket, run the jitted scan, slice the pad
        off.  Returns ([n, K] f64 raw scores, bucket).  ``span_ctx`` is
        a list of (trace_id, parent_id) pairs to attribute the pad /
        device-execute spans to (one pair per member request — the batch
        phases are shared, the trace trees are per request)."""
        import jax.numpy as jnp
        n = bins.shape[0]
        t_pad0 = time.time()
        b = self._bucket(n)
        if b > n:
            bins = np.concatenate(
                [bins, np.zeros((b - n, bins.shape[1]), bins.dtype)])
        with self._lock:
            self._buckets.add(b)
        t_exec0 = time.time()
        faults.check("serve_device")
        aot_fn = self._aot_fns.get(b)
        if aot_fn is not None:
            # AOT-loaded executable: already compiled for this device,
            # no jit dispatch, no compile — the zero-cold-start path
            out = aot_fn(self.forest, jnp.asarray(bins))
        elif self._device is not None:
            import jax
            with jax.default_device(self._device):
                out = self._device_fn(self.forest, jnp.asarray(bins))
        else:
            out = self._device_fn(self.forest, jnp.asarray(bins))
        raw = np.asarray(out, dtype=np.float64)[:n]
        if self.average_factor:
            raw /= self.average_factor
        if span_ctx:
            t_end = time.time()
            for tid, pid in span_ctx:
                obs.emit_span("serve/pad", t_pad0, (t_exec0 - t_pad0) * 1e3,
                              tid, parent_id=pid,
                              attrs={"rows": n, "bucket": b})
                obs.emit_span("serve/device_execute", t_exec0,
                              (t_end - t_exec0) * 1e3, tid, parent_id=pid,
                              attrs={"bucket": b})
        return raw, b

    def _run_host(self, X: np.ndarray, span_ctx=None) -> np.ndarray:
        """Degraded path: per-tree value-space traversal on the host."""
        t0 = time.time()
        K = self.num_tpi
        out = np.zeros((X.shape[0], K))
        for i, tree in enumerate(self._trees):
            out[:, i % K] += tree.predict(X)
        if self.average_factor:
            out /= self.average_factor
        if span_ctx:
            dur = (time.time() - t0) * 1e3
            for tid, pid in span_ctx:
                obs.emit_span("serve/host_fallback", t0, dur, tid,
                              parent_id=pid,
                              attrs={"rows": int(X.shape[0])})
        return out

    def _note_degraded(self, exc: BaseException) -> None:
        if not self._degraded:
            self._degraded = True
            self._last_probe = time.monotonic()
            self.metrics.set_degraded(True)
            log.warning("serve: device predictor failed (%s: %s); "
                        "degrading to the host predictor"
                        + (" (re-probing every %.3gs)" % self.reprobe_s
                           if self.reprobe_s > 0 else ""),
                        type(exc).__name__, exc)
            obs.event("serve_degraded",
                      error=f"{type(exc).__name__}: {exc}")
            # the flip is exactly what the flight recorder exists for:
            # persist the last N spans/events leading up to it.  force=
            # True: each degradation is a distinct incident, so the
            # storm cooldown must never swallow ITS post-mortem
            self._flight_dump("serve_degraded", force=True)

    def _maybe_reprobe(self) -> bool:
        """While degraded, periodically try one tiny device execution;
        success flips the session (and /health, and the /metrics
        ``degraded`` gauge) back to the device path.  Returns True when
        the probe recovered the device."""
        if not self._degraded or self.reprobe_s <= 0:
            return False
        now = time.monotonic()
        with self._lock:
            if now - self._last_probe < self.reprobe_s:
                return False
            self._last_probe = now
        try:
            self._run_device(
                np.zeros((1, self.num_features), np.int32))
        except Exception as exc:  # noqa: BLE001 — stay degraded
            obs.event("serve_probe", ok=False,
                      error=f"{type(exc).__name__}: {exc}")
            return False
        self._degraded = False
        self.metrics.set_degraded(False)
        obs.event("serve_probe", ok=True)
        obs.event("serve_recovered")
        log.info("serve: device probe succeeded — leaving degraded mode, "
                 "device predictions resume")
        return True

    def _note_degraded_explain(self, exc: BaseException) -> None:
        if not self._explain_degraded:
            self._explain_degraded = True
            self._last_explain_probe = time.monotonic()
            log.warning("serve: device TreeSHAP kernel failed (%s: %s); "
                        "degrading /explain to the host oracle"
                        + (" (re-probing every %.3gs)" % self.reprobe_s
                           if self.reprobe_s > 0 else ""),
                        type(exc).__name__, exc)
            obs.event("serve_degraded", plane="explain",
                      error=f"{type(exc).__name__}: {exc}")
            self._flight_dump("serve_degraded", force=True)

    def _maybe_reprobe_explain(self) -> bool:
        """Explain-plane twin of ``_maybe_reprobe`` — the probe runs the
        TreeSHAP kernel itself (a 1-row predict proving nothing about
        the much larger explain working set)."""
        if not self._explain_degraded or self.reprobe_s <= 0:
            return False
        now = time.monotonic()
        with self._lock:
            if now - self._last_explain_probe < self.reprobe_s:
                return False
            self._last_explain_probe = now
        try:
            self._run_device_explain(
                np.zeros((1, self.num_features), np.int32))
        except Exception as exc:  # noqa: BLE001 — stay degraded
            obs.event("serve_probe", plane="explain", ok=False,
                      error=f"{type(exc).__name__}: {exc}")
            return False
        self._explain_degraded = False
        obs.event("serve_probe", plane="explain", ok=True)
        obs.event("serve_recovered", plane="explain")
        log.info("serve: TreeSHAP probe succeeded — leaving explain "
                 "degraded mode, device explanations resume")
        return True

    def _note_overload(self, rows: int, queue_rows: int,
                       priority: str = "normal") -> None:
        """Shared overload accounting for both submit paths: counter,
        per-priority shed count, event, and the storm check (>= _STORM_N
        rejects inside _STORM_WINDOW_S dumps the flight ring once per
        cooldown)."""
        storm = False
        now = time.monotonic()
        with self._lock:
            self._n_overload += 1
            self._overload_times.append(now)
            storm = (len(self._overload_times) == _STORM_N
                     and now - self._overload_times[0] <= _STORM_WINDOW_S)
        if self.replica_id is None:
            # shed counters mean CLIENT-VISIBLE rejections.  A fleet
            # replica's queue-full may still be served by a sibling
            # (failover spill), so inside a router the ROUTER counts the
            # shed — exactly once, on final rejection — while the
            # per-replica serve_overload event below keeps the
            # queue-level diagnostic
            self.metrics.count_shed(priority)
        obs.event("serve_overload", rows=int(rows), queue_rows=queue_rows,
                  priority=priority)
        if storm:
            self._flight_dump("overload_storm")

    def _flight_dump(self, reason: str, force: bool = False) -> None:
        """Rate-limited flight-ring dump (no-op when the ring is off).
        ``force`` bypasses the cooldown for one-shot events whose dump
        must not be suppressed by an earlier storm's."""
        now = time.monotonic()
        with self._lock:
            if (not force and self._last_flight_dump is not None
                    and now - self._last_flight_dump < _STORM_COOLDOWN_S):
                return
            self._last_flight_dump = now
        if obs.flight_enabled():
            obs.flight_dump(reason, extra={"stats": self.stats()})

    def _convert(self, raw: np.ndarray, raw_score: bool) -> np.ndarray:
        squeezed = raw if self.num_tpi > 1 else raw[:, 0]
        if raw_score or self.objective is None:
            return squeezed
        return np.asarray(self.objective.convert_output(squeezed))

    # ------------------------------------------------------------------
    def predict(self, X, raw_score: bool = False) -> np.ndarray:
        """Synchronous prediction, bypassing the queue (still bucketed,
        so it shares the bounded compile set with the async path)."""
        X = self._check_input(X)
        t0 = time.perf_counter()
        raw = np.zeros((X.shape[0], self.num_tpi))
        for lo in range(0, X.shape[0], self.max_batch):
            chunk = X[lo:lo + self.max_batch]
            raw[lo:lo + chunk.shape[0]] = self._predict_chunk(chunk)
        self._note_request(X.shape[0], (time.perf_counter() - t0) * 1e3)
        if self._drift is not None:
            try:
                self._drift.observe(X, raw)
                self._drift.maybe_check()
            except Exception as exc:  # noqa: BLE001 — monitor never fails serving
                log.warning("drift observe failed: %s", exc)
        return self._convert(raw, raw_score)

    def _predict_chunk(self, X: np.ndarray) -> np.ndarray:
        if self._degraded:
            self._maybe_reprobe()
        if not self._degraded:
            try:
                return self._run_device(self.space.bin_matrix(X))[0]
            except Exception as exc:  # noqa: BLE001 — degrade, don't fail
                self._note_degraded(exc)
        return self._run_host(X)

    # ------------------------------------------------------------------
    # explanation serving: batched device TreeSHAP (explain/)
    # ------------------------------------------------------------------
    def _ensure_explain(self):
        """Pack the TreeSHAP state on first use: per-leaf path metadata
        (zero fractions from the trees' cover counts), the jitted
        EXTEND/UNWIND kernel, and a second microbatcher with its OWN
        pow2 bucket family — explain rows cost O(leaves x depth^2), so
        they must not share predict's row buckets or its queue budget
        accounting would lie.  Raises on a model without cover counts
        (TreeSHAP cannot be computed) or when explaining is disabled."""
        if not self.explain_enabled:
            raise RuntimeError(
                "explanation serving is disabled (tpu_explain=false)")
        got = self._explain
        if got is not None:
            return got
        with self._explain_lock:
            if self._explain is None:
                from ..explain import forest_shap_fn, stack_explain
                K, F = self.num_tpi, self.num_features
                trees_np = [self.space.tree_arrays_np(t, with_counts=True)
                            for t in self._trees]
                arrays = stack_explain(trees_np, F)
                # the kernel reads only the decision arrays — the counts
                # were folded into the path metadata host-side, so the
                # stacked forest stays count-free (no HBM growth over
                # the predict forest; it IS the predict forest)
                forest = self.forest
                fn = forest_shap_fn(self.space.meta, K, F)
                self._raw_explain_fn = fn
                if obs.profile_enabled():
                    fn = obs.profile_wrap("lgbm/forest_shap", fn)
                if self._aot is not None:
                    from .aot import AOTStore
                    self._aot_x_digest = AOTStore._digest_tree(
                        (forest, arrays, self.space.meta))
                    for b in self._bucket_sweep(self.explain_max_batch):
                        st, afn = self._aot.load("explain", self._aot.key(
                            "explain", b, self._aot_x_digest,
                            self._aot_extra))
                        if st == "hit":
                            self._aot_x_fns[b] = afn
                batcher = MicroBatcher(
                    self._execute_explain_batch,
                    max_batch=self.explain_max_batch,
                    max_wait_s=self.explain_max_wait_ms / 1e3,
                    max_queue_rows=self.queue_depth,
                    name="lgbm-serve-explain",
                    shed_fracs=self._shed_fracs)
                self._explain = (forest, arrays, fn, batcher)
        return self._explain

    def warmup_explain(self) -> int:
        """Pre-compile every explain bucket shape (the analog of
        ``warmup`` for the TreeSHAP kernel's own bucket family), AOT-
        persisting missing buckets when the store is armed.  Returns the
        bucket count."""
        self._ensure_explain()
        n = 0
        for size in self._bucket_sweep(self.explain_max_batch):
            if self._aot is not None and size not in self._aot_x_fns:
                self._aot_export_explain(size)
            self._run_device_explain(
                np.zeros((size, self.num_features), np.int32))
            n += 1
        return n

    def _aot_export_explain(self, size: int) -> None:
        """Explain twin of ``_aot_export``: one TreeSHAP bucket lowered,
        compiled, registered, persisted."""
        import jax
        import jax.numpy as jnp
        forest, arrays, _, _ = self._ensure_explain()
        try:
            bins = jnp.asarray(
                np.zeros((size, self.num_features), np.int32))
            if self._device is not None:
                with jax.default_device(self._device):
                    comp = self._raw_explain_fn.lower(
                        forest, arrays, bins).compile()
            else:
                comp = self._raw_explain_fn.lower(
                    forest, arrays, bins).compile()
            self._aot_x_fns[size] = comp
            self._aot.save("explain", self._aot.key(
                "explain", size, self._aot_x_digest, self._aot_extra),
                comp, note={"bucket": size, "model": self.model_name})
        except Exception as exc:  # noqa: BLE001 — store is best-effort
            log.warning("AOT explain export failed for bucket %d (%s: %s)",
                        size, type(exc).__name__, exc)

    def _bucket_explain(self, n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return min(b, self.explain_max_batch)

    def _run_device_explain(self, bins: np.ndarray, span_ctx=None):
        """Pad to the explain pow2 bucket, run the jitted TreeSHAP scan,
        slice the pad off.  Returns ([n, K, F+1] f64 contributions,
        bucket)."""
        import jax.numpy as jnp
        forest, arrays, fn, _ = self._ensure_explain()
        n = bins.shape[0]
        t_pad0 = time.time()
        b = self._bucket_explain(n)
        if b > n:
            bins = np.concatenate(
                [bins, np.zeros((b - n, bins.shape[1]), bins.dtype)])
        with self._lock:
            self._explain_buckets.add(b)
        t_exec0 = time.time()
        # the explain plane's OWN injection point (ISSUE 10): a wedge in
        # the TreeSHAP kernel must be injectable without touching the
        # predict plane, or the degrade-isolation contract is untestable
        faults.check("serve_explain_device")
        aot_fn = self._aot_x_fns.get(b)
        if aot_fn is not None:
            out = aot_fn(forest, arrays, jnp.asarray(bins))
        elif self._device is not None:
            import jax
            with jax.default_device(self._device):
                out = fn(forest, arrays, jnp.asarray(bins))
        else:
            out = fn(forest, arrays, jnp.asarray(bins))
        contrib = np.asarray(out, dtype=np.float64)[:n]
        if span_ctx:
            t_end = time.time()
            for tid, pid in span_ctx:
                obs.emit_span("explain/pad", t_pad0,
                              (t_exec0 - t_pad0) * 1e3, tid, parent_id=pid,
                              attrs={"rows": n, "bucket": b})
                obs.emit_span("explain/device_execute", t_exec0,
                              (t_end - t_exec0) * 1e3, tid, parent_id=pid,
                              attrs={"bucket": b})
        return contrib, b

    def _run_host_explain(self, X: np.ndarray, span_ctx=None) -> np.ndarray:
        """Degraded path: the host TreeSHAP recursion (core/shap.py) —
        per-row Python, slow, but requests keep succeeding."""
        from ..core.shap import _expected_value, _tree_shap
        t0 = time.time()
        K, F = self.num_tpi, self.num_features
        out = np.zeros((X.shape[0], K, F + 1))
        for i, tree in enumerate(self._trees):
            k = i % K
            out[:, k, F] += _expected_value(tree)
            if tree.num_leaves > 1:
                for r in range(X.shape[0]):
                    _tree_shap(tree, X[r], out[r, k, :F], 0, 0, [],
                               1.0, 1.0, -1)
        if span_ctx:
            dur = (time.time() - t0) * 1e3
            for tid, pid in span_ctx:
                obs.emit_span("explain/host_fallback", t0, dur, tid,
                              parent_id=pid,
                              attrs={"rows": int(X.shape[0])})
        return out

    def _convert_explain(self, contrib: np.ndarray) -> np.ndarray:
        """[n, K, F+1] -> the ``predict_contrib`` surface: [n, F+1], or
        [n, K*(F+1)] for multiclass (last column per class = expected
        value).  Contributions live in raw-score space — no objective
        conversion, matching the host oracle."""
        n, K = contrib.shape[0], self.num_tpi
        return (contrib.reshape(n, K * (self.num_features + 1))
                if K > 1 else contrib[:, 0, :])

    def explain(self, X) -> np.ndarray:
        """Synchronous SHAP contributions, bypassing the queue (still
        bucketed, so it shares the bounded explain compile set with the
        async path)."""
        X = self._check_input(X)
        self._ensure_explain()
        t0 = time.perf_counter()
        K, F = self.num_tpi, self.num_features
        out = np.zeros((X.shape[0], K, F + 1))
        for lo in range(0, X.shape[0], self.explain_max_batch):
            chunk = X[lo:lo + self.explain_max_batch]
            out[lo:lo + chunk.shape[0]] = self._explain_chunk(chunk)
        self._note_explain_request(X.shape[0],
                                   (time.perf_counter() - t0) * 1e3)
        return self._convert_explain(out)

    def _explain_chunk(self, X: np.ndarray) -> np.ndarray:
        if self._degraded:
            self._maybe_reprobe()
        if self._explain_degraded:
            self._maybe_reprobe_explain()
        if not (self._degraded or self._explain_degraded):
            try:
                return self._run_device_explain(
                    self.space.bin_matrix(X))[0]
            except Exception as exc:  # noqa: BLE001 — degrade, don't fail
                self._note_degraded_explain(exc)
        return self._run_host_explain(X)

    def submit_explain(self, X, deadline_ms: Optional[float] = None,
                       trace_id: Optional[str] = None,
                       parent_id: Optional[str] = None,
                       priority: str = "normal") -> Ticket:
        """Queue rows for the next coalesced TreeSHAP batch — the
        explain analog of ``submit`` (same chunking, deadline,
        backpressure and priority-shedding semantics, its own queue +
        bucket family)."""
        X = self._check_input(X)
        if self._closed:
            raise RuntimeError("session is closed")
        # explain-plane injection point (ISSUE 10): a fault here models
        # an admission-side failure (bad pack state, OOM on metadata)
        # distinct from the device kernel's
        faults.check("serve_explain_submit")
        priority = normalize_priority(priority)
        _, _, _, batcher = self._ensure_explain()
        if trace_id is None and obs.span_record_enabled():
            trace_id = obs.new_trace_id()
        deadline = (time.monotonic() + float(deadline_ms) / 1e3
                    if deadline_ms is not None else None)
        parts = []
        try:
            for lo in range(0, max(X.shape[0], 1),
                            self.explain_max_batch):
                chunk = X[lo:lo + self.explain_max_batch]
                req = Request(self.space.bin_matrix(chunk), chunk,
                              deadline=deadline, trace_id=trace_id,
                              parent_id=parent_id, priority=priority)
                parts.append((batcher.submit(req), chunk.shape[0]))
        except ServeOverloadError:
            self._note_overload(X.shape[0], batcher.queue_rows,
                                priority=priority)
            for fut, _ in parts:  # a partially queued ticket must not leak
                fut.cancel()
            raise
        return Ticket(parts, int(X.shape[0]), False, kind="explain",
                      priority=priority)

    def _execute_explain_batch(self, reqs) -> None:
        """Explain batcher callback: expire, coalesce, pad, dispatch the
        TreeSHAP kernel, split — ``_execute_batch`` semantics with the
        explain bucket family, ``explain/*`` spans and the
        ``explain_batch`` event."""
        now = time.monotonic()
        live = []
        for r in reqs:
            if r.future.cancelled():
                continue
            if r.deadline is not None and now > r.deadline:
                waited = (now - r.t_submit) * 1e3
                _safe_resolve(r.future, error=DeadlineExceeded(
                    f"request expired after {waited:.1f}ms in queue"))
            else:
                live.append(r)
        if not live:
            return
        rows = sum(r.n for r in live)
        span_ctx = None
        if obs.span_record_enabled():
            t_dispatch = time.time()
            span_ctx = []
            for r in live:
                tid = r.trace_id or obs.new_trace_id()
                obs.emit_span("explain/queue_wait", r.t_submit_wall,
                              (now - r.t_submit) * 1e3, tid,
                              parent_id=r.parent_id, attrs={"rows": r.n})
                obs.emit_span("explain/coalesce", r.t_submit_wall,
                              max(t_dispatch - r.t_submit_wall, 0.0)
                              * 1e3, tid, parent_id=r.parent_id,
                              attrs={"requests": len(live), "rows": rows})
                span_ctx.append((tid, r.parent_id))
        t0 = time.perf_counter()
        if self._degraded:
            self._maybe_reprobe()
        if self._explain_degraded:
            self._maybe_reprobe_explain()
        degraded = self._degraded or self._explain_degraded
        contrib, bucket = None, rows
        if not degraded:
            try:
                bins = (live[0].bins if len(live) == 1
                        else np.concatenate([r.bins for r in live]))
                contrib, bucket = self._run_device_explain(
                    bins, span_ctx=span_ctx)
            except Exception as exc:  # noqa: BLE001 — degrade, don't fail
                self._note_degraded_explain(exc)
                degraded = True
        if degraded:
            contrib = (np.concatenate([self._run_host_explain(r.raw)
                                       for r in live])
                       if len(live) > 1
                       else self._run_host_explain(live[0].raw,
                                                   span_ctx=span_ctx))
            if span_ctx and len(live) > 1:
                # chunk-level spans would mis-attribute across requests;
                # one fallback span per request trace instead (the
                # predict twin's convention)
                t_end = time.time()
                for tid, pid in span_ctx:
                    obs.emit_span("explain/host_fallback", t_dispatch,
                                  (t_end - t_dispatch) * 1e3, tid,
                                  parent_id=pid, attrs={"rows": rows})
        exec_ms = (time.perf_counter() - t0) * 1e3
        off = 0
        for r in live:
            _safe_resolve(r.future, result=contrib[off:off + r.n])
            off += r.n
        with self._lock:
            self._explain_batches += 1
            self._explain_rows += rows
            self._explain_padded += bucket
        batcher = self._explain[3] if self._explain else None
        obs.event("explain_batch", rows=rows, padded=int(bucket),
                  requests=len(live),
                  queue_rows=batcher.queue_rows if batcher else 0,
                  exec_ms=round(exec_ms, 3), degraded=degraded)

    def _note_explain_request(self, rows: int, total_ms: float,
                              priority: str = "normal") -> None:
        with self._lock:
            self._n_explain += 1
            self._n_explain_ok += 1
            self._xlat_ms.append(total_ms)
            if len(self._xlat_ms) > _LAT_RESERVOIR:
                del self._xlat_ms[:_LAT_RESERVOIR // 2]
        self.metrics.observe_explain(total_ms, ok=True)
        self.metrics.count_served(priority)
        obs.event("explain_request", rows=int(rows),
                  total_ms=round(total_ms, 3), ok=True)

    # ------------------------------------------------------------------
    def submit(self, X, deadline_ms: Optional[float] = None,
               raw_score: bool = False, trace_id: Optional[str] = None,
               parent_id: Optional[str] = None,
               priority: str = "normal") -> Ticket:
        """Queue rows for the next coalesced batch.  Raises
        ``ServeOverloadError`` when the bounded queue is full (explicit
        backpressure) — or when this request's ``priority`` class has
        exhausted its share of the queue budget (load shedding: low
        sheds first).  Oversize submissions are chunked to the batch
        cap; a chunk is never split across device batches.  ``trace_id``
        /``parent_id`` thread the request's trace context through the
        batcher (the HTTP edge mints them from ``X-Request-Id``); a
        direct caller gets a fresh trace id when recording is on."""
        X = self._check_input(X)
        if self._closed:
            raise RuntimeError("session is closed")
        priority = normalize_priority(priority)
        if trace_id is None and obs.span_record_enabled():
            trace_id = obs.new_trace_id()
        deadline = (time.monotonic() + float(deadline_ms) / 1e3
                    if deadline_ms is not None else None)
        parts = []
        try:
            for lo in range(0, max(X.shape[0], 1), self.max_batch):
                chunk = X[lo:lo + self.max_batch]
                req = Request(self.space.bin_matrix(chunk), chunk,
                              deadline=deadline, trace_id=trace_id,
                              parent_id=parent_id, priority=priority)
                parts.append((self._batcher.submit(req), chunk.shape[0]))
        except ServeOverloadError:
            self._note_overload(X.shape[0], self._batcher.queue_rows,
                                priority=priority)
            for fut, _ in parts:  # a partially queued ticket must not leak
                fut.cancel()
            raise
        return Ticket(parts, int(X.shape[0]), raw_score,
                      priority=priority)

    def result(self, ticket: Ticket, timeout: Optional[float] = None
               ) -> np.ndarray:
        """Block for a ticket's predictions (converted like
        ``predict``).  Raises what the batch raised — including
        ``DeadlineExceeded`` for requests that outlived their deadline.
        Request-level accounting (stats + ``serve_request`` events)
        happens HERE, once per ticket, so every outcome the caller sees
        — success, deadline, worker failure, wait timeout — is counted
        the same way."""
        end = None if timeout is None else time.monotonic() + timeout
        chunks = []
        try:
            for fut, _ in ticket.parts:
                left = (None if end is None
                        else max(end - time.monotonic(), 0.0))
                chunks.append(fut.result(left))
        except BaseException as exc:
            self._note_failure(ticket, exc)
            raise
        raw = chunks[0] if len(chunks) == 1 else np.concatenate(chunks)
        total_ms = (time.perf_counter() - ticket.t0) * 1e3
        if ticket.kind == "explain":
            if not ticket.counted:
                ticket.counted = True
                self._note_explain_request(ticket.rows, total_ms,
                                           priority=ticket.priority)
            return self._convert_explain(raw)
        if not ticket.counted:
            ticket.counted = True
            self._note_request(ticket.rows, total_ms,
                               priority=ticket.priority)
        return self._convert(raw, ticket.raw_score)

    def _note_failure(self, ticket: Ticket, exc: BaseException) -> None:
        if ticket.counted:
            return
        ticket.counted = True
        reason = ("deadline" if isinstance(exc, DeadlineExceeded)
                  else type(exc).__name__)
        total_ms = (time.perf_counter() - ticket.t0) * 1e3
        if ticket.kind == "explain":
            with self._lock:
                self._n_explain += 1
                if reason == "deadline":
                    self._n_explain_deadline += 1
            self.metrics.observe_explain(total_ms, ok=False)
            obs.event("explain_request", rows=int(ticket.rows),
                      total_ms=round(total_ms, 3), ok=False, reason=reason)
            return
        with self._lock:
            self._n_req += 1
            if reason == "deadline":
                self._n_deadline += 1
        self.metrics.observe(total_ms, ok=False)
        obs.event("serve_request", rows=int(ticket.rows),
                  total_ms=round(total_ms, 3), ok=False, reason=reason)

    # ------------------------------------------------------------------
    def _execute_batch(self, reqs) -> None:
        """Batcher callback: expire, coalesce, pad, dispatch, split."""
        now = time.monotonic()
        live = []
        for r in reqs:
            if r.future.cancelled():
                # an overloaded submit cancelled its partial ticket; the
                # already-queued chunks must not be scored (resolution
                # races are still possible later — _safe_resolve absorbs
                # them)
                continue
            if r.deadline is not None and now > r.deadline:
                # stats/events for the miss are recorded by result() —
                # the one accounting point every outcome flows through
                waited = (now - r.t_submit) * 1e3
                _safe_resolve(r.future, error=DeadlineExceeded(
                    f"request expired after {waited:.1f}ms in queue"))
            else:
                live.append(r)
        if not live:
            return
        rows = sum(r.n for r in live)
        span_ctx = None
        if obs.span_record_enabled():
            # queue-wait + coalesce spans per member request: the batch
            # phases are shared wall time, but each request's trace tree
            # must carry the whole queue->coalesce->pad->execute chain
            t_dispatch = time.time()
            span_ctx = []
            for r in live:
                tid = r.trace_id or obs.new_trace_id()
                obs.emit_span("serve/queue_wait", r.t_submit_wall,
                              (now - r.t_submit) * 1e3, tid,
                              parent_id=r.parent_id,
                              attrs={"rows": r.n})
                # the coalesce span starts at THIS request's submit, not
                # the batch's oldest member — a child slice must not
                # begin before its root span nor charge other requests'
                # wait to this trace
                obs.emit_span("serve/coalesce", r.t_submit_wall,
                              max(t_dispatch - r.t_submit_wall, 0.0)
                              * 1e3, tid, parent_id=r.parent_id,
                              attrs={"requests": len(live), "rows": rows})
                span_ctx.append((tid, r.parent_id))
        t0 = time.perf_counter()
        if self._degraded:
            self._maybe_reprobe()
        degraded = self._degraded
        raw, bucket = None, rows
        if not degraded:
            try:
                bins = (live[0].bins if len(live) == 1
                        else np.concatenate([r.bins for r in live]))
                raw, bucket = self._run_device(bins, span_ctx=span_ctx)
            except Exception as exc:  # noqa: BLE001 — degrade, don't fail
                self._note_degraded(exc)
                degraded = True
        if degraded:
            raw = (np.concatenate([self._run_host(r.raw) for r in live])
                   if len(live) > 1
                   else self._run_host(live[0].raw, span_ctx=span_ctx))
            if span_ctx and len(live) > 1:
                # chunk-level spans would mis-attribute across requests;
                # one fallback span per request trace instead
                t_end = time.time()
                for tid, pid in span_ctx:
                    obs.emit_span("serve/host_fallback", t_dispatch,
                                  (t_end - t_dispatch) * 1e3, tid,
                                  parent_id=pid, attrs={"rows": rows})
        exec_ms = (time.perf_counter() - t0) * 1e3
        if self._drift is not None:
            # before the futures resolve: observe() is a buffered append
            # (histogramming runs on flush), so the latency cost is a few
            # microseconds — and a caller that saw result() return can
            # then force a check knowing this batch is already in the
            # sketch. maybe_check (the expensive part) stays after.
            try:
                self._drift.observe([r.raw for r in live], raw)
            except Exception as exc:  # noqa: BLE001 — monitor never fails serving
                log.warning("drift observe failed: %s", exc)
        off = 0
        for r in live:
            _safe_resolve(r.future, result=raw[off:off + r.n])
            off += r.n
        if self._drift is not None:
            try:
                self._drift.maybe_check()
            except Exception as exc:  # noqa: BLE001 — monitor never fails serving
                log.warning("drift observe failed: %s", exc)
        with self._lock:
            self._batches += 1
            self._real_rows += rows
            self._padded_rows += bucket
        obs.event("serve_batch", rows=rows, padded=int(bucket),
                  requests=len(live), queue_rows=self._batcher.queue_rows,
                  exec_ms=round(exec_ms, 3), degraded=degraded)

    def _check_input(self, X) -> np.ndarray:
        X = np.ascontiguousarray(np.asarray(X), dtype=np.float64)
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2 or X.shape[1] != self.num_features:
            raise ValueError(
                f"The number of features in data "
                f"({X.shape[1] if X.ndim == 2 else '?'}) is not the same "
                f"as it was in training data ({self.num_features})")
        return X

    def _note_request(self, rows: int, total_ms: float,
                      priority: str = "normal") -> None:
        with self._lock:
            self._n_req += 1
            self._n_ok += 1
            self._lat_ms.append(total_ms)
            if len(self._lat_ms) > _LAT_RESERVOIR:
                del self._lat_ms[:_LAT_RESERVOIR // 2]
        self.metrics.observe(total_ms, ok=True)
        self.metrics.count_served(priority)
        obs.event("serve_request", rows=int(rows),
                  total_ms=round(total_ms, 3), ok=True)

    # ------------------------------------------------------------------
    def resident_bytes(self) -> int:
        """Device bytes this session's packed model holds resident: the
        stacked forest plus (when armed) the TreeSHAP arrays — the
        per-version residency figure behind
        ``tpu_serve_resident_bytes`` (the first brick of
        memory-pressure-aware registry residency)."""
        import jax
        total = 0
        for leaf in jax.tree_util.tree_leaves(self.forest):
            if hasattr(leaf, "nbytes"):
                total += int(leaf.nbytes)
        if self._explain is not None:
            for leaf in jax.tree_util.tree_leaves(self._explain[:3]):
                if hasattr(leaf, "nbytes"):
                    total += int(leaf.nbytes)
        return total

    def stats(self) -> dict:
        """Serving counters + latency percentiles (for /health and the
        serve bench)."""
        from ..obs.report import percentile
        with self._lock:
            lat = sorted(self._lat_ms)
            xlat = sorted(self._xlat_ms)

            def pct(p):
                return percentile(lat, p)

            padded = self._padded_rows
            explain = {
                "explain_enabled": self.explain_enabled,
                "explain_armed": self._explain is not None,
                "explain_requests": self._n_explain,
                "explain_ok": self._n_explain_ok,
                "explain_batches": self._explain_batches,
                "explain_rows": self._explain_rows,
                "explain_padded_rows": self._explain_padded,
                "explain_occupancy": (
                    round(self._explain_rows / self._explain_padded, 4)
                    if self._explain_padded else None),
                "explain_p50_ms": percentile(xlat, 0.50),
                "explain_p99_ms": percentile(xlat, 0.99),
                "explain_buckets": sorted(self._explain_buckets),
                "explain_max_batch": self.explain_max_batch,
                "explain_deadline_missed": self._n_explain_deadline,
                "explain_degraded": self._explain_degraded,
            }
            return {
                **explain,
                "requests": self._n_req,
                "ok": self._n_ok,
                "deadline_missed": self._n_deadline,
                "overloads": self._n_overload,
                "batches": self._batches,
                "rows": self._real_rows,
                "padded_rows": padded,
                "occupancy": (round(self._real_rows / padded, 4)
                              if padded else None),
                "p50_ms": pct(0.50),
                "p99_ms": pct(0.99),
                "buckets": sorted(self._buckets),
                "queue_rows": (0 if self._closed
                               else self._batcher.queue_rows),
                "degraded": self._degraded,
                "trees": self.num_trees,
                "num_class": self.num_tpi,
                "num_features": self.num_features,
                "max_batch": self.max_batch,
                # load-balancer-grade health signals (ISSUE 6): how long
                # this replica has lived, how many XLA compiles it paid,
                # and how fast it is burning its p99 error budget
                "uptime_s": round(time.time() - self._t_start, 1),
                "compile_count": int(obs.compile_count()
                                     - self._compiles0),
                "slo_p99_ms": self.slo_p99_ms or None,
                "slo_burn": self.metrics.slo_burn(),
                # probe-and-recover (ISSUE 7): degradation is no longer
                # a one-way latch — these say how often it flipped
                "reprobe_s": self.reprobe_s or None,
                "degraded_transitions": self.metrics.degraded_transitions,
                "recoveries": self.metrics.recoveries,
                # fleet identity (None outside a router/registry): which
                # model version this session's numbers belong to
                "model": self.model_name,
                "version": self.model_version,
                "replica": self.replica_id,
                "resident_bytes": self.resident_bytes(),
                # drift plane (obs/drift.py): None when unarmed
                "drift": (self._drift.status()
                          if self._drift is not None else None),
                # AOT executable store (serve/aot.py): None when unarmed.
                # aot_buckets says which bucket shapes dispatch without
                # the jit path at all — a fully-AOT session shows
                # compile_count 0 after a cold boot
                "aot": (None if self._aot is None else {
                    **self._aot.stats(),
                    "buckets": sorted(self._aot_fns),
                    "explain_buckets": sorted(self._aot_x_fns),
                }),
            }

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._batcher.close()
            if self._explain is not None:
                self._explain[3].close()
            if obs.enabled():
                obs.event("serve_stop", **{k: v for k, v in
                                           self.stats().items()
                                           if not isinstance(v, list)})

    def __enter__(self) -> "PredictorSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
