"""Replica router: N ``PredictorSession`` replicas behind one surface.

One session is one batcher thread, one device binding, one degradation
state — a single point of failure.  The router fronts ``n_replicas``
sessions packed from the SAME model version (per-device on a multi-chip
host — replicas round-robin over ``jax.local_devices()`` — thread-pool
replicas on CPU) so a wedged replica degrades CAPACITY, not
availability:

- **health-based routing** — submits go to the routable replica with
  the shallowest batcher queue; draining and breaker-open replicas are
  skipped.
- **per-replica circuit breakers** — ``robust/watchdog.py
  CircuitBreaker``: the same transient/fatal taxonomy and bounded
  deterministic backoff the training watchdog uses.  A replica whose
  dispatch fails trips its breaker and drops out of the routing set;
  after the backoff one half-open probe request is let through, and a
  success closes the breaker again.
- **failover** — a submit that fails on one replica is retried on the
  next routable one before the caller ever sees an error; only when
  EVERY replica rejects does the router re-raise (an all-overloaded
  fleet raises ``ServeOverloadError`` so the backpressure contract is
  preserved).
- **draining** — ``drain(i)`` removes a replica from the routing set
  without killing its in-flight work (the ops hatch for rolling a
  replica out of a fleet).

The router duck-types the session surface the HTTP front end and the
benches consume (``submit``/``submit_explain``/``result``/``predict``/
``explain``/``stats``/``metrics``/``warmup``/``close``), so
``PredictServer`` serves a router exactly like a bare session.  All
replicas of one version share ONE ``ServeMetrics`` — the fleet latency
histogram and shed counters aggregate without a merge step.

Fault injection (robust/faults.py): every dispatch passes
``serve_replica`` and ``serve_replica_{i}`` points, so a chaos run can
wedge exactly one replica (``serve_replica_0:raise@n=-1``) and prove
requests keep succeeding on the survivors.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import List, Optional

import numpy as np

from .. import obs
from ..robust import faults
from ..robust.watchdog import CircuitBreaker
from ..utils import log
from .batcher import ServeOverloadError
from .metrics import ServeMetrics
from .session import PredictorSession, Ticket


class NoReplicaAvailable(ServeOverloadError):
    """Every replica is breaker-open or draining — the fleet has zero
    routable capacity.  A ``ServeOverloadError`` subclass so the HTTP
    edge maps it to 503 + ``Retry-After`` like any other backpressure."""


class RoutedTicket:
    """A session ticket plus the fleet identity that resolved it: which
    replica ran it and which model version the answer came from —
    ``result()`` must be redeemed against the SAME session that issued
    the inner ticket, and responses echo the version so every answer is
    attributable to exactly one model."""

    __slots__ = ("inner", "replica", "model", "version", "router")

    def __init__(self, inner: Ticket, replica: "Replica",
                 model: Optional[str], version: Optional[int],
                 router: Optional["ReplicaRouter"] = None):
        self.inner = inner
        self.replica = replica
        self.model = model
        self.version = version
        self.router = router

    @property
    def rows(self) -> int:
        return self.inner.rows

    @property
    def parts(self):
        return self.inner.parts

    @property
    def kind(self) -> str:
        return self.inner.kind


class Replica:
    """One session + its breaker + drain flag."""

    def __init__(self, idx: int, session: PredictorSession,
                 breaker: CircuitBreaker):
        self.idx = idx
        self.session = session
        self.breaker = breaker
        self.draining = False

    @property
    def routable(self) -> bool:
        return not self.draining and self.breaker.allow()

    def stats_row(self) -> dict:
        st = self.session.stats()
        return {
            "replica": f"r{self.idx}",
            "healthy": (not self.draining
                        and self.breaker.state == "closed"
                        and not st["degraded"]),
            "draining": self.draining,
            "degraded": st["degraded"],
            "explain_degraded": st["explain_degraded"],
            "breaker": self.breaker.snapshot(),
            "queue_rows": st["queue_rows"],
            "requests": st["requests"],
            "batches": st["batches"],
            "buckets": st["buckets"],
            "uptime_s": st["uptime_s"],
        }


class ReplicaRouter:
    """Health-routed fleet of replicas serving one model version."""

    def __init__(self, model, n_replicas: int = 2, config=None,
                 name: Optional[str] = None,
                 version: Optional[int] = None,
                 metrics: Optional[ServeMetrics] = None,
                 sessions: Optional[List[PredictorSession]] = None,
                 **session_kw):
        self.name = name
        self.version = version
        # the model source + build inputs are kept so restart_replica
        # can cold-boot a replacement replica (chaos: replica restart
        # under load); caller-provided session lists have no source to
        # rebuild from, so restart is unsupported there
        self._model_src = model if sessions is None else None
        self._config = config
        self._session_kw = dict(session_kw)
        if sessions is None:
            n = max(int(n_replicas), 1)
            devices = self._replica_devices(n)
            # one DriftMonitor per version, shared by every replica
            # (like ServeMetrics below): the sidecar loads once and the
            # merged sketch needs no cross-replica merge step
            from ..obs.drift import DriftMonitor
            shared_drift = DriftMonitor.maybe_load(model, config)
            sessions = [PredictorSession(model, config=config,
                                         metrics=metrics,
                                         device=devices[i],
                                         drift=shared_drift,
                                         **session_kw)
                        for i in range(n)]
        if not sessions:
            raise ValueError("router needs at least one replica")
        # all replicas share the first session's metrics unless the
        # caller provided one (the registry passes a fresh instance per
        # version so post-swap health deltas start from zero)
        self.metrics = metrics if metrics is not None \
            else sessions[0].metrics
        cfg = config if config is not None else sessions[0].config
        if isinstance(cfg, dict):
            cfg = None  # knobs below fall back to defaults
        trip = int(getattr(cfg, "tpu_serve_breaker_trip", 3) or 3)
        base = float(getattr(cfg, "tpu_serve_breaker_backoff_s", 0.5)
                     or 0.5)
        # drift: adopt replica 0's monitor (caller-built sessions may
        # each have armed "auto" — unify to one so the sketch merges)
        self.drift = getattr(sessions[0], "_drift", None)
        self.replicas = []
        for i, s in enumerate(sessions):
            s.model_name = self.name
            s.model_version = self.version
            s.replica_id = f"r{i}"
            s.metrics = self.metrics
            s._drift = self.drift
            self.replicas.append(Replica(
                i, s, CircuitBreaker(trip_after=trip, backoff_base_s=base,
                                     seed=i)))
        if self.drift is not None:
            self.drift.model_name = self.name or "default"
            self.drift.model_version = int(self.version or 0)
        self._rr = itertools.count()
        self._lock = threading.Lock()
        self.failovers = 0
        self._t_start = time.time()
        # compile accounting is router-level: the obs counter is
        # process-global, so per-session deltas (each measured from its
        # own construction) would double-count across replicas
        obs.install_recompile_hook()
        self._compiles0 = obs.compile_count()

    @staticmethod
    def _replica_devices(n: int):
        """Round-robin device assignment: on a multi-chip host each
        replica pins its forest + dispatch to its own device; with one
        device (CPU) every replica shares it (thread-pool replicas)."""
        try:
            import jax
            devs = jax.local_devices()
        except Exception:  # noqa: BLE001 — backend not up yet
            return [None] * n
        if len(devs) <= 1:
            return [None] * n
        return [devs[i % len(devs)] for i in range(n)]

    # ---- session-surface passthroughs --------------------------------
    @property
    def session(self) -> PredictorSession:
        """The first replica's session (canary/introspection surface)."""
        return self.replicas[0].session

    def __getattr__(self, item):
        # static model facts (num_features, num_tpi, num_trees,
        # explain_enabled, max_batch, ...) are identical across replicas
        if "replicas" not in self.__dict__:  # guard __init__ recursion
            raise AttributeError(item)
        return getattr(self.replicas[0].session, item)

    def warmup(self) -> int:
        return sum(r.session.warmup() for r in self.replicas)

    def warmup_explain(self) -> int:
        return sum(r.session.warmup_explain() for r in self.replicas)

    # ---- routing ------------------------------------------------------
    def _candidates(self) -> List[Replica]:
        """Routable replicas, shallowest queue first (round-robin tiebreak
        via the submit counter so equal-depth replicas share load).  A
        replica whose breaker just flipped to half-open sorts FIRST: its
        one probe request must actually reach it — otherwise a healthier
        sibling absorbs every request and the breaker never closes (the
        probe is safe: a failure fails over to the next candidate)."""
        rot = next(self._rr) % max(len(self.replicas), 1)
        order = self.replicas[rot:] + self.replicas[:rot]
        avail = [r for r in order if r.routable]
        avail.sort(key=lambda r: (0 if r.breaker.state == "half_open"
                                  else 1,
                                  r.session._batcher.queue_rows))
        return avail

    def _dispatch(self, kind: str, X, **kw) -> RoutedTicket:
        cands = self._candidates()
        if not cands:
            self.metrics.count_shed(str(kw.get("priority") or "normal"))
            raise NoReplicaAvailable(
                f"no routable replica ({len(self.replicas)} total, all "
                "breaker-open or draining)",
                priority=str(kw.get("priority") or "normal"))
        last_exc: Optional[BaseException] = None
        for rep in cands:
            try:
                faults.check("serve_replica")
                faults.check(f"serve_replica_{rep.idx}")
                fn = (rep.session.submit if kind == "predict"
                      else rep.session.submit_explain)
                ticket = fn(X, **kw)
                rep.breaker.record_ok()
                return RoutedTicket(ticket, rep, self.name, self.version,
                                    router=self)
            except ServeOverloadError as exc:
                # a full queue on one replica is load, not sickness: no
                # breaker strike, just spill to the next replica
                last_exc = exc
            except Exception as exc:  # noqa: BLE001 — failover point
                last_exc = exc
                cls = rep.breaker.record_failure(exc)
                with self._lock:
                    self.failovers += 1
                log.warning("serve router: replica r%d %s failure (%s: "
                            "%s) — breaker %s; failing over",
                            rep.idx, cls, type(exc).__name__, exc,
                            rep.breaker.state)
                obs.event("serve_failover", replica=rep.idx,
                          classify=cls, breaker=rep.breaker.state,
                          error=f"{type(exc).__name__}: {exc}")
        if isinstance(last_exc, ServeOverloadError):
            # the CLIENT-visible shed is counted here, once — replica
            # sessions skip their own count inside a router so a spill
            # that succeeded on a sibling never inflates the counters
            self.metrics.count_shed(
                getattr(last_exc, "priority", None)
                or str(kw.get("priority") or "normal"))
        raise last_exc if last_exc is not None else NoReplicaAvailable(
            "no replica accepted the request")

    def submit(self, X, **kw) -> RoutedTicket:
        return self._dispatch("predict", X, **kw)

    def submit_explain(self, X, **kw) -> RoutedTicket:
        return self._dispatch("explain", X, **kw)

    def result(self, ticket: RoutedTicket, timeout: Optional[float] = None
               ) -> np.ndarray:
        if not isinstance(ticket, RoutedTicket):
            # a bare ticket can only have come from replica 0's session
            # surface (sync predict path) — redeem it there
            return self.replicas[0].session.result(ticket, timeout)
        try:
            out = ticket.replica.session.result(ticket.inner, timeout)
        except Exception as exc:
            from .batcher import DeadlineExceeded
            from concurrent.futures import TimeoutError as _FT
            if not isinstance(exc, (DeadlineExceeded, _FT,
                                    ServeOverloadError)):
                # a worker-side failure is a replica-health signal; a
                # deadline/timeout is the caller's budget, not sickness
                ticket.replica.breaker.record_failure(exc)
            raise
        ticket.replica.breaker.record_ok()
        return out

    def predict(self, X, raw_score: bool = False) -> np.ndarray:
        ticket = self.submit(X, raw_score=raw_score)
        return self.result(ticket)

    def explain(self, X) -> np.ndarray:
        ticket = self.submit_explain(X)
        return self.result(ticket)

    # ---- fleet management --------------------------------------------
    def drain(self, idx: int) -> None:
        self.replicas[idx].draining = True
        obs.event("serve_drain", replica=idx, draining=True)

    def undrain(self, idx: int) -> None:
        self.replicas[idx].draining = False
        obs.event("serve_drain", replica=idx, draining=False)

    def restart_replica(self, idx: int) -> dict:
        """Kill one replica and cold-boot a replacement in place: a new
        ``PredictorSession`` packed from the router's model source, a
        fresh breaker, the shared metrics/drift.  With an AOT store
        armed the reboot loads its bucket executables instead of
        compiling — the "replica restart under load" chaos scenario
        asserts the rebooted replica's first request pays zero JIT
        compiles.  In-flight work on the old replica fails over like any
        dispatch failure (its batcher drains with errors on close)."""
        if self._model_src is None:
            raise RuntimeError(
                "router built from caller-provided sessions has no "
                "model source to restart a replica from")
        rep = self.replicas[idx]
        rep.draining = True          # drop out of the routing set now
        device = getattr(rep.session, "_device", None)
        t0 = time.perf_counter()
        c0 = obs.compile_count()
        sess = PredictorSession(self._model_src, config=self._config,
                                metrics=self.metrics, device=device,
                                drift=self.drift, **self._session_kw)
        sess.model_name = self.name
        sess.model_version = self.version
        sess.replica_id = f"r{idx}"
        cfg = self._config if not isinstance(self._config, dict) else None
        trip = int(getattr(cfg, "tpu_serve_breaker_trip", 3) or 3)
        base = float(getattr(cfg, "tpu_serve_breaker_backoff_s", 0.5)
                     or 0.5)
        fresh = Replica(idx, sess,
                        CircuitBreaker(trip_after=trip,
                                       backoff_base_s=base, seed=idx))
        old = rep.session
        self.replicas[idx] = fresh   # atomic: list item assignment
        try:
            old.close()
        except Exception as exc:  # noqa: BLE001 — replacement already live
            log.warning("restart_replica(%d): old session close failed "
                        "(%s: %s)", idx, type(exc).__name__, exc)
        boot = {"replica": idx,
                "boot_ms": round((time.perf_counter() - t0) * 1e3, 3),
                "boot_compiles": int(obs.compile_count() - c0),
                "aot": (sess.stats() or {}).get("aot") is not None}
        obs.event("serve_replica_restart", **boot)
        log.info("router: replica r%d restarted in %.1fms "
                 "(%d compile(s) at boot)", idx, boot["boot_ms"],
                 boot["boot_compiles"])
        return boot

    def routable_count(self) -> int:
        return sum(1 for r in self.replicas
                   if not r.draining and r.breaker.state != "open")

    def stats(self) -> dict:
        """Aggregate fleet stats in the single-session shape (so
        ``render_prometheus`` and ``/health`` consumers keep working)
        plus the per-replica rows."""
        rows = [r.stats_row() for r in self.replicas]
        per = [r.session.stats() for r in self.replicas]
        agg = {}
        for key in ("requests", "ok", "deadline_missed", "overloads",
                    "batches", "rows", "padded_rows", "explain_requests",
                    "explain_ok", "explain_batches", "explain_rows",
                    "explain_padded_rows", "queue_rows",
                    "explain_deadline_missed"):
            agg[key] = sum(int(s.get(key) or 0) for s in per)
        # one process-global counter: a router-level delta, NOT a sum of
        # per-session deltas (those each start at their own construction
        # and would count every sibling's compiles again).  Still shared
        # across fleets in one process — a per-model split would need
        # per-compile attribution the jax hook does not expose
        agg["compile_count"] = int(obs.compile_count() - self._compiles0)
        from ..obs.report import percentile
        all_lat, all_xlat = [], []
        for r in self.replicas:
            with r.session._lock:  # reservoirs mutate under this lock
                all_lat.extend(r.session._lat_ms)
                all_xlat.extend(r.session._xlat_ms)
        all_lat.sort()
        all_xlat.sort()
        agg["p50_ms"] = percentile(all_lat, 0.50)
        agg["p99_ms"] = percentile(all_lat, 0.99)
        agg["explain_p50_ms"] = percentile(all_xlat, 0.50)
        agg["explain_p99_ms"] = percentile(all_xlat, 0.99)
        agg["explain_occupancy"] = (
            round(agg["explain_rows"] / agg["explain_padded_rows"], 4)
            if agg["explain_padded_rows"] else None)
        agg["explain_buckets"] = sorted(
            {b for s in per for b in s["explain_buckets"]})
        agg["explain_max_batch"] = per[0]["explain_max_batch"]
        agg["occupancy"] = (round(agg["rows"] / agg["padded_rows"], 4)
                            if agg["padded_rows"] else None)
        agg["buckets"] = sorted({b for s in per for b in s["buckets"]})
        agg["degraded"] = all(s["degraded"] for s in per)
        agg["any_degraded"] = any(s["degraded"] for s in per)
        agg["explain_degraded"] = all(s["explain_degraded"] for s in per)
        agg["degraded_transitions"] = self.metrics.degraded_transitions
        agg["recoveries"] = self.metrics.recoveries
        agg["slo_p99_ms"] = per[0]["slo_p99_ms"]
        agg["slo_burn"] = self.metrics.slo_burn()
        agg["uptime_s"] = round(time.time() - self._t_start, 1)
        agg["trees"] = per[0]["trees"]
        agg["num_class"] = per[0]["num_class"]
        agg["num_features"] = per[0]["num_features"]
        agg["max_batch"] = per[0]["max_batch"]
        agg["explain_enabled"] = per[0]["explain_enabled"]
        agg["explain_armed"] = any(s["explain_armed"] for s in per)
        agg["model"] = self.name
        agg["version"] = self.version
        agg["n_replicas"] = len(self.replicas)
        agg["routable_replicas"] = self.routable_count()
        agg["failovers"] = self.failovers
        agg["resident_bytes"] = self.resident_bytes()
        # AOT executable store (serve/aot.py): per-replica stores share
        # one directory, so entries come from any row while the traffic
        # counters (loads/fallbacks) sum across replicas
        aots = [s.get("aot") for s in per if s.get("aot")]
        agg["aot"] = ({"dir": aots[0].get("dir"),
                       "entries": aots[0].get("entries"),
                       "loaded": sum(int(a.get("loaded") or 0)
                                     for a in aots),
                       "saved": sum(int(a.get("saved") or 0)
                                    for a in aots),
                       "fallbacks": sum(int(a.get("fallbacks") or 0)
                                        for a in aots),
                       "save_errors": sum(int(a.get("save_errors") or 0)
                                          for a in aots)}
                      if aots else None)
        agg["drift"] = (self.drift.status()
                        if self.drift is not None else None)
        agg["replicas"] = rows
        return agg

    def resident_bytes(self) -> int:
        """Device bytes this version's replicas hold resident (the
        ``tpu_serve_resident_bytes`` gauge; each replica packs its own
        forest, so the total is a sum even on a shared device)."""
        return sum(int(r.session.resident_bytes())
                   for r in self.replicas)

    def close(self) -> None:
        for r in self.replicas:
            r.session.close()

    def __enter__(self) -> "ReplicaRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
