"""Model registry: named versions, canary-gated hot-swap, rollback.

The reference reloads a Booster as a blocking offline swap
(src/c_api.cpp Booster reload path) — the serving process stops
answering while the new model loads.  A production fleet cannot: this
registry owns every resident model version and makes a model push a
*governed* transition instead of a file overwrite:

1. **load beside** — the candidate version packs its own
   ``ReplicaRouter`` (its own device forests, batchers, metrics) while
   the live version keeps serving; nothing about the live path changes.
2. **canary gate** — before any traffic shifts, the candidate must pass
   (a) *parity*: device predictions on a pinned probe set match the
   candidate's own host-oracle traversal (the bit-space contract that
   caught every packing bug so far), (b) *finite outputs*: no NaN/Inf
   leaves the kernel, (c) a *latency probe*: p99 over
   ``tpu_serve_canary_probes`` single-row predicts, gated against
   ``tpu_serve_canary_p99_ms`` when that knob is > 0 (recorded either
   way).  A gate failure closes the candidate and leaves the old
   version serving — the swap simply did not happen.
3. **atomic flip** — the live pointer swaps under the registry lock.
   In-flight tickets hold references to the version that issued them,
   so they complete against the OLD forests: zero dropped requests, and
   every response remains attributable to exactly one version.
4. **instant rollback** — the previous version stays resident (device
   arrays and all).  ``rollback()`` is another pointer flip, not a
   reload.  After a swap the registry watches the new version's
   ``ServeMetrics`` (each version gets a FRESH instance, so post-swap
   deltas start from zero) for ``tpu_serve_rollback_watch_s`` seconds:
   a failed-request rate over ``tpu_serve_rollback_error_rate``,
   ``tpu_serve_rollback_degraded`` degraded transitions, or an SLO burn
   over ``tpu_serve_rollback_slo_burn`` triggers an AUTOMATIC rollback
   (plus a flight-recorder dump — the post-mortem for "why did the push
   bounce").

Fault injection: ``serve_swap`` fires before the flip (swap-mid-flight
chaos), ``serve_canary`` inside the gate (canary-fail chaos).
``tools/chaos_serve.py`` drives every scenario on CPU.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import obs
from ..robust import faults
from ..utils import log
from .metrics import ServeMetrics
from .router import ReplicaRouter
from .session import _env_num

_CANARY_SEED = 17          # the pinned probe set is deterministic
_CANARY_ATOL = 1e-5        # device-vs-host parity tolerance (f32 forest)
_POSTSWAP_MIN_REQUESTS = 4  # error-rate needs a denominator


class UnknownModelError(KeyError):
    """The requested model name is not registered."""


class SwapRejected(RuntimeError):
    """The canary gate (or an injected swap fault) refused the flip;
    the previous version is still serving."""

    def __init__(self, msg: str, report: dict):
        super().__init__(msg)
        self.report = report


class _Version:
    """One resident model version: a router + lifecycle state."""

    __slots__ = ("version", "router", "source", "state", "created_t",
                 "canary", "baseline", "watch_until")

    def __init__(self, version: int, router: ReplicaRouter, source: str):
        self.version = version
        self.router = router
        self.source = source
        self.state = "canary"          # canary|live|previous|retired
        self.created_t = time.time()
        self.canary: Optional[dict] = None
        self.baseline: Optional[dict] = None   # metrics at flip time
        self.watch_until: Optional[float] = None

    def row(self) -> dict:
        try:
            resident = int(self.router.resident_bytes())
        except Exception:  # noqa: BLE001 — a torn-down router lists as None
            resident = None
        return {"version": self.version, "state": self.state,
                "source": self.source,
                "created_t": round(self.created_t, 1),
                "canary": self.canary,
                "resident_bytes": resident}


class _Entry:
    """All versions of one model name."""

    def __init__(self, name: str):
        self.name = name
        self.live: Optional[_Version] = None
        self.previous: Optional[_Version] = None
        self.history: List[dict] = []   # retired/rejected version rows
        self.next_version = 1
        self.swaps = 0
        self.swaps_rejected = 0
        self.rollbacks = 0
        self.swap_lock = threading.Lock()  # one swap at a time per model
        # latest rolling-quality breach (serve/quality.py
        # note_quality_breach); cleared whenever the live version changes
        self.quality_breach: Optional[dict] = None


class ModelRegistry:
    """Named model versions with canary-gated zero-downtime swaps."""

    def __init__(self, config=None, n_replicas: Optional[int] = None,
                 **session_kw):
        self.config = config
        self.n_replicas = int(
            n_replicas if n_replicas is not None else _env_num(
                "LGBM_TPU_SERVE_REPLICAS", int,
                getattr(config, "tpu_serve_replicas", 2)))
        self._session_kw = session_kw
        self._models: Dict[str, _Entry] = {}
        self._default: Optional[str] = None
        self._lock = threading.Lock()
        # canary + rollback policy knobs
        self.canary_rows = int(getattr(config, "tpu_serve_canary_rows",
                                       64) or 64)
        self.canary_probes = int(getattr(config,
                                         "tpu_serve_canary_probes", 16)
                                 or 16)
        self.canary_p99_ms = float(getattr(config,
                                           "tpu_serve_canary_p99_ms",
                                           0.0) or 0.0)
        self.rollback_watch_s = float(_env_num(
            "LGBM_TPU_SERVE_ROLLBACK_WATCH_S", float,
            getattr(config, "tpu_serve_rollback_watch_s", 30.0)))
        self.rollback_error_rate = float(getattr(
            config, "tpu_serve_rollback_error_rate", 0.5) or 0.5)
        self.rollback_degraded = int(getattr(
            config, "tpu_serve_rollback_degraded", 2) or 2)
        self.rollback_slo_burn = float(getattr(
            config, "tpu_serve_rollback_slo_burn", 0.0) or 0.0)
        self.swap_warmup = bool(getattr(config, "tpu_serve_swap_warmup",
                                        True))
        # drift/quality breaches gate rollback only on opt-in; default
        # they just annotate the post-swap watch report
        self.rollback_on_drift = bool(_env_num(
            "LGBM_TPU_SERVE_ROLLBACK_ON_DRIFT", int,
            getattr(config, "tpu_serve_rollback_on_drift", False)))
        # online-loop stats provider (online/loop.py run_online wires
        # loop.stats here) — rendered into the fleet /metrics
        self.online_provider = None
        # multi-tenant forest arena (serve/arena.py): registered model
        # names always win; names known only to the arena route there
        self.arena = None

    def attach_arena(self, arena) -> "ModelRegistry":
        """Attach a ``ForestArena`` so arena tenants serve through the
        fleet surface (HTTP routing, /models, /metrics).  Returns self
        for chaining."""
        self.arena = arena
        return self

    # ------------------------------------------------------------------
    def _build_version(self, entry: _Entry, model) -> _Version:
        vnum = entry.next_version
        entry.next_version += 1
        slo = float(getattr(self.config, "tpu_serve_slo_p99_ms", 250.0)
                    or 0.0) if self.config is not None else 250.0
        router = ReplicaRouter(
            model, n_replicas=self.n_replicas, config=self.config,
            name=entry.name, version=vnum,
            metrics=ServeMetrics(slo_p99_ms=slo), **self._session_kw)
        return _Version(vnum, router,
                        model if isinstance(model, str)
                        else type(model).__name__)

    def add_model(self, name: str, model, canary: bool = True) -> dict:
        """Register (and immediately serve) the first version of
        ``name``.  The canary gate runs by default even for an initial
        deploy — a model that cannot pass parity should never reach
        traffic."""
        with self._lock:
            if name in self._models:
                raise ValueError(
                    f"model {name!r} already registered — use swap()")
            entry = self._models[name] = _Entry(name)
            if self._default is None:
                self._default = name
        ver = self._build_version(entry, model)
        if canary:
            report = self.canary_gate(ver.router)
            ver.canary = report
            if not report["ok"]:
                ver.router.close()
                with self._lock:
                    del self._models[name]
                    if self._default == name:
                        self._default = next(iter(self._models), None)
                raise SwapRejected(
                    f"initial deploy of {name!r} failed the canary gate: "
                    f"{report['checks']}", report)
        if ver.router.drift is not None:
            # canary probes ran synthetic traffic through the real
            # predict path; the live window starts empty
            ver.router.drift.reset_window()
        with self._lock:
            ver.state = "live"
            entry.live = ver
        obs.event("serve_swap", model=name, ok=True, to_version=ver.version,
                  initial=True)
        log.info("registry: model %r v%d live (%d replica(s))", name,
                 ver.version, self.n_replicas)
        return {"ok": True, "model": name, "version": ver.version,
                "canary": ver.canary}

    # ------------------------------------------------------------------
    def canary_gate(self, router) -> dict:
        """Validate a candidate router before it may take traffic.
        Returns ``{"ok": bool, "checks": {...}, "p99_ms": float}``;
        never raises (an exception inside the gate IS a failed gate)."""
        sess = router.session
        checks: Dict[str, bool] = {}
        p99 = None
        t0 = time.perf_counter()
        mon = getattr(router, "drift", None)
        if mon is not None:
            # probe rows are synthetic — they must not feed the sketch
            mon.pause()
        try:
            faults.check("serve_canary")
            rng = np.random.default_rng(_CANARY_SEED)
            X = rng.normal(size=(self.canary_rows, sess.num_features))
            X[rng.random(X.shape) < 0.05] = np.nan
            # chunk to the batch cap like predict() does: an oversize
            # probe must not compile an off-bucket shape the bounded
            # pow2 compile budget never pays for again
            dev = np.concatenate(
                [sess._run_device(sess.space.bin_matrix(
                    X[lo:lo + sess.max_batch]))[0]
                 for lo in range(0, X.shape[0], sess.max_batch)])
            checks["finite"] = bool(np.isfinite(dev).all())
            host = sess._run_host(X)
            checks["parity"] = bool(np.allclose(dev, host,
                                                atol=_CANARY_ATOL,
                                                rtol=_CANARY_ATOL))
            # p99 probe: single-row predicts through the real sync path
            # (bucketed, so these compiles are the ones traffic reuses)
            lats = []
            for _ in range(max(self.canary_probes, 1)):
                t = time.perf_counter()
                sess.predict(X[:1])
                lats.append((time.perf_counter() - t) * 1e3)
            from ..obs.report import percentile
            p99 = percentile(sorted(lats), 0.99)
            checks["latency"] = (p99 <= self.canary_p99_ms
                                 if self.canary_p99_ms > 0 else True)
            checks["not_degraded"] = not sess._degraded
        except Exception as exc:  # noqa: BLE001 — a failed gate, not a crash
            checks["gate"] = False
            report = {"ok": False, "checks": dict(checks), "p99_ms": p99,
                      "error": f"{type(exc).__name__}: {exc}",
                      "ms": round((time.perf_counter() - t0) * 1e3, 1)}
            obs.event("serve_canary", model=router.name or "?",
                      version=int(router.version or 0), ok=False,
                      checks={k: bool(v) for k, v in checks.items()})
            return report
        finally:
            if mon is not None:
                mon.resume()
        ok = all(checks.values())
        report = {"ok": ok, "checks": checks, "p99_ms": p99,
                  "ms": round((time.perf_counter() - t0) * 1e3, 1)}
        obs.event("serve_canary", model=router.name or "?",
                  version=int(router.version or 0), ok=ok, checks=checks,
                  **({} if p99 is None else {"p99_ms": p99}))
        return report

    # ------------------------------------------------------------------
    def swap(self, name: str, model) -> dict:
        """Canary-gated hot swap: pack ``model`` beside the live
        version, gate it, atomically flip, keep the old version resident
        for rollback, and arm the post-swap health watch.  Returns the
        swap report; raises :class:`SwapRejected` when the gate (or an
        injected swap fault) refuses — the old version keeps serving."""
        entry = self._entry(name)
        with entry.swap_lock:
            t0 = time.perf_counter()
            span_id = (obs.new_span_id()
                       if obs.span_record_enabled() else None)
            t0_wall = time.time()
            ver = None
            try:
                faults.check("serve_swap")
                ver = self._build_version(entry, model)
                report = self.canary_gate(ver.router)
                ver.canary = report
                if not report["ok"]:
                    raise SwapRejected(
                        f"swap of {name!r} rejected by the canary gate: "
                        f"{report.get('error') or report['checks']}",
                        report)
                if self.swap_warmup:
                    # compile every bucket shape BEFORE the flip, while
                    # the old version still serves — post-flip traffic
                    # must never pay the candidate's XLA compiles (the
                    # zero-cold-start half of "zero-downtime")
                    report["warmed_buckets"] = ver.router.warmup()
            except SwapRejected as exc:
                self._reject(entry, ver, exc.report, t0)
                raise
            except Exception as exc:  # noqa: BLE001 — injected/packing fail
                report = {"ok": False, "checks": {},
                          "error": f"{type(exc).__name__}: {exc}"}
                self._reject(entry, ver, report, t0)
                raise SwapRejected(
                    f"swap of {name!r} failed before the flip: "
                    f"{type(exc).__name__}: {exc}", report) from exc
            if ver.router.drift is not None:
                # canary probes ran synthetic traffic through the real
                # predict path; the live window starts empty
                ver.router.drift.reset_window()
            # ---- atomic flip ----------------------------------------
            with self._lock:
                old = entry.live
                retired = entry.previous
                entry.previous = old
                if old is not None:
                    old.state = "previous"
                ver.state = "live"
                ver.baseline = ver.router.metrics.snapshot()
                if self.rollback_watch_s > 0:
                    ver.watch_until = (time.monotonic()
                                       + self.rollback_watch_s)
                entry.live = ver
                entry.swaps += 1
                # a quality breach describes the version that produced
                # it — the fresh flip starts with a clean slate
                entry.quality_breach = None
            if retired is not None:
                # the version two pushes back leaves the fleet; closing
                # it drains its (by now idle) batchers
                retired.state = "retired"
                entry.history.append(retired.row())
                retired.router.close()
            ms = round((time.perf_counter() - t0) * 1e3, 1)
            obs.event("serve_swap", model=name, ok=True,
                      from_version=(old.version if old else 0),
                      to_version=ver.version, ms=ms)
            if span_id is not None:
                obs.emit_span("serve/swap", t0_wall, ms,
                              obs.new_trace_id(), span_id=span_id,
                              attrs={"model": name,
                                     "to_version": ver.version})
            log.info("registry: %r v%s -> v%d live (canary p99 %.3gms, "
                     "%s)", name,
                     old.version if old else "-", ver.version,
                     ver.canary.get("p99_ms") or 0,
                     f"{self.rollback_watch_s:g}s health watch"
                     if self.rollback_watch_s else "no health watch")
            if ver.watch_until is not None:
                self._start_watch(name, ver)
            return {"ok": True, "model": name,
                    "from_version": old.version if old else None,
                    "to_version": ver.version, "canary": ver.canary,
                    "ms": ms}

    def _reject(self, entry: _Entry, ver: Optional[_Version],
                report: dict, t0: float) -> None:
        with self._lock:
            entry.swaps_rejected += 1
        if ver is not None:
            ver.state = "rejected"
            entry.history.append(ver.row())
            ver.router.close()
        obs.event("serve_swap", model=entry.name, ok=False,
                  to_version=ver.version if ver else 0,
                  ms=round((time.perf_counter() - t0) * 1e3, 1))
        log.warning("registry: swap of %r REJECTED (%s) — previous "
                    "version keeps serving", entry.name,
                    report.get("error") or report.get("checks"))

    # ------------------------------------------------------------------
    def rollback(self, name: str, reason: str = "manual") -> dict:
        """Instant flip back to the previous resident version.  The bad
        version is closed (it may be actively broken); the flight
        recorder dumps the moments leading up to the bounce."""
        entry = self._entry(name)
        with self._lock:
            if entry.previous is None:
                raise RuntimeError(
                    f"model {name!r} has no previous version resident")
            bad = entry.live
            entry.live = entry.previous
            entry.live.state = "live"
            entry.live.watch_until = None
            entry.previous = None
            entry.rollbacks += 1
            entry.quality_breach = None
        bad.state = "rolled_back"
        entry.history.append(bad.row())
        if entry.live.router.drift is not None:
            # the restored version's sketch holds pre-swap traffic; its
            # fresh serving run is scored from an empty window
            entry.live.router.drift.reset_window()
        obs.event("serve_rollback", model=name,
                  from_version=bad.version,
                  to_version=entry.live.version, reason=reason)
        log.warning("registry: ROLLED BACK %r v%d -> v%d (%s)", name,
                    bad.version, entry.live.version, reason)
        if obs.flight_enabled():
            # same post-mortem contract as a degradation storm: the ring
            # holds the requests/events that made the new version bounce
            obs.flight_dump("serve_rollback",
                            extra={"model": name,
                                   "from_version": bad.version,
                                   "to_version": entry.live.version,
                                   "reason": reason})
        bad.router.close()
        return {"ok": True, "model": name, "from_version": bad.version,
                "to_version": entry.live.version, "reason": reason}

    # ------------------------------------------------------------------
    def check_postswap(self, name: str) -> Optional[dict]:
        """One post-swap health evaluation of the live version against
        its flip-time metrics baseline.  Returns a rollback report when
        a regression threshold tripped (and the rollback ran), the
        string ``"watching"``/``"clear"`` wrapped in a dict otherwise.
        Deterministically callable — the chaos matrix drives it directly
        instead of racing the background watcher."""
        entry = self._entry(name)
        with self._lock:
            ver = entry.live
            if (ver is None or ver.baseline is None
                    or entry.previous is None):
                return None
            watching = (ver.watch_until is not None
                        and time.monotonic() < ver.watch_until)
        snap = ver.router.metrics.snapshot()
        base = ver.baseline
        ok_d = snap["ok"] - base["ok"]
        failed_d = snap["failed"] - base["failed"]
        total = ok_d + failed_d
        deg_d = (snap["degraded_transitions"]
                 - base["degraded_transitions"])
        burn = snap.get("slo_burn")
        reason = None
        if (total >= _POSTSWAP_MIN_REQUESTS
                and failed_d / total > self.rollback_error_rate):
            reason = (f"error_rate {failed_d}/{total} > "
                      f"{self.rollback_error_rate:g}")
        elif deg_d >= self.rollback_degraded:
            reason = (f"degraded_transitions {deg_d} >= "
                      f"{self.rollback_degraded}")
        elif (self.rollback_slo_burn > 0 and burn is not None
                and burn > self.rollback_slo_burn):
            reason = f"slo_burn {burn:g} > {self.rollback_slo_burn:g}"
        # drift / quality plane (obs/drift.py + serve/quality.py): the
        # latched breach always annotates the watch report; it becomes a
        # rollback signal like the burns above only on the
        # tpu_serve_rollback_on_drift opt-in
        drift_mon = getattr(ver.router, "drift", None)
        drift_breach = (drift_mon.breach if drift_mon is not None
                        else None)
        quality_breach = entry.quality_breach
        if reason is None and self.rollback_on_drift:
            if drift_breach is not None:
                worst = max(float(drift_breach.get("psi_max") or 0.0),
                            float(drift_breach.get("pred_psi") or 0.0))
                reason = (f"drift_psi {worst:g} > "
                          f"{drift_breach.get('threshold'):g}")
            elif quality_breach is not None:
                reason = (f"quality_drop auc_delta "
                          f"{quality_breach.get('auc_delta')}")
        if reason is not None:
            return self.rollback(name, reason=f"auto: {reason}")
        out = {"ok": True, "status": "watching" if watching else "clear",
               "requests": total, "failed": failed_d,
               "degraded_transitions": deg_d, "slo_burn": burn}
        if drift_breach is not None:
            out["drift_breach"] = drift_breach
        if quality_breach is not None:
            out["quality_breach"] = quality_breach
        return out

    def note_quality_breach(self, name: Optional[str],
                            info: dict) -> None:
        """Latch the online loop's rolling-quality breach
        (serve/quality.py) so the post-swap watch folds it into its
        verdict.  Unknown names are ignored — the quality tracker must
        never take its feed down."""
        try:
            entry = self._entry(name)
        except UnknownModelError:
            return
        entry.quality_breach = dict(info)
        log.warning("registry: quality breach latched on %r (%s)",
                    entry.name, info.get("auc_delta"))

    def _start_watch(self, name: str, ver: _Version) -> None:
        """Background post-swap watcher: polls ``check_postswap`` until
        the watch window closes, the version is replaced, or a rollback
        fires.  Daemon — a hung fleet never blocks process exit."""
        interval = max(min(self.rollback_watch_s / 10.0, 2.0), 0.05)

        def watch():
            while True:
                time.sleep(interval)
                entry = self._models.get(name)
                if entry is None or entry.live is not ver:
                    return  # replaced or rolled back already
                if (ver.watch_until is None
                        or time.monotonic() >= ver.watch_until):
                    return
                try:
                    out = self.check_postswap(name)
                except Exception as exc:  # noqa: BLE001 — watcher must die quietly
                    log.warning("registry: post-swap watch of %r failed "
                                "(%s: %s)", name, type(exc).__name__, exc)
                    return
                if out is not None and out.get("reason"):
                    return  # rolled back

        threading.Thread(target=watch, daemon=True,
                         name=f"lgbm-swap-watch-{name}").start()

    # ------------------------------------------------------------------
    def _entry(self, name: Optional[str]) -> _Entry:
        key = name or self._default
        if key is None or key not in self._models:
            raise UnknownModelError(name or "<default>")
        return self._models[key]

    def resolve(self, name: Optional[str]) -> _Version:
        """The live version serving ``name`` (None = default model)."""
        entry = self._entry(name)
        with self._lock:
            ver = entry.live
        if ver is None:
            raise UnknownModelError(name or "<default>")
        return ver

    @property
    def default(self) -> Optional[str]:
        return self._default

    def models(self) -> List[dict]:
        """One row per registered model (GET /models)."""
        out = []
        with self._lock:
            entries = list(self._models.values())
        for e in entries:
            drift = (getattr(e.live.router, "drift", None)
                     if e.live else None)
            out.append({
                "name": e.name,
                "default": e.name == self._default,
                "live_version": e.live.version if e.live else None,
                "previous_version": (e.previous.version
                                     if e.previous else None),
                "swaps": e.swaps,
                "swaps_rejected": e.swaps_rejected,
                "rollbacks": e.rollbacks,
                # resident = live + rollback-held device bytes (the
                # tpu_serve_resident_bytes gauge per version)
                "resident_bytes": sum(
                    int(v.router.resident_bytes())
                    for v in (e.live, e.previous) if v is not None),
                "drift": drift.status() if drift is not None else None,
                "quality_breach": e.quality_breach,
                "versions": ([e.live.row()] if e.live else [])
                + ([e.previous.row()] if e.previous else [])
                + e.history[-4:],
            })
        return out

    def submit(self, X, model: Optional[str] = None, **kw):
        # registered versions shadow arena tenants of the same name —
        # the governed (canary/rollback) plane wins a collision
        if (self.arena is not None and model is not None
                and model not in self._models
                and self.arena.has(model)):
            return self.arena.submit(X, model=model, **kw)
        ver = self.resolve(model)
        return ver.router.submit(X, **kw)

    def submit_explain(self, X, model: Optional[str] = None, **kw):
        ver = self.resolve(model)
        return ver.router.submit_explain(X, **kw)

    def result(self, ticket, timeout: Optional[float] = None):
        # a RoutedTicket carries its issuing router — redemption never
        # touches the (possibly since-swapped) live pointer, so a ticket
        # submitted before a flip completes against the version that
        # issued it (and keeps the router's breaker accounting).  Arena
        # tickets have no router; they redeem against the arena.
        router = getattr(ticket, "router", None)
        if router is None and self.arena is not None:
            return self.arena.result(ticket, timeout)
        return router.result(ticket, timeout)

    def stats(self) -> dict:
        with self._lock:
            # snapshot (name, live router) pairs under the lock — a
            # concurrent close()/failed deploy mutates _models, and a
            # /stats scrape racing it must not 500
            live = {name: (e.live.router if e.live else None)
                    for name, e in self._models.items()}
        return {m["name"]: dict(
            m, live=(live[m["name"]].stats()
                     if live.get(m["name"]) is not None else None))
            for m in self.models() if m["name"] in live}

    def close(self) -> None:
        with self._lock:
            entries = list(self._models.values())
            self._models.clear()
            self._default = None
            arena, self.arena = self.arena, None
        for e in entries:
            for v in (e.live, e.previous):
                if v is not None:
                    v.router.close()
        if arena is not None:
            arena.close()

    def __enter__(self) -> "ModelRegistry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
