"""TPU-resident serving subsystem.

The reference ships inference as a dedicated ``Predictor`` pipeline
decoupled from the trainer (reference: src/application/predictor.hpp,
src/boosting/prediction_early_stop.cpp); this package is the TPU-native
equivalent: a model (trained in-process or loaded from a file) is packed
once into device-resident bin-space arrays and served through a dynamic
microbatcher behind a threaded HTTP front end.

- ``packing``  — model-derived bin space + stacked forest (no train_ds)
- ``session``  — ``PredictorSession``: sync ``predict`` + async
  ``submit``/``result`` over the microbatcher, plus ``explain`` /
  ``submit_explain`` — batched device TreeSHAP (explain/) behind its
  own microbatch queue and pow2 bucket family (``POST /explain``)
- ``batcher``  — request coalescing, power-of-two padding, backpressure
  with priority-class load shedding (low sheds first)
- ``router``   — ``ReplicaRouter``: >=2 session replicas behind
  health-based routing, per-replica circuit breakers, and draining —
  one wedged replica degrades capacity, not availability
- ``registry`` — ``ModelRegistry``: named model versions with a
  canary-gated zero-downtime hot-swap (parity/finite/latency gate,
  atomic flip, resident previous version, automatic post-swap
  rollback on health regression)
- ``server``   — JSON-over-HTTP front end with deadlines + /health,
  /metrics (Prometheus), /stats, /models, /models/{name}/swap,
  /models/{name}/rollback, /debug/flight
- ``metrics``  — lock-cheap counters/histogram + SLO-burn behind
  /metrics, with the minimal text-format parser for reading it back
- ``aot``      — ``AOTStore``: persisted pre-compiled bucket
  executables (``jax.experimental.serialize_executable``) so a cold
  process serves request #1 with ZERO JIT compiles; corrupt/stale
  entries fall back to JIT loudly (``aot_fallback``), never crash
- ``arena``    — ``ForestArena``: many tenant forests packed into one
  device-resident stacked forest with a per-tree model-id lane,
  cross-model microbatching, and LRU residency under a byte budget
  (``tpu_serve_arena_bytes``) with transparent re-admission
"""
from .aot import AOTStore, resolve_aot_dir
from .arena import ForestArena
from .batcher import (PRIORITIES, DeadlineExceeded, MicroBatcher,
                      ServeOverloadError, normalize_priority)
from .metrics import ServeMetrics, parse_prometheus
from .packing import ServeBinSpace
from .registry import ModelRegistry, SwapRejected, UnknownModelError
from .router import NoReplicaAvailable, ReplicaRouter
from .server import PredictServer
from .session import PredictorSession

__all__ = ["AOTStore", "ForestArena", "PRIORITIES", "DeadlineExceeded",
           "MicroBatcher", "ModelRegistry", "NoReplicaAvailable",
           "PredictServer", "PredictorSession", "ReplicaRouter",
           "ServeBinSpace", "ServeMetrics", "ServeOverloadError",
           "SwapRejected", "UnknownModelError", "normalize_priority",
           "parse_prometheus", "resolve_aot_dir"]
