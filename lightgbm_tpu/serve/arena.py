"""Multi-tenant forest arena: many small models, one executable.

The registry scales versions of a FEW models; the "thousands of small
tenant models" direction (ROADMAP 3, PAPER.md layers 5-7) breaks it:
every ``PredictorSession`` owns its own bucket family (compiles x
models), its own device-resident ``ForestArrays`` (HBM x models), and
its own microbatcher (heavy-tail tenants never fill a wave).  The arena
packs every resident tenant's trees into ONE stacked forest with a
per-tree ``model_id`` lane (core/forest.py ``arena_predict_fn``), so:

- **one executable serves every tenant** — per-model routing is baked
  into the scan as a ``row_model[i] == model_id[t]`` mask, the same
  trick as the padded query blocks of the rank scorer;
- **cross-model microbatching** — requests for different tenants share
  one device launch (each ``Request`` carries its tenant; the execute
  callback builds the per-row model-id vector), so Zipf-tail traffic
  amortizes into full waves instead of thousands of 1-row launches;
- **LRU residency under a byte budget** — ``tpu_serve_arena_bytes``
  bounds the packed forest; admission past the budget evicts the
  least-recently-used tenant (its host trees are kept, so its next
  request re-admits it transparently), with evictions + occupancy
  surfaced through ``/metrics`` and ``/models``.

Parity contract: an arena-packed tenant predicts BIT-IDENTICALLY to its
own ``PredictorSession``.  The union bin space quantizes DECISIONS, not
data — it holds every resident model's thresholds, so each node compare
stays exact — and the arena scan freezes a row's Kahan (score, comp)
state across other tenants' trees, so the accumulation trajectory is
exactly the per-model sequence.  Tenants that type the SAME column
differently (categorical in one model, numerical in another) get
distinct physical columns in the union space — the numerical side's
splits are remapped to an appended column and its input columns are
scattered to match at binning time, so neither side's bins collapse.
One documented collapse remains: a shared column's missing type is the
worst across ALL resident tenants (the same rule ``ServeBinSpace``
applies across trees within one model), so tenants that disagree on a
feature's missing-value convention can route NaN/zero rows differently
than a solo session would.

Rebinning happens at EXECUTE time against an immutable state snapshot
(space, forest, fn, generation), so a repack mid-flight can never mix a
request binned in the old space with the new forest.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import obs
from ..robust import faults
from ..utils import log
from .batcher import (DeadlineExceeded, MicroBatcher, Request,
                      ServeOverloadError, normalize_priority)
from .packing import ServeBinSpace, collect_split_state
from .session import Ticket, _env_num, _safe_resolve

_LAT_RESERVOIR = 8192
_CANARY_ROWS = 64      # pinned parity probe per admitted/swapped tenant
_CANARY_ATOL = 1e-5    # registry canary_gate's own tolerance
_CANARY_SEED = 17


class ArenaTicket(Ticket):
    """A session ticket plus the tenant that owns the answer — result
    conversion (objective transform, K-column slice) is per-model."""

    __slots__ = ("model",)

    def __init__(self, parts, rows, raw_score, model,
                 priority="normal"):
        super().__init__(parts, rows, raw_score, priority=priority)
        self.model = model


class _Tenant:
    """Host-side truth for one arena model: the value-space trees (kept
    across evictions — re-admission repacks from here, no disk round
    trip), conversion state, and residency bookkeeping."""

    __slots__ = ("name", "trees", "num_tpi", "num_features", "objective",
                 "average_factor", "resident", "last_used", "version",
                 "mid")

    def __init__(self, name, trees, num_tpi, num_features, objective,
                 average_factor, version=1):
        self.name = name
        self.trees = trees
        self.num_tpi = int(num_tpi)
        self.num_features = int(num_features)
        self.objective = objective
        self.average_factor = float(average_factor)
        self.resident = False
        self.last_used = time.monotonic()
        self.version = int(version)
        self.mid = -1           # model-id lane value while resident

    def host_predict(self, X: np.ndarray) -> np.ndarray:
        """Value-space host traversal — the parity oracle and the
        degraded path (mirrors ``PredictorSession._run_host``)."""
        K = self.num_tpi
        out = np.zeros((X.shape[0], K))
        for i, tree in enumerate(self.trees):
            out[:, i % K] += tree.predict(X[:, :self.num_features])
        if self.average_factor:
            out /= self.average_factor
        return out


def _load_tenant(name: str, model, version: int = 1) -> _Tenant:
    """Normalize a model surface (file path / Booster / GBDT) into a
    ``_Tenant`` — the same unpacking ``PredictorSession`` does."""
    gbdt = model
    if isinstance(model, str):
        from ..io.model_io import load_model_file
        gbdt, _ = load_model_file(model)
    elif hasattr(model, "_gbdt"):   # a basic.Booster
        gbdt = model._gbdt
    trees = list(gbdt.models)
    if not trees:
        raise ValueError(f"cannot admit empty model {name!r}")
    K = int(gbdt.num_tpi)
    if gbdt.train_ds is not None:
        F = int(gbdt.train_ds.num_total_features)
    else:
        F = int(getattr(gbdt, "num_features", 0)
                or len(getattr(gbdt, "feature_names", []) or []))
    if F <= 0:
        raise ValueError(f"model {name!r} declares no feature space")
    avg = (float(len(trees) // K) if getattr(gbdt, "average_output", False)
           else 0.0)
    return _Tenant(name, trees, K, F, getattr(gbdt, "objective", None),
                   avg, version=version)


class _RemapTree:
    """Packing-only view of a host tree whose split features are moved
    to arena union columns.  Only ``split_feature`` differs; everything
    else (thresholds, bitsets, leaf values) delegates to the real tree,
    which stays untouched for the host parity oracle."""

    __slots__ = ("_t", "split_feature")

    def __init__(self, tree, colmap):
        self._t = tree
        nn = max(tree.num_leaves - 1, 0)
        self.split_feature = [int(colmap[int(f)])
                              for f in tree.split_feature[:nn]]

    def __getattr__(self, name):
        return getattr(self._t, name)


class _ArenaState:
    """One immutable pack generation: swap the whole object atomically
    on repack so in-flight executes stay self-consistent."""

    __slots__ = ("generation", "space", "forest", "fn", "K", "F",
                 "order", "bytes", "aot_fns", "colmaps")

    def __init__(self, generation, space, forest, fn, K, F, order,
                 nbytes, colmaps=None):
        self.generation = generation
        self.space = space
        self.forest = forest
        self.fn = fn
        self.K = K              # max trees-per-iteration across tenants
        self.F = F              # union feature width (+ conflict cols)
        self.order = order      # resident tenant names, pack order
        self.bytes = nbytes
        self.aot_fns: dict = {}
        # tenant name -> union column index per model feature (only for
        # tenants with a cat/numeric column conflict; identity otherwise)
        self.colmaps: dict = colmaps or {}


class ForestArena:
    """Pack-many, serve-as-one multi-tenant engine.

    Duck-types the slice of the session surface the HTTP edge and the
    benches consume (``submit``/``result``/``predict``/``stats``/
    ``warmup``/``close``/``has``), with every submit carrying a
    ``model=`` tenant name."""

    def __init__(self, config=None, budget_bytes: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None):
        self.config = config
        self.budget_bytes = int(
            budget_bytes if budget_bytes is not None else _env_num(
                "LGBM_TPU_SERVE_ARENA_BYTES", int,
                getattr(config, "tpu_serve_arena_bytes", 0)))
        self.max_batch = int(max_batch if max_batch is not None else _env_num(
            "LGBM_TPU_SERVE_MAX_BATCH", int,
            getattr(config, "tpu_serve_max_batch", 1024)))
        self.max_wait_ms = float(
            max_wait_ms if max_wait_ms is not None else _env_num(
                "LGBM_TPU_SERVE_MAX_WAIT_MS", float,
                getattr(config, "tpu_serve_max_wait_ms", 2.0)))
        self.queue_depth = int(
            queue_depth if queue_depth is not None else _env_num(
                "LGBM_TPU_SERVE_QUEUE_DEPTH", int,
                getattr(config, "tpu_serve_queue_depth", 8192)))
        self._tenants: Dict[str, _Tenant] = {}
        self._state: Optional[_ArenaState] = None
        self._lock = threading.RLock()
        self._closed = False
        self._t_start = time.time()
        # residency + traffic counters
        self._generation = 0
        self._evictions = 0
        self._readmissions = 0
        self._repacks = 0
        self._swaps = 0
        self._swap_rejects = 0
        self._batches = 0
        self._cross_model_batches = 0
        self._real_rows = 0
        self._padded_rows = 0
        self._n_req = 0
        self._n_ok = 0
        self._n_deadline = 0
        self._n_overload = 0
        self._buckets: set = set()
        self._lat_ms: List[float] = []
        obs.install_recompile_hook()
        self._compiles0 = obs.compile_count()
        # AOT executable store (serve/aot.py): arena packs change with
        # residency, so each generation loads/persists its own entries
        from .aot import AOTStore, resolve_aot_dir
        aot_dir = resolve_aot_dir(config)
        self._aot = AOTStore(aot_dir) if aot_dir else None
        self._batcher = MicroBatcher(
            self._execute_batch, max_batch=self.max_batch,
            max_wait_s=self.max_wait_ms / 1e3,
            max_queue_rows=self.queue_depth,
            name="lgbm-serve-arena")

    # ---- residency ----------------------------------------------------
    def has(self, name) -> bool:
        return name in self._tenants

    def tenant_names(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    def admit(self, name: str, model, version: Optional[int] = None
              ) -> dict:
        """Load + make resident (repacking the arena).  Admitting past
        the byte budget LRU-evicts cold tenants; admitting an existing
        name is a hot swap — see ``swap``."""
        with self._lock:
            if name in self._tenants:
                return self.swap(name, model)
            ten = _load_tenant(name, model,
                               version=version if version is not None
                               else 1)
            ten.resident = True
            ten.last_used = time.monotonic()
            self._tenants[name] = ten
            try:
                self._repack(protect=name)
            except Exception:
                # a pack that cannot be built must not strand a broken
                # tenant in the table
                del self._tenants[name]
                self._repack_existing()
                raise
            st = self._state
            obs.event("arena_admit", model=name,
                      tenants=len(self._tenants),
                      resident=len(st.order), bytes=int(st.bytes),
                      readmit=False)
            return {"ok": True, "model": name, "resident": True,
                    "generation": st.generation, "bytes": int(st.bytes)}

    def evict(self, name: str, reason: str = "manual") -> bool:
        """Drop a tenant from the device pack (host trees are kept, so
        its next request re-admits it)."""
        with self._lock:
            ten = self._tenants.get(name)
            if ten is None or not ten.resident:
                return False
            ten.resident = False
            self._evictions += 1
            obs.event("arena_evict", model=name, reason=reason)
            obs.count("serve/arena_evictions")
            self._repack_existing()
            return True

    def remove(self, name: str) -> bool:
        """Forget a tenant entirely (trees included)."""
        with self._lock:
            if name not in self._tenants:
                return False
            del self._tenants[name]
            self._repack_existing()
            return True

    def swap(self, name: str, model) -> dict:
        """Hot-swap one tenant behind a parity canary: the candidate is
        packed into a CANDIDATE generation and its arena predictions are
        checked against its own host oracle on a pinned probe set before
        the flip — a bad artifact never reaches traffic (the old trees
        keep serving)."""
        with self._lock:
            faults.check("serve_arena_swap")
            old = self._tenants.get(name)
            if old is None:
                return self.admit(name, model)
            cand = _load_tenant(name, model, version=old.version + 1)
            cand.resident = True
            cand.last_used = time.monotonic()
            self._tenants[name] = cand
            try:
                self._repack(protect=name)
                self._canary(cand)
            except Exception as exc:
                # roll back: restore the old tenant and its pack
                self._tenants[name] = old
                self._repack_existing()
                self._swap_rejects += 1
                obs.event("arena_swap", model=name, ok=False,
                          error=f"{type(exc).__name__}: {exc}")
                raise
            self._swaps += 1
            st = self._state
            obs.event("arena_swap", model=name, ok=True,
                      version=cand.version, generation=st.generation)
            return {"ok": True, "model": name,
                    "to_version": cand.version,
                    "generation": st.generation}

    def _canary(self, ten: _Tenant) -> None:
        """Pinned-probe parity gate for one tenant against its own host
        oracle (the registry canary's arbiter, same tolerance)."""
        rng = np.random.default_rng(_CANARY_SEED)
        X = rng.standard_normal((_CANARY_ROWS, ten.num_features))
        got = self._device_predict_sync(X, ten)
        want = ten.host_predict(X)
        if not np.all(np.isfinite(got)):
            raise RuntimeError("arena canary: non-finite predictions")
        err = float(np.max(np.abs(got - want))) if got.size else 0.0
        if err > _CANARY_ATOL:
            raise RuntimeError(
                f"arena canary: parity {err:.3g} > {_CANARY_ATOL}")

    def _lru_candidates(self, protect: Optional[str]) -> List[_Tenant]:
        """Resident tenants, coldest first, excluding ``protect``."""
        cands = [t for t in self._tenants.values()
                 if t.resident and t.name != protect]
        cands.sort(key=lambda t: t.last_used)
        return cands

    def _repack_existing(self) -> None:
        self._repack(protect=None)

    def _repack(self, protect: Optional[str]) -> None:
        """Rebuild the device pack from the resident set, LRU-evicting
        under the byte budget (``protect`` is the tenant being admitted
        — it never evicts itself).  Called with the lock held."""
        t0 = time.perf_counter()
        while True:
            resident = [t for t in self._tenants.values() if t.resident]
            if not resident:
                self._generation += 1
                self._state = None
                for t in self._tenants.values():
                    t.mid = -1
                return
            state = self._build_state(resident)
            if (self.budget_bytes <= 0 or state.bytes <= self.budget_bytes
                    or len(resident) <= 1):
                break
            victims = self._lru_candidates(protect)
            if not victims:
                break
            v = victims[0]
            v.resident = False
            v.mid = -1
            self._evictions += 1
            log.info("arena: evicting %r (LRU, %d bytes over budget %d)",
                     v.name, state.bytes, self.budget_bytes)
            obs.event("arena_evict", model=v.name, reason="budget",
                      bytes=int(state.bytes))
            obs.count("serve/arena_evictions")
        self._state = state
        for t in self._tenants.values():
            if not t.resident:
                t.mid = -1      # stale lanes must never match a row
        self._repacks += 1
        ms = (time.perf_counter() - t0) * 1e3
        obs.event("arena_repack", generation=state.generation,
                  tenants=len(state.order),
                  trees=int(np.asarray(state.forest.num_leaves).shape[0]),
                  bytes=int(state.bytes), ms=round(ms, 3))

    def _build_state(self, resident: List[_Tenant]) -> _ArenaState:
        """Pack a resident set: union bin space over every tenant's
        trees, one stacked forest with the model-id lane, one jitted (or
        AOT-loaded) arena scan."""
        import jax
        from ..core.forest import arena_predict_fn
        resident = sorted(resident, key=lambda t: t.name)
        F0 = max(t.num_features for t in resident)
        K = max(t.num_tpi for t in resident)
        # per-tenant column typing: a column categorical in one model
        # and numerical in another cannot share a union column (cat bins
        # are raw category values, numeric bins are threshold ranks) —
        # the numerical side gets an appended physical column instead
        num_used, cat_used = {}, {}
        u_num = np.zeros(F0, bool)
        u_cat = np.zeros(F0, bool)
        for ten in resident:
            thr, _, ic, _, _ = collect_split_state(ten.trees,
                                                   ten.num_features)
            nu = np.array([bool(v) for v in thr], bool)
            num_used[ten.name], cat_used[ten.name] = nu, ic
            u_num[:nu.size] |= nu
            u_cat[:ic.size] |= ic
        conflict = {int(f): F0 + j
                    for j, f in enumerate(np.nonzero(u_num & u_cat)[0])}
        F = F0 + len(conflict)
        colmaps: Dict[str, np.ndarray] = {}
        all_trees, class_ids, model_ids = [], [], []
        for mid, ten in enumerate(resident):
            ten.mid = mid
            trees = ten.trees
            cm = np.arange(ten.num_features, dtype=np.int32)
            moved = False
            for f, dest in conflict.items():
                if (f < ten.num_features and num_used[ten.name][f]
                        and not cat_used[ten.name][f]):
                    cm[f] = dest
                    moved = True
            if moved:
                colmaps[ten.name] = cm
                trees = [_RemapTree(t, cm) for t in trees]
            for i, tree in enumerate(trees):
                all_trees.append(tree)
                class_ids.append(i % ten.num_tpi)
                model_ids.append(mid)
        space = ServeBinSpace(all_trees, F)
        forest = space.pack(all_trees,
                            np.asarray(class_ids, np.int32),
                            model_ids=np.asarray(model_ids, np.int32))
        fn = arena_predict_fn(space.meta, K)
        nbytes = sum(int(leaf.nbytes)
                     for leaf in jax.tree_util.tree_leaves(forest)
                     if hasattr(leaf, "nbytes"))
        self._generation += 1
        state = _ArenaState(self._generation, space, forest, fn, K, F,
                            [t.name for t in resident], nbytes,
                            colmaps=colmaps)
        if self._aot is not None:
            digest = type(self._aot)._digest_tree((forest, space.meta))
            extra = f"K={K}|F={F}|arena"
            for b in self._bucket_sweep():
                status, afn = self._aot.load("arena", self._aot.key(
                    "arena", b, digest, extra))
                if status == "hit":
                    state.aot_fns[b] = afn
            # keep key inputs for warmup-time export
            self._aot_key_parts = (digest, extra)
        return state

    # ---- serving ------------------------------------------------------
    def _bucket_sweep(self):
        from .session import PredictorSession
        return PredictorSession._bucket_sweep(self.max_batch)

    def _bucket(self, n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return min(b, self.max_batch)

    def warmup(self) -> int:
        """Pre-compile (or AOT-load) every bucket of the CURRENT
        generation; with the store armed, missing buckets are exported
        so the next process boots compile-free."""
        with self._lock:
            state = self._state
        if state is None:
            return 0
        n = 0
        for size in self._bucket_sweep():
            self._dispatch(state, np.zeros((size, state.F), np.int32),
                           np.full(size, -1, np.int32), export=True)
            n += 1
        return n

    def _dispatch(self, state: _ArenaState, bins: np.ndarray,
                  row_model: np.ndarray, export: bool = False):
        """Pad to the pow2 bucket and run one arena launch.  Pad rows
        carry model id -1, which matches no tree and scores zero."""
        import jax.numpy as jnp
        n = bins.shape[0]
        b = self._bucket(n)
        if b > n:
            bins = np.concatenate(
                [bins, np.zeros((b - n, bins.shape[1]), bins.dtype)])
            row_model = np.concatenate(
                [row_model, np.full(b - n, -1, np.int32)])
        with self._lock:
            self._buckets.add(b)
        faults.check("serve_arena_device")
        fn = state.aot_fns.get(b)
        if fn is None and export and self._aot is not None:
            fn = self._aot_export(state, b)
        if fn is not None:
            out = fn(state.forest, jnp.asarray(bins),
                     jnp.asarray(row_model))
        else:
            out = state.fn(state.forest, jnp.asarray(bins),
                           jnp.asarray(row_model))
        return np.asarray(out, dtype=np.float64)[:n], b

    def _aot_export(self, state: _ArenaState, size: int):
        """Lower + compile one arena bucket, register it for dispatch,
        persist it (best-effort, like the session's ``_aot_export``)."""
        import jax.numpy as jnp
        try:
            digest, extra = self._aot_key_parts
            bins = jnp.asarray(np.zeros((size, state.F), np.int32))
            rm = jnp.asarray(np.zeros(size, np.int32))
            comp = state.fn.lower(state.forest, bins, rm).compile()
            state.aot_fns[size] = comp
            self._aot.save("arena", self._aot.key("arena", size, digest,
                                                  extra), comp,
                           note={"bucket": size,
                                 "generation": state.generation})
            return comp
        except Exception as exc:  # noqa: BLE001 — store is best-effort
            log.warning("arena AOT export failed for bucket %d (%s: %s)",
                        size, type(exc).__name__, exc)
            return None

    def _resolve(self, model: Optional[str]) -> _Tenant:
        """Tenant lookup + transparent re-admission: a known-but-evicted
        tenant repacks back in on its next request (LRU may push out a
        colder sibling)."""
        with self._lock:
            if model is None:
                if len(self._tenants) == 1:
                    model = next(iter(self._tenants))
                else:
                    raise KeyError(
                        "arena holds multiple tenants — requests must "
                        "name one (model=...)")
            ten = self._tenants.get(model)
            if ten is None:
                raise KeyError(f"unknown arena tenant {model!r}")
            ten.last_used = time.monotonic()
            if not ten.resident:
                ten.resident = True
                self._readmissions += 1
                self._repack(protect=ten.name)
                st = self._state
                obs.event("arena_admit", model=ten.name,
                          tenants=len(self._tenants),
                          resident=len(st.order) if st else 0,
                          bytes=int(st.bytes) if st else 0,
                          readmit=True)
            return ten

    def submit(self, X, model: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               raw_score: bool = False,
               trace_id: Optional[str] = None,
               parent_id: Optional[str] = None,
               priority: str = "normal") -> ArenaTicket:
        """Queue rows for the next coalesced (possibly cross-model)
        batch.  The raw float rows ride the request; binning happens at
        execute time against the live pack generation, so a repack
        between submit and execute stays consistent."""
        if self._closed:
            raise RuntimeError("arena is closed")
        ten = self._resolve(model)
        X = np.ascontiguousarray(np.asarray(X), dtype=np.float64)
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2 or X.shape[1] != ten.num_features:
            raise ValueError(
                f"The number of features in data "
                f"({X.shape[1] if X.ndim == 2 else '?'}) is not the same "
                f"as it was in training data ({ten.num_features})")
        priority = normalize_priority(priority)
        deadline = (time.monotonic() + float(deadline_ms) / 1e3
                    if deadline_ms is not None else None)
        parts = []
        try:
            for lo in range(0, max(X.shape[0], 1), self.max_batch):
                chunk = X[lo:lo + self.max_batch]
                req = Request(chunk, chunk, deadline=deadline,
                              trace_id=trace_id, parent_id=parent_id,
                              priority=priority, model=ten.name)
                parts.append((self._batcher.submit(req), chunk.shape[0]))
        except ServeOverloadError:
            with self._lock:
                self._n_overload += 1
            for fut, _ in parts:
                fut.cancel()
            raise
        return ArenaTicket(parts, int(X.shape[0]), raw_score, ten.name,
                           priority=priority)

    def result(self, ticket: ArenaTicket,
               timeout: Optional[float] = None) -> np.ndarray:
        end = None if timeout is None else time.monotonic() + timeout
        chunks = []
        try:
            for fut, _ in ticket.parts:
                left = (None if end is None
                        else max(end - time.monotonic(), 0.0))
                chunks.append(fut.result(left))
        except BaseException as exc:
            if not ticket.counted:
                ticket.counted = True
                with self._lock:
                    self._n_req += 1
                    if isinstance(exc, DeadlineExceeded):
                        self._n_deadline += 1
            raise
        raw = chunks[0] if len(chunks) == 1 else np.concatenate(chunks)
        if not ticket.counted:
            ticket.counted = True
            total_ms = (time.perf_counter() - ticket.t0) * 1e3
            with self._lock:
                self._n_req += 1
                self._n_ok += 1
                self._lat_ms.append(total_ms)
                if len(self._lat_ms) > _LAT_RESERVOIR:
                    del self._lat_ms[:_LAT_RESERVOIR // 2]
            obs.event("serve_request", rows=int(ticket.rows),
                      total_ms=round(total_ms, 3), ok=True)
        ten = self._tenants[ticket.model]
        out = raw[:, :ten.num_tpi]
        squeezed = out if ten.num_tpi > 1 else out[:, 0]
        if ticket.raw_score or ten.objective is None:
            return squeezed
        return np.asarray(ten.objective.convert_output(squeezed))

    def predict(self, X, model: Optional[str] = None,
                raw_score: bool = False) -> np.ndarray:
        """Synchronous path (bypasses the queue, shares the buckets)."""
        ten = self._resolve(model)
        X = np.ascontiguousarray(np.asarray(X), dtype=np.float64)
        if X.ndim == 1:
            X = X[None, :]
        raw = self._device_predict_sync(X, ten)
        squeezed = raw if ten.num_tpi > 1 else raw[:, 0]
        if raw_score or ten.objective is None:
            return squeezed
        return np.asarray(ten.objective.convert_output(squeezed))

    def _device_predict_sync(self, X: np.ndarray, ten: _Tenant
                             ) -> np.ndarray:
        with self._lock:
            state = self._state
        if state is None or not ten.resident:
            return ten.host_predict(X)
        out = np.zeros((X.shape[0], ten.num_tpi))
        for lo in range(0, X.shape[0], self.max_batch):
            chunk = X[lo:lo + self.max_batch]
            bins = state.space.bin_matrix(
                self._project(chunk, state, ten.name))
            rm = np.full(chunk.shape[0], ten.mid, np.int32)
            raw, _ = self._dispatch(state, bins, rm)
            out[lo:lo + chunk.shape[0]] = raw[:, :ten.num_tpi]
        if ten.average_factor:
            out /= ten.average_factor
        return out

    @staticmethod
    def _project(X: np.ndarray, state: "_ArenaState", name: str
                 ) -> np.ndarray:
        """Place a tenant's raw columns at its union positions.  For
        most tenants that is plain zero-padding to the union width: the
        extra columns belong to other tenants' spaces — this tenant's
        trees never split on them, and cross-tenant tree hits are masked
        anyway.  Tenants holding the numerical side of a cat/numeric
        column conflict scatter through their colmap so each value lands
        in the column their remapped trees split on."""
        cm = state.colmaps.get(name)
        if cm is None:
            if X.shape[1] >= state.F:
                return X
            return np.concatenate(
                [X, np.zeros((X.shape[0], state.F - X.shape[1]),
                             X.dtype)], axis=1)
        out = np.zeros((X.shape[0], state.F), np.float64)
        out[:, cm] = X[:, :cm.size]
        return out

    # ---- batcher callback ---------------------------------------------
    def _execute_batch(self, reqs) -> None:
        """Coalesce a (possibly multi-tenant) wave into one launch."""
        now = time.monotonic()
        live = []
        for r in reqs:
            if r.future.cancelled():
                continue
            if r.deadline is not None and now > r.deadline:
                waited = (now - r.t_submit) * 1e3
                _safe_resolve(r.future, error=DeadlineExceeded(
                    f"request expired after {waited:.1f}ms in queue"))
            else:
                live.append(r)
        if not live:
            return
        with self._lock:
            state = self._state
            mids = {r.model: self._tenants[r.model].mid for r in live}
        rows = sum(r.n for r in live)
        models = {r.model for r in live}
        t0 = time.perf_counter()
        raw, bucket = None, rows
        if state is not None and all(m >= 0 for m in mids.values()):
            try:
                bins = np.concatenate(
                    [state.space.bin_matrix(
                        self._project(r.raw, state, r.model))
                     for r in live])
                row_model = np.concatenate(
                    [np.full(r.n, mids[r.model], np.int32) for r in live])
                raw, bucket = self._dispatch(state, bins, row_model)
            except Exception as exc:  # noqa: BLE001 — degrade, don't fail
                log.warning("arena device launch failed (%s: %s); host "
                            "fallback for this batch",
                            type(exc).__name__, exc)
                obs.event("serve_degraded", plane="arena",
                          error=f"{type(exc).__name__}: {exc}")
                raw = None
        off = 0
        for r in live:
            if raw is None:
                ten = self._tenants[r.model]
                host = ten.host_predict(r.raw)
                full = np.zeros((r.n, state.K if state else ten.num_tpi))
                full[:, :ten.num_tpi] = host
                _safe_resolve(r.future, result=full)
            else:
                ten = self._tenants[r.model]
                part = np.array(raw[off:off + r.n])
                if ten.average_factor:
                    part /= ten.average_factor
                _safe_resolve(r.future, result=part)
            off += r.n
        exec_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            self._batches += 1
            if len(models) > 1:
                self._cross_model_batches += 1
            self._real_rows += rows
            self._padded_rows += bucket
        obs.event("serve_batch", rows=rows, padded=int(bucket),
                  requests=len(live), queue_rows=self._batcher.queue_rows,
                  exec_ms=round(exec_ms, 3),
                  degraded=raw is None, models=len(models))

    # ---- introspection ------------------------------------------------
    def tenants(self) -> List[dict]:
        """Per-tenant residency rows for /models."""
        with self._lock:
            now = time.monotonic()
            return [{"name": t.name, "resident": t.resident,
                     "version": t.version, "num_class": t.num_tpi,
                     "num_features": t.num_features,
                     "trees": len(t.trees),
                     "idle_s": round(now - t.last_used, 1)}
                    for t in sorted(self._tenants.values(),
                                    key=lambda t: t.name)]

    def stats(self) -> dict:
        from ..obs.report import percentile
        with self._lock:
            state = self._state
            lat = sorted(self._lat_ms)
            resident = sum(1 for t in self._tenants.values() if t.resident)
            return {
                "tenants": len(self._tenants),
                "resident": resident,
                "generation": self._generation,
                "packed_bytes": int(state.bytes) if state else 0,
                "budget_bytes": self.budget_bytes,
                "evictions": self._evictions,
                "readmissions": self._readmissions,
                "repacks": self._repacks,
                "swaps": self._swaps,
                "swap_rejects": self._swap_rejects,
                "requests": self._n_req,
                "ok": self._n_ok,
                "deadline_missed": self._n_deadline,
                "overloads": self._n_overload,
                "batches": self._batches,
                "cross_model_batches": self._cross_model_batches,
                "rows": self._real_rows,
                "padded_rows": self._padded_rows,
                "occupancy": (round(self._real_rows / self._padded_rows, 4)
                              if self._padded_rows else None),
                "p50_ms": percentile(lat, 0.50),
                "p99_ms": percentile(lat, 0.99),
                "buckets": sorted(self._buckets),
                "max_batch": self.max_batch,
                "queue_rows": (0 if self._closed
                               else self._batcher.queue_rows),
                "uptime_s": round(time.time() - self._t_start, 1),
                "compile_count": int(obs.compile_count()
                                     - self._compiles0),
                "aot": self._aot.stats() if self._aot else None,
            }

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._batcher.close()
            if obs.enabled():
                obs.event("arena_stop", tenants=len(self._tenants),
                          evictions=self._evictions,
                          repacks=self._repacks)

    def __enter__(self) -> "ForestArena":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
