"""Live serving metrics: counters + a fixed-bucket latency histogram,
exposed as Prometheus text (``GET /metrics``) and JSON (``GET /stats``).

Lock-cheap by construction: every observation is a handful of integer
bumps under one small lock plus a bounded ring append — no JSONL
readback, no sort on the hot path (percentile-ish questions are answered
from the fixed histogram buckets and the recent-window ring at SCRAPE
time).  The SLO-burn gauge follows the standard error-budget framing:
with a p99 objective of ``slo_p99_ms``, 1% of requests are allowed over
the target; ``slo_burn`` is (observed over-target fraction in the recent
window) / 1%, so 1.0 means burning budget exactly at the allowed rate
and >1 means the SLO is being violated.

``parse_prometheus`` is the minimal text-format parser the tests and
``tools/bench_serve.py`` share to read the exposition back.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

# Prometheus-convention cumulative buckets, in milliseconds.  Fixed at
# import so every replica's histograms aggregate; +Inf is implicit.
LATENCY_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0)

_SLO_WINDOW = 1024     # recent requests the burn gauge is computed over
_ERROR_BUDGET = 0.01   # a p99 objective tolerates 1% over-target


class ServeMetrics:
    """Request-level counters for one serving session."""

    def __init__(self, slo_p99_ms: float = 0.0):
        self.slo_p99_ms = max(float(slo_p99_ms or 0.0), 0.0)
        self._lock = threading.Lock()
        self._buckets = [0] * (len(LATENCY_BUCKETS_MS) + 1)  # last = +Inf
        self._lat_sum = 0.0
        self._lat_count = 0
        self._ok = 0
        self._failed = 0
        # explanation requests get their own histogram + outcome
        # counters: a TreeSHAP row costs O(leaves x depth^2) vs the
        # predictor's O(depth), so folding both into one latency
        # distribution would make either signal unreadable
        self._x_buckets = [0] * (len(LATENCY_BUCKETS_MS) + 1)
        self._x_lat_sum = 0.0
        self._x_lat_count = 0
        self._x_ok = 0
        self._x_failed = 0
        self._status: Dict[int, int] = {}
        # priority-class accounting for load shedding (serve/batcher.py
        # PRIORITIES): served vs shed per class is the evidence that
        # overload dropped low-priority traffic first
        self._served: Dict[str, int] = {}
        self._shed: Dict[str, int] = {}
        self._recent = deque(maxlen=_SLO_WINDOW)
        # degradation is a recoverable state (serve/session.py re-probes
        # the device), so the gauge needs transition counters beside it:
        # how many times the session fell back, and how many times the
        # probe brought it back
        self._degraded = False
        self._degraded_transitions = 0
        self._recoveries = 0

    # ---- hot path ----------------------------------------------------
    @staticmethod
    def _bucket_index(ms: float) -> int:
        """Index into LATENCY_BUCKETS_MS (+1 overflow slot) — the ONE
        copy of the histogram bucketing rule, shared by the predict and
        explain observers so the two histograms cannot drift."""
        i = 0
        for b in LATENCY_BUCKETS_MS:
            if ms <= b:
                break
            i += 1
        return i

    def observe(self, latency_ms: float, ok: bool = True) -> None:
        """Account one finished request (any outcome)."""
        ms = float(latency_ms)
        i = self._bucket_index(ms)
        with self._lock:
            self._buckets[i] += 1
            self._lat_sum += ms
            self._lat_count += 1
            if ok:
                self._ok += 1
            else:
                self._failed += 1
            self._recent.append(ms)

    def observe_explain(self, latency_ms: float, ok: bool = True) -> None:
        """Account one finished explanation request (any outcome)."""
        ms = float(latency_ms)
        i = self._bucket_index(ms)
        with self._lock:
            self._x_buckets[i] += 1
            self._x_lat_sum += ms
            self._x_lat_count += 1
            if ok:
                self._x_ok += 1
            else:
                self._x_failed += 1

    def count_status(self, code: int) -> None:
        """Bump the HTTP-status counter (server front end only)."""
        code = int(code)
        with self._lock:
            self._status[code] = self._status.get(code, 0) + 1

    def count_served(self, priority: str) -> None:
        """One successfully served request of this priority class."""
        p = str(priority or "normal")
        with self._lock:
            self._served[p] = self._served.get(p, 0) + 1

    def count_shed(self, priority: str) -> None:
        """One request rejected by overload/shedding in this class."""
        p = str(priority or "normal")
        with self._lock:
            self._shed[p] = self._shed.get(p, 0) + 1

    def set_degraded(self, flag: bool) -> None:
        """Record a degradation-state transition (session -> host
        fallback, or a successful device re-probe recovering it)."""
        flag = bool(flag)
        with self._lock:
            if flag and not self._degraded:
                self._degraded_transitions += 1
            elif not flag and self._degraded:
                self._recoveries += 1
            self._degraded = flag

    @property
    def degraded_transitions(self) -> int:
        with self._lock:
            return self._degraded_transitions

    @property
    def recoveries(self) -> int:
        with self._lock:
            return self._recoveries

    # ---- scrape time -------------------------------------------------
    def slo_burn(self) -> Optional[float]:
        """Error-budget burn rate over the recent window (None when no
        SLO is configured, 0.0 when nothing was served yet)."""
        if not self.slo_p99_ms:
            return None
        with self._lock:
            recent = list(self._recent)
        if not recent:
            return 0.0
        over = sum(1 for v in recent if v > self.slo_p99_ms)
        return round((over / len(recent)) / _ERROR_BUDGET, 3)

    def snapshot(self) -> dict:
        burn = self.slo_burn()
        with self._lock:
            cum, total = [], 0
            for c in self._buckets:
                total += c
                cum.append(total)
            x_cum, x_total = [], 0
            for c in self._x_buckets:
                x_total += c
                x_cum.append(x_total)
            return {
                "latency_buckets_ms": list(LATENCY_BUCKETS_MS),
                "latency_cumulative": cum,
                "latency_sum_ms": round(self._lat_sum, 3),
                "latency_count": self._lat_count,
                "ok": self._ok,
                "failed": self._failed,
                "explain_latency_cumulative": x_cum,
                "explain_latency_sum_ms": round(self._x_lat_sum, 3),
                "explain_latency_count": self._x_lat_count,
                "explain_ok": self._x_ok,
                "explain_failed": self._x_failed,
                "status": dict(sorted(self._status.items())),
                "served_by_priority": dict(sorted(self._served.items())),
                "shed_by_priority": dict(sorted(self._shed.items())),
                "slo_p99_ms": self.slo_p99_ms or None,
                "slo_burn": burn,
                "degraded": self._degraded,
                "degraded_transitions": self._degraded_transitions,
                "recoveries": self._recoveries,
            }


def _fmt(v) -> str:
    if v is None:
        return "0"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


_DRIFT_KINDS = ("psi_max", "psi_mean", "ks_max", "pred_psi", "pred_ks")


def _aot_series(out, head, ao) -> None:
    """Render one AOT-store stats dict (serve/aot.py
    ``AOTStore.stats()``) as the ``tpu_serve_aot_*`` series.  The
    fallbacks counter is the alert surface: a fleet silently re-paying
    JIT compiles at boot shows up here, not in a crash log."""
    head("tpu_serve_aot_entries", "gauge",
         "Serialized executables resident in the AOT store directory.")
    out.append("tpu_serve_aot_entries %d" % int(ao.get("entries") or 0))
    head("tpu_serve_aot_loaded_total", "counter",
         "Executables deserialized from the AOT store (each one is a "
         "JIT compile the boot path did not pay).")
    out.append("tpu_serve_aot_loaded_total %d" % int(ao.get("loaded")
                                                     or 0))
    head("tpu_serve_aot_saved_total", "counter",
         "Executables serialized into the AOT store by this process.")
    out.append("tpu_serve_aot_saved_total %d" % int(ao.get("saved") or 0))
    head("tpu_serve_aot_fallbacks_total", "counter",
         "AOT entries present but unusable (corrupt/stale/cross-"
         "backend) — each one fell back to a JIT compile, loudly.")
    out.append("tpu_serve_aot_fallbacks_total %d"
               % int(ao.get("fallbacks") or 0))
    head("tpu_serve_aot_save_errors_total", "counter",
         "Failed attempts to persist an executable (costs the next "
         "boot a compile, never this process a request).")
    out.append("tpu_serve_aot_save_errors_total %d"
               % int(ao.get("save_errors") or 0))


def _arena_series(out, head, ast) -> None:
    """Render one forest-arena stats dict (serve/arena.py
    ``ForestArena.stats()``) as the ``tpu_serve_arena_*`` series."""
    head("tpu_serve_arena_tenants", "gauge",
         "Tenant models known to the arena (resident + evicted).")
    out.append("tpu_serve_arena_tenants %d" % int(ast.get("tenants")
                                                  or 0))
    head("tpu_serve_arena_resident", "gauge",
         "Tenant models currently packed into the device arena.")
    out.append("tpu_serve_arena_resident %d" % int(ast.get("resident")
                                                   or 0))
    head("tpu_serve_arena_bytes", "gauge",
         "Device bytes of the packed multi-tenant forest.")
    out.append("tpu_serve_arena_bytes %d" % int(ast.get("packed_bytes")
                                                or 0))
    head("tpu_serve_arena_budget_bytes", "gauge",
         "Configured arena residency budget (tpu_serve_arena_bytes; "
         "0 = unbounded).")
    out.append("tpu_serve_arena_budget_bytes %d"
               % int(ast.get("budget_bytes") or 0))
    head("tpu_serve_arena_evictions_total", "counter",
         "Tenants LRU-evicted from the arena (budget pressure or "
         "manual).")
    out.append("tpu_serve_arena_evictions_total %d"
               % int(ast.get("evictions") or 0))
    head("tpu_serve_arena_readmissions_total", "counter",
         "Evicted tenants transparently repacked on their next "
         "request.")
    out.append("tpu_serve_arena_readmissions_total %d"
               % int(ast.get("readmissions") or 0))
    head("tpu_serve_arena_repacks_total", "counter",
         "Arena pack generations built (admissions, evictions, swaps).")
    out.append("tpu_serve_arena_repacks_total %d"
               % int(ast.get("repacks") or 0))
    head("tpu_serve_arena_batches_total", "counter",
         "Coalesced arena batches executed.")
    out.append("tpu_serve_arena_batches_total %d"
               % int(ast.get("batches") or 0))
    head("tpu_serve_arena_cross_model_batches_total", "counter",
         "Arena batches that coalesced requests for more than one "
         "tenant into a single device launch.")
    out.append("tpu_serve_arena_cross_model_batches_total %d"
               % int(ast.get("cross_model_batches") or 0))
    head("tpu_serve_arena_occupancy", "gauge",
         "Real rows / padded rows across arena launches.")
    out.append("tpu_serve_arena_occupancy %s"
               % _fmt(ast.get("occupancy")))
    head("tpu_serve_arena_requests_total", "counter",
         "Requests answered by the arena, by outcome.")
    out.append('tpu_serve_arena_requests_total{outcome="ok"} %d'
               % int(ast.get("ok") or 0))
    out.append('tpu_serve_arena_requests_total{outcome="deadline"} %d'
               % int(ast.get("deadline_missed") or 0))
    out.append('tpu_serve_arena_requests_total{outcome="overload"} %d'
               % int(ast.get("overloads") or 0))


def _drift_series(out, head, dr) -> None:
    """Render one drift-monitor status dict (obs/drift.py
    ``DriftMonitor.status()``) as the ``tpu_serve_drift_*`` series.
    ``head`` is the caller's HELP/TYPE emitter so repeated calls (one
    per model in a fleet scrape) share a single header block."""
    model = dr.get("model") or "default"
    version = int(dr.get("version") or 0)
    scores = dr.get("scores") or {}
    head("tpu_serve_drift_score", "gauge",
         "Live-traffic drift vs the training reference from the last "
         "cadence check (PSI/KS over feature bins and the prediction "
         "histogram, by kind).")
    for kind in _DRIFT_KINDS:
        out.append(
            'tpu_serve_drift_score{model="%s",version="%d",kind="%s"} %s'
            % (model, version, kind, _fmt(scores.get(kind))))
    head("tpu_serve_drift_rows", "gauge",
         "Rows accumulated in the live drift sketch since the last "
         "reset, by stream (feat = sampled feature rows, pred = scored "
         "responses).")
    for kind in ("feat", "pred"):
        out.append(
            'tpu_serve_drift_rows{model="%s",version="%d",kind="%s"} %d'
            % (model, version, kind, int(dr.get(kind + "_rows") or 0)))
    head("tpu_serve_drift_breach", "gauge",
         "1 while a drift breach is latched (PSI above "
         "tpu_drift_psi_warn at the last cadence check).")
    out.append('tpu_serve_drift_breach{model="%s",version="%d"} %d'
               % (model, version, 1 if dr.get("breach") else 0))


def render_prometheus(session) -> str:
    """Prometheus text exposition for one session (its ``ServeMetrics``
    plus the live gauges out of ``session.stats()``)."""
    m: ServeMetrics = session.metrics
    snap = m.snapshot()
    st = session.stats()
    out = []

    def head(name, kind, help_):
        out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} {kind}")

    head("tpu_serve_requests_total", "counter",
         "Requests by HTTP status (front end).")
    for code, n in (snap["status"] or {200: 0}).items():
        out.append('tpu_serve_requests_total{status="%s"} %d' % (code, n))
    head("tpu_serve_session_requests_total", "counter",
         "Session-level requests by outcome.")
    out.append('tpu_serve_session_requests_total{outcome="ok"} %d'
               % snap["ok"])
    out.append('tpu_serve_session_requests_total{outcome="failed"} %d'
               % snap["failed"])
    head("tpu_serve_request_latency_ms", "histogram",
         "Request latency (submit to result), milliseconds.")
    for b, c in zip(LATENCY_BUCKETS_MS, snap["latency_cumulative"]):
        out.append('tpu_serve_request_latency_ms_bucket{le="%g"} %d'
                   % (b, c))
    out.append('tpu_serve_request_latency_ms_bucket{le="+Inf"} %d'
               % snap["latency_count"])
    out.append("tpu_serve_request_latency_ms_sum %s"
               % _fmt(snap["latency_sum_ms"]))
    out.append("tpu_serve_request_latency_ms_count %d"
               % snap["latency_count"])
    head("tpu_serve_explain_requests_total", "counter",
         "Explanation requests by outcome (POST /explain).")
    out.append('tpu_serve_explain_requests_total{outcome="ok"} %d'
               % snap["explain_ok"])
    out.append('tpu_serve_explain_requests_total{outcome="failed"} %d'
               % snap["explain_failed"])
    head("tpu_serve_explain_latency_ms", "histogram",
         "Explanation request latency (submit to result), milliseconds.")
    for b, c in zip(LATENCY_BUCKETS_MS, snap["explain_latency_cumulative"]):
        out.append('tpu_serve_explain_latency_ms_bucket{le="%g"} %d'
                   % (b, c))
    out.append('tpu_serve_explain_latency_ms_bucket{le="+Inf"} %d'
               % snap["explain_latency_count"])
    out.append("tpu_serve_explain_latency_ms_sum %s"
               % _fmt(snap["explain_latency_sum_ms"]))
    out.append("tpu_serve_explain_latency_ms_count %d"
               % snap["explain_latency_count"])
    # priority-class shedding (serve/batcher.py): served vs shed per
    # class — every class is rendered even at 0 so a scrape series never
    # appears mid-overload
    from .batcher import PRIORITIES
    head("tpu_serve_served_total", "counter",
         "Successfully served requests by priority class.")
    for p in PRIORITIES:
        out.append('tpu_serve_served_total{priority="%s"} %d'
                   % (p, snap["served_by_priority"].get(p, 0)))
    head("tpu_serve_shed_total", "counter",
         "Requests rejected by overload shedding, by priority class "
         "(low sheds first).")
    for p in PRIORITIES:
        out.append('tpu_serve_shed_total{priority="%s"} %d'
                   % (p, snap["shed_by_priority"].get(p, 0)))

    gauges = (
        ("tpu_serve_queue_rows", "gauge", "Rows waiting in the batcher "
         "queue.", st.get("queue_rows")),
        ("tpu_serve_batch_occupancy", "gauge", "Real rows / padded rows "
         "over the session lifetime.", st.get("occupancy")),
        ("tpu_serve_pad_waste_rows_total", "counter", "Padded minus real "
         "rows dispatched to the device.",
         max(int(st.get("padded_rows") or 0) - int(st.get("rows") or 0), 0)),
        ("tpu_serve_batches_total", "counter", "Device/host batches "
         "executed.", st.get("batches")),
        ("tpu_serve_rows_total", "counter", "Real rows scored.",
         st.get("rows")),
        ("tpu_serve_explain_batches_total", "counter", "Device/host "
         "TreeSHAP batches executed.", st.get("explain_batches")),
        ("tpu_serve_explain_rows_total", "counter", "Real rows "
         "explained.", st.get("explain_rows")),
        ("tpu_serve_overloads_total", "counter", "Submits rejected by "
         "backpressure.", st.get("overloads")),
        ("tpu_serve_deadline_missed_total", "counter", "Requests expired "
         "in queue.", st.get("deadline_missed")),
        ("tpu_serve_recompiles_total", "counter", "XLA compiles since "
         "the session started.", st.get("compile_count")),
        ("tpu_serve_degraded", "gauge", "1 while the session is falling "
         "back to the host predictor (recoverable: the session re-probes "
         "the device).", bool(st.get("degraded"))),
        ("tpu_serve_degraded_transitions_total", "counter", "Times the "
         "session fell back to the host predictor.",
         st.get("degraded_transitions")),
        ("tpu_serve_recoveries_total", "counter", "Times a device "
         "re-probe recovered a degraded session.",
         st.get("recoveries")),
        ("tpu_serve_uptime_seconds", "gauge", "Seconds since the session "
         "packed its model.", st.get("uptime_s")),
        ("tpu_serve_slo_p99_ms", "gauge", "Configured p99 latency "
         "objective (tpu_serve_slo_p99_ms).", m.slo_p99_ms or 0.0),
        ("tpu_serve_slo_burn", "gauge", "Error-budget burn rate vs the "
         "p99 objective (1.0 = at budget).", snap["slo_burn"]),
    )
    for name, kind, help_, v in gauges:
        head(name, kind, help_)
        out.append(f"{name} {_fmt(v)}")
    # replica fleet view (serve/router.py): when the target is a
    # ReplicaRouter its stats() carries per-replica rows — rendered with
    # a replica label so one scrape shows which replica is degraded /
    # breaker-open / draining
    reps = st.get("replicas")
    if isinstance(reps, list) and reps:
        _BREAKER_CODE = {"closed": 0, "half_open": 1, "open": 2}
        head("tpu_serve_replica_healthy", "gauge",
             "1 when the replica is routable (breaker closed, not "
             "draining, not degraded).")
        for r in reps:
            out.append('tpu_serve_replica_healthy{replica="%s"} %d'
                       % (r.get("replica"), 1 if r.get("healthy") else 0))
        head("tpu_serve_replica_breaker_state", "gauge",
             "Replica circuit-breaker state: 0 closed, 1 half_open, "
             "2 open.")
        for r in reps:
            out.append(
                'tpu_serve_replica_breaker_state{replica="%s"} %d'
                % (r.get("replica"),
                   _BREAKER_CODE.get((r.get("breaker") or {})
                                     .get("state"), 0)))
        head("tpu_serve_replica_queue_rows", "gauge",
             "Rows waiting in each replica's batcher queue.")
        for r in reps:
            out.append('tpu_serve_replica_queue_rows{replica="%s"} %d'
                       % (r.get("replica"),
                          int(r.get("queue_rows") or 0)))
    # drift plane (obs/drift.py): stats() carries the monitor status
    # when the model shipped a quality-profile sidecar and tpu_drift is
    # on — rendered with model/version labels so the fleet exposition
    # can mix per-model series without a collision
    dr = st.get("drift")
    if isinstance(dr, dict) and dr.get("armed"):
        _drift_series(out, head, dr)
    # AOT executable store (serve/aot.py): rendered only when armed so
    # a storeless session's exposition is unchanged
    ao = st.get("aot")
    if isinstance(ao, dict):
        _aot_series(out, head, ao)
    if st.get("resident_bytes") is not None:
        head("tpu_serve_resident_bytes", "gauge",
             "Device bytes held resident by this serving target "
             "(packed forest + explanation planes, all replicas).")
        out.append("tpu_serve_resident_bytes %d"
                   % int(st["resident_bytes"]))
    return "\n".join(out) + "\n"


def render_prometheus_fleet(registry) -> str:
    """Prometheus text for a ``ModelRegistry`` fleet: the default
    model's live router rendered as the primary series (so dashboards
    built against the single-session exposition keep working), plus the
    registry-level model/version/swap/rollback series."""
    ver = registry.resolve(None)
    out = [render_prometheus(ver.router).rstrip("\n")]

    def head(name, kind, help_):
        out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} {kind}")

    listing = registry.models()
    head("tpu_serve_models", "gauge", "Models resident in the registry.")
    out.append("tpu_serve_models %d" % len(listing))
    head("tpu_serve_model_version", "gauge",
         "Live version per registered model.")
    for m in listing:
        out.append('tpu_serve_model_version{model="%s"} %d'
                   % (m["name"], m["live_version"]))
    head("tpu_serve_swaps_total", "counter",
         "Completed hot-swaps per model (canary-gated flips).")
    for m in listing:
        out.append('tpu_serve_swaps_total{model="%s"} %d'
                   % (m["name"], m["swaps"]))
    head("tpu_serve_swaps_rejected_total", "counter",
         "Swap attempts rejected by the canary gate.")
    for m in listing:
        out.append('tpu_serve_swaps_rejected_total{model="%s"} %d'
                   % (m["name"], m["swaps_rejected"]))
    head("tpu_serve_rollbacks_total", "counter",
         "Rollbacks per model (manual + automatic post-swap).")
    for m in listing:
        out.append('tpu_serve_rollbacks_total{model="%s"} %d'
                   % (m["name"], m["rollbacks"]))
    head("tpu_serve_resident_bytes", "gauge",
         "Device bytes held resident per model version (live and the "
         "rollback-held previous version).")
    for m in listing:
        for v in m.get("versions") or []:
            if v.get("resident_bytes") is not None:
                out.append(
                    'tpu_serve_resident_bytes{model="%s",version="%d"} %d'
                    % (m["name"], int(v["version"]),
                       int(v["resident_bytes"])))
    # per-model drift for the non-default models (the default model's
    # live router is the primary section above, already rendered)
    seen_heads = set()

    def head_once(name, kind, help_):
        if name not in seen_heads:
            seen_heads.add(name)
            head(name, kind, help_)

    for m in listing:
        dr = m.get("drift")
        if not m.get("default") and isinstance(dr, dict) \
                and dr.get("armed"):
            _drift_series(out, head_once, dr)
    # multi-tenant forest arena (serve/arena.py): occupancy, residency
    # and eviction pressure for the packed-tenant plane, plus its own
    # AOT store counters when armed
    arena = getattr(registry, "arena", None)
    if arena is not None:
        try:
            ast = arena.stats()
        except Exception:  # noqa: BLE001 — a scrape never fails for a
            # closing arena
            ast = None
        if ast:
            _arena_series(out, head, ast)
            # the default router's section may already carry the
            # tpu_serve_aot_* series (same store directory) — render
            # the arena's copy only when it did not
            if (isinstance(ast.get("aot"), dict)
                    and "tpu_serve_aot_entries" not in out[0]):
                _aot_series(out, head_once, ast["aot"])
    # online learning loop (online/loop.py): the run_online driver
    # parks its stats provider on the registry so one fleet scrape
    # covers serving AND the refresh loop feeding it
    prov = getattr(registry, "online_provider", None)
    if prov is not None:
        try:
            ost = prov() if callable(prov) else dict(prov)
        except Exception:  # noqa: BLE001 — a scrape never fails for a
            # dead provider
            ost = None
        if ost:
            head("tpu_online_refresh_total", "counter",
                 "Online-loop refresh outcomes (pushed = adopted by "
                 "the registry, rejected = bounced by the canary gate, "
                 "failed = died before the push, skipped = cadence "
                 "fired on a stalled ingest).")
            for outcome, key in (("pushed", "versions"),
                                 ("rejected", "rejected"),
                                 ("failed", "failed"),
                                 ("skipped", "skipped")):
                out.append('tpu_online_refresh_total{outcome="%s"} %d'
                           % (outcome, int(ost.get(key) or 0)))
            head("tpu_online_swap_rejected_total", "counter",
                 "Online refreshes the canary gate refused to flip.")
            out.append("tpu_online_swap_rejected_total %d"
                       % int(ost.get("rejected") or 0))
            head("tpu_online_rows_ingested_total", "counter",
                 "Labeled rows the online loop has ingested.")
            out.append("tpu_online_rows_ingested_total %d"
                       % int(ost.get("rows_ingested") or 0))
            head("tpu_online_last_refresh_age_seconds", "gauge",
                 "Seconds since the online loop last attempted a "
                 "refresh (stalls show up as unbounded growth).")
            out.append("tpu_online_last_refresh_age_seconds %s"
                       % _fmt(ost.get("last_refresh_age_s")))
    return "\n".join(out) + "\n"


def parse_prometheus(text: str) -> Dict[str, float]:
    """Minimal Prometheus text parser: ``{'name{labels}': value}`` (and
    bare ``name`` for label-less samples).  Enough to assert on an
    exposition in tests and to embed a scrape in a bench artifact."""
    out: Dict[str, float] = {}
    for line in (text or "").splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            key, val = line.rsplit(None, 1)
        except ValueError:
            continue
        try:
            out[key] = float(val)
        except ValueError:
            continue
    return out
