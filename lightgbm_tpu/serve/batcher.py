"""Dynamic request microbatching for the serving engine.

Requests queue as they arrive and are coalesced into one device batch
when either the row cap (``tpu_serve_max_batch``) fills or the OLDEST
queued request has waited ``tpu_serve_max_wait_ms`` — latency is bounded
by the wait knob, throughput by the cap.  The queue itself is bounded in
ROWS (``tpu_serve_queue_depth``): when full, ``submit`` raises
``ServeOverloadError`` immediately — explicit backpressure the caller
can act on (shed load, retry elsewhere) instead of unbounded memory
growth and an eventual OOM.

The batcher owns only the queueing policy; padding to power-of-two row
buckets and the actual device dispatch live in the session's execute
callback (serve/session.py), which also decides host-fallback
degradation.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import List, Optional


# priority classes, most- to least-important.  Overload sheds from the
# LOW end first: each class owns a fraction of the queue-row budget, so
# a saturating flood of low-priority bulk traffic hits ITS cap while
# interactive high-priority requests still have headroom.
PRIORITIES = ("high", "normal", "low")
DEFAULT_SHED_FRACS = {"high": 1.0, "normal": 0.85, "low": 0.5}


def normalize_priority(value) -> str:
    """Map a request's priority field to a known class (unknown/absent
    values serve as ``normal`` rather than erroring — shedding is an
    overload policy, not an input validator)."""
    p = str(value or "normal").strip().lower()
    return p if p in PRIORITIES else "normal"


class ServeOverloadError(RuntimeError):
    """The bounded request queue is full — backpressure, not OOM.
    ``priority`` is the class of the rejected request; ``shed`` is True
    when the rejection came from a priority class's partial budget
    (rows remained for higher classes), False at the absolute cap."""

    def __init__(self, msg: str, priority: str = "normal",
                 shed: bool = False):
        super().__init__(msg)
        self.priority = priority
        self.shed = shed


class DeadlineExceeded(RuntimeError):
    """The request outlived its deadline before results were ready."""


class Request:
    """One queued prediction request: binned rows for the device path,
    the raw rows kept alongside for host-fallback degradation.
    Request-level accounting lives in the session's ``result()`` (one
    count per ticket); this carries only the batching state plus the
    trace context (trace_id minted at the HTTP edge, parent_id = the
    request's root span) the session's span emission attributes to.

    ``model`` is the cross-model coalescing lane (serve/arena.py): an
    arena batcher mixes requests for DIFFERENT resident tenants in one
    device launch, so each request carries its tenant and the execute
    callback builds the per-row model-id vector from it.  None outside
    an arena — single-model sessions never read it."""

    __slots__ = ("bins", "raw", "n", "future", "deadline", "t_submit",
                 "t_submit_wall", "trace_id", "parent_id", "priority",
                 "model")

    def __init__(self, bins, raw, deadline: Optional[float] = None,
                 trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 priority: str = "normal",
                 model: Optional[str] = None):
        self.bins = bins
        self.raw = raw
        self.n = int(bins.shape[0])
        self.future: Future = Future()
        self.deadline = deadline        # absolute time.monotonic() or None
        self.t_submit = time.monotonic()
        self.t_submit_wall = time.time()  # span timestamps are wall clock
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.priority = normalize_priority(priority)
        self.model = model


class MicroBatcher:
    """Coalesce queued requests into batches of <= ``max_batch`` rows on
    a single worker thread; dispatch order is arrival order (whole
    requests only — a request is never split across batches)."""

    def __init__(self, execute, max_batch: int, max_wait_s: float,
                 max_queue_rows: int, name: str = "lgbm-serve-batcher",
                 shed_fracs: Optional[dict] = None):
        self._execute = execute
        self.max_batch = max(int(max_batch), 1)
        self.max_wait_s = max(float(max_wait_s), 0.0)
        self.max_queue_rows = max(int(max_queue_rows), self.max_batch)
        # per-priority queue-row budgets (fraction of max_queue_rows);
        # high priority always owns the full queue, and normal/high
        # budgets floor at one full batch so default traffic can always
        # be admitted to an idle queue — only LOW may be configured
        # below a batch (bulk traffic on a tiny queue is shed by design)
        fracs = dict(DEFAULT_SHED_FRACS)
        fracs.update(shed_fracs or {})
        fracs["high"] = 1.0
        self.shed_caps = {p: max(int(self.max_queue_rows
                                     * min(max(float(fracs.get(p, 1.0)),
                                               0.0), 1.0)),
                                 0 if p == "low" else self.max_batch)
                          for p in PRIORITIES}
        self._q: deque = deque()
        self._rows = 0
        self._closed = False
        self._cv = threading.Condition()
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)
        self._thread.start()

    @property
    def queue_rows(self) -> int:
        with self._cv:
            return self._rows

    def submit(self, req: Request) -> Future:
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            cap = self.shed_caps.get(req.priority, self.max_queue_rows)
            if self._rows + req.n > cap:
                raise ServeOverloadError(
                    f"serve queue full for priority {req.priority!r} "
                    f"({self._rows} rows queued, cap {cap} of "
                    f"{self.max_queue_rows})",
                    priority=req.priority,
                    shed=cap < self.max_queue_rows)
            self._q.append(req)
            self._rows += req.n
            self._cv.notify_all()
        return req.future

    # ------------------------------------------------------------------
    def _take_batch(self) -> Optional[List[Request]]:
        """Block until a batch is ready; None once closed AND drained."""
        with self._cv:
            while not self._q and not self._closed:
                self._cv.wait()
            if not self._q:
                return None
            # linger until the cap fills or the oldest request's wait
            # budget runs out; close drains immediately
            deadline = self._q[0].t_submit + self.max_wait_s
            while self._rows < self.max_batch and not self._closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            batch: List[Request] = []
            total = 0
            while self._q and (not batch
                               or total + self._q[0].n <= self.max_batch):
                r = self._q.popleft()
                batch.append(r)
                total += r.n
            self._rows -= total
            return batch

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            try:
                self._execute(batch)
            except BaseException as exc:  # noqa: BLE001 — worker must live
                for r in batch:
                    if not r.future.done():
                        try:
                            r.future.set_exception(exc)
                        except BaseException:  # noqa: BLE001 cancel race
                            pass

    # ------------------------------------------------------------------
    def close(self, timeout: float = 10.0) -> None:
        """Stop accepting work, drain the queue, join the worker.  Any
        request the worker could not drain fails loudly rather than
        hanging its caller."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout)
        with self._cv:
            leftovers = list(self._q)
            self._q.clear()
            self._rows = 0
        for r in leftovers:
            if not r.future.done():
                try:
                    r.future.set_exception(RuntimeError("batcher closed"))
                except BaseException:  # noqa: BLE001 — cancel race
                    pass
