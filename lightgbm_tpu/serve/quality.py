"""Rolling label-quality tracking for served models.

The online loop's labeled stream (online/loop.py ``ingest``) doubles as
a delayed ground-truth feed: every labeled row the loop banks for the
next refit is ALSO scored against the currently-served model here, and
each full ``tpu_quality_window`` rows produce one ``quality_window``
telemetry event — windowed AUC, a single-query NDCG@10, and expected
calibration error — so a quietly-degrading refit shows up BETWEEN
swaps instead of only at the next canary gate.

Breach wiring: when the profile carries a training-AUC baseline
(obs/drift.py ``QualityProfile``) and a window's AUC drops more than
``tpu_quality_drop_warn`` below it, the tracker dumps the flight
recorder and latches a breach record on the registry
(``note_quality_breach``) that the post-swap health watch folds into
its verdict — default non-gating, ``tpu_serve_rollback_on_drift``
opt-in for rollback, exactly like the drift-PSI signal beside it.

Pure numpy; the model only enters through ``predict_fn``.
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

from .. import obs
from ..obs.drift import QualityProfile, _binary_auc, _knob
from ..utils import log


def _sigmoid(s: np.ndarray) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-np.clip(s, -60.0, 60.0)))


def _calibration_error(scores: np.ndarray, label: np.ndarray,
                       bins: int = 10) -> Optional[float]:
    """Expected calibration error of sigmoid(score) vs binary labels:
    |mean predicted - observed rate| averaged over equal-width
    probability bins, weighted by bin mass."""
    p = _sigmoid(np.asarray(scores, np.float64).ravel())
    y = np.asarray(label, np.float64).ravel()
    if p.size == 0 or p.size != y.size:
        return None
    idx = np.clip((p * bins).astype(np.int64), 0, bins - 1)
    n = np.bincount(idx, minlength=bins).astype(np.float64)
    conf = np.bincount(idx, weights=p, minlength=bins)
    acc = np.bincount(idx, weights=y, minlength=bins)
    mask = n > 0
    if not mask.any():
        return None
    return float(np.sum(np.abs(conf[mask] - acc[mask])) / p.size)


def _window_ndcg(scores: np.ndarray, label: np.ndarray,
                 k: int = 10) -> Optional[float]:
    """NDCG@k treating the whole window as one query (gain 2^y - 1):
    a top-of-ranking quality signal even without query structure —
    degenerate (None) when no row has positive gain."""
    s = np.asarray(scores, np.float64).ravel()
    y = np.asarray(label, np.float64).ravel()
    if s.size == 0 or s.size != y.size:
        return None
    gain = np.power(2.0, y) - 1.0
    if gain.sum() <= 0:
        return None
    disc = 1.0 / np.log2(np.arange(2, min(k, s.size) + 2))
    order = np.argsort(-s, kind="mergesort")
    dcg = float(np.sum(gain[order[:len(disc)]] * disc))
    ideal = np.sort(gain)[::-1]
    idcg = float(np.sum(ideal[:len(disc)] * disc))
    return dcg / idcg if idcg > 0 else None


class QualityTracker:
    """Windowed quality evaluation of a served model against its
    delayed labels.  One per online loop; thread-safe only in the
    loop's single-ingest-thread sense (matching ``OnlineLoop``)."""

    def __init__(self, predict_fn, profile: Optional[QualityProfile],
                 config=None, *, registry=None, model_name: str = "default"):
        self.predict_fn = predict_fn
        self.profile = profile
        self.registry = registry
        self.model_name = model_name
        self.window = max(int(_knob(config, "tpu_quality_window",
                                    int, 512)), 1)
        self.drop_warn = float(_knob(config, "tpu_quality_drop_warn",
                                     float, 0.05))
        self.auc_ref = (profile.meta.get("train_auc")
                        if profile is not None else None)
        self._X: list = []
        self._y: list = []
        self._buffered = 0
        self.windows = 0
        self.rows = 0
        self.breaches = 0
        self.last: Optional[dict] = None

    # -- feed ---------------------------------------------------------
    def add(self, X, y) -> None:
        """Bank labeled rows; evaluates one window per ``window`` rows.
        Scoring failures degrade to a warning — quality tracking must
        never take the ingest path down."""
        X = np.asarray(X)
        y = np.asarray(y, np.float64).ravel()
        if X.ndim == 1:
            X = X.reshape(1, -1)
        if X.shape[0] == 0 or X.shape[0] != y.size:
            return
        self._X.append(X)
        self._y.append(y)
        self._buffered += int(X.shape[0])
        self.rows += int(X.shape[0])
        while self._buffered >= self.window:
            Xa = np.concatenate(self._X, axis=0)
            ya = np.concatenate(self._y)
            Xw, yw = Xa[:self.window], ya[:self.window]
            rest_X, rest_y = Xa[self.window:], ya[self.window:]
            self._X = [rest_X] if rest_X.shape[0] else []
            self._y = [rest_y] if rest_y.shape[0] else []
            self._buffered = int(rest_X.shape[0])
            try:
                self._evaluate(Xw, yw)
            except Exception as exc:  # noqa: BLE001 — never break ingest
                log.warning("quality window evaluation failed: %s", exc)

    # -- evaluation ---------------------------------------------------
    def _evaluate(self, X: np.ndarray, y: np.ndarray) -> None:
        scores = np.asarray(self.predict_fn(X), np.float64)
        scores = scores[:, 0] if scores.ndim == 2 else scores.ravel()
        version = self._served_version()
        auc = _binary_auc(scores, y) \
            if set(np.unique(y)) <= {0.0, 1.0} else None
        cal = _calibration_error(scores, y) if auc is not None else None
        ndcg = _window_ndcg(scores, y)
        delta = (round(self.auc_ref - auc, 6)
                 if auc is not None and self.auc_ref is not None else None)
        breached = delta is not None and delta > self.drop_warn
        self.windows += 1
        rec = {"rows": int(X.shape[0]), "version": version,
               "auc": None if auc is None else round(auc, 6),
               "auc_ref": (None if self.auc_ref is None
                           else round(self.auc_ref, 6)),
               "auc_delta": delta,
               "cal_err": None if cal is None else round(cal, 6),
               "ndcg": None if ndcg is None else round(ndcg, 6),
               "breach": breached,
               "at_unix": round(time.time(), 3)}
        self.last = rec
        ev = {k: v for k, v in rec.items()
              if v is not None and k != "at_unix"}
        ev.setdefault("breach", False)
        obs.event("quality_window", model=self.model_name, **ev)
        if breached:
            self.breaches += 1
            obs.flight_dump(f"quality_drop:{self.model_name}",
                            extra={"quality": rec,
                                   "threshold": self.drop_warn})
            if self.registry is not None and hasattr(self.registry,
                                                     "note_quality_breach"):
                self.registry.note_quality_breach(self.model_name, rec)

    def _served_version(self) -> int:
        if self.registry is None:
            return 0
        try:
            ent = self.registry._models.get(self.model_name)
            return int(ent.live.version) if ent and ent.live else 0
        except Exception:  # noqa: BLE001
            return 0

    # -- introspection ------------------------------------------------
    def stats(self) -> dict:
        out = {"window": self.window, "drop_warn": self.drop_warn,
               "rows": self.rows, "windows": self.windows,
               "buffered": self._buffered, "breaches": self.breaches,
               "auc_ref": self.auc_ref}
        if self.last is not None:
            out["last"] = self.last
        return out
