"""Ahead-of-time executable store: zero-compile cold starts.

A fresh serving process pays ``ceil(log2(max_batch)) + 1`` JIT compiles
(one per pow2 bucket, plus the explain family when armed) before request
#1 meets SLO.  The persistent XLA cache (utils/compile_cache.py) shaves
the backend compile but still traces, lowers, and probes the cache on
the request path.  This store removes the compiler from the boot path
entirely: a warmed process serializes its compiled bucket executables
(``jax.experimental.serialize_executable``) and a cold process loads
them back as ready-to-call executables — request #1 runs at steady-state
latency with the obs compile counter pinned at 0.

Key schema (one entry per executable)::

    sha256(kind | backend | jax version | bucket | rows-cap | K |
           num_features | early-stop spec | forest leaf shapes+dtypes |
           bin-space digest (meta array CONTENT) | device)

The forest and ``DeviceMeta`` arrays are CLOSURE CONSTANTS baked into
the executable by ``jax.jit(...).lower().compile()`` — two models with
identical shapes but different thresholds produce different programs —
so the key hashes the bin-space content, not just shapes.  Backend and
jax version ride in both the key and the entry header: a cross-backend
or cross-version entry is STALE, and every failed load (truncated file,
unpicklable payload, deserialization error) falls back to JIT loudly —
an ``aot_fallback`` telemetry event + the ``tpu_serve_aot_fallbacks``
metric — and never crashes the serving process.

Armed via ``tpu_serve_aot_dir`` / ``$LGBM_TPU_SERVE_AOT_DIR`` (the env
var wins, matching every other serve knob); ``tpu_serve_aot=false``
disarms without unsetting the directory.
"""
from __future__ import annotations

import hashlib
import os
import pickle
from typing import Optional

import numpy as np

from .. import obs
from ..robust import faults
from ..utils import log
from ..utils.compile_cache import atomic_write_bytes, store_entries

_MAGIC = "lgbm-aot-v1"
_SUFFIX = ".aot"


def resolve_aot_dir(config=None) -> Optional[str]:
    """The AOT store directory in effect, or None (store unarmed).
    ``$LGBM_TPU_SERVE_AOT_DIR`` wins over ``tpu_serve_aot_dir``;
    ``tpu_serve_aot=false`` disarms both."""
    if config is not None and not getattr(config, "tpu_serve_aot", True):
        return None
    p = (os.environ.get("LGBM_TPU_SERVE_AOT_DIR", "").strip()
         or str(getattr(config, "tpu_serve_aot_dir", "") or "").strip())
    return os.path.abspath(os.path.expanduser(p)) if p else None


class AOTStore:
    """One directory of serialized executables, content-keyed.

    ``load`` returns ``(status, fn)`` with status in {"hit", "miss",
    "fallback"}: a *miss* is a cold store (nothing to say), a *fallback*
    is an entry that EXISTS but cannot be trusted — corrupt bytes, a
    different backend/jax version, a deserialization failure — reported
    via the ``aot_fallback`` event so a fleet silently re-paying JIT
    compiles is visible, then served by the JIT path as if the store
    were cold."""

    def __init__(self, path: str):
        self.path = str(path)
        self.loaded = 0
        self.saved = 0
        self.fallbacks = 0
        self.save_errors = 0

    # ---- keying ------------------------------------------------------
    @staticmethod
    def _digest_tree(tree) -> str:
        """Content digest of a pytree of arrays (forest / DeviceMeta):
        the executable bakes these in as constants, so identical shapes
        with different values are different programs."""
        import jax
        h = hashlib.sha256()
        for leaf in jax.tree_util.tree_leaves(tree):
            a = np.asarray(leaf)
            h.update(str(a.dtype).encode())
            h.update(str(a.shape).encode())
            h.update(np.ascontiguousarray(a).tobytes())
        return h.hexdigest()

    @staticmethod
    def backend() -> str:
        import jax
        try:
            return str(jax.default_backend())
        except Exception:  # noqa: BLE001 — backend not up
            return "unknown"

    def key(self, kind: str, bucket: int, content_digest: str,
            extra: str = "") -> str:
        import jax
        parts = "|".join([_MAGIC, kind, self.backend(), jax.__version__,
                          str(int(bucket)), content_digest, extra])
        return hashlib.sha256(parts.encode()).hexdigest()[:32]

    def _entry_path(self, kind: str, key: str) -> str:
        return os.path.join(self.path, f"{kind}_{key}{_SUFFIX}")

    # ---- load / save -------------------------------------------------
    def load(self, kind: str, key: str):
        """(status, fn): "hit" + a ready executable, "miss" + None for
        a cold store, "fallback" + None for a present-but-untrusted
        entry (already reported loudly)."""
        path = self._entry_path(kind, key)
        if not os.path.exists(path):
            return "miss", None
        try:
            faults.check("serve_aot_load")
            with open(path, "rb") as fh:
                blob = pickle.load(fh)
            if not (isinstance(blob, dict) and blob.get("magic") == _MAGIC):
                raise ValueError("bad magic / not an AOT entry")
            import jax
            if blob.get("backend") != self.backend():
                raise ValueError(
                    f"backend mismatch (entry {blob.get('backend')!r}, "
                    f"process {self.backend()!r})")
            if blob.get("jax") != jax.__version__:
                raise ValueError(
                    f"jax version mismatch (entry {blob.get('jax')!r}, "
                    f"process {jax.__version__!r})")
            from jax.experimental import serialize_executable as se
            fn = se.deserialize_and_load(
                blob["payload"], blob["in_tree"], blob["out_tree"])
            self.loaded += 1
            return "hit", fn
        except Exception as exc:  # noqa: BLE001 — fall back to JIT, loudly
            self.fallbacks += 1
            log.warning("AOT store: entry %s unusable (%s: %s) — falling "
                        "back to JIT compile", os.path.basename(path),
                        type(exc).__name__, exc)
            obs.event("aot_fallback", kind=kind,
                      entry=os.path.basename(path),
                      reason=f"{type(exc).__name__}: {exc}")
            obs.count("serve/aot_fallbacks")
            return "fallback", None

    def save(self, kind: str, key: str, compiled, note: dict = None) -> bool:
        """Serialize a compiled executable into the store (atomic).
        Returns False on failure — a store write failure costs the next
        boot a compile, never this process a request."""
        try:
            import jax
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = se.serialize(compiled)
            blob = {"magic": _MAGIC, "backend": self.backend(),
                    "jax": jax.__version__, "kind": kind,
                    "payload": payload, "in_tree": in_tree,
                    "out_tree": out_tree, "note": dict(note or {})}
            atomic_write_bytes(self._entry_path(kind, key),
                               pickle.dumps(blob, protocol=4))
            self.saved += 1
            return True
        except Exception as exc:  # noqa: BLE001
            self.save_errors += 1
            log.warning("AOT store: failed to persist %s/%s (%s: %s)",
                        kind, key, type(exc).__name__, exc)
            return False

    # ---- introspection -----------------------------------------------
    def entries(self) -> list:
        return store_entries(self.path, _SUFFIX)

    def stats(self) -> dict:
        return {"dir": self.path, "entries": len(self.entries()),
                "loaded": self.loaded, "saved": self.saved,
                "fallbacks": self.fallbacks,
                "save_errors": self.save_errors}
