"""Threaded JSON-over-HTTP front end for the serving plane.

Wraps a bare ``PredictorSession``, a ``ReplicaRouter``, or a full
``ModelRegistry`` fleet.  Stdlib ``http.server`` only — no new
dependencies.  Protocol:

    POST /predict   body {"rows": [[...], ...], "raw_score": false,
                          "deadline_ms": 250, "model": "name",
                          "priority": "high|normal|low"}
                 -> 200 {"predictions": [...], "rows": N,
                         "latency_ms": ..., "trace_id": ...,
                         "model": ..., "version": V, "replica": "rI"}
                    — model/version/replica echoed only on a registry
                    fleet: every response is attributable to exactly
                    one model version (a mid-flight hot swap never
                    changes which forest answered).  ``priority`` (or
                    the ``X-Priority`` header) picks the load-shedding
                    class; a shed 503 carries ``Retry-After``.
    POST /explain   body {"rows": [[...], ...], "deadline_ms": 250}
                 -> 200 {"contributions": [[...]], "rows": N,
                         "num_features": F, "num_class": K, ...}
                    — per-row SHAP contributions ([F+1] per class, last
                    column = expected value), computed by the batched
                    device TreeSHAP kernel (explain/) through its OWN
                    microbatch queue and pow2 bucket family; 404 when
                    ``tpu_explain=false``
    POST /models/{name}/swap      body {"model_file": path}
                 -> 200 swap report (canary checks, versions) on a
                    completed flip; 409 when the canary gate rejected
                    (the previous version keeps serving untouched)
    POST /models/{name}/rollback  body {"reason": "..."}
                 -> 200 rollback report (instant flip to the resident
                    previous version); 409 when none is resident
    GET  /models       -> 200 registry listing (live/previous versions,
                               swap/rollback counts, canary reports)
    GET  /health       -> 200 {"status": "ok"|"degraded", queue_rows,
                               uptime_s, compile_count, slo_burn,
                               ...session stats...; on a fleet also
                               per-replica rows (breaker state,
                               degraded planes, queue depth) and
                               per-model status}
    GET  /metrics      -> 200 Prometheus text (request counts by status,
                               latency histogram, queue depth, occupancy,
                               pad waste, recompiles, degraded gauge,
                               SLO-burn) — scrape-cheap, no JSONL readback
    GET  /stats        -> 200 the same numbers as JSON
    GET  /debug/flight -> 200 the flight-recorder ring (last N spans +
                               operational events), the live post-mortem

Every request gets a trace id at this edge — an incoming
``X-Request-Id`` header is honored (sanitized) and echoed back — and the
id rides through the batcher so the whole
queue->coalesce->pad->execute span chain carries it (obs/spans.py).
Replies that served a prediction carry the id in the JSON body too.

Error mapping (all JSON bodies with an ``error`` field):

- 400 malformed body / wrong feature count
- 503 queue full (``ServeOverloadError`` — explicit backpressure; shed
  or retry elsewhere, the server never buffers unboundedly)
- 504 deadline exceeded in queue, or the reply wait timed out
- 500 anything else

When the device backend dies mid-flight the SESSION degrades to the
host numpy predictor (serve/session.py) — requests keep succeeding and
``/health`` flips to ``"degraded"`` so a load balancer can drain the
replica gracefully instead of seeing a wall of 500s (and the flight
recorder dumps ``FLIGHT_rN.json`` with the moments before the flip).
Degradation is NOT a one-way latch: the session re-probes the device
every ``tpu_serve_reprobe_s`` seconds and a successful probe flips
``/health`` (and the ``/metrics`` ``tpu_serve_degraded`` gauge) back to
``"ok"`` — the ``tpu_serve_degraded_transitions_total`` /
``tpu_serve_recoveries_total`` counters record every flip.
"""
from __future__ import annotations

import json
import re
import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .. import obs
from ..utils import log
from .batcher import DeadlineExceeded, ServeOverloadError, \
    normalize_priority
from .metrics import render_prometheus, render_prometheus_fleet

# grace added to a request's own deadline before the HTTP thread gives
# up waiting on the batcher (the batch may be mid-flight on the device)
_REPLY_GRACE_S = 30.0
_DEFAULT_REPLY_TIMEOUT_S = 120.0

_MODEL_PATH = re.compile(r"^/models/([A-Za-z0-9._-]{1,64})/(swap|rollback)$")


def _json_safe(o):
    try:
        return o.item()  # numpy / jax scalars
    except Exception:  # noqa: BLE001
        return repr(o)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # stderr stays silent; the structured ``serve_access`` telemetry
    # event (log_request below) is the access log when a sink is on
    def log_message(self, fmt, *args):  # noqa: A002
        pass

    def log_request(self, code="-", size="-"):
        """http.server's per-response hook (send_response calls it):
        one ``serve_access`` event per reply — status, latency, trace id
        — instead of the stderr line.  A no-telemetry run stays silent
        (obs.event gates itself)."""
        try:
            status = int(getattr(code, "value", code))
        except (TypeError, ValueError):
            status = 0
        t0 = getattr(self, "_t0", None)
        # malformed/over-long request lines error out before the base
        # handler ever assigns self.path/command — getattr everything.
        # Normalized exactly like the route dispatch (query stripped,
        # trailing slash dropped) so the flight ring's scrape-path
        # filter sees the same string the router matched.
        path = str(getattr(self, "path", "") or "?").split("?")[0]
        obs.event("serve_access",
                  method=str(getattr(self, "command", "") or "?"),
                  path=path.rstrip("/") or path[:1] or "?",
                  status=status,
                  latency_ms=(round((time.perf_counter() - t0) * 1e3, 3)
                              if t0 is not None else 0.0),
                  trace_id=getattr(self, "_trace_id", None) or "-")

    def _begin(self) -> None:
        """Per-request edge state: wall/perf start + the trace id (an
        incoming ``X-Request-Id`` is honored, else minted here)."""
        self._t0 = time.perf_counter()
        self._t0_wall = time.time()
        self._trace_id = obs.new_trace_id(self.headers.get("X-Request-Id"))
        self._status = None

    def _end(self) -> None:
        """Clear the per-request edge state.  On a keep-alive connection
        the handler instance persists across requests, and a malformed
        follow-up request errors out BEFORE do_GET/do_POST (and _begin)
        run — without this, its access-log line would reuse the previous
        request's trace id and measure latency from its start."""
        self._t0 = None
        self._trace_id = None

    def _reply(self, code: int, payload: dict, headers=None) -> None:
        body = json.dumps(payload, default=_json_safe).encode()
        self._reply_bytes(code, body, "application/json", headers=headers)

    def _reply_bytes(self, code: int, body: bytes, ctype: str,
                     headers=None) -> None:
        self._status = code
        try:
            self.server.session.metrics.count_status(code)
        except Exception:  # noqa: BLE001 — an empty registry must not 500
            pass
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if getattr(self, "_trace_id", None):
            self.send_header("X-Request-Id", self._trace_id)
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _shed_headers(self) -> dict:
        """503 responses tell the client when to come back — the
        shedding contract (``tpu_serve_retry_after_s``)."""
        return {"Retry-After":
                "%g" % getattr(self.server, "retry_after_s", 1.0)}

    def do_GET(self):  # noqa: N802 — http.server API
        self._begin()
        try:
            reg = getattr(self.server, "registry", None)
            sess = self.server.session
            path = self.path.split("?")[0].rstrip("/")
            if path in ("", "/health"):
                st = sess.stats()
                # fleet view: a router serves through its replicas, so
                # "degraded" at the top level means NO replica still has
                # a healthy device path (all-degraded), not any-replica
                st["status"] = "degraded" if st.get("degraded") else "ok"
                st["health_mode"] = obs.health_mode() or "off"
                if reg is not None:
                    st["models"] = {m["name"]: m for m in reg.models()}
                    arena = getattr(reg, "arena", None)
                    if arena is not None:
                        st["arena"] = arena.stats()
                self._reply(200, st)
            elif path == "/metrics":
                text = (render_prometheus_fleet(reg) if reg is not None
                        else render_prometheus(sess))
                self._reply_bytes(200, text.encode(),
                                  "text/plain; version=0.0.4")
            elif path == "/stats":
                body = {"stats": sess.stats(),
                        "metrics": sess.metrics.snapshot()}
                if reg is not None:
                    body["models"] = reg.stats()
                    arena = getattr(reg, "arena", None)
                    if arena is not None:
                        body["arena"] = {"stats": arena.stats(),
                                         "tenants": arena.tenants()}
                self._reply(200, body)
            elif path == "/models":
                if reg is None:
                    self._reply(404, {"error": "no_registry",
                                      "detail": "server wraps a bare "
                                      "session, not a model registry"})
                else:
                    body = {"default": reg.default,
                            "models": reg.models()}
                    arena = getattr(reg, "arena", None)
                    if arena is not None:
                        # residency view: which tenants are device-
                        # resident, eviction/occupancy counters
                        body["arena"] = {"stats": arena.stats(),
                                         "tenants": arena.tenants()}
                    self._reply(200, body)
            elif path == "/drift":
                # drift/quality plane (obs/drift.py): per-model monitor
                # status — thresholds, live sketch rows, last scores,
                # breach latch — for dashboards that want the raw view
                # behind the tpu_serve_drift_* series
                if reg is not None:
                    body = {}
                    for m in reg.models():
                        body[m["name"]] = {
                            "drift": m.get("drift"),
                            "quality_breach": m.get("quality_breach"),
                        }
                    self._reply(200, {"models": body})
                else:
                    dr = sess.stats().get("drift")
                    self._reply(200, {"drift": dr,
                                      "armed": bool(dr)})
            elif path == "/debug/flight":
                self._reply(200, {"enabled": obs.flight_enabled(),
                                  "ring_len": obs.flight_len(),
                                  "events": obs.flight_snapshot()})
            else:
                self._reply(404, {"error": "not_found", "path": self.path})
        finally:
            self._end()

    def do_POST(self):  # noqa: N802 — http.server API
        self._begin()
        path = self.path.split("?")[0].rstrip("/")
        m = _MODEL_PATH.match(path)
        if m is not None:
            try:
                self._do_admin(m.group(1), m.group(2))
            finally:
                self._end()
            return
        if path not in ("/predict", "/explain"):
            try:
                self._reply(404, {"error": "not_found", "path": self.path})
            finally:
                self._end()
            return
        explain = path == "/explain"
        reg = getattr(self.server, "registry", None)
        t0 = self._t0
        root_id = (obs.new_span_id() if obs.span_record_enabled()
                   else None)
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
            # fleet routing: the body's "model" picks a registered model
            # (default model when absent); a bare-session server ignores
            # it.  Resolution happens HERE, once — the resolved version
            # serves this whole request even if a swap lands mid-flight
            model = payload.get("model")
            version = None
            arena_hit = False
            if reg is not None:
                from .registry import UnknownModelError
                arena = getattr(reg, "arena", None)
                try:
                    ver = reg.resolve(model)
                except UnknownModelError:
                    # arena tenants serve names the version registry
                    # does not know (registered names always win)
                    if arena is not None and (
                            model is None or arena.has(model)):
                        sess, arena_hit = arena, True
                    else:
                        self._reply(404, {"error": "unknown_model",
                                          "model": model})
                        return
                else:
                    sess, model, version = ver.router, ver.router.name, \
                        ver.version
            else:
                sess = self.server.session
            if explain and arena_hit:
                self._reply(404, {"error": "explain_disabled",
                                  "detail": "arena tenants serve "
                                  "predictions only"})
                return
            if explain and not getattr(sess, "explain_enabled", False):
                self._reply(404, {"error": "explain_disabled",
                                  "detail": "explanation serving is off "
                                  "(tpu_explain=false)"})
                return
            rows = payload.get("rows")
            if rows is None:
                raise ValueError("body needs a 'rows' matrix")
            X = np.asarray(rows, dtype=np.float64)
            deadline_ms = payload.get("deadline_ms")
            # priority class for load shedding: body field wins, then
            # the X-Priority header; anything unknown serves as normal
            priority = normalize_priority(
                payload.get("priority")
                or self.headers.get("X-Priority"))
            if explain:
                ticket = sess.submit_explain(X, deadline_ms=deadline_ms,
                                             trace_id=self._trace_id,
                                             parent_id=root_id,
                                             priority=priority)
            elif arena_hit:
                ticket = sess.submit(
                    X, model=model, deadline_ms=deadline_ms,
                    raw_score=bool(payload.get("raw_score")),
                    trace_id=self._trace_id, parent_id=root_id,
                    priority=priority)
            else:
                ticket = sess.submit(
                    X, deadline_ms=deadline_ms,
                    raw_score=bool(payload.get("raw_score")),
                    trace_id=self._trace_id, parent_id=root_id,
                    priority=priority)
            wait_s = (float(deadline_ms) / 1e3 + _REPLY_GRACE_S
                      if deadline_ms is not None
                      else _DEFAULT_REPLY_TIMEOUT_S)
            pred = sess.result(ticket, timeout=wait_s)
            body = {
                "rows": int(ticket.rows),
                "latency_ms": round((time.perf_counter() - t0) * 1e3, 3),
                "trace_id": self._trace_id,
            }
            if version is not None:
                # every response is attributable to exactly one model
                # version (the ticket's, which survived any mid-flight
                # swap) — the bit-consistency contract chaos_serve proves
                body["model"] = model
                body["version"] = int(getattr(ticket, "version", version))
                if getattr(ticket, "replica", None) is not None:
                    body["replica"] = f"r{ticket.replica.idx}"
            elif arena_hit:
                body["model"] = ticket.model
                body["arena"] = True
            if explain:
                # [n, F+1] (or [n, K*(F+1)] multiclass); the last column
                # per class block is the expected value, like
                # predict_contrib
                body["contributions"] = np.asarray(pred).tolist()
                body["num_features"] = int(sess.num_features)
                body["num_class"] = int(sess.num_tpi)
            else:
                body["predictions"] = np.asarray(pred).tolist()
            self._reply(200, body)
        except ServeOverloadError as exc:
            self._reply(503, {"error": "overloaded", "detail": str(exc),
                              "priority": getattr(exc, "priority",
                                                  "normal"),
                              "shed": bool(getattr(exc, "shed", False))},
                        headers=self._shed_headers())
        except (DeadlineExceeded, _FutureTimeout) as exc:
            self._reply(504, {"error": "deadline_exceeded",
                              "detail": str(exc)})
        except (ValueError, TypeError, KeyError) as exc:
            self._reply(400, {"error": "bad_request", "detail": str(exc)})
        except Exception as exc:  # noqa: BLE001 — HTTP thread must reply
            self._reply(500, {"error": type(exc).__name__,
                              "detail": str(exc)})
        finally:
            if root_id is not None:
                # the request's root span: the whole HTTP handling wall
                # time, parent of the queue/coalesce/pad/execute chain
                obs.emit_span(
                    "explain/request" if explain else "serve/request",
                    self._t0_wall,
                    (time.perf_counter() - t0) * 1e3, self._trace_id,
                    span_id=root_id,
                    attrs={"status": self._status, "path": path})
            self._end()

    def _do_admin(self, name: str, action: str) -> None:
        """POST /models/{name}/swap  body {"model_file": path}
        POST /models/{name}/rollback  body {"reason": "..."} —
        the registry's governed transitions over HTTP.  A canary-gate
        rejection maps to 409 (the flip did not happen; the previous
        version keeps serving)."""
        reg = getattr(self.server, "registry", None)
        if reg is None:
            self._reply(404, {"error": "no_registry",
                              "detail": "server wraps a bare session, "
                              "not a model registry"})
            return
        from .registry import SwapRejected, UnknownModelError
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
            if action == "swap":
                model = (payload.get("model_file")
                         or payload.get("model"))
                if not model:
                    raise ValueError("swap body needs 'model_file'")
                arena = getattr(reg, "arena", None)
                registered = name in [m["name"] for m in reg.models()]
                if (arena is not None and not registered
                        and (arena.has(name) or payload.get("arena"))):
                    # arena tenant hot-swap (or first admit with
                    # {"arena": true}) — canary-gated inside the arena;
                    # a parity failure rolls back and maps to 409 below
                    try:
                        report = arena.swap(name, model)
                    except (RuntimeError, ValueError) as exc:
                        self._reply(409, {"error": "swap_rejected",
                                          "detail": str(exc),
                                          "arena": True})
                        return
                    self._reply(200, report)
                    return
                report = (reg.swap(name, model) if registered
                          else reg.add_model(name, model))
                self._reply(200, report)
            else:  # rollback
                report = reg.rollback(
                    name, reason=str(payload.get("reason") or "manual"))
                self._reply(200, report)
        except SwapRejected as exc:
            self._reply(409, {"error": "swap_rejected",
                              "detail": str(exc),
                              "report": exc.report})
        except UnknownModelError:
            self._reply(404, {"error": "unknown_model", "model": name})
        except (ValueError, TypeError, KeyError) as exc:
            self._reply(400, {"error": "bad_request", "detail": str(exc)})
        except RuntimeError as exc:
            # rollback without a resident previous version
            self._reply(409, {"error": "conflict", "detail": str(exc)})
        except Exception as exc:  # noqa: BLE001 — HTTP thread must reply
            self._reply(500, {"error": type(exc).__name__,
                              "detail": str(exc)})


class _FleetHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer whose ``session`` resolves through the model
    registry at ACCESS time (so /health, /metrics and the status
    counters always describe the CURRENT live version after a swap),
    falling back to the bare session the server was built with."""

    registry = None
    bare_session = None
    retry_after_s = 1.0

    @property
    def session(self):
        if self.registry is not None:
            return self.registry.resolve(None).router
        return self.bare_session


class PredictServer:
    """Threaded HTTP server wrapping one serving target; ``port=0``
    binds an ephemeral port (read it back from ``.port``).

    The target may be a bare ``PredictorSession`` (the original
    single-model surface), a ``ReplicaRouter``, or a ``ModelRegistry``
    — a registry additionally arms the fleet endpoints (``GET /models``,
    ``POST /models/{name}/swap`` and ``/models/{name}/rollback``,
    per-model ``/health`` blocks, ``model``/``version`` echo on every
    prediction)."""

    def __init__(self, target, host: str = "127.0.0.1", port: int = 0):
        is_registry = (hasattr(target, "resolve")
                       and hasattr(target, "swap"))
        self.registry = target if is_registry else None
        self._httpd = _FleetHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        if is_registry:
            self._httpd.registry = target
            cfg = getattr(target, "config", None)
            self._httpd.retry_after_s = float(
                getattr(cfg, "tpu_serve_retry_after_s", 1.0) or 1.0)
        else:
            self._httpd.bare_session = target
            cfg = getattr(target, "config", None)
            if not isinstance(cfg, dict):
                self._httpd.retry_after_s = float(
                    getattr(cfg, "tpu_serve_retry_after_s", 1.0) or 1.0)
        self._thread = None

    @property
    def session(self):
        """The current serving target (post-swap: the NEW live router)."""
        return self._httpd.session

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "PredictServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="lgbm-serve-http",
            daemon=True)
        self._thread.start()
        log.info("serving %d trees on %s (POST /predict%s, GET /health "
                 "/metrics /stats /debug/flight)",
                 self.session.num_trees, self.url,
                 " /explain" if getattr(self.session, "explain_enabled",
                                        False) else "")
        return self

    def stop(self, close_session: bool = False) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        if close_session:
            # a registry owns (and closes) every resident version; a
            # bare session/router closes itself
            (self.registry if self.registry is not None
             else self.session).close()

    def serve_forever(self) -> None:
        """Blocking CLI entry: run until interrupted, then drain the
        session's queue before exiting (graceful shutdown)."""
        self.start()
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            log.info("serve: interrupt — draining and shutting down")
        finally:
            self.stop(close_session=True)

    def __enter__(self) -> "PredictServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(close_session=True)
