"""Threaded JSON-over-HTTP front end for a ``PredictorSession``.

Stdlib ``http.server`` only — no new dependencies.  Protocol:

    POST /predict   body {"rows": [[...], ...], "raw_score": false,
                          "deadline_ms": 250}
                 -> 200 {"predictions": [...], "rows": N,
                         "latency_ms": ...}
    GET  /health -> 200 {"status": "ok"|"degraded", ...session stats...}

Error mapping (all JSON bodies with an ``error`` field):

- 400 malformed body / wrong feature count
- 503 queue full (``ServeOverloadError`` — explicit backpressure; shed
  or retry elsewhere, the server never buffers unboundedly)
- 504 deadline exceeded in queue, or the reply wait timed out
- 500 anything else

When the device backend dies mid-flight the SESSION degrades to the
host numpy predictor (serve/session.py) — requests keep succeeding and
``/health`` flips to ``"degraded"`` so a load balancer can drain the
replica gracefully instead of seeing a wall of 500s.
"""
from __future__ import annotations

import json
import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .. import obs
from ..utils import log
from .batcher import DeadlineExceeded, ServeOverloadError

# grace added to a request's own deadline before the HTTP thread gives
# up waiting on the batcher (the batch may be mid-flight on the device)
_REPLY_GRACE_S = 30.0
_DEFAULT_REPLY_TIMEOUT_S = 120.0


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # stay quiet on stderr; the obs serve_* event stream is the record
    def log_message(self, fmt, *args):  # noqa: A002
        pass

    def _reply(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 — http.server API
        if self.path.split("?")[0].rstrip("/") in ("", "/health"):
            sess = self.server.session
            st = sess.stats()
            st["status"] = "degraded" if st.get("degraded") else "ok"
            st["health_mode"] = obs.health_mode() or "off"
            self._reply(200, st)
        else:
            self._reply(404, {"error": "not_found", "path": self.path})

    def do_POST(self):  # noqa: N802 — http.server API
        if self.path.split("?")[0].rstrip("/") != "/predict":
            self._reply(404, {"error": "not_found", "path": self.path})
            return
        sess = self.server.session
        t0 = time.perf_counter()
        try:
            length = int(self.headers.get("Content-Length", 0) or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
            rows = payload.get("rows")
            if rows is None:
                raise ValueError("body needs a 'rows' matrix")
            X = np.asarray(rows, dtype=np.float64)
            deadline_ms = payload.get("deadline_ms")
            ticket = sess.submit(X, deadline_ms=deadline_ms,
                                 raw_score=bool(payload.get("raw_score")))
            wait_s = (float(deadline_ms) / 1e3 + _REPLY_GRACE_S
                      if deadline_ms is not None
                      else _DEFAULT_REPLY_TIMEOUT_S)
            pred = sess.result(ticket, timeout=wait_s)
            self._reply(200, {
                "predictions": np.asarray(pred).tolist(),
                "rows": int(ticket.rows),
                "latency_ms": round((time.perf_counter() - t0) * 1e3, 3),
            })
        except ServeOverloadError as exc:
            self._reply(503, {"error": "overloaded", "detail": str(exc)})
        except (DeadlineExceeded, _FutureTimeout) as exc:
            self._reply(504, {"error": "deadline_exceeded",
                              "detail": str(exc)})
        except (ValueError, TypeError, KeyError) as exc:
            self._reply(400, {"error": "bad_request", "detail": str(exc)})
        except Exception as exc:  # noqa: BLE001 — HTTP thread must reply
            self._reply(500, {"error": type(exc).__name__,
                              "detail": str(exc)})


class PredictServer:
    """Threaded HTTP server wrapping one session; ``port=0`` binds an
    ephemeral port (read it back from ``.port`` after construction)."""

    def __init__(self, session, host: str = "127.0.0.1", port: int = 0):
        self.session = session
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.session = session
        self._thread = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "PredictServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="lgbm-serve-http",
            daemon=True)
        self._thread.start()
        log.info("serving %d trees on %s (POST /predict, GET /health)",
                 self.session.num_trees, self.url)
        return self

    def stop(self, close_session: bool = False) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        if close_session:
            self.session.close()

    def serve_forever(self) -> None:
        """Blocking CLI entry: run until interrupted, then drain the
        session's queue before exiting (graceful shutdown)."""
        self.start()
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            log.info("serve: interrupt — draining and shutting down")
        finally:
            self.stop(close_session=True)

    def __enter__(self) -> "PredictServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop(close_session=True)
