"""Model-derived bin space: device prediction without training state.

The fast device predictor (core/forest.py) traverses trees in BIN space,
which training gets for free from the dataset's ``BinMapper``s.  A model
loaded from disk carries no mappers — only value-space thresholds and
category bitsets — so serving rebuilds a bin space from the forest
itself:

- **numerical** features: the sorted distinct thresholds the window's
  trees split on become the bin upper bounds
  (``io.binning.BinMapper.from_thresholds``).  A node with threshold
  ``thr`` gets ``threshold_bin = value_to_bin(thr)`` and the bin-space
  compare ``col <= threshold_bin`` is exactly the host's ``v <= thr`` —
  the serving bins quantize the DECISIONS, not the data, so parity is
  structural, not approximate.
- **categorical** features: the category value itself is the bin, so the
  model's value-space bitsets (``Tree.cat_threshold``) are already
  bin-space bitsets.  NaN / negative / out-of-range categories map to a
  sentinel bin whose bitset word is zero-padded, routing right exactly
  like the reference's CategoricalDecision (tree.h:262-303).

This is shared by ``serve.session.PredictorSession`` (the serving
engine) and ``boosting.gbdt.PredictorBase`` (the device fast path for
``Booster(model_file=...)``).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.meta import DeviceMeta
from ..io.binning import MISSING_NONE, BinMapper


def collect_split_state(models, num_features: int,
                        want_cats: bool = False):
    """Walk a forest once and gather everything a model-derived bin
    space needs, per ORIGINAL feature: the numerical split thresholds,
    the worst missing type, the categorical flag, and the widest node
    bitset word count.

    Shared by :class:`ServeBinSpace` (serving-side bin space) and
    ``online/binspace.py`` (the train-continue path).  Only the latter
    needs the SET of category values the bitsets reference (to rebuild
    TRAINING categorical mappers) — ``want_cats=True`` decodes the
    bitsets bit by bit; the default keeps the serving-side rebuild at
    its original cost (word counts only, empty sets returned).
    Returns ``(thr_vals, miss, is_cat, cats, words)``."""
    F = int(num_features)
    thr_vals: List[List[float]] = [[] for _ in range(F)]
    miss = np.zeros(F, np.int32)
    is_cat = np.zeros(F, bool)
    cats = [set() for _ in range(F)]
    words = 0
    for tree in models:
        nn = max(tree.num_leaves - 1, 0)
        for i in range(nn):
            f = int(tree.split_feature[i])
            if f < 0 or f >= F:
                raise ValueError(
                    f"model splits on feature {f} outside the declared "
                    f"feature space [0, {F})")
            if tree.is_categorical(i):
                is_cat[f] = True
                ci = int(tree.threshold[i])
                lo = int(tree.cat_boundaries[ci])
                hi = int(tree.cat_boundaries[ci + 1])
                words = max(words, hi - lo)
                if want_cats:
                    for w in range(hi - lo):
                        bits = int(tree.cat_threshold[lo + w])
                        while bits:
                            b = bits & -bits
                            cats[f].add(w * 32 + b.bit_length() - 1)
                            bits ^= b
            else:
                thr_vals[f].append(float(tree.threshold[i]))
                miss[f] = max(miss[f], tree.missing_type(i))
    return thr_vals, miss, is_cat, cats, words


class ServeBinSpace:
    """Per-feature value->bin mapping + ``DeviceMeta`` rebuilt from the
    forest's own split state (no dataset required)."""

    def __init__(self, models, num_features: int):
        F = max(int(num_features), 1)
        self.num_features = F
        thr_vals, miss, is_cat, _, words = collect_split_state(models, F)

        # one zero word past the widest node bitset: the sentinel bin's
        # word gathers 0, so unseen/NaN categories route right everywhere
        self.cat_words = max(words, 1)
        self.min_words = self.cat_words + 1
        self.sentinel = self.cat_words * 32

        self.mappers: List[Optional[BinMapper]] = [None] * F
        num_bins = np.ones(F, np.int32)
        default_bins = np.zeros(F, np.int32)
        for f in range(F):
            if is_cat[f]:
                num_bins[f] = self.sentinel + 1
            elif thr_vals[f]:
                m = BinMapper.from_thresholds(thr_vals[f], int(miss[f]))
                self.mappers[f] = m
                num_bins[f] = m.num_bin
                default_bins[f] = m.default_bin
        self._num_bins = num_bins
        self._default_bins = default_bins
        self._missing = miss
        self._is_cat = is_cat

        import jax.numpy as jnp
        self.meta = DeviceMeta(
            num_bins=jnp.asarray(num_bins),
            default_bins=jnp.asarray(default_bins),
            missing_types=jnp.asarray(miss),
            monotone=jnp.asarray(np.zeros(F, np.int32)),
            penalties=jnp.asarray(np.ones(F, np.float32)),
            is_categorical=jnp.asarray(is_cat),
            feat2phys=jnp.asarray(np.arange(F, dtype=np.int32)),
            feat_offset=jnp.asarray(np.zeros(F, np.int32)),
            needs_fix=jnp.asarray(np.zeros(F, bool)),
        )

    # ------------------------------------------------------------------
    def bin_matrix(self, X: np.ndarray) -> np.ndarray:
        """Bin raw float rows into this serving space: [N, F] i32.

        Features no tree splits on are never read by the traversal, so
        their columns stay zero — binning cost scales with the USED
        feature set, not the input width."""
        X = np.ascontiguousarray(X, dtype=np.float64)
        if X.ndim != 2 or X.shape[1] < self.num_features:
            raise ValueError(
                f"serve input has {X.shape[-1] if X.ndim else 0} features, "
                f"model needs {self.num_features}")
        out = np.zeros((X.shape[0], self.num_features), dtype=np.int32)
        for f in range(self.num_features):
            if self._is_cat[f]:
                col = X[:, f]
                # the reference casts to int and sends NaN/negatives right
                # before missing handling (tree.h:262-265); out-of-range
                # categories can't be in any node bitset either, so both
                # collapse to the zero-word sentinel
                v = np.where(np.isnan(col) | (col < 0), -1.0, col)
                iv = v.astype(np.int64)
                out[:, f] = np.where((iv < 0) | (iv >= self.sentinel),
                                     self.sentinel, iv).astype(np.int32)
            elif self.mappers[f] is not None:
                out[:, f] = self.mappers[f].value_to_bin(X[:, f])
        return out

    # ------------------------------------------------------------------
    def tree_arrays_np(self, tree, with_counts: bool = False) -> dict:
        """Bin-space numpy arrays for one value-space host ``Tree`` — the
        unit ``core.forest.stack_forest`` batches (the serving analog of
        ``GBDT._tree_arrays_np``, which needs a live train_ds).

        ``with_counts`` adds the per-node data-cover counts the explain/
        TreeSHAP path needs — model.txt carries them
        (``internal_count=``/``leaf_count=`` lines), so file-loaded
        serving sessions can explain without training state."""
        nl = tree.num_leaves
        nn = max(nl - 1, 0)
        sf = np.asarray(tree.split_feature[:nn], np.int32)
        thr_bin = np.zeros(nn, np.int32)
        dl = np.zeros(nn, bool)
        cat_bits = np.zeros((max(nn, 1), self.cat_words), np.uint32)
        for i in range(nn):
            if tree.is_categorical(i):
                ci = int(tree.threshold[i])
                lo = int(tree.cat_boundaries[ci])
                hi = int(tree.cat_boundaries[ci + 1])
                cat_bits[i, :hi - lo] = tree.cat_threshold[lo:hi]
            else:
                m = self.mappers[int(sf[i])]
                thr_bin[i] = int(m.value_to_bin(float(tree.threshold[i])))
                dl[i] = tree.default_left(i)
        out = dict(
            split_feature=sf,
            threshold_bin=thr_bin,
            default_left=dl,
            left_child=np.asarray(tree.left_child[:nn], np.int32),
            right_child=np.asarray(tree.right_child[:nn], np.int32),
            leaf_value=np.asarray(tree.leaf_value[:nl], np.float32),
            num_leaves=np.int32(nl),
            cat_bitset=cat_bits[:nn] if nn else cat_bits[:0],
        )
        if with_counts:
            out["internal_count"] = \
                np.asarray(tree.internal_count[:nn], np.int32)
            out["leaf_count"] = np.asarray(tree.leaf_count[:nl], np.int32)
        return out

    def pack(self, trees, class_ids: np.ndarray,
             with_counts: bool = False, model_ids=None):
        """Stack a tree window into one device-ready ``ForestArrays``.
        ``model_ids`` ([T] i32) stamps the per-tree tenant lane when this
        space packs a multi-tenant arena (serve/arena.py)."""
        from ..core.forest import stack_forest
        return stack_forest([self.tree_arrays_np(t, with_counts=with_counts)
                             for t in trees],
                            np.asarray(class_ids, np.int32),
                            min_words=self.min_words,
                            with_counts=with_counts,
                            model_ids=model_ids)
