"""Analytical cost model for the device TreeSHAP kernel.

``explain/kernel.py`` evaluates, per tree, four dense stages over the
[N rows, L leaves, P path slots] block: the one-fraction merge, EXTEND,
UNWIND (each a P-step scan of elementwise ops over the block) and the
contribution scatter.  ``shap_cost`` is the hand-written roofline for
that work — the ``wave_kernel_cost``/``partition_cost`` sibling for the
explain plane, so profile mode (``lgbm/forest_shap``) and
``docs/ROOFLINE.md`` quote the same numbers.

The op constants are empirical tallies of the emitted elementwise ops
per block cell per scan step, not derivations — the same contract as
``split_scan_cost``.
"""
from __future__ import annotations

# elementwise ops per [N, L, P] cell per scan step, by stage: the
# AND-fold merge, the closed-form EXTEND update (2 mul + 2 fma + div),
# and the branchy UNWIND step
_MERGE_OPS = 3.0
_EXTEND_OPS = 7.0
_UNWIND_OPS = 10.0
_SCATTER_OPS = 4.0   # contrib product + scatter-add, once per cell


def shap_cost(N: int, T: int, L: int, P: int, F: int, K: int = 1):
    """Analytical (FLOPs, HBM bytes) of ``forest_shap_fn`` over ``N``
    rows, ``T`` trees of <= ``L`` leaves and path depth <= ``P``,
    emitting [N, K, F+1] contributions.

    FLOPs: the three P-step scans each touch the [N, L, P] block per
    step (O(N L P^2) per tree — path decomposition recomputes shared
    path prefixes, the price of exposing row x leaf parallelism), plus
    the per-node decision pass and the scatter.  Bytes: the bins matrix
    read once per tree scan step, the per-tree path metadata, and the
    [N, K, F+1] accumulator round-trip per tree (the scan carries it in
    registers/VMEM on TPU, but the model charges the conservative HBM
    leg like the other cost models)."""
    N, T, L, P, F, K = (float(N), float(T), float(L), float(P), float(F),
                        float(K))
    block = N * L * P
    scans = (_MERGE_OPS + _EXTEND_OPS + _UNWIND_OPS) * block * P
    decisions = 12.0 * N * max(L - 1.0, 1.0)   # split_decision op tally
    flops = T * (scans + decisions + _SCATTER_OPS * block)
    meta_bytes = L * P * (4 + 1 + 4 + 4 + 4)   # path/slot arrays per tree
    nbytes = T * (N * F * 4.0          # bins re-read per scan step
                  + meta_bytes
                  + 2.0 * N * K * (F + 1.0) * 4.0)   # phi read+write
    return flops, nbytes
