"""Analytical cost models for the ranking plane.

``objective/rank.py`` evaluates a dense [qc, P, P] sigmoid pair tensor
per padded query chunk (the device form of GetGradientsForOneQuery) and
``metric/rank.py`` sorts + cumsums [Q, P] blocks per eval round (the
device form of the dcg_calculator loop).  ``rank_pair_cost`` /
``ndcg_eval_cost`` are the hand-written rooflines for that work — the
``wave_kernel_cost``/``partition_cost``/``shap_cost`` siblings for the
ranking plane, so ``docs/ROOFLINE.md``'s "Ranking plane" section and
the tests quote the same numbers.

The op constants are empirical tallies of the emitted elementwise ops,
not derivations — the same contract as ``split_scan_cost``.  Costs are
in terms of the PADDED bucket geometry (``bucket_shapes``): padding is
real VPU work the pow2 scheme pays for static shapes, so the model
charges it.
"""
from __future__ import annotations

import numpy as np

from ..core.query import CHUNK_ELEMS, bucket_shapes  # noqa: F401 — the
# geometry helper is re-exported here so cost-model callers read it
# beside the models; core/query.py owns the single implementation the
# block builder itself materializes

# elementwise ops per [P, P] pair slot: score/gain/discount gaps (3),
# delta product + inv scale (2), norm gate + divide (3), sigmoid
# (exp ~6 + 2), lambda/hessian products (6), validity mask fold (2)
_PAIR_SLOT_OPS = 24.0
# per element per log2(P) step of a device sort network (compare +
# select on key/index lanes); the pair pass pays it twice (rank
# positions need sort + inverse), the NDCG kernel once + a gather
_SORT_OPS = 8.0
# per sorted element of the NDCG kernel: gain gather, discount
# multiply, cumsum add, plus slack for the per-k gathers
_NDCG_ELEM_OPS = 4.0


def mslr_like_sizes(rows: int, rng=None) -> np.ndarray:
    """MSLR-WEB30K-shaped ragged query sizes: lognormal(3.8, 1.0)
    clamped to [1, 1251] docs (mean ~72), totalling ``rows``.  The
    SAME generator bench.py's rank legs draw from, so the ROOFLINE
    numbers and the bench shape agree by construction."""
    if rng is None:
        rng = np.random.default_rng(0)
    out = []
    total = 0
    while total < rows:
        s = int(min(max(1, rng.lognormal(3.8, 1.0)), 1251))
        s = min(s, rows - total)
        out.append(s)
        total += s
    return np.asarray(out, dtype=np.int64)


def rank_pair_cost(sizes, chunk_elems: int = CHUNK_ELEMS):
    """Analytical (FLOPs, HBM bytes) of ONE lambdarank gradient pass
    (``pair_lambdas``) over the padded query buckets for ``sizes``.

    FLOPs: the [qc, P, P] pair tensor per chunk (O(sum Qp * P^2) — the
    pow2 padding's quadratic price is charged, which is why MIN_PAD
    stays small) plus the two stable argsorts per block.  Bytes: the
    static block tensors (idx/labs/gains + inv) read once, the score
    gather, and the g/h scatter read-modify-write; the pair tensor
    itself lives in VMEM (``lax.map`` chunking bounds it) and is not
    charged to HBM."""
    flops = 0.0
    nbytes = 0.0
    for P, Qp, _qc in bucket_shapes(sizes, chunk_elems):
        flops += Qp * P * P * _PAIR_SLOT_OPS
        flops += 2.0 * Qp * P * np.log2(P) * _SORT_OPS
        nbytes += Qp * P * (12.0    # idx + labs + gains
                            + 4.0   # score gather
                            + 16.0)  # g/h scatter read-modify-write
        nbytes += Qp * 4.0          # inverse max DCG
    return flops, nbytes


def ndcg_eval_cost(sizes, num_at: int = 1,
                   chunk_elems: int = CHUNK_ELEMS):
    """Analytical (FLOPs, HBM bytes) of ONE device NDCG@k eval
    (``metric/rank.py _ndcg_device_fn``) over the padded query buckets:
    one stable sort + gain-discount cumsum per block, ``num_at`` DCG
    gathers + fma per query.  Bytes: idx/gains + score gather + the
    per-k lookup tables; the [len(eval_at)] result is the ONLY thing
    that leaves the device (vs the [N] score copy + per-query host
    loop of the oracle path)."""
    num_at = max(int(num_at), 1)
    flops = 0.0
    nbytes = 0.0
    for P, Qp, _qc in bucket_shapes(sizes, chunk_elems):
        flops += Qp * P * (np.log2(P) * _SORT_OPS + _NDCG_ELEM_OPS)
        flops += Qp * num_at * 2.0
        nbytes += Qp * P * (8.0 + 4.0)   # idx + gains + score gather
        nbytes += Qp * (num_at * 12.0 + 4.0)  # k tables + query weight
    return flops, nbytes
