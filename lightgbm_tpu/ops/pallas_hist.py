"""Pallas TPU histogram kernel — the ConstructHistogram replacement.

The reference's hottest loop gathers bins and accumulates (g, h, count)
per bin with scalar code (reference: src/io/dense_bin.hpp:71-135) or
workgroup atomics (reference: src/treelearner/ocl/histogram256.cl:350).
TPUs have no fast scatter, so this kernel turns accumulation into MXU
matmuls with the one-hot factor built directly in VMEM — it never touches
HBM, unlike the XLA fallback in core/histogram.py which materializes
one-hot tiles.

Channel packing: the MXU processes 128 output lanes per pass regardless of
how many are used, so the kernel accumulates ``C=128`` weight channels at
once.  Two channel layouts exist:

* ``packed`` (the default fast path): each leaf owns a LANE PAIR (g*m,
  h*m) — 63 leaves per wave — and the count channel is folded into the
  same accumulation as ONE extra single-pass matmul whose channel matrix
  is the 0/1 membership mask.  The mask is exactly representable in
  bf16 and accumulation is f32, so the folded counts are bit-identical
  to dedicated f32 count lanes while costing one hardware pass instead
  of a third of the lane budget.  Capacity 42 -> 63 leaves per launch
  means ~1.5x fewer kernel launches (and full bins-array reads) per
  tree.
* ``triple`` (the differential oracle): (g*m, h*m, m) triples for up to
  42 leaf masks — the original layout, kept for packed-vs-triple
  differential testing and for the mixed-width XLA side-pass, which
  speaks this layout.

Sibling fusion: with a ``parent`` operand the kernel also emits
parent-minus-child sibling histograms from the same ``pallas_call`` —
the parent block is read into VMEM once per feature block and the
sibling written on the final row step, eliminating the separate XLA
subtraction pass and its extra [F, B, C] HBM round-trip per wave
(reference: serial_tree_learner.cpp:567 subtracts the smaller child
from the parent the same way).

Data layout: bins are FEATURE-MAJOR ``[F, N]`` uint8 (the TPU-native
resident layout — per-feature column access is a contiguous row slice, and
the uint8 32-sublane tile constraint lands on the feature axis).

Per grid step (j=feature block, i=row block):
  bins block  [FB, BR]   uint8
  gh block    [BR, C]    f32 (pre-masked channels)
  out block   [FB, B, C] f32, accumulated across the i sweep
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# channel capacity: one MXU lane pass
C_MAX = 128
_DEF_BR = 1024
_DEF_FB = 32  # uint8 sublane tile
# wave capacity per layout: triple = 3 lanes/leaf; packed = a lane pair
# per leaf with the top pair left free (63, matching the max_bin=63
# economics the docs quote) so the count-lane map keeps a dead sentinel
P_MAX_TRIPLE = C_MAX // 3       # 42
P_MAX_PACKED = C_MAX // 2 - 1   # 63
# VMEM budget select_wave_blocks fits the per-grid-step blocks into:
# ~16MB physical minus headroom for double buffering + compiler temps
_VMEM_BUDGET = 10 * 2 ** 20


def wave_capacity_max(packed: bool) -> int:
    """Leaves one kernel launch can histogram under the given layout."""
    return P_MAX_PACKED if packed else P_MAX_TRIPLE


def _feat_pack(B: int, FB: int) -> int:
    """Features whose one-hot factors share one MXU pass (B <= 64)."""
    pack = max(1, 128 // B)
    return pack if 128 % B == 0 and FB % pack == 0 else 1

# pallas-tpu renamed TPUCompilerParams -> CompilerParams between the jax
# versions we run on (CPU CI container vs TPU image); take whichever exists
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams")


def _hist_kernel(bins_ref, gh_ref, out_ref, *, B: int, FB: int):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    gh = gh_ref[...]  # [BR, C]
    # bin-width specialization: B <= 64 concatenates 128//B features'
    # one-hot factors into one MXU operand (see _hist_wave_kernel — the
    # wave kernel had this; the channel kernel now shares it)
    pack = _feat_pack(B, FB)
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    for f in range(0, FB, pack):
        if pack == 1:
            eq = bins_ref[f, :].astype(jnp.int32)[:, None] == iota
        else:
            eq = jnp.concatenate(
                [bins_ref[f + p, :].astype(jnp.int32)[:, None] == iota
                 for p in range(pack)], axis=1)           # [BR, pack*B]
        oh = eq.astype(jnp.float32)
        acc = jax.lax.dot_general(
            oh, gh, (((0,), (0,)), ((), ())),             # [pack*B, C]
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)
        if pack == 1:
            out_ref[f] += acc
        else:
            for p in range(pack):
                out_ref[f + p] += acc[p * B:(p + 1) * B]


@functools.partial(jax.jit, static_argnames=("B", "block_rows", "feat_block"))
@jax.named_scope("lgbm/pallas_hist")
def hist_pallas_channels(bins_fm, gh, B: int, block_rows: int = _DEF_BR,
                         feat_block: int = _DEF_FB):
    """Multi-channel histogram: bins_fm [F, N] uint8, gh [N, C] f32 ->
    [F, B, C] f32 with out[f, b, c] = sum_r gh[r, c] * (bins_fm[f, r] == b)."""
    F, N = bins_fm.shape
    C = gh.shape[1]
    assert C % 128 == 0, f"channel dim must be a multiple of 128, got {C}"
    BR = min(block_rows, max(128, N))
    FB = min(feat_block, max(F, 1))
    pad_rows = (-N) % BR
    if pad_rows:
        # padded rows get bin 0 but zero weight in every channel
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad_rows)))
        gh = jnp.pad(gh, ((0, pad_rows), (0, 0)))
    pad_f = (-F) % FB
    if pad_f:
        bins_fm = jnp.pad(bins_fm, ((0, pad_f), (0, 0)))
    Fp, Np = bins_fm.shape

    grid = (Fp // FB, Np // BR)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, B=B, FB=FB),
        grid=grid,
        in_specs=[
            pl.BlockSpec((FB, BR), lambda j, i: (j, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BR, C), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((FB, B, C), lambda j, i: (j, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Fp, B, C), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(bins_fm, gh)
    return out[:F]


def _hist_wave_kernel(*refs, B: int, FB: int, mode: str, packed: bool,
                      fused: bool):
    """Multi-leaf histogram step: the per-leaf channel matrix is built in
    VMEM from leaf_id + the slot->leaf map, never touching HBM.

    ``mode`` selects the matmul precision/throughput trade:
      "highest" — f32 operands at Precision.HIGHEST (~3 MXU passes);
      "2xbf16"  — hi/lo bf16 split of the channel matrix, 2 MXU passes:
                  the one-hot operand is exactly representable in bf16 and
                  accumulation is always f32, so only g/h are rounded — to
                  ~16 mantissa bits, tighter than one bf16 pass and ~1.5x
                  faster than "highest";
      "bf16"    — single bf16 pass (~8 mantissa bits on g/h).

    ``packed`` selects the channel layout: lane pairs (g, h) per leaf with
    the count channel folded into one extra single-pass matmul (63 leaves)
    vs (g, h, count) lane triples (42 leaves).  The folded count pass runs
    in bf16 in EVERY mode — the membership weights are the 0/1 bag mask,
    exact in bf16, and accumulation is f32, so folded counts are
    bit-identical to dedicated count lanes at any precision mode.

    ``fused`` adds parent blocks as inputs and sibling blocks as outputs:
    on the final row step (the accumulators now hold the full child
    histograms for this feature block) the sibling is written as
    parent - child straight from VMEM."""
    n_out = 2 if packed else 1
    n_par = n_out if fused else 0
    bins_ref, vecs_ref, slot_ref = refs[:3]
    par_refs = refs[3:3 + n_par]
    acc_refs = refs[3 + n_par:3 + n_par + n_out]
    sib_refs = refs[3 + n_par + n_out:]

    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        for r in acc_refs:
            r[...] = jnp.zeros_like(r)

    vecs = vecs_ref[...]                                  # [BR, 4]
    leaf = vecs[:, 3].astype(jnp.int32)                   # [BR]
    slot_leaf = slot_ref[0, :].astype(jnp.int32)          # [C]
    lanes = 2 if packed else 3
    kind = jax.lax.broadcasted_iota(jnp.int32, (1, C_MAX), 1) % lanes
    m = (leaf[:, None] == slot_leaf[None, :]) & (slot_leaf >= 0)[None, :]
    if packed:
        vals = jnp.where(kind == 0, vecs[:, 0][:, None], vecs[:, 1][:, None])
        slot_ct = slot_ref[1, :].astype(jnp.int32)        # [C] count lanes
        mc = (leaf[:, None] == slot_ct[None, :]) & (slot_ct >= 0)[None, :]
        ct_b = jnp.where(mc, vecs[:, 2][:, None], 0.0).astype(jnp.bfloat16)
    else:
        vals = jnp.where(kind == 0, vecs[:, 0][:, None],
                         jnp.where(kind == 1, vecs[:, 1][:, None],
                                   vecs[:, 2][:, None]))
    gh = jnp.where(m, vals, 0.0)                          # [BR, C]
    if mode == "2xbf16":
        gh_hi = gh.astype(jnp.bfloat16)
        gh_lo = (gh - gh_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    elif mode == "bf16":
        gh_b = gh.astype(jnp.bfloat16)

    # Feature packing: with B <= 64 a single feature's one-hot only spans B
    # of the MXU's 128 output rows — concatenating ``pack`` features' one-hot
    # factors into one [BR, pack*B] operand fills the systolic array, so a
    # max_bin=63 run really is ~4x cheaper than max_bin=255 (the reference's
    # GPU backend has the same bins-per-workgroup economics and recommends
    # 63 bins, docs/GPU-Performance.rst:128-130).
    pack = _feat_pack(B, FB)
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    dims = (((0,), (0,)), ((), ()))
    for f in range(0, FB, pack):
        if pack == 1:
            eq = bins_ref[f, :].astype(jnp.int32)[:, None] == iota
        else:
            eq = jnp.concatenate(
                [bins_ref[f + p, :].astype(jnp.int32)[:, None] == iota
                 for p in range(pack)], axis=1)        # [BR, pack*B]
        if mode == "highest":
            oh = eq.astype(jnp.float32)
            acc = jax.lax.dot_general(
                oh, gh, dims,
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32)
        elif mode == "2xbf16":
            oh = eq.astype(jnp.bfloat16)
            acc = (jax.lax.dot_general(
                       oh, gh_hi, dims,
                       preferred_element_type=jnp.float32)
                   + jax.lax.dot_general(
                       oh, gh_lo, dims,
                       preferred_element_type=jnp.float32))
        else:
            oh = eq.astype(jnp.bfloat16)
            acc = jax.lax.dot_general(
                oh, gh_b, dims,
                preferred_element_type=jnp.float32)
        if packed:
            acc_ct = jax.lax.dot_general(
                eq.astype(jnp.bfloat16), ct_b, dims,
                preferred_element_type=jnp.float32)
        if pack == 1:
            acc_refs[0][f] += acc
            if packed:
                acc_refs[1][f] += acc_ct
        else:
            for p in range(pack):
                acc_refs[0][f + p] += acc[p * B:(p + 1) * B]
                if packed:
                    acc_refs[1][f + p] += acc_ct[p * B:(p + 1) * B]

    if fused:
        # final row step: accumulators hold the complete child histograms
        # for this feature block — emit the sibling without the child
        # ever round-tripping through HBM
        @pl.when(i == pl.num_programs(1) - 1)
        def _sibling():
            for par, accr, sibr in zip(par_refs, acc_refs, sib_refs):
                sibr[...] = par[...] - accr[...]


def _resolve_mode(highest) -> str:
    """Back-compat: bool True -> "highest", False -> "bf16"; strings pass
    through ("highest" | "2xbf16" | "bf16")."""
    if isinstance(highest, str):
        assert highest in ("highest", "2xbf16", "bf16"), highest
        return highest
    return "highest" if highest else "bf16"


# MXU passes per precision mode (see _hist_wave_kernel)
WAVE_MXU_PASSES = {"highest": 3, "2xbf16": 2, "bf16": 1}


def wave_kernel_cost(rows, F: int, B: int, mode="2xbf16",
                     feat_block: int = _DEF_FB, waves: int = 1,
                     packed: bool = False, fused: bool = False):
    """Analytical (FLOPs, HBM bytes) of ``hist_pallas_wave`` over ``rows``
    total rows across ``waves`` kernel launches — ``docs/ROOFLINE.md``'s
    hand-written cost model in code, so profile mode and
    ``tools/prof_kernels.py`` compare measured kernel time against the
    same numbers the doc quotes.

    FLOPs are what the MXU is CHARGED, not useful work: the one-hot
    operand is 255/256 zeros but every lane is paid for.  Mirrors the
    kernel's feature packing (B <= 64 packs 128//B features per matmul);
    an unpacked B < 128 operand still occupies one full 128-lane group.
    ``packed`` charges the folded count as one extra hardware pass on
    top of the mode's g/h passes (the lane-pair layout fits 63 leaves
    where triples fit 42, so per-LEAF MXU cost is unchanged — the win is
    1.5x fewer launches, i.e. fewer ``waves`` and fewer bins reads).
    Bytes count the HBM legs only — bins + packed [N, 4] vectors read
    once per ROW, the histogram outputs written once per LAUNCH (hence
    ``waves``; two output arrays when packed); ``fused`` adds the parent
    read and sibling write per launch, and is what REPLACES the separate
    XLA subtraction pass (which paid the same parent/sibling legs PLUS a
    re-read of the child).  The one-hot factor lives in VMEM and never
    touches HBM.  ``rows`` is the tier-compacted total (the wave
    grower's ``report_waves`` stats carry exactly this figure).
    """
    mode = _resolve_mode(mode)
    passes = WAVE_MXU_PASSES[mode] + (1 if packed else 0)
    pack = _feat_pack(B, feat_block)
    lanes = max(pack * B, C_MAX) / pack      # charged output rows / feature
    flops = passes * 2.0 * float(rows) * F * lanes * C_MAX
    hist_bytes = F * B * C_MAX * 4
    n_out = 2 if packed else 1
    per_launch = hist_bytes * n_out          # child histogram write(s)
    if fused:
        per_launch += 2 * hist_bytes * n_out  # parent read + sibling write
    nbytes = (float(rows) * (F * 1 + 4 * 4)
              + max(int(waves), 1) * per_launch)
    return flops, nbytes


def select_wave_blocks(B: int, mode="2xbf16", packed: bool = True,
                       fused: bool = True, block_rows: int = _DEF_BR,
                       vmem_budget: int = _VMEM_BUDGET):
    """Cost-model-driven (block_rows, feat_block) for ``hist_pallas_wave``.

    The per-grid-step VMEM residency is dominated by the [FB, B, C] f32
    histogram blocks: 1 (triple) or 2 (packed) accumulators, plus parent
    and sibling blocks of the same shape when fused.  This picks the
    largest feat_block whose blocks + streamed operands fit the budget —
    bin-width specialization in block form: B=64 runs FB=32 fused where
    B=256 must drop to FB=8, and the unfused/triple oracle paths get the
    larger blocks their smaller footprint allows.  ``block_rows`` is
    passed through (row blocking is an HBM-streaming knob, not a VMEM
    one, at these shapes)."""
    mode = _resolve_mode(mode)
    n_out = 2 if packed else 1
    n_big = n_out * (3 if fused else 1)   # acc (+ parent + sibling)
    for FB in (128, 64, 32, 16, 8):
        pack = _feat_pack(B, FB)
        oh_bytes = block_rows * max(pack * B, C_MAX) * \
            (4 if mode == "highest" else 2)
        stream = 2 * (FB * block_rows + block_rows * 4 * 4)  # bins + vecs
        total = FB * B * C_MAX * 4 * n_big + oh_bytes + stream
        if total <= vmem_budget:
            return block_rows, FB
    return block_rows, 8


@functools.partial(jax.jit,
                   static_argnames=("B", "block_rows", "feat_block", "highest",
                                    "interpret", "packed"))
@jax.named_scope("lgbm/pallas_hist_wave")
def hist_pallas_wave(bins_fm, gv, hv, cv, leaf_id, slot_leaf, B: int,
                     block_rows: int = 1024, feat_block: int = _DEF_FB,
                     highest="bf16", interpret: bool = False,
                     packed: bool = False, parent=None):
    """Wave histogram: bins_fm [F, N] uint8; gv/hv/cv f32 [N] (bag-masked
    g, h, ones); leaf_id i32 [N]; slot_leaf i32 [C_MAX] maps channel c to
    a leaf id (-1 = unused).

    Channel layouts (``packed``):
      triple (False) — channel kinds cycle g,h,count; returns
        [F, B, C_MAX] f32 where channels 3s..3s+2 hold leaf
        slot_leaf[3s]'s (sum_g, sum_h, count) histograms.
      packed (True) — channels pair up (g, h) per leaf (slot_leaf[2s] ==
        slot_leaf[2s+1] is leaf s); the count channel is folded into the
        same accumulation as one extra bf16 pass whose lane s carries
        leaf slot_leaf[2s]'s count.  Returns ``(gh, cnt)``: gh [F, B,
        C_MAX] with the lane pairs, cnt [F, B, C_MAX] with counts in the
        first C_MAX//2 lanes.  Exactness: count weights are the 0/1 bag
        mask — exact in bf16 with f32 accumulation, so folded counts
        bit-match dedicated lanes in every precision mode.

    ``parent`` fuses sibling subtraction in-kernel: pass the parent
    histograms in the SAME channel layout as the output ([F, B, C_MAX],
    or the (gh, cnt) pair when packed) and the call returns
    ``(child, sibling)`` with sibling = parent - child written from VMEM
    on the final row step — no separate XLA subtraction pass, no child
    re-read from HBM.

    ``highest``: precision mode — True/"highest", "2xbf16", or
    False/"bf16" (see _hist_wave_kernel)."""
    F, N = bins_fm.shape
    BR = min(block_rows, max(128, N))
    FB = min(feat_block, max(F, 1))
    fused = parent is not None
    par_arrs = (list(parent) if packed else [parent]) if fused else []
    pad_rows = (-N) % BR
    if pad_rows:
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad_rows)))
        gv = jnp.pad(gv, (0, pad_rows))
        hv = jnp.pad(hv, (0, pad_rows))
        cv = jnp.pad(cv, (0, pad_rows))
        leaf_id = jnp.pad(leaf_id, (0, pad_rows), constant_values=-2)
    pad_f = (-F) % FB
    if pad_f:
        bins_fm = jnp.pad(bins_fm, ((0, pad_f), (0, 0)))
        par_arrs = [jnp.pad(pa, ((0, pad_f), (0, 0), (0, 0)))
                    for pa in par_arrs]
    Fp, Np = bins_fm.shape
    mode = _resolve_mode(highest)
    # pack row vectors into one [N, 4] array (g, h, count-weight, leaf_id);
    # leaf ids are exact in f32 up to 2^24
    vecs = jnp.stack([gv, hv, cv, leaf_id.astype(jnp.float32)], axis=1)
    nb = Np // BR

    if packed:
        # second slot row: the count-lane map (lane s -> leaf of pair s)
        half = C_MAX // 2
        slot_ct = jnp.concatenate(
            [slot_leaf[::2],
             jnp.full((C_MAX - half,), -1, slot_leaf.dtype)])
        slot = jnp.stack([slot_leaf, slot_ct])
    else:
        slot = slot_leaf.reshape(1, C_MAX)

    n_out = 2 if packed else 1
    hist_spec = pl.BlockSpec((FB, B, C_MAX), lambda j, i: (j, 0, 0),
                             memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((FB, BR), lambda j, i: (j, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((BR, 4), lambda j, i: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((slot.shape[0], C_MAX), lambda j, i: (0, 0),
                     memory_space=pltpu.VMEM),
    ] + [hist_spec] * len(par_arrs)
    n_res = n_out * (2 if fused else 1)
    grid = (Fp // FB, nb)
    res = pl.pallas_call(
        functools.partial(_hist_wave_kernel, B=B, FB=FB, mode=mode,
                          packed=packed, fused=fused),
        grid=grid,
        in_specs=in_specs,
        out_specs=[hist_spec] * n_res,
        out_shape=[jax.ShapeDtypeStruct((Fp, B, C_MAX), jnp.float32)
                   for _ in range(n_res)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(bins_fm, vecs, slot, *par_arrs)
    res = [r[:F] for r in res]
    child = (res[0], res[1]) if packed else res[0]
    if not fused:
        return child
    sib = (res[2], res[3]) if packed else res[1]
    return child, sib


def hist_pallas_fm(bins_fm, g, h, mask, B: int):
    """Single-leaf histogram from feature-major bins: [F, B, 3] f32."""
    N = bins_fm.shape[1]
    gh = jnp.zeros((N, C_MAX), jnp.float32)
    gh = gh.at[:, 0].set(g * mask)
    gh = gh.at[:, 1].set(h * mask)
    gh = gh.at[:, 2].set(mask)
    out = hist_pallas_channels(bins_fm, gh, B)
    return out[..., :3]


def hist_pallas(bins, g, h, mask, B: int):
    """Drop-in replacement for ``core.histogram.hist_onehot`` (row-major
    bins input; transposes once — prefer hist_pallas_fm for resident data)."""
    return hist_pallas_fm(bins.T, g, h, mask, B)
