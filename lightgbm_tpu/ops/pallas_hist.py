"""Pallas TPU histogram kernel — the ConstructHistogram replacement.

The reference's hottest loop gathers bins and accumulates (g, h, count)
per bin with scalar code (reference: src/io/dense_bin.hpp:71-135) or
workgroup atomics (reference: src/treelearner/ocl/histogram256.cl:350).
TPUs have no fast scatter, so this kernel turns accumulation into MXU
matmuls with the one-hot factor built directly in VMEM — it never touches
HBM, unlike the XLA fallback in core/histogram.py which materializes
one-hot tiles.

Channel packing: the MXU processes 128 output lanes per pass regardless of
how many are used, so the kernel accumulates ``C=128`` weight channels at
once. Callers pack (g*m, h*m, m) triples for up to 42 different leaf masks
into those channels, making one data pass produce 42 leaves' histograms —
this is what makes wave-scheduled leaf growth (core/wave_grower.py) run at
full MXU utilization.

Data layout: bins are FEATURE-MAJOR ``[F, N]`` uint8 (the TPU-native
resident layout — per-feature column access is a contiguous row slice, and
the uint8 32-sublane tile constraint lands on the feature axis).

Per grid step (j=feature block, i=row block):
  bins block  [FB, BR]   uint8
  gh block    [BR, C]    f32 (pre-masked channels)
  out block   [FB, B, C] f32, accumulated across the i sweep
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# channel capacity: one MXU lane pass
C_MAX = 128
_DEF_BR = 1024
_DEF_FB = 32  # uint8 sublane tile

# pallas-tpu renamed TPUCompilerParams -> CompilerParams between the jax
# versions we run on (CPU CI container vs TPU image); take whichever exists
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams")


def _hist_kernel(bins_ref, gh_ref, out_ref, *, B: int, FB: int):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    gh = gh_ref[...]  # [BR, C]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    for f in range(FB):
        col = bins_ref[f, :].astype(jnp.int32)           # [BR]
        oh = (col[:, None] == iota).astype(jnp.float32)  # [BR, B]
        acc = jax.lax.dot_general(
            oh, gh, (((0,), (0,)), ((), ())),            # [B, C]
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)
        out_ref[f] += acc


@functools.partial(jax.jit, static_argnames=("B", "block_rows", "feat_block"))
@jax.named_scope("lgbm/pallas_hist")
def hist_pallas_channels(bins_fm, gh, B: int, block_rows: int = _DEF_BR,
                         feat_block: int = _DEF_FB):
    """Multi-channel histogram: bins_fm [F, N] uint8, gh [N, C] f32 ->
    [F, B, C] f32 with out[f, b, c] = sum_r gh[r, c] * (bins_fm[f, r] == b)."""
    F, N = bins_fm.shape
    C = gh.shape[1]
    assert C % 128 == 0, f"channel dim must be a multiple of 128, got {C}"
    BR = min(block_rows, max(128, N))
    FB = min(feat_block, max(F, 1))
    pad_rows = (-N) % BR
    if pad_rows:
        # padded rows get bin 0 but zero weight in every channel
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad_rows)))
        gh = jnp.pad(gh, ((0, pad_rows), (0, 0)))
    pad_f = (-F) % FB
    if pad_f:
        bins_fm = jnp.pad(bins_fm, ((0, pad_f), (0, 0)))
    Fp, Np = bins_fm.shape

    grid = (Fp // FB, Np // BR)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, B=B, FB=FB),
        grid=grid,
        in_specs=[
            pl.BlockSpec((FB, BR), lambda j, i: (j, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BR, C), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((FB, B, C), lambda j, i: (j, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Fp, B, C), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(bins_fm, gh)
    return out[:F]


def _hist_wave_kernel(bins_ref, vecs_ref, slot_ref, out_ref, *,
                      B: int, FB: int, mode: str):
    """Multi-leaf histogram step: the (g,h,count)x42-leaf channel matrix is
    built in VMEM from leaf_id + the slot->leaf map, never touching HBM.

    ``mode`` selects the matmul precision/throughput trade:
      "highest" — f32 operands at Precision.HIGHEST (~3 MXU passes);
      "2xbf16"  — hi/lo bf16 split of the channel matrix, 2 MXU passes:
                  the one-hot operand is exactly representable in bf16 and
                  accumulation is always f32, so only g/h are rounded — to
                  ~16 mantissa bits, tighter than one bf16 pass and ~1.5x
                  faster than "highest";
      "bf16"    — single bf16 pass (~8 mantissa bits on g/h)."""
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    vecs = vecs_ref[...]                                  # [BR, 4]
    leaf = vecs[:, 3].astype(jnp.int32)                   # [BR]
    slot_leaf = slot_ref[0, :].astype(jnp.int32)          # [C]
    kind = jax.lax.broadcasted_iota(jnp.int32, (1, C_MAX), 1) % 3
    m = (leaf[:, None] == slot_leaf[None, :]) & (slot_leaf >= 0)[None, :]
    vals = jnp.where(kind == 0, vecs[:, 0][:, None],
                     jnp.where(kind == 1, vecs[:, 1][:, None],
                               vecs[:, 2][:, None]))
    gh = jnp.where(m, vals, 0.0)                          # [BR, C]
    if mode == "2xbf16":
        gh_hi = gh.astype(jnp.bfloat16)
        gh_lo = (gh - gh_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    elif mode == "bf16":
        gh_b = gh.astype(jnp.bfloat16)

    # Feature packing: with B <= 64 a single feature's one-hot only spans B
    # of the MXU's 128 output rows — concatenating ``pack`` features' one-hot
    # factors into one [BR, pack*B] operand fills the systolic array, so a
    # max_bin=63 run really is ~4x cheaper than max_bin=255 (the reference's
    # GPU backend has the same bins-per-workgroup economics and recommends
    # 63 bins, docs/GPU-Performance.rst:128-130).
    pack = max(1, 128 // B) if 128 % B == 0 and FB % max(1, 128 // B) == 0 \
        else 1
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    for f in range(0, FB, pack):
        if pack == 1:
            eq = bins_ref[f, :].astype(jnp.int32)[:, None] == iota
        else:
            eq = jnp.concatenate(
                [bins_ref[f + p, :].astype(jnp.int32)[:, None] == iota
                 for p in range(pack)], axis=1)        # [BR, pack*B]
        if mode == "highest":
            oh = eq.astype(jnp.float32)
            acc = jax.lax.dot_general(
                oh, gh, (((0,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32)
        elif mode == "2xbf16":
            oh = eq.astype(jnp.bfloat16)
            dims = (((0,), (0,)), ((), ()))
            acc = (jax.lax.dot_general(
                       oh, gh_hi, dims,
                       preferred_element_type=jnp.float32)
                   + jax.lax.dot_general(
                       oh, gh_lo, dims,
                       preferred_element_type=jnp.float32))
        else:
            oh = eq.astype(jnp.bfloat16)
            acc = jax.lax.dot_general(
                oh, gh_b, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        if pack == 1:
            out_ref[f] += acc
        else:
            for p in range(pack):
                out_ref[f + p] += acc[p * B:(p + 1) * B]


def _resolve_mode(highest) -> str:
    """Back-compat: bool True -> "highest", False -> "bf16"; strings pass
    through ("highest" | "2xbf16" | "bf16")."""
    if isinstance(highest, str):
        assert highest in ("highest", "2xbf16", "bf16"), highest
        return highest
    return "highest" if highest else "bf16"


# MXU passes per precision mode (see _hist_wave_kernel)
WAVE_MXU_PASSES = {"highest": 3, "2xbf16": 2, "bf16": 1}


def wave_kernel_cost(rows, F: int, B: int, mode="2xbf16",
                     feat_block: int = _DEF_FB, waves: int = 1):
    """Analytical (FLOPs, HBM bytes) of ``hist_pallas_wave`` over ``rows``
    total rows across ``waves`` kernel launches — ``docs/ROOFLINE.md``'s
    hand-written cost model in code, so profile mode and
    ``tools/prof_kernels.py`` compare measured kernel time against the
    same numbers the doc quotes.

    FLOPs are what the MXU is CHARGED, not useful work: the one-hot
    operand is 255/256 zeros but every lane is paid for.  Mirrors the
    kernel's feature packing (B <= 64 packs 128//B features per matmul);
    an unpacked B < 128 operand still occupies one full 128-lane group.
    Bytes count the HBM legs only — bins + packed [N, 4] vectors read
    once per ROW, the [F, B, C] output written once per LAUNCH (hence
    ``waves``); the one-hot factor lives in VMEM and never touches HBM.
    ``rows`` is the tier-compacted total (the wave grower's
    ``report_waves`` stats carry exactly this figure).
    """
    mode = _resolve_mode(mode)
    passes = WAVE_MXU_PASSES[mode]
    pack = max(1, 128 // B) if 128 % B == 0 and \
        feat_block % max(1, 128 // B) == 0 else 1
    lanes = max(pack * B, C_MAX) / pack      # charged output rows / feature
    flops = passes * 2.0 * float(rows) * F * lanes * C_MAX
    nbytes = (float(rows) * (F * 1 + 4 * 4)
              + max(int(waves), 1) * F * B * C_MAX * 4)
    return flops, nbytes


@functools.partial(jax.jit,
                   static_argnames=("B", "block_rows", "feat_block", "highest",
                                    "interpret"))
@jax.named_scope("lgbm/pallas_hist_wave")
def hist_pallas_wave(bins_fm, gv, hv, cv, leaf_id, slot_leaf, B: int,
                     block_rows: int = 1024, feat_block: int = _DEF_FB,
                     highest="bf16", interpret: bool = False):
    """Wave histogram: bins_fm [F, N] uint8; gv/hv/cv f32 [N] (bag-masked
    g, h, ones); leaf_id i32 [N]; slot_leaf i32 [C_MAX] maps channel c to a
    leaf id (channel kinds cycle g,h,count; -1 = unused).  Returns
    [F, B, C_MAX] f32 where channels 3s..3s+2 hold leaf slot_leaf[3s]'s
    (sum_g, sum_h, count) histograms.

    ``highest``: precision mode — True/"highest", "2xbf16", or
    False/"bf16" (see _hist_wave_kernel)."""
    F, N = bins_fm.shape
    BR = min(block_rows, max(128, N))
    FB = min(feat_block, max(F, 1))
    pad_rows = (-N) % BR
    if pad_rows:
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad_rows)))
        gv = jnp.pad(gv, (0, pad_rows))
        hv = jnp.pad(hv, (0, pad_rows))
        cv = jnp.pad(cv, (0, pad_rows))
        leaf_id = jnp.pad(leaf_id, (0, pad_rows), constant_values=-2)
    pad_f = (-F) % FB
    if pad_f:
        bins_fm = jnp.pad(bins_fm, ((0, pad_f), (0, 0)))
    Fp, Np = bins_fm.shape
    mode = _resolve_mode(highest)
    # pack row vectors into one [N, 4] array (g, h, count-weight, leaf_id);
    # leaf ids are exact in f32 up to 2^24
    vecs = jnp.stack([gv, hv, cv, leaf_id.astype(jnp.float32)], axis=1)
    nb = Np // BR

    grid = (Fp // FB, nb)
    out = pl.pallas_call(
        functools.partial(_hist_wave_kernel, B=B, FB=FB, mode=mode),
        grid=grid,
        in_specs=[
            pl.BlockSpec((FB, BR), lambda j, i: (j, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BR, 4), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, C_MAX), lambda j, i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((FB, B, C_MAX), lambda j, i: (j, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Fp, B, C_MAX), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(bins_fm, vecs, slot_leaf.reshape(1, C_MAX))
    return out[:F]


def hist_pallas_fm(bins_fm, g, h, mask, B: int):
    """Single-leaf histogram from feature-major bins: [F, B, 3] f32."""
    N = bins_fm.shape[1]
    gh = jnp.zeros((N, C_MAX), jnp.float32)
    gh = gh.at[:, 0].set(g * mask)
    gh = gh.at[:, 1].set(h * mask)
    gh = gh.at[:, 2].set(mask)
    out = hist_pallas_channels(bins_fm, gh, B)
    return out[..., :3]


def hist_pallas(bins, g, h, mask, B: int):
    """Drop-in replacement for ``core.histogram.hist_onehot`` (row-major
    bins input; transposes once — prefer hist_pallas_fm for resident data)."""
    return hist_pallas_fm(bins.T, g, h, mask, B)
