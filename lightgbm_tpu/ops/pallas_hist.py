"""Pallas TPU histogram kernel — the ConstructHistogram replacement.

The reference's hottest loop gathers bins and accumulates (g, h, count)
per bin with scalar code (reference: src/io/dense_bin.hpp:71-135) or
workgroup atomics (reference: src/treelearner/ocl/histogram256.cl:350).
TPUs have no fast scatter, so this kernel turns accumulation into MXU
matmuls with the one-hot factor built directly in VMEM — it never touches
HBM, unlike the XLA fallback in core/histogram.py which materializes
one-hot tiles.

Channel packing: the MXU processes 128 output lanes per pass regardless of
how many are used, so the kernel accumulates ``C=128`` weight channels at
once.  Two channel layouts exist:

* ``packed`` (the default fast path): each leaf owns a LANE PAIR (g*m,
  h*m) — 63 leaves per wave — and the count channel is folded into the
  same accumulation as ONE extra single-pass matmul whose channel matrix
  is the 0/1 membership mask.  The mask is exactly representable in
  bf16 and accumulation is f32, so the folded counts are bit-identical
  to dedicated f32 count lanes while costing one hardware pass instead
  of a third of the lane budget.  Capacity 42 -> 63 leaves per launch
  means ~1.5x fewer kernel launches (and full bins-array reads) per
  tree.
* ``triple`` (the differential oracle): (g*m, h*m, m) triples for up to
  42 leaf masks — the original layout, kept for packed-vs-triple
  differential testing and for the mixed-width XLA side-pass, which
  speaks this layout.

Quantized accumulation (``tpu_hist_dtype=int16|int8`` — LightGBM 4.x's
quantized-training trick, Shi et al.): g/h arrive as stochastic-rounded
INTEGERS under per-tree symmetric scales (``stochastic_round`` below;
the grower computes scales on device from the global |g|/|h| maxima).
The integer values are fed to the MXU exactly — int16 as an exact hi/lo
bf16 split (|hi/256| <= 129 and lo in [0, 255] are both exactly
representable in bf16's 8-bit mantissa), int8 as one exact bf16 pass —
so accumulation is INTEGER-exact up to f32's 2^24 mantissa, layout- and
shard-independent, and the fused sibling subtraction runs in integer
units (bit-identical to the XLA oracle by construction).  The f32
dequant (value = sum * scale per channel) happens downstream at
split-scan time in the wave grower, the one place the sums are
consumed as values.
The HBM win: the per-row vector stream shrinks from [N, 4] f32 (16 B)
to [N, 4] int16 (8 B), and with ``tpu_fused_grad`` the f32 g/h arrays
never round-trip HBM at all (``grad_stream_bytes`` models both legs).

Sibling fusion: with a ``parent`` operand the kernel also emits
parent-minus-child sibling histograms from the same ``pallas_call`` —
the parent block is read into VMEM once per feature block and the
sibling written on the final row step, eliminating the separate XLA
subtraction pass and its extra [F, B, C] HBM round-trip per wave
(reference: serial_tree_learner.cpp:567 subtracts the smaller child
from the parent the same way).

Data layout: bins are FEATURE-MAJOR ``[F, N]`` uint8 (the TPU-native
resident layout — per-feature column access is a contiguous row slice, and
the uint8 32-sublane tile constraint lands on the feature axis).

Per grid step (j=feature block, i=row block):
  bins block  [FB, BR]   uint8
  gh block    [BR, C]    f32 (pre-masked channels)
  out block   [FB, B, C] f32, accumulated across the i sweep
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# channel capacity: one MXU lane pass
C_MAX = 128
_DEF_BR = 1024
_DEF_FB = 32  # uint8 sublane tile
# wave capacity per layout: triple = 3 lanes/leaf; packed = a lane pair
# per leaf with the top pair left free (63, matching the max_bin=63
# economics the docs quote) so the count-lane map keeps a dead sentinel
P_MAX_TRIPLE = C_MAX // 3       # 42
P_MAX_PACKED = C_MAX // 2 - 1   # 63
# VMEM budget select_wave_blocks fits the per-grid-step blocks into:
# ~16MB physical minus headroom for double buffering + compiler temps
_VMEM_BUDGET = 10 * 2 ** 20


def wave_capacity_max(packed: bool) -> int:
    """Leaves one kernel launch can histogram under the given layout."""
    return P_MAX_PACKED if packed else P_MAX_TRIPLE


# quantized-accumulation modes (tpu_hist_dtype) and their symmetric
# integer range: q in [-QMAX, QMAX], scale = max|x| / QMAX per tree
QUANT_MODES = ("int16", "int8")
QUANT_QMAX = {"int16": 32767.0, "int8": 127.0}


def stochastic_round(x, seed=0):
    """Value-hash stochastic rounding to integers: ``floor(x + u(x))``
    with ``u`` in [0, 1) derived from the float's own bit pattern mixed
    with ``seed`` (two rounds of a murmur-style finalizer).

    Properties the quantized path relies on:
      * deterministic under a fixed seed (the satellite test pins it);
      * value-based, not position-based — a row's rounding depends only
        on its gradient VALUE, so data-parallel shards quantize
        identically to the single-device run (mesh-parity for free);
      * exact zeros stay zero (``floor(0 + u) == 0`` for u < 1), so
        bag-masked rows never leak quantization noise;
      * the result is always floor(x) or ceil(x).

    ``seed`` may be a Python int or a traced uint32 scalar."""
    xf = x.astype(jnp.float32)
    bits = jax.lax.bitcast_convert_type(xf, jnp.uint32)
    z = bits ^ jnp.uint32(seed)
    z = (z ^ (z >> 16)) * jnp.uint32(0x7FEB352D)
    z = (z ^ (z >> 15)) * jnp.uint32(0x846CA68B)
    z = z ^ (z >> 16)
    u = (z >> 8).astype(jnp.float32) * jnp.float32(2.0 ** -24)
    return jnp.floor(xf + u)


def quant_error_bound(counts, scale):
    """Analytic per-bin bound on |dequantized − f32| histogram deltas:
    each row's stochastic-rounded value is within one quantization step
    of its f32 value, and the integer accumulation is exact, so a bin
    accumulating ``counts`` rows is off by at most ``counts * scale``
    (plus f32 accumulation rounding, covered by the 1.01 headroom the
    differential suite applies).  The contract tests/test_hist_quant.py
    asserts against the kernel."""
    import numpy as np
    return np.asarray(counts, np.float64) * float(scale)


def _feat_pack(B: int, FB: int) -> int:
    """Features whose one-hot factors share one MXU pass (B <= 64)."""
    pack = max(1, 128 // B)
    return pack if 128 % B == 0 and FB % pack == 0 else 1

# pallas-tpu renamed TPUCompilerParams -> CompilerParams between the jax
# versions we run on (CPU CI container vs TPU image); take whichever exists
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams")


def _hist_kernel(bins_ref, gh_ref, out_ref, *, B: int, FB: int):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    gh = gh_ref[...]  # [BR, C]
    # bin-width specialization: B <= 64 concatenates 128//B features'
    # one-hot factors into one MXU operand (see _hist_wave_kernel — the
    # wave kernel had this; the channel kernel now shares it)
    pack = _feat_pack(B, FB)
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    for f in range(0, FB, pack):
        if pack == 1:
            eq = bins_ref[f, :].astype(jnp.int32)[:, None] == iota
        else:
            eq = jnp.concatenate(
                [bins_ref[f + p, :].astype(jnp.int32)[:, None] == iota
                 for p in range(pack)], axis=1)           # [BR, pack*B]
        oh = eq.astype(jnp.float32)
        acc = jax.lax.dot_general(
            oh, gh, (((0,), (0,)), ((), ())),             # [pack*B, C]
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)
        if pack == 1:
            out_ref[f] += acc
        else:
            for p in range(pack):
                out_ref[f + p] += acc[p * B:(p + 1) * B]


@functools.partial(jax.jit, static_argnames=("B", "block_rows", "feat_block"))
@jax.named_scope("lgbm/pallas_hist")
def hist_pallas_channels(bins_fm, gh, B: int, block_rows: int = _DEF_BR,
                         feat_block: int = _DEF_FB):
    """Multi-channel histogram: bins_fm [F, N] uint8, gh [N, C] f32 ->
    [F, B, C] f32 with out[f, b, c] = sum_r gh[r, c] * (bins_fm[f, r] == b)."""
    F, N = bins_fm.shape
    C = gh.shape[1]
    assert C % 128 == 0, f"channel dim must be a multiple of 128, got {C}"
    BR = min(block_rows, max(128, N))
    FB = min(feat_block, max(F, 1))
    pad_rows = (-N) % BR
    if pad_rows:
        # padded rows get bin 0 but zero weight in every channel
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad_rows)))
        gh = jnp.pad(gh, ((0, pad_rows), (0, 0)))
    pad_f = (-F) % FB
    if pad_f:
        bins_fm = jnp.pad(bins_fm, ((0, pad_f), (0, 0)))
    Fp, Np = bins_fm.shape

    grid = (Fp // FB, Np // BR)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, B=B, FB=FB),
        grid=grid,
        in_specs=[
            pl.BlockSpec((FB, BR), lambda j, i: (j, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BR, C), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((FB, B, C), lambda j, i: (j, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Fp, B, C), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(bins_fm, gh)
    return out[:F]


def _hist_wave_kernel(*refs, B: int, FB: int, mode: str, packed: bool,
                      fused: bool):
    """Multi-leaf histogram step: the per-leaf channel matrix is built in
    VMEM from leaf_id + the slot->leaf map, never touching HBM.

    ``mode`` selects the matmul precision/throughput trade:
      "highest" — f32 operands at Precision.HIGHEST (~3 MXU passes);
      "2xbf16"  — hi/lo bf16 split of the channel matrix, 2 MXU passes:
                  the one-hot operand is exactly representable in bf16 and
                  accumulation is always f32, so only g/h are rounded — to
                  ~16 mantissa bits, tighter than one bf16 pass and ~1.5x
                  faster than "highest";
      "bf16"    — single bf16 pass (~8 mantissa bits on g/h).

    ``packed`` selects the channel layout: lane pairs (g, h) per leaf with
    the count channel folded into one extra single-pass matmul (63 leaves)
    vs (g, h, count) lane triples (42 leaves).  The folded count pass runs
    in bf16 in EVERY mode — the membership weights are the 0/1 bag mask,
    exact in bf16, and accumulation is f32, so folded counts are
    bit-identical to dedicated count lanes at any precision mode.

    ``fused`` adds parent blocks as inputs and sibling blocks as outputs:
    on the final row step (the accumulators now hold the full child
    histograms for this feature block) the sibling is written as
    parent - child straight from VMEM.

    Quantized modes ("int16" / "int8"): vecs arrive as int16 integers;
    int16 splits each value into an EXACT hi/lo bf16 pair (2 MXU
    passes, like 2xbf16 but with zero representation error), int8 is
    one exact bf16 pass.  Everything — accumulators, emitted
    histograms, the fused sibling subtraction, and the parent operand —
    stays in INTEGER units: dequantization happens downstream at
    split-scan time (core/wave_grower.py), which keeps fused and
    unfused siblings bit-identical (an in-kernel dequant would let the
    compiler fuse ``parent - child*scale`` into an FMA whose rounding
    the separate XLA subtraction cannot reproduce)."""
    quant = mode in QUANT_MODES
    n_out = 2 if packed else 1
    n_par = n_out if fused else 0
    bins_ref, vecs_ref, slot_ref = refs[:3]
    par_refs = refs[3:3 + n_par]
    acc_refs = refs[3 + n_par:3 + n_par + n_out]
    sib_refs = refs[3 + n_par + n_out:]

    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        for r in acc_refs:
            r[...] = jnp.zeros_like(r)

    vecs = vecs_ref[...]                                  # [BR, 4]
    if quant:
        vecs = vecs.astype(jnp.int32)                     # int16 -> i32
    leaf = vecs[:, 3].astype(jnp.int32)                   # [BR]
    slot_leaf = slot_ref[0, :].astype(jnp.int32)          # [C]
    lanes = 2 if packed else 3
    kind = jax.lax.broadcasted_iota(jnp.int32, (1, C_MAX), 1) % lanes
    m = (leaf[:, None] == slot_leaf[None, :]) & (slot_leaf >= 0)[None, :]
    zero = 0 if quant else 0.0
    if packed:
        vals = jnp.where(kind == 0, vecs[:, 0][:, None], vecs[:, 1][:, None])
        slot_ct = slot_ref[1, :].astype(jnp.int32)        # [C] count lanes
        mc = (leaf[:, None] == slot_ct[None, :]) & (slot_ct >= 0)[None, :]
        ct_src = vecs[:, 2][:, None]
        ct_b = jnp.where(mc, ct_src, zero).astype(jnp.bfloat16)
    else:
        vals = jnp.where(kind == 0, vecs[:, 0][:, None],
                         jnp.where(kind == 1, vecs[:, 1][:, None],
                                   vecs[:, 2][:, None]))
    gh = jnp.where(m, vals, zero)                         # [BR, C]
    if mode == "2xbf16":
        gh_hi = gh.astype(jnp.bfloat16)
        gh_lo = (gh - gh_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    elif mode == "int16":
        # exact integer hi/lo split: hi is a multiple of 256 with
        # |hi| <= 33024 (|hi/256| <= 129 fits bf16's 8-bit mantissa),
        # lo in [0, 255] — both EXACT in bf16, so two passes accumulate
        # the integer sum with no representation error at all
        gh_hi_i = (gh >> 8) << 8
        gh_hi = gh_hi_i.astype(jnp.bfloat16)
        gh_lo = (gh - gh_hi_i).astype(jnp.bfloat16)
    elif mode in ("bf16", "int8"):
        # int8: |q| <= 127 is exact in bf16 — one pass, zero error
        gh_b = gh.astype(jnp.bfloat16)

    # Feature packing: with B <= 64 a single feature's one-hot only spans B
    # of the MXU's 128 output rows — concatenating ``pack`` features' one-hot
    # factors into one [BR, pack*B] operand fills the systolic array, so a
    # max_bin=63 run really is ~4x cheaper than max_bin=255 (the reference's
    # GPU backend has the same bins-per-workgroup economics and recommends
    # 63 bins, docs/GPU-Performance.rst:128-130).
    pack = _feat_pack(B, FB)
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    dims = (((0,), (0,)), ((), ()))
    for f in range(0, FB, pack):
        if pack == 1:
            eq = bins_ref[f, :].astype(jnp.int32)[:, None] == iota
        else:
            eq = jnp.concatenate(
                [bins_ref[f + p, :].astype(jnp.int32)[:, None] == iota
                 for p in range(pack)], axis=1)        # [BR, pack*B]
        if mode == "highest":
            oh = eq.astype(jnp.float32)
            acc = jax.lax.dot_general(
                oh, gh, dims,
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32)
        elif mode in ("2xbf16", "int16"):
            oh = eq.astype(jnp.bfloat16)
            acc = (jax.lax.dot_general(
                       oh, gh_hi, dims,
                       preferred_element_type=jnp.float32)
                   + jax.lax.dot_general(
                       oh, gh_lo, dims,
                       preferred_element_type=jnp.float32))
        else:
            oh = eq.astype(jnp.bfloat16)
            acc = jax.lax.dot_general(
                oh, gh_b, dims,
                preferred_element_type=jnp.float32)
        if packed:
            acc_ct = jax.lax.dot_general(
                eq.astype(jnp.bfloat16), ct_b, dims,
                preferred_element_type=jnp.float32)
        if pack == 1:
            acc_refs[0][f] += acc
            if packed:
                acc_refs[1][f] += acc_ct
        else:
            for p in range(pack):
                acc_refs[0][f + p] += acc[p * B:(p + 1) * B]
                if packed:
                    acc_refs[1][f + p] += acc_ct[p * B:(p + 1) * B]

    if fused:
        # final row step: accumulators hold the complete child histograms
        # for this feature block — emit the sibling without the child
        # ever round-tripping through HBM
        @pl.when(i == pl.num_programs(1) - 1)
        def _sibling():
            for par, accr, sibr in zip(par_refs, acc_refs, sib_refs):
                sibr[...] = par[...] - accr[...]


def _resolve_mode(highest) -> str:
    """Back-compat: bool True -> "highest", False -> "bf16"; strings pass
    through ("highest" | "2xbf16" | "bf16" | "int16" | "int8")."""
    if isinstance(highest, str):
        assert highest in ("highest", "2xbf16", "bf16") + QUANT_MODES, \
            highest
        return highest
    return "highest" if highest else "bf16"


# MXU passes per precision mode (see _hist_wave_kernel): int16 is the
# exact hi/lo integer split (2 passes, like 2xbf16 but representation-
# error-free); int8 is one exact bf16 pass
WAVE_MXU_PASSES = {"highest": 3, "2xbf16": 2, "bf16": 1,
                   "int16": 2, "int8": 1}

# per-row bytes of the packed vector stream the kernel reads from HBM:
# [N, 4] f32 (g, h, count-weight, leaf) vs [N, 4] int16 quantized
_VEC_BYTES = {"highest": 16, "2xbf16": 16, "bf16": 16,
              "int16": 8, "int8": 8}


def grad_stream_bytes(n_rows, rows, mode="2xbf16",
                      fused_grad: bool = False):
    """Per-ITERATION HBM bytes of the gradient stream — the [N]-sized
    legs this pipeline exists to shrink, modeled separately from the
    bins/histogram legs so the quantized + fused-grad win is a checkable
    prediction (docs/ROOFLINE.md "gradient stream" table):

      * unfused: the objective writes g and h as [N] f32 (2*4*n), the
        quantize/pack pass reads them back (2*4*n) and writes the packed
        [N, 4] vector array (vec_bytes*n);
      * fused (``tpu_fused_grad``): gradients are computed inside the
        same jit that quantizes and packs — the only [N] write is the
        vector array itself;
      * both pay the kernel's per-histogrammed-row vector read
        (vec_bytes per row over the tier-compacted ``rows`` total).

    int16+fused vs the PR 8 2xbf16+unfused baseline at the HIGGS bench
    shape is a ~2.3x byte cut (the >= 1.5x acceptance bar,
    tests/test_hist_quant.py pins it)."""
    mode = _resolve_mode(mode)
    vb = _VEC_BYTES[mode]
    pack_legs = float(n_rows) * (vb if fused_grad else (8 + 8 + vb))
    return pack_legs + float(rows) * vb


def wave_kernel_cost(rows, F: int, B: int, mode="2xbf16",
                     feat_block: int = _DEF_FB, waves: int = 1,
                     packed: bool = False, fused: bool = False,
                     fused_grad: bool = False, n_rows=None):
    """Analytical (FLOPs, HBM bytes) of ``hist_pallas_wave`` over ``rows``
    total rows across ``waves`` kernel launches — ``docs/ROOFLINE.md``'s
    hand-written cost model in code, so profile mode and
    ``tools/prof_kernels.py`` compare measured kernel time against the
    same numbers the doc quotes.

    FLOPs are what the MXU is CHARGED, not useful work: the one-hot
    operand is 255/256 zeros but every lane is paid for.  Mirrors the
    kernel's feature packing (B <= 64 packs 128//B features per matmul);
    an unpacked B < 128 operand still occupies one full 128-lane group.
    ``packed`` charges the folded count as one extra hardware pass on
    top of the mode's g/h passes (the lane-pair layout fits 63 leaves
    where triples fit 42, so per-LEAF MXU cost is unchanged — the win is
    1.5x fewer launches, i.e. fewer ``waves`` and fewer bins reads).
    Bytes count the HBM legs only — bins + packed [N, 4] vectors read
    once per ROW, the histogram outputs written once per LAUNCH (hence
    ``waves``; two output arrays when packed); ``fused`` adds the parent
    read and sibling write per launch, and is what REPLACES the separate
    XLA subtraction pass (which paid the same parent/sibling legs PLUS a
    re-read of the child).  The one-hot factor lives in VMEM and never
    touches HBM.  ``rows`` is the tier-compacted total (the wave
    grower's ``report_waves`` stats carry exactly this figure).

    Quantized modes ("int16"/"int8") charge their exact-integer MXU
    passes (2 / 1, see ``WAVE_MXU_PASSES``) and halve the per-row
    vector-stream bytes ([N, 4] int16 vs f32).  With ``n_rows`` given
    the model additionally charges the per-iteration gradient legs
    (``grad_stream_bytes``): the f32 g/h round-trip the unfused path
    pays and ``fused_grad`` deletes.
    """
    mode = _resolve_mode(mode)
    passes = WAVE_MXU_PASSES[mode] + (1 if packed else 0)
    pack = _feat_pack(B, feat_block)
    lanes = max(pack * B, C_MAX) / pack      # charged output rows / feature
    flops = passes * 2.0 * float(rows) * F * lanes * C_MAX
    hist_bytes = F * B * C_MAX * 4
    n_out = 2 if packed else 1
    per_launch = hist_bytes * n_out          # child histogram write(s)
    if fused:
        per_launch += 2 * hist_bytes * n_out  # parent read + sibling write
    nbytes = (float(rows) * (F * 1 + _VEC_BYTES[mode])
              + max(int(waves), 1) * per_launch)
    if n_rows is not None:
        # grad_stream_bytes counts the kernel's vector read too — that
        # leg is already in nbytes above, so only the pack legs add here
        nbytes += (grad_stream_bytes(n_rows, 0.0, mode,
                                     fused_grad=fused_grad))
    return flops, nbytes


def select_wave_blocks(B: int, mode="2xbf16", packed: bool = True,
                       fused: bool = True, block_rows: int = _DEF_BR,
                       vmem_budget: int = _VMEM_BUDGET):
    """Cost-model-driven (block_rows, feat_block) for ``hist_pallas_wave``.

    The per-grid-step VMEM residency is dominated by the [FB, B, C] f32
    histogram blocks: 1 (triple) or 2 (packed) accumulators, plus parent
    and sibling blocks of the same shape when fused.  This picks the
    largest feat_block whose blocks + streamed operands fit the budget —
    bin-width specialization in block form: B=64 runs FB=32 fused where
    B=256 must drop to FB=8, and the unfused/triple oracle paths get the
    larger blocks their smaller footprint allows.  ``block_rows`` is
    passed through (row blocking is an HBM-streaming knob, not a VMEM
    one, at these shapes)."""
    mode = _resolve_mode(mode)
    n_out = 2 if packed else 1
    n_big = n_out * (3 if fused else 1)   # acc (+ parent + sibling)
    for FB in (128, 64, 32, 16, 8):
        pack = _feat_pack(B, FB)
        oh_bytes = block_rows * max(pack * B, C_MAX) * \
            (4 if mode == "highest" else 2)
        # bins + vecs double-buffered stream; quantized vecs are int16
        stream = 2 * (FB * block_rows + block_rows * _VEC_BYTES[mode])
        total = FB * B * C_MAX * 4 * n_big + oh_bytes + stream
        if total <= vmem_budget:
            return block_rows, FB
    return block_rows, 8


@functools.partial(jax.jit,
                   static_argnames=("B", "block_rows", "feat_block", "highest",
                                    "interpret", "packed"))
@jax.named_scope("lgbm/pallas_hist_wave")
def hist_pallas_wave(bins_fm, gv, hv, cv, leaf_id, slot_leaf, B: int,
                     block_rows: int = 1024, feat_block: int = _DEF_FB,
                     highest="bf16", interpret: bool = False,
                     packed: bool = False, parent=None):
    """Wave histogram: bins_fm [F, N] uint8; gv/hv/cv f32 [N] (bag-masked
    g, h, ones); leaf_id i32 [N]; slot_leaf i32 [C_MAX] maps channel c to
    a leaf id (-1 = unused).

    Channel layouts (``packed``):
      triple (False) — channel kinds cycle g,h,count; returns
        [F, B, C_MAX] f32 where channels 3s..3s+2 hold leaf
        slot_leaf[3s]'s (sum_g, sum_h, count) histograms.
      packed (True) — channels pair up (g, h) per leaf (slot_leaf[2s] ==
        slot_leaf[2s+1] is leaf s); the count channel is folded into the
        same accumulation as one extra bf16 pass whose lane s carries
        leaf slot_leaf[2s]'s count.  Returns ``(gh, cnt)``: gh [F, B,
        C_MAX] with the lane pairs, cnt [F, B, C_MAX] with counts in the
        first C_MAX//2 lanes.  Exactness: count weights are the 0/1 bag
        mask — exact in bf16 with f32 accumulation, so folded counts
        bit-match dedicated lanes in every precision mode.

    ``parent`` fuses sibling subtraction in-kernel: pass the parent
    histograms in the SAME channel layout as the output ([F, B, C_MAX],
    or the (gh, cnt) pair when packed) and the call returns
    ``(child, sibling)`` with sibling = parent - child written from VMEM
    on the final row step — no separate XLA subtraction pass, no child
    re-read from HBM.

    ``highest``: precision mode — True/"highest", "2xbf16", "int16",
    "int8", or False/"bf16" (see _hist_wave_kernel).  The quantized
    modes take gv/hv as INTEGER-valued arrays (``stochastic_round``
    output) and return histograms in INTEGER units — the caller
    dequantizes at split-scan time (value = sum * scale).  The vector
    stream travels as [N, 4] int16 (half the f32 HBM bytes), so leaf
    ids must fit int16 (config caps ``num_leaves`` accordingly)."""
    F, N = bins_fm.shape
    BR = min(block_rows, max(128, N))
    FB = min(feat_block, max(F, 1))
    fused = parent is not None
    par_arrs = (list(parent) if packed else [parent]) if fused else []
    pad_rows = (-N) % BR
    if pad_rows:
        bins_fm = jnp.pad(bins_fm, ((0, 0), (0, pad_rows)))
        gv = jnp.pad(gv, (0, pad_rows))
        hv = jnp.pad(hv, (0, pad_rows))
        cv = jnp.pad(cv, (0, pad_rows))
        leaf_id = jnp.pad(leaf_id, (0, pad_rows), constant_values=-2)
    pad_f = (-F) % FB
    if pad_f:
        bins_fm = jnp.pad(bins_fm, ((0, pad_f), (0, 0)))
        par_arrs = [jnp.pad(pa, ((0, pad_f), (0, 0), (0, 0)))
                    for pa in par_arrs]
    Fp, Np = bins_fm.shape
    mode = _resolve_mode(highest)
    quant = mode in QUANT_MODES
    # pack row vectors into one [N, 4] array (g, h, count-weight, leaf_id);
    # leaf ids are exact in f32 up to 2^24.  Quantized modes carry the
    # stream as int16 — the values are already integers by construction
    # (stochastic_round output, 0/1 count weights, leaf ids capped), so
    # the cast is exact and the HBM read halves.
    vecs = jnp.stack([gv, hv, cv, leaf_id.astype(jnp.float32)], axis=1)
    if quant:
        vecs = vecs.astype(jnp.int16)
    nb = Np // BR

    if packed:
        # second slot row: the count-lane map (lane s -> leaf of pair s)
        half = C_MAX // 2
        slot_ct = jnp.concatenate(
            [slot_leaf[::2],
             jnp.full((C_MAX - half,), -1, slot_leaf.dtype)])
        slot = jnp.stack([slot_leaf, slot_ct])
    else:
        slot = slot_leaf.reshape(1, C_MAX)

    n_out = 2 if packed else 1
    hist_spec = pl.BlockSpec((FB, B, C_MAX), lambda j, i: (j, 0, 0),
                             memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((FB, BR), lambda j, i: (j, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((BR, 4), lambda j, i: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((slot.shape[0], C_MAX), lambda j, i: (0, 0),
                     memory_space=pltpu.VMEM),
    ] + [hist_spec] * len(par_arrs)
    n_res = n_out * (2 if fused else 1)
    grid = (Fp // FB, nb)
    res = pl.pallas_call(
        functools.partial(_hist_wave_kernel, B=B, FB=FB, mode=mode,
                          packed=packed, fused=fused),
        grid=grid,
        in_specs=in_specs,
        out_specs=[hist_spec] * n_res,
        out_shape=[jax.ShapeDtypeStruct((Fp, B, C_MAX), jnp.float32)
                   for _ in range(n_res)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(bins_fm, vecs, slot, *par_arrs)
    res = [r[:F] for r in res]
    child = (res[0], res[1]) if packed else res[0]
    if not fused:
        return child
    sib = (res[2], res[3]) if packed else res[1]
    return child, sib


def hist_pallas_fm(bins_fm, g, h, mask, B: int):
    """Single-leaf histogram from feature-major bins: [F, B, 3] f32."""
    N = bins_fm.shape[1]
    gh = jnp.zeros((N, C_MAX), jnp.float32)
    gh = gh.at[:, 0].set(g * mask)
    gh = gh.at[:, 1].set(h * mask)
    gh = gh.at[:, 2].set(mask)
    out = hist_pallas_channels(bins_fm, gh, B)
    return out[..., :3]


def hist_pallas(bins, g, h, mask, B: int):
    """Drop-in replacement for ``core.histogram.hist_onehot`` (row-major
    bins input; transposes once — prefer hist_pallas_fm for resident data)."""
    return hist_pallas_fm(bins.T, g, h, mask, B)
