"""Fault tolerance: atomic checkpoint/resume, the device-wedge watchdog,
and the deterministic fault-injection harness.

Three cooperating layers, each usable alone:

- :mod:`.checkpoint` — ``CheckpointManager``: versioned
  write-temp-fsync-rename checkpoints (forest + RNG + score state + eval
  history + config digest) every ``tpu_checkpoint_freq`` iterations, and
  bit-exact resume from the newest valid one (``engine.train`` drives it
  when ``tpu_checkpoint_dir`` is set).
- :mod:`.watchdog` — ``DeviceGuard``: classify device failures
  (transient vs fatal), retry transients with bounded exponential
  backoff + deterministic jitter, stamp stalled steps against a rolling
  per-step p99 deadline, and on a fatal wedge dump the flight recorder,
  write a boundary checkpoint, and abort / fall back to CPU per
  ``tpu_on_device_error``.
- :mod:`.faults` — the ``LGBM_TPU_FAULTS`` injection harness: seeded,
  deterministic faults (``raise``/``transient``/``sleep``) at named
  points (device_execute, gradients, collective, serve_device,
  checkpoint_write) so every recovery branch is CI-provable on CPU.
"""
from .checkpoint import CheckpointManager, config_digest
from .faults import FaultInjected, FaultTransient
from .watchdog import DeviceGuard, DeviceWedgedError, classify_error

__all__ = [
    "CheckpointManager", "config_digest",
    "DeviceGuard", "DeviceWedgedError", "classify_error",
    "FaultInjected", "FaultTransient",
]
