"""Fault tolerance: atomic checkpoint/resume, the device-wedge watchdog,
and the deterministic fault-injection harness.

Three cooperating layers, each usable alone:

- :mod:`.checkpoint` — ``CheckpointManager``: versioned
  write-temp-fsync-rename checkpoints (forest + RNG + score state + eval
  history + config digest) every ``tpu_checkpoint_freq`` iterations, and
  bit-exact resume from the newest valid one (``engine.train`` drives it
  when ``tpu_checkpoint_dir`` is set).
- :mod:`.watchdog` — ``DeviceGuard``: classify device failures
  (transient vs fatal), retry transients with bounded exponential
  backoff + deterministic jitter, stamp stalled steps against a rolling
  per-step p99 deadline, and on a fatal wedge dump the flight recorder,
  write a boundary checkpoint, and abort / fall back to CPU per
  ``tpu_on_device_error``.  ``CircuitBreaker`` applies the same
  taxonomy + backoff to serving-replica routing (serve/router.py): a
  wedged replica drops out of the routing set and a half-open probe
  re-admits it.
- :mod:`.faults` — the ``LGBM_TPU_FAULTS`` injection harness: seeded,
  deterministic faults (``raise``/``transient``/``sleep``) at named
  points (device_execute, gradients, collective, serve_device,
  serve_explain_submit/serve_explain_device, serve_replica{_i},
  serve_swap, serve_canary, checkpoint_write) so every recovery branch
  — training (tools/fault_matrix.py) and serving
  (tools/chaos_serve.py) — is CI-provable on CPU.
"""
from .checkpoint import CheckpointManager, config_digest
from .faults import FaultInjected, FaultTransient
from .watchdog import (CircuitBreaker, DeviceGuard, DeviceWedgedError,
                       classify_error)

__all__ = [
    "CheckpointManager", "config_digest",
    "CircuitBreaker", "DeviceGuard", "DeviceWedgedError",
    "classify_error",
    "FaultInjected", "FaultTransient",
]
