"""Deterministic fault-injection harness (``LGBM_TPU_FAULTS``).

Every recovery path in the trainer and the serving engine must be
*provable* in CI, the way the health sentinel proved the numerics paths
— which needs faults that fire exactly where and when a test says, on a
CPU-only container.  The spec grammar (env var ``LGBM_TPU_FAULTS`` or
:func:`configure`):

    spec      := leg (';' leg)*
    leg       := point ':' action ('@' cond ('&' cond)*)?
    point     := device_execute | gradients | collective | serve_device
                 | serve_explain_submit | serve_explain_device
                 | serve_replica | serve_replica_N | serve_swap
                 | serve_canary | checkpoint_write
                 | online_ingest | online_refit | online_swap
                 | ingest_chunk
                 (free-form: any check() name)
    action    := raise | transient | sleep=SECONDS | hang
    cond      := iter=N     fire only during boosting iteration N
               | call=N     fire on the N-th check() at this point (1-based)
               | p=F        fire with probability F (seeded, deterministic)
               | n=N        fire at most N times (default 1; -1 = always)

Examples::

    LGBM_TPU_FAULTS='device_execute:raise@iter=7'
    LGBM_TPU_FAULTS='device_execute:transient@iter=3&n=2;serve_device:raise'
    LGBM_TPU_FAULTS='gradients:transient@p=0.05' LGBM_TPU_FAULTS_SEED=7

Actions: ``raise`` throws :class:`FaultInjected` (classified FATAL by
the watchdog), ``transient`` throws :class:`FaultTransient` (classified
transient — the retry path), ``sleep=S`` delays the step by S seconds
without failing it (the stall-detector path), ``hang`` sleeps 3600s (a
hard wedge; only for supervised tests).  Probabilistic conds draw from
one ``numpy`` generator seeded by ``LGBM_TPU_FAULTS_SEED`` (default 0),
so a given spec+seed replays the identical fault schedule.

Injection points live in the trainer's guarded device dispatch
(boosting/gbdt.py), the gradient step, the host collective path
(parallel/distributed.py), the serving predict + explain device paths
(serve/session.py: ``serve_device``, ``serve_explain_submit``,
``serve_explain_device``), the replica router's dispatch
(serve/router.py: ``serve_replica`` plus per-replica
``serve_replica_{i}`` so a chaos run can wedge exactly one replica),
the model registry's swap/canary path (serve/registry.py:
``serve_swap``, ``serve_canary``), the checkpoint writer, and the
online learning loop (online/loop.py: ``online_ingest`` per ingest
batch, ``online_refit`` at the top of a refresh, ``online_swap``
before the registry push — ``tools/fault_matrix.py`` proves a refit
fault leaves the old version serving), and the streaming ingestion
subsystem (ingest/stream.py: ``ingest_chunk`` guards every chunk
fetch of both passes — a transient read fault retries with backoff, a
fatal one aborts loudly, a ``sleep`` stall is stamped when
``tpu_wedge_timeout_s`` is set).  When no plan is configured every
:func:`check` call is one ``None`` test.
"""
from __future__ import annotations

import os
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from ..utils import log


class FaultInjected(RuntimeError):
    """An injected fault (classified FATAL by the watchdog)."""

    def __init__(self, point: str, detail: str = ""):
        super().__init__(
            f"INVALID_ARGUMENT: injected fault at {point}"
            + (f" ({detail})" if detail else ""))
        self.point = point


class FaultTransient(FaultInjected):
    """An injected TRANSIENT fault (the watchdog's retry path)."""

    def __init__(self, point: str, detail: str = ""):
        RuntimeError.__init__(
            self, f"UNAVAILABLE: injected transient fault at {point}"
            + (f" ({detail})" if detail else ""))
        self.point = point


@dataclass
class FaultSpec:
    point: str
    action: str                      # raise | transient | sleep | hang
    arg: float = 0.0                 # sleep seconds
    iter_: Optional[int] = None
    call: Optional[int] = None
    p: Optional[float] = None
    remaining: int = 1               # -1 = unlimited
    fired: int = field(default=0)


_PLAN: Optional[List[FaultSpec]] = None
_RNG: Optional[np.random.Generator] = None
_calls = defaultdict(int)            # point -> check() count


def parse_spec(spec: str) -> List[FaultSpec]:
    """Parse the ``LGBM_TPU_FAULTS`` grammar; raises ``ValueError`` on a
    malformed spec (the env path warns instead — see module init)."""
    out: List[FaultSpec] = []
    for leg in spec.split(";"):
        leg = leg.strip()
        if not leg:
            continue
        head, _, conds = leg.partition("@")
        point, sep, action = head.partition(":")
        if not sep or not point.strip() or not action.strip():
            raise ValueError(f"fault leg {leg!r}: expected point:action")
        action = action.strip()
        arg = 0.0
        if action.startswith("sleep"):
            _, _, v = action.partition("=")
            arg = float(v) if v else 0.1
            action = "sleep"
        elif action == "hang":
            action, arg = "sleep", 3600.0
        elif action not in ("raise", "transient"):
            raise ValueError(f"fault leg {leg!r}: unknown action "
                             f"{action!r}")
        fs = FaultSpec(point=point.strip(), action=action, arg=arg)
        for cond in conds.split("&"):
            cond = cond.strip()
            if not cond:
                continue
            k, sep, v = cond.partition("=")
            if not sep:
                raise ValueError(f"fault leg {leg!r}: bad cond {cond!r}")
            k = k.strip()
            if k == "iter":
                fs.iter_ = int(v)
            elif k == "call":
                fs.call = int(v)
            elif k == "p":
                fs.p = float(v)
            elif k == "n":
                fs.remaining = int(v)
            else:
                raise ValueError(f"fault leg {leg!r}: unknown cond key "
                                 f"{k!r}")
        out.append(fs)
    return out


def configure(spec: str, seed: Optional[int] = None) -> None:
    """Arm the harness with ``spec`` (empty string disarms).  Resets the
    per-point call counters so a spec replays identically."""
    global _PLAN, _RNG
    plan = parse_spec(spec) if spec else []
    _calls.clear()
    if not plan:
        _PLAN = None
        _RNG = None
        return
    if seed is None:
        try:
            seed = int(os.environ.get("LGBM_TPU_FAULTS_SEED", "0") or 0)
        except ValueError:
            seed = 0
    _RNG = np.random.default_rng(seed)
    _PLAN = plan
    log.warning("fault injection ARMED: %s (seed %d)", spec, seed)


def disarm() -> None:
    configure("")


def armed() -> bool:
    return _PLAN is not None


def plan() -> List[FaultSpec]:
    return list(_PLAN or [])


def check(point: str, iteration: Optional[int] = None) -> None:
    """The injection point: call sites sprinkle this where a fault can
    strike.  One ``None`` test when disarmed; when armed, fires the
    first matching spec's action (raises, or sleeps and returns)."""
    if _PLAN is None:
        return
    _calls[point] += 1
    call_idx = _calls[point]
    for fs in _PLAN:
        if fs.point != point or fs.remaining == 0:
            continue
        if fs.iter_ is not None and fs.iter_ != iteration:
            continue
        if fs.call is not None and fs.call != call_idx:
            continue
        if fs.p is not None and not (_RNG.random() < fs.p):
            continue
        if fs.remaining > 0:
            fs.remaining -= 1
        fs.fired += 1
        from .. import obs
        obs.event("fault_injected", point=point, action=fs.action,
                  call=call_idx,
                  **({} if iteration is None else {"iteration": iteration}))
        detail = (f"iter={iteration}" if iteration is not None
                  else f"call={call_idx}")
        if fs.action == "sleep":
            log.warning("fault injection: sleeping %.3fs at %s (%s)",
                        fs.arg, point, detail)
            time.sleep(fs.arg)
            return
        if fs.action == "transient":
            raise FaultTransient(point, detail)
        raise FaultInjected(point, detail)


def fired_counts() -> dict:
    """{point: times fired} across the armed plan (for tests/digests)."""
    out = defaultdict(int)
    for fs in _PLAN or []:
        out[fs.point] += fs.fired
    return dict(out)


_env_spec = os.environ.get("LGBM_TPU_FAULTS", "")
if _env_spec:
    try:
        configure(_env_spec)
    except ValueError as _exc:   # env path cannot raise at import time
        log.warning("ignoring malformed LGBM_TPU_FAULTS=%r (%s)",
                    _env_spec, _exc)
