"""Device-wedge watchdog: classify, retry, stamp stalls, fail safely.

The TPU failure modes this exists for are the ones the bench history
already paid for: BENCH_r04 lost a whole lease window to a wedged
backend, BENCH_r05 silently ran CPU-fallback.  ``DeviceGuard`` wraps the
trainer's synced device dispatch (boosting/gbdt.py) and gives every
failure a deliberate outcome instead of a stack trace at iteration
499/500:

- **classify** — :func:`classify_error` sorts exceptions into
  ``transient`` (UNAVAILABLE / RESOURCE_EXHAUSTED / DEADLINE_EXCEEDED /
  ABORTED — the runtime says "try again") vs ``fatal`` (everything
  else).  :func:`classify_text` applies the same patterns to a
  subprocess's output tail (tools/tpu_window.py reuses it for bench
  legs).
- **retry** — transient failures re-dispatch with bounded exponential
  backoff + DETERMINISTIC jitter (seeded, so a fault-injection replay
  produces the identical schedule).  The guarded closures are
  functional (inputs unread after dispatch), so a retry is a pure
  re-execution.
- **stall** — a ``threading.Timer`` heartbeat stamps a step that blows
  its deadline (explicit ``tpu_wedge_timeout_s``, else 4x the rolling
  per-step p99 with a floor) with a ``device_stall`` event and a flight
  dump.  Advisory by design: Python cannot interrupt a wedged XLA call,
  so the stamp is the post-mortem and the supervisor (SIGTERM handler,
  ``tools/tpu_window.py`` leg timeout) is the kill.
- **fatal** — dump the flight recorder, invoke ``on_fatal`` (the
  trainer's boundary-checkpoint hook), then per ``tpu_on_device_error``:
  ``abort`` raises :class:`DeviceWedgedError`; ``fallback`` re-executes
  the step once under the CPU default device (best-effort — committed
  TPU buffers may still pin the old backend); ``retry`` means transient
  retries first, then abort.

The guard is ACTIVE only when ``tpu_watchdog=true`` or the fault
harness is armed; inactive it forwards the call untouched (no extra
sync), so default runs keep their async pipelining.
"""
from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Callable, Optional

import numpy as np

from ..utils import log
from . import faults


class DeviceWedgedError(RuntimeError):
    """A device step failed fatally (or exhausted its retries) and the
    policy said abort.  By the time this propagates the flight recorder
    has dumped and the boundary checkpoint hook has run."""


# substrings that mark a failure as transient — the gRPC/absl status
# names the TPU runtime uses for "the hardware/runtime hiccupped, the
# program is fine" (plus the injection harness's own marker)
_TRANSIENT_PATTERNS = (
    "UNAVAILABLE", "RESOURCE_EXHAUSTED", "DEADLINE_EXCEEDED", "ABORTED",
    "CANCELLED", "UNKNOWN: ", "injected transient",
    "socket closed", "connection reset", "network error",
)

# output-tail substrings that mark a SUBPROCESS bench leg as wedged /
# retryable (tools/tpu_window.py); a plain assertion failure matches
# none of these and is never retried
_WEDGE_TEXT_PATTERNS = _TRANSIENT_PATTERNS + (
    "timed out", "backend wedge", "heartbeat", "hbm oom",
    "failed to connect", "tpu initialization",
)


def classify_error(exc: BaseException) -> str:
    """``'transient'`` or ``'fatal'`` for an in-process exception."""
    if isinstance(exc, faults.FaultTransient):
        return "transient"
    if isinstance(exc, faults.FaultInjected):
        return "fatal"
    msg = f"{type(exc).__name__}: {exc}"
    low = msg.lower()
    for pat in _TRANSIENT_PATTERNS:
        if pat.lower() in low:
            return "transient"
    return "fatal"


def classify_text(text: str, timed_out: bool = False) -> Optional[str]:
    """Classify a subprocess output tail: ``'wedge'`` (timeout / hang),
    ``'transient'`` (retryable runtime error), or None (a real failure
    that retrying would only repeat)."""
    if timed_out:
        return "wedge"
    low = (text or "").lower()
    for pat in _WEDGE_TEXT_PATTERNS:
        if pat.lower() in low:
            return "transient"
    return None


def backoff_delays(retries: int, base_s: float = 0.05, cap_s: float = 2.0,
                   seed: int = 0) -> list:
    """The full deterministic backoff schedule: ``base * 2^k`` capped,
    plus up to 25% seeded jitter (decorrelates a fleet of workers
    retrying the same wedge without sacrificing replayability)."""
    rng = np.random.default_rng(seed)
    return [min(base_s * (2.0 ** k), cap_s) * (1.0 + 0.25 * rng.random())
            for k in range(max(retries, 0))]


class DeviceGuard:
    """Retry/stall/fatal policy around one trainer's device dispatch."""

    def __init__(self, policy: str = "retry", retries: int = 3,
                 backoff_base_s: float = 0.05, backoff_cap_s: float = 2.0,
                 stall_timeout_s: float = 0.0, stall_floor_s: float = 60.0,
                 seed: int = 0, enabled: bool = False,
                 on_fatal: Optional[Callable] = None, name: str = "train"):
        if policy not in ("abort", "fallback", "retry"):
            raise ValueError(f"unknown device-error policy {policy!r}")
        self.policy = policy
        self.retries = max(int(retries), 0)
        self.stall_timeout_s = float(stall_timeout_s)
        self.stall_floor_s = float(stall_floor_s)
        self.enabled = bool(enabled)
        self.on_fatal = on_fatal
        self.name = name
        self._delays = backoff_delays(self.retries, backoff_base_s,
                                      backoff_cap_s, seed)
        self._durations: deque = deque(maxlen=64)
        self._lock = threading.Lock()
        self.retry_count = 0
        self.stall_count = 0

    @property
    def active(self) -> bool:
        """The guard engages when armed explicitly (``tpu_watchdog``) or
        when the fault harness is live — otherwise ``run`` is a passthrough
        and the training loop keeps async dispatch."""
        return self.enabled or faults.armed()

    # ------------------------------------------------------------------
    def _deadline_s(self) -> float:
        """Stall deadline: explicit timeout wins (negative disables the
        heartbeat); else 4x the rolling per-step p99 once enough steps
        are measured, floored so early iterations (compiles!) never
        false-positive."""
        if self.stall_timeout_s < 0:
            return 0.0
        if self.stall_timeout_s > 0:
            return self.stall_timeout_s
        with self._lock:
            samples = sorted(self._durations)
        if len(samples) >= 8:
            p99 = samples[min(int(np.ceil(0.99 * len(samples))) - 1,
                              len(samples) - 1)]
            return max(4.0 * p99, self.stall_floor_s)
        return self.stall_floor_s

    def snapshot(self) -> dict:
        """Scrapeable retry/stall state for the live train board
        (obs/board.py provider hook).  ``_deadline_s`` takes the lock
        itself, so it is resolved BEFORE the state read — never while
        holding it."""
        deadline = self._deadline_s()
        with self._lock:
            return {
                "active": self.active,
                "policy": self.policy,
                "retries_budget": self.retries,
                "retry_count": self.retry_count,
                "stall_count": self.stall_count,
                "deadline_s": round(deadline, 3),
            }

    def _on_stall(self, point: str, iteration, t0: float,
                  deadline: float) -> None:
        from .. import obs
        with self._lock:
            self.stall_count += 1
        elapsed = time.perf_counter() - t0
        log.warning("%s watchdog: step %r stalled — %.1fs elapsed, "
                    "deadline %.1fs (iteration %s); dumping flight "
                    "recorder (a hung XLA call cannot be interrupted "
                    "from Python — the supervisor owns the kill)",
                    self.name, point, elapsed, deadline, iteration)
        obs.event("device_stall", point=point, elapsed_s=round(elapsed, 3),
                  deadline_s=round(deadline, 3),
                  **({} if iteration is None else {"iteration": iteration}))
        if obs.flight_enabled():
            obs.flight_dump(f"device_stall:{point}")

    # ------------------------------------------------------------------
    def run(self, fn: Callable, point: str = "device_execute",
            iteration: Optional[int] = None):
        """Execute ``fn()`` under the policy.  Inactive: a passthrough.
        Active: injection check, dispatch, block-until-ready (errors must
        surface HERE, not at a later async fetch), retry/fatal
        handling."""
        if not self.active:
            return fn()
        import jax
        attempt = 0
        while True:
            deadline = self._deadline_s()
            t0 = time.perf_counter()
            timer = None
            if deadline > 0:
                timer = threading.Timer(
                    deadline, self._on_stall, (point, iteration, t0,
                                               deadline))
                timer.daemon = True
                timer.start()
            try:
                faults.check(point, iteration=iteration)
                out = jax.block_until_ready(fn())
                with self._lock:
                    self._durations.append(time.perf_counter() - t0)
                return out
            except Exception as exc:  # noqa: BLE001 — the classify point
                cls = classify_error(exc)
                can_retry = (cls == "transient" and attempt < self.retries
                             and self.policy != "abort")
                self._note_retry(point, attempt, cls, exc, can_retry,
                                 iteration)
                if not can_retry:
                    return self._fatal(exc, cls, fn, point, iteration)
                time.sleep(self._delays[attempt])
                attempt += 1
            finally:
                if timer is not None:
                    timer.cancel()

    def _note_retry(self, point, attempt, cls, exc, will_retry,
                    iteration) -> None:
        from .. import obs
        with self._lock:
            self.retry_count += 1
        action = ("retry" if will_retry
                  else "fallback" if self.policy == "fallback" else "abort")
        delay = (round(self._delays[attempt] * 1e3, 3)
                 if will_retry else None)
        log.warning("%s watchdog: %s failure at %r (attempt %d): %s — %s%s",
                    self.name, cls, point, attempt + 1,
                    f"{type(exc).__name__}: {exc}", action,
                    f" in {delay}ms" if delay is not None else "")
        fields = dict(point=point, attempt=attempt, classify=cls,
                      action=action, error=f"{type(exc).__name__}: {exc}")
        if delay is not None:
            fields["delay_ms"] = delay
        if iteration is not None:
            fields["iteration"] = iteration
        obs.event("retry", **fields)

    def _fatal(self, exc, cls, fn, point, iteration):
        """Flight dump + boundary-checkpoint hook, then abort or CPU
        fallback per policy."""
        from .. import obs
        if obs.flight_enabled():
            obs.flight_dump(f"device_wedge:{point}",
                            extra={"error": f"{type(exc).__name__}: {exc}",
                                   "classify": cls})
        if self.on_fatal is not None:
            try:
                self.on_fatal(f"device_wedge:{point}", exc)
            except Exception as hook_exc:  # noqa: BLE001
                log.warning("%s watchdog: on_fatal hook failed (%s: %s)",
                            self.name, type(hook_exc).__name__, hook_exc)
        if self.policy == "fallback":
            import jax
            log.warning("%s watchdog: continuing on the CPU backend "
                        "(tpu_on_device_error=fallback; best-effort — "
                        "buffers committed to the dead backend may still "
                        "fail)", self.name)
            cpu = jax.devices("cpu")[0]
            with jax.default_device(cpu):
                return jax.block_until_ready(fn())
        raise DeviceWedgedError(
            f"device step {point!r} failed ({cls})"
            + (f" at iteration {iteration}" if iteration is not None else "")
            + f": {type(exc).__name__}: {exc}") from exc


class CircuitBreaker:
    """Per-replica circuit breaker for the serving router (serve/router.py).

    The serving twin of :class:`DeviceGuard`: same failure taxonomy
    (:func:`classify_error`), same bounded deterministic backoff
    (:func:`backoff_delays`) — but instead of retrying in place it takes
    a replica OUT of the routing set, so one wedged replica costs
    capacity, never availability.  States:

    - **closed** — healthy; every request is allowed.
    - **open** — tripped (a FATAL failure immediately, or ``trip_after``
      consecutive transient ones); requests are routed elsewhere until
      the backoff delay expires.  Re-trips walk the bounded backoff
      schedule, so a flapping replica is probed less and less often.
    - **half_open** — the backoff expired; exactly ONE probe request is
      let through.  Success closes the breaker, failure re-opens it at
      the next backoff step.
    """

    def __init__(self, trip_after: int = 3, backoff_base_s: float = 0.5,
                 backoff_cap_s: float = 30.0, seed: int = 0):
        self.trip_after = max(int(trip_after), 1)
        # a long-enough schedule that a permanently dead replica keeps
        # being probed at the cap instead of running off the end
        self._delays = backoff_delays(16, backoff_base_s, backoff_cap_s,
                                      seed)
        self._lock = threading.Lock()
        self.state = "closed"
        self.failures = 0            # lifetime failure count
        self.consecutive = 0         # consecutive failures since last ok
        self.opens = 0               # times the breaker tripped
        self._open_until = 0.0
        self._open_step = 0          # index into the backoff schedule
        # a half-open probe that never resolves (the probing request was
        # never dispatched — e.g. a sibling replica answered first, or
        # its thread died) must not strand the breaker: after this long
        # in half_open without a verdict, another probe is allowed
        self._probe_timeout_s = max(float(backoff_cap_s), 1.0)
        self._half_open_since = 0.0
        self.last_error = ""

    def allow(self) -> bool:
        """True when a request may be routed to this replica.  While
        open, flips to half_open (one probe) once the backoff expires;
        a probe that evaporates is re-allowed after the probe timeout."""
        with self._lock:
            now = time.monotonic()
            if self.state == "closed":
                return True
            if self.state == "half_open":
                if now - self._half_open_since > self._probe_timeout_s:
                    self._half_open_since = now
                    return True  # the earlier probe never resolved
                return False  # a probe is already in flight
            if now >= self._open_until:
                self.state = "half_open"
                self._half_open_since = now
                return True
            return False

    def record_ok(self) -> None:
        with self._lock:
            self.consecutive = 0
            if self.state == "open":
                # only the half-open probe may close a tripped breaker:
                # a success belonging to a request dispatched BEFORE the
                # trip (a stale in-flight result) must not re-admit the
                # replica or reset the backoff escalation
                return
            self.state = "closed"
            self._open_step = 0

    def record_failure(self, exc: BaseException) -> str:
        """Account one failure; returns the classification.  A fatal
        failure (or a half-open probe failure, or ``trip_after``
        consecutive transients) opens the breaker."""
        cls = classify_error(exc)
        with self._lock:
            self.failures += 1
            self.consecutive += 1
            self.last_error = f"{type(exc).__name__}: {exc}"
            trip = (cls == "fatal" or self.state == "half_open"
                    or self.consecutive >= self.trip_after)
            if trip:
                self.state = "open"
                self.opens += 1
                delay = self._delays[min(self._open_step,
                                         len(self._delays) - 1)]
                self._open_step += 1
                self._open_until = time.monotonic() + delay
        return cls

    def snapshot(self) -> dict:
        with self._lock:
            open_for = (max(self._open_until - time.monotonic(), 0.0)
                        if self.state == "open" else 0.0)
            return {"state": self.state, "failures": self.failures,
                    "consecutive": self.consecutive, "opens": self.opens,
                    "open_for_s": round(open_for, 3),
                    "last_error": self.last_error or None}


# convenience for one-off guarded calls (the host collective path uses
# this — a full per-trainer guard would be overkill there; heartbeat
# disabled: collectives are guarded for retries only)
_ONEOFF = DeviceGuard(policy="retry", retries=2, backoff_base_s=0.02,
                      stall_timeout_s=-1.0, name="collective")


def guarded_call(fn: Callable, point: str):
    """Run ``fn`` with transient-retry semantics (active only when the
    fault harness is armed — real collective errors pass through
    unchanged, preserving existing behavior)."""
    return _ONEOFF.run(fn, point=point)
