"""Atomic, versioned training checkpoints with bit-exact resume.

A crash at iteration 499/500 must cost at most ``tpu_checkpoint_freq``
iterations, and the resumed run must produce the SAME model a straight
run would have — bit-identical, RNG state and all — so the differential
test (tests/test_robust.py) can prove recovery the way the sequential-
split oracle proved the wave apply.

One checkpoint is one directory ``ckpt_{iteration:08d}/`` holding:

- ``model.txt`` — the full forest in the LightGBM v3 text format
  (shortest-round-trip float formatting: the f64 leaf/threshold values
  reload bit-exactly);
- ``state.npz`` — the device state that CANNOT be replayed without
  rounding drift: the f32 ``[N, K]`` train score, every valid-set score,
  and the live bagging mask.  Replaying trees onto a fresh score would
  re-round f64 sums into f32 in a different order; saving the array
  sidesteps the whole question;
- ``meta.json`` — iteration, the boosting-specific RNG/weight state
  (``GBDT.checkpoint_state``; DART adds its drop RNG and tree weights),
  the recorded eval history (replayed through the stateful callbacks on
  resume so early stopping continues mid-stream), a digest of the
  training config (resume REFUSES a mismatched config rather than
  silently diverging), and sha256 checksums of the other two files.

Atomicity is write-temp → fsync(every file) → ``os.rename`` (atomic on
POSIX) → fsync(parent dir).  A crash mid-write leaves a ``.tmp-*``
orphan the next save sweeps; a torn rename cannot happen; a corrupt or
truncated checkpoint fails its checksum and the loader falls back to
the next-newest valid one.
"""
from __future__ import annotations

import glob
import hashlib
import json
import os
import re
import shutil
import time
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from ..utils import log
from . import faults

_CKPT_RE = re.compile(r"ckpt_(\d{8})$")
FORMAT_VERSION = 1

# config fields that may differ between the crashed and the resuming
# invocation without changing the training trajectory
_DIGEST_SKIP = frozenset((
    "config", "task", "output_model", "output_result", "input_model",
    "snapshot_freq", "verbosity", "convert_model",
    "tpu_checkpoint_dir", "tpu_checkpoint_freq", "tpu_checkpoint_keep",
    "tpu_telemetry", "tpu_profile", "tpu_trace", "tpu_flight_len",
    "tpu_health", "tpu_fingerprint_freq", "tpu_compile_cache_dir",
    "tpu_watchdog", "tpu_on_device_error", "tpu_device_retries",
    "tpu_wedge_timeout_s",
    # kernel-pipeline knobs proven bit-identical by the ISSUE 8/11
    # differential suites: flipping them must not refuse a resume.
    # (tpu_wave_overlap and tpu_hist_dtype are deliberately NOT here —
    # both change the trees a resumed run would grow.)
    "tpu_fused_sibling", "tpu_batched_split_apply", "tpu_fused_grad",
    # eval-only: the device NDCG kernel never touches gradients or
    # trees, so flipping it must not refuse a resume
    "tpu_rank_device_eval",
    # bit-identical knob (tests/test_rank_device.py pins the sharded
    # pair pass against the single-device oracle across mesh sizes)
    "tpu_rank_sharded_grad",
    # streamed ingestion is bit-identical to the in-RAM load given the
    # same sample (tests/test_ingest_stream.py), and chunk size / memmap
    # backing never change the constructed dataset — so flipping them
    # must not refuse a resume.  (tpu_ingest_sample_seed and the shard
    # knobs are deliberately NOT here: they change the sample / the
    # local rows, hence the trees.)
    "tpu_ingest", "tpu_ingest_chunk_rows", "tpu_ingest_memmap",
))

# world-shape knobs, additionally skipped in FLEET mode (tpu_fleet set):
# the elastic fleet trains a full replica on every rank (fleet/elastic.py
# replicate mode — provably world-independent), so a resume after the
# world shrank or healed must not be refused just because the shard
# count changed.  Outside fleet mode these knobs keep refusing a resume:
# they change the local rows, hence the trees.
_DIGEST_SKIP_FLEET_WORLD = frozenset((
    "tpu_ingest_shards", "tpu_ingest_shard_id",
    "num_machines", "machines", "machine_list_filename",
    "local_listen_port", "time_out",
))


def config_digest(config) -> str:
    """Stable hash of the training-relevant config surface."""
    import dataclasses
    fleet = bool(getattr(config, "tpu_fleet", 0))
    items = {}
    for f in dataclasses.fields(config):
        if f.name in _DIGEST_SKIP or f.name == "is_parallel":
            continue
        # the tpu_fleet_* family is always operational (heartbeat cadence,
        # heal policy, rendezvous dir) — never training-relevant
        if f.name.startswith("tpu_fleet"):
            continue
        v = getattr(config, f.name)
        if fleet and f.name in _DIGEST_SKIP_FLEET_WORLD:
            # neutralize (don't drop) the world-geometry knobs: the
            # keyset stays identical, so a fleet checkpoint resumes at
            # ANY world size — including world 1, the single-process
            # digest an elastic shrink-to-one lands on
            v = f.default
        if isinstance(v, (list, tuple)):
            v = list(v)
        if f.name == "tpu_hist_dtype":
            # hash the RESOLVED kernel mode — covering the quantized
            # modes too, the same way — so back-compat aliases
            # ("float32" -> "2xbf16", "bfloat16" -> "bf16"), the ISSUE 8
            # default rename and the ISSUE 11 int16/int8 names can never
            # refuse a resume whose effective mode did not change
            from ..boosting.gbdt import GBDT
            v = GBDT._hist_mode(config)
        items[f.name] = v
    blob = json.dumps(items, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _world_size() -> int:
    """Informational world size stamped into checkpoint meta (fleet
    post-mortems read it); never part of the digest — a shrunk-world
    resume is exactly the point of the elastic fleet."""
    try:
        from ..parallel.distributed import world_size
        return int(world_size())
    except Exception:  # noqa: BLE001 — meta decoration only
        return 1


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _fsync_write(path: str, data, binary: bool = False) -> None:
    with open(path, "wb" if binary else "w") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # platforms without dir fsync
        pass


@dataclass
class RestoreState:
    iteration: int
    path: str
    eval_history: List[Tuple] = field(default_factory=list)
    meta: dict = field(default_factory=dict)


class CheckpointManager:
    """Owns one checkpoint directory: periodic save, prune, scan,
    validate, restore."""

    def __init__(self, ckpt_dir: str, freq: int = 100, keep: int = 3,
                 digest: Optional[str] = None):
        self.dir = ckpt_dir
        self.freq = max(int(freq), 0)
        self.keep = max(int(keep), 1)
        # the digest is captured from the PRISTINE config (from_config
        # runs before the first iteration): reset_parameter schedules
        # mutate booster.config.learning_rate mid-run, and a digest
        # computed at save time would never match the resuming
        # process's fresh config
        self.digest = digest

    @classmethod
    def from_config(cls, config) -> Optional["CheckpointManager"]:
        d = getattr(config, "tpu_checkpoint_dir", "") or ""
        if not d:
            return None
        return cls(d, freq=int(getattr(config, "tpu_checkpoint_freq", 100)),
                   keep=int(getattr(config, "tpu_checkpoint_keep", 3)),
                   digest=config_digest(config))

    def should_save(self, iteration: int) -> bool:
        return self.freq > 0 and iteration > 0 and iteration % self.freq == 0

    # ------------------------------------------------------------------
    def list_checkpoints(self) -> List[str]:
        """Checkpoint dirs, newest iteration first."""
        out = []
        for d in glob.glob(os.path.join(self.dir, "ckpt_*")):
            m = _CKPT_RE.search(os.path.basename(d))
            if m and os.path.isdir(d):
                out.append((int(m.group(1)), d))
        return [d for _, d in sorted(out, reverse=True)]

    def trim_to(self, iteration: int) -> int:
        """Drop every checkpoint NEWER than ``iteration`` — the elastic
        rollback: survivors agree on the fleet-wide common iteration and
        trim so the auto-resume lands exactly there on every rank.
        Returns the number of checkpoints removed."""
        removed = 0
        for d in self.list_checkpoints():
            m = _CKPT_RE.search(os.path.basename(d))
            if m and int(m.group(1)) > int(iteration):
                shutil.rmtree(d, ignore_errors=True)
                removed += 1
                log.info("checkpoint: trimmed %s (rollback to iteration "
                         "%d)", d, iteration)
        return removed

    def _sweep_orphans(self) -> None:
        for d in glob.glob(os.path.join(self.dir, ".tmp-*")):
            shutil.rmtree(d, ignore_errors=True)

    # ------------------------------------------------------------------
    def save(self, booster, iteration: int, eval_history=(),
             reason: str = "periodic") -> Optional[str]:
        """Write one atomic checkpoint; returns its path (None when the
        write failed — checkpointing must never kill training)."""
        from .. import obs
        from ..io.model_io import model_to_string
        t0 = time.perf_counter()
        gbdt = booster._gbdt
        try:
            faults.check("checkpoint_write", iteration=iteration)
            os.makedirs(self.dir, exist_ok=True)
            self._sweep_orphans()
            model_txt = model_to_string(gbdt, num_iteration=-1)
            state_meta, arrays = gbdt.checkpoint_state()
            tmp = os.path.join(self.dir, f".tmp-{os.getpid()}-{iteration}")
            os.makedirs(tmp, exist_ok=True)
            _fsync_write(os.path.join(tmp, "model.txt"), model_txt)
            with open(os.path.join(tmp, "state.npz"), "wb") as fh:
                np.savez(fh, **arrays)
                fh.flush()
                os.fsync(fh.fileno())
            meta = {
                "kind": "lgbm_tpu_checkpoint",
                "format": FORMAT_VERSION,
                "iteration": int(iteration),
                "t": round(time.time(), 6),
                "reason": reason,
                "config_digest": (self.digest
                                  or config_digest(booster.config)),
                "num_data": int(gbdt.train_ds.num_data),
                "world_size": _world_size(),
                "num_class": int(gbdt.num_tpi),
                "best_iteration": int(booster.best_iteration),
                "eval_history": [[int(it), [list(e) for e in entries]]
                                 for it, entries in eval_history],
                "state": state_meta,
                "sha256": {
                    "model.txt": _sha256_file(
                        os.path.join(tmp, "model.txt")),
                    "state.npz": _sha256_file(
                        os.path.join(tmp, "state.npz")),
                },
            }
            _fsync_write(os.path.join(tmp, "meta.json"),
                         json.dumps(meta, indent=1))
            final = os.path.join(self.dir, f"ckpt_{iteration:08d}")
            if os.path.isdir(final):   # re-save of the same iteration
                shutil.rmtree(final)
            os.rename(tmp, final)
            _fsync_dir(self.dir)
            self._prune(meta["config_digest"])
            ms = round((time.perf_counter() - t0) * 1e3, 3)
            size = sum(os.path.getsize(os.path.join(final, f))
                       for f in os.listdir(final))
            obs.event("checkpoint", iteration=int(iteration), path=final,
                      bytes=int(size), ms=ms, reason=reason)
            log.info("checkpoint: wrote %s (%d bytes, %.1fms, %s)",
                     final, size, ms, reason)
            return final
        except Exception as exc:  # noqa: BLE001 — never kill training
            log.warning("checkpoint write failed at iteration %d (%s: %s)",
                        iteration, type(exc).__name__, exc)
            return None

    def _prune(self, digest: Optional[str] = None) -> None:
        """Drop checkpoints beyond ``keep``.  Checkpoints written under
        a DIFFERENT config digest are removed first regardless of their
        iteration number: a fresh run in a reused directory must not
        have its (lower-iteration) checkpoints shadowed — and then
        pruned away — by a previous run's stale higher-iteration ones,
        which ``peek`` could never resume from anyway."""
        keep_pool = []
        for d in self.list_checkpoints():
            if digest is not None:
                try:
                    with open(os.path.join(d, "meta.json")) as fh:
                        have = json.load(fh).get("config_digest")
                except (OSError, ValueError):
                    have = None
                if have != digest:
                    log.warning("checkpoint prune: removing %s (written "
                                "under a different training config)", d)
                    shutil.rmtree(d, ignore_errors=True)
                    continue
            keep_pool.append(d)
        for d in keep_pool[self.keep:]:
            shutil.rmtree(d, ignore_errors=True)

    # ------------------------------------------------------------------
    def _validate(self, path: str) -> Optional[dict]:
        """Meta of a structurally valid checkpoint (checksums included),
        else None."""
        try:
            with open(os.path.join(path, "meta.json")) as fh:
                meta = json.load(fh)
            if meta.get("kind") != "lgbm_tpu_checkpoint":
                return None
            if int(meta.get("format", -1)) > FORMAT_VERSION:
                log.warning("checkpoint %s has newer format %s; skipping",
                            path, meta.get("format"))
                return None
            for fname, want in (meta.get("sha256") or {}).items():
                got = _sha256_file(os.path.join(path, fname))
                if got != want:
                    log.warning("checkpoint %s: %s checksum mismatch "
                                "(corrupt/truncated); skipping",
                                path, fname)
                    return None
            return meta
        except (OSError, ValueError, KeyError) as exc:
            log.warning("checkpoint %s unreadable (%s); skipping",
                        path, exc)
            return None

    def peek(self, config=None) -> Optional[Tuple[str, dict]]:
        """Newest valid checkpoint compatible with this manager's
        (pristine) config digest: returns ``(path, meta)`` without
        touching any trainer state.  A config digest mismatch refuses
        the WHOLE resume (older checkpoints are from the same run —
        they'd mismatch too)."""
        want = self.digest or (config_digest(config)
                               if config is not None else None)
        for path in self.list_checkpoints():
            meta = self._validate(path)
            if meta is None:
                continue
            if want is not None and meta.get("config_digest") != want:
                log.warning(
                    "checkpoint %s was written under a different training "
                    "config (digest %s != %s); refusing to resume — "
                    "starting fresh", path, meta.get("config_digest"),
                    want)
                return None
            return path, meta
        return None

    def resume(self, booster, peeked: Tuple[str, dict]) -> RestoreState:
        """Load a peeked checkpoint into ``booster`` (call AFTER valid
        sets are attached so their score slots exist)."""
        from .. import obs
        from ..io.model_io import load_model_string
        path, meta = peeked
        gbdt = booster._gbdt
        if int(meta.get("num_data", -1)) != int(gbdt.train_ds.num_data):
            raise ValueError(
                f"checkpoint {path} was trained on "
                f"{meta.get('num_data')} rows but this dataset has "
                f"{gbdt.train_ds.num_data}")
        with open(os.path.join(path, "model.txt")) as fh:
            loaded, _ = load_model_string(fh.read())
        gbdt.load_initial_models(list(loaded.models), replay_scores=False)
        with np.load(os.path.join(path, "state.npz")) as npz:
            arrays = {k: npz[k] for k in npz.files}
        gbdt.restore_checkpoint_state(meta["state"], arrays)
        booster.best_iteration = int(meta.get("best_iteration", -1))
        history = [(int(it), [tuple(e) for e in entries])
                   for it, entries in meta.get("eval_history", [])]
        obs.event("restore", iteration=int(meta["iteration"]), path=path)
        log.info("checkpoint: resumed from %s at iteration %d "
                 "(%d trees, %d recorded eval rounds)", path,
                 int(meta["iteration"]), len(loaded.models), len(history))
        return RestoreState(iteration=int(meta["iteration"]), path=path,
                            eval_history=history, meta=meta)
