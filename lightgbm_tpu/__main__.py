"""``python -m lightgbm_tpu task=... conf=...`` (reference: src/main.cpp)."""
from .app import main

if __name__ == "__main__":
    main()
