"""Device-side tree traversal over binned data.

Replaces the reference's training-time ``Tree::AddPredictionToScore`` inner
traversal (reference: include/LightGBM/tree.h:101-114, src/io/tree.cpp) with
a vectorized gather loop: every row walks the tree simultaneously, one level
per ``while_loop`` step, until all rows rest in leaves.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .grower import TreeArrays, decode_feature_col
from .meta import DeviceMeta
from .splitter import split_decision


@jax.named_scope("lgbm/tree_traverse")
def predict_leaf_bins(tree: TreeArrays, bins, meta: DeviceMeta,
                      phys: bool = False):
    """Leaf index per row for binned inputs. bins: [N, F] uint8/int32.

    ``phys=True`` reads EFB physical-column layout (training/valid bins of a
    bundled dataset) and decodes each node's feature bin on the fly;
    ``phys=False`` expects per-feature (inner) columns."""
    N = bins.shape[0]
    start = jnp.where(tree.num_leaves > 1, 0, ~0)
    node = jnp.full((N,), start, jnp.int32)

    def cond(node):
        return jnp.any(node >= 0)

    def step(node):
        active = node >= 0
        nd = jnp.maximum(node, 0)
        f = tree.split_feature[nd]
        fcol = meta.feat2phys[f] if phys else f
        col = jnp.take_along_axis(bins, fcol[:, None].astype(jnp.int32),
                                  axis=1)[:, 0].astype(jnp.int32)
        if phys:
            col = decode_feature_col(col, f, meta)
        # categorical nodes: membership in the node's bin-space bitset
        # (reference: Tree::CategoricalDecisionInner, tree.h:265-303) —
        # the word holding col's bit is gathered per row, then the shared
        # split_decision helper routes numerical/missing/categorical alike
        word = jnp.take_along_axis(tree.cat_bitset[nd],
                                   (col // 32)[:, None], axis=1)[:, 0]
        gl = split_decision(col, tree.threshold_bin[nd],
                            tree.default_left[nd], meta.is_categorical[f],
                            word, meta.missing_types[f], meta.num_bins[f],
                            meta.default_bins[f])
        nxt = jnp.where(gl, tree.left_child[nd], tree.right_child[nd])
        return jnp.where(active, nxt, node)

    node = jax.lax.while_loop(cond, step, node)
    return ~node


def add_score_bins(score, tree: TreeArrays, bins, meta: DeviceMeta, shrinkage,
                   phys: bool = False):
    """score += shrinkage * leaf_value[leaf(row)] (reference:
    src/boosting/score_updater.hpp:84-108)."""
    leaf = predict_leaf_bins(tree, bins, meta, phys=phys)
    return score + shrinkage * tree.leaf_value[leaf]
