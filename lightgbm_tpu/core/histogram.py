"""Histogram construction — the hottest loop of the framework.

The reference accumulates per-feature (sum_grad, sum_hess, count) bins with a
scalar gather-accumulate loop on CPU (reference: src/io/dense_bin.hpp:71-135)
and a workgroup-atomic OpenCL kernel on GPU (reference:
src/treelearner/ocl/histogram256.cl:350).  TPUs have no fast arbitrary
scatter, so the TPU-native formulation turns bin accumulation into one-hot
matmuls that run on the MXU:

    hist[f, b, :] = sum_r onehot(X_bin[r, f])[b] * (g, h, 1)[r]

i.e. a single ``[F*B, C] @ [C, 3]`` contraction per row-chunk, scanned over
chunks so the one-hot tile never exceeds a few tens of MB.  A Pallas kernel
(ops/pallas_hist.py) implements the same contraction with the one-hot tile
built directly in VMEM.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# target one-hot tile size in elements (f32): ~32MB
_TILE_ELEMS = 8 * 1024 * 1024


def _chunk_rows(F: int, B: int) -> int:
    c = max(128, _TILE_ELEMS // (F * B))
    # round down to a multiple of 128 (sublane-friendly)
    return max(128, (c // 128) * 128)


@functools.partial(jax.jit, static_argnames=("B",))
@jax.named_scope("lgbm/hist_onehot")
def hist_onehot(bins, g, h, mask, B: int):
    """Dense histogram via chunked one-hot contraction.

    Parameters
    ----------
    bins : uint8/int32 [C, F] per-row bin indices (feature-local, unpadded)
    g, h : float32 [C] gradients / hessians
    mask : float32 [C] 1.0 for rows to accumulate (bagging x leaf membership)
    B : static padded bin width

    Returns
    -------
    float32 [F, B, 3] — (sum_grad, sum_hess, count) per feature x bin.
    """
    C, F = bins.shape
    ghc = jnp.stack([g, h, jnp.ones_like(g)], axis=-1) * mask[:, None]  # [C,3]
    chunk = _chunk_rows(F, B)
    if C <= chunk:
        return _hist_block(bins, ghc, B)
    n_chunks = -(-C // chunk)
    pad = n_chunks * chunk - C
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        ghc = jnp.pad(ghc, ((0, pad), (0, 0)))
    bins_c = bins.reshape(n_chunks, chunk, F)
    ghc_c = ghc.reshape(n_chunks, chunk, 3)

    def body(acc, xs):
        b, z = xs
        return acc + _hist_block(b, z, B), None

    init = jnp.zeros((F, B, 3), dtype=jnp.float32)
    out, _ = jax.lax.scan(body, init, (bins_c, ghc_c))
    return out


def _hist_block(bins, ghc, B: int):
    """One-hot contraction for a single row block: [c,F],[c,3] -> [F,B,3]."""
    c, F = bins.shape
    oh = jax.nn.one_hot(bins.astype(jnp.int32), B, dtype=jnp.float32)  # [c,F,B]
    # contract over rows: [c, F*B]^T @ [c, 3]
    flat = oh.reshape(c, F * B)
    out = jax.lax.dot_general(
        flat, ghc, (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)
    return out.reshape(F, B, 3)


@functools.partial(jax.jit, static_argnames=("B",))
@jax.named_scope("lgbm/hist_scatter")
def hist_scatter(bins, g, h, mask, B: int):
    """Scatter-add histogram for VERY wide physical layouts (wide-sparse
    EFB datasets): cost O(N*F) instead of the one-hot path's O(N*F*B).

    The reference's answer to wide sparse data is SparseBin's
    nonzero-stream accumulate (src/io/sparse_bin.hpp:72); a dense one-hot
    contraction over 50k+ features x thousands of bundle bins would
    materialize terabytes.  Scatter-add is not MXU-friendly, but at these
    shapes it is the only formulation with a feasible op count — and
    wide-sparse is a CPU/host-dominant regime in the reference too.

    Same contract as ``hist_onehot``: bins [N, F] -> f32 [F, B, 3].
    """
    N, F = bins.shape
    ghc = jnp.stack([g, h, jnp.ones_like(g)], axis=-1) * mask[:, None]
    offsets = (jnp.arange(F, dtype=jnp.int32) * B)[None, :]
    # chunk rows so the broadcasted [c, F, 3] update tensor stays ~100MB
    chunk = max(256, min(N, (8 * 1024 * 1024) // max(F, 1)))
    out = jnp.zeros((F * B, 3), jnp.float32)
    if N <= chunk:
        flat = bins.astype(jnp.int32) + offsets
        out = out.at[flat].add(ghc[:, None, :])
        return out.reshape(F, B, 3)
    n_chunks = -(-N // chunk)
    pad = n_chunks * chunk - N
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        ghc = jnp.pad(ghc, ((0, pad), (0, 0)))
    bins_c = bins.reshape(n_chunks, chunk, F)
    ghc_c = ghc.reshape(n_chunks, chunk, 3)

    def body(acc, xs):
        b, z = xs
        flat = b.astype(jnp.int32) + offsets
        return acc.at[flat].add(z[:, None, :]), None

    out, _ = jax.lax.scan(body, out, (bins_c, ghc_c))
    return out.reshape(F, B, 3)


@functools.partial(jax.jit, static_argnames=("B",))
@jax.named_scope("lgbm/hist_wave_xla")
def hist_wave_xla(bins_rm, gv, hv, cv, leaf_id, slot_leaf, B: int):
    """XLA analog of ``ops.pallas_hist.hist_pallas_wave`` for WIDE
    (>256-bin) features — the side-pass of the mixed-width wave path.

    The Pallas kernel's one-hot tile is built per uint8 feature block in
    VMEM; features with more than 256 bins don't fit that layout, so the
    few wide columns (high-cardinality categoricals, mostly) take this
    chunked one-hot contraction instead and are merged with the kernel's
    output before the split scan (core/wave_grower.py).

    bins_rm: ROW-major [N, Fw] bin indices; gv/hv/cv: f32 [N] (bag-masked
    g, h, ones); leaf_id: i32 [N]; slot_leaf: i32 [C] channel->leaf map
    (kinds cycle g,h,count; -1 = unused).  Returns [Fw, B, C] f32 matching
    the kernel's channel semantics.
    """
    N, Fw = bins_rm.shape
    C = slot_leaf.shape[0]
    kind = jnp.arange(C, dtype=jnp.int32) % 3
    vals = jnp.stack([gv, hv, cv], axis=1)               # [N, 3]
    chunk = _chunk_rows(Fw, B)
    if N > chunk:
        pad = (-N) % chunk
        if pad:
            bins_rm = jnp.pad(bins_rm, ((0, pad), (0, 0)))
            vals = jnp.pad(vals, ((0, pad), (0, 0)))
            leaf_id = jnp.pad(leaf_id, (0, pad), constant_values=-2)
        n_chunks = bins_rm.shape[0] // chunk
        bins_c = bins_rm.reshape(n_chunks, chunk, Fw)
        vals_c = vals.reshape(n_chunks, chunk, 3)
        leaf_c = leaf_id.reshape(n_chunks, chunk)
    else:
        bins_c = bins_rm[None]
        vals_c = vals[None]
        leaf_c = leaf_id[None]

    def body(acc, xs):
        b, v, l = xs
        m = (l[:, None] == slot_leaf[None, :]) & (slot_leaf >= 0)[None, :]
        gh = jnp.where(m, v[:, kind], 0.0)               # [c, C]
        oh = jax.nn.one_hot(b.astype(jnp.int32), B, dtype=jnp.float32)
        out = jax.lax.dot_general(
            oh.reshape(b.shape[0], Fw * B), gh, (((0,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)          # [Fw*B, C]
        return acc + out, None

    init = jnp.zeros((Fw * B, C), jnp.float32)
    out, _ = jax.lax.scan(body, init, (bins_c, vals_c, leaf_c))
    return out.reshape(Fw, B, C)


def hist_onehot_cost(N: int, F: int, B: int, C: int = 3):
    """Analytical (FLOPs, bytes) of ``hist_onehot``/``hist_wave_xla`` over
    N rows: the one-hot contraction is charged 2*N*F*B*C FLOPs, and —
    unlike the Pallas kernel — XLA materializes the one-hot tiles, so the
    memory leg includes the [chunk, F, B] f32 factor round-trip.  Used by
    profile mode and ``tools/prof_kernels.py`` for roofline comparison."""
    flops = 2.0 * N * F * B * C
    nbytes = float(N) * F * (4 + 8 * B) + N * C * 4 + F * B * C * 4
    return flops, nbytes


def hist_scatter_cost(N: int, F: int, C: int = 3):
    """Analytical (FLOPs, bytes) of ``hist_scatter``: O(N*F) scatter-adds
    (no B term — that is the whole point of the wide-layout path)."""
    flops = float(C) * N * F
    nbytes = float(N) * F * (4 + C * 4) + N * C * 4
    return flops, nbytes


def hist_subtract(parent, child):
    """Sibling histogram by subtraction (reference:
    src/treelearner/feature_histogram.hpp:75-81, serial_tree_learner.cpp:567)."""
    return parent - child


def expand_bundled(hist_phys, meta, B_out: int):
    """EFB bundle expansion: physical-column histograms -> per-feature
    histograms (see io/bundling.py for the bin layout).

    hist_phys: f32 [F_phys, B_phys, C]; returns [F, B_out, C] where
    out[f, b] = hist_phys[feat2phys[f], feat_offset[f] + b] for b within
    feature f's bins, zero elsewhere.  Histogram-sized (not data-sized), so
    the gather is cheap relative to the kernel pass it follows.
    """
    Fp, Bp, C = hist_phys.shape
    b = jnp.arange(B_out, dtype=jnp.int32)
    idx = (meta.feat2phys[:, None] * Bp + meta.feat_offset[:, None]
           + b[None, :])                                  # [F, B_out]
    valid = (b[None, :] < meta.num_bins[:, None]) & \
        (meta.feat_offset[:, None] + b[None, :] < Bp)
    flat = hist_phys.reshape(Fp * Bp, C)
    out = flat[jnp.where(valid, idx, 0)]
    return out * valid[..., None]


def fix_default_bins(hist, tg, th, tc, meta, alive=None):
    """Reconstruct each bundled member's elided default-bin mass from the
    leaf totals (reference: Dataset::FixHistogram, src/io/dataset.cpp:
    1044-1063): hist[f, default_bin_f] += total - sum_b hist[f, b].

    hist: f32 [F, B, 3]; tg/th/tc: scalar leaf totals.  ``alive`` (bool
    [F_phys], optional) marks physical columns that survived a lossy
    reduce (voting-parallel's top-k gate): members of a gated-OFF column
    must stay all-zero — fixing them would fabricate the whole leaf mass
    at their default bin and produce phantom splits."""
    sums = hist.sum(axis=1)                               # [F, 3]
    totals = jnp.stack([tg, th, tc]).astype(hist.dtype)   # [3]
    fix = meta.needs_fix
    if alive is not None:
        fix = fix & alive[meta.feat2phys]
    resid = jnp.where(fix[:, None], totals[None, :] - sums, 0.0)
    F = hist.shape[0]
    return hist.at[jnp.arange(F), meta.default_bins].add(resid)
