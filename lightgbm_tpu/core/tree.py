"""Host-side tree model: raw-value prediction and serialization state.

The reference ``Tree`` (reference: include/LightGBM/tree.h:25-530,
src/io/tree.cpp) keeps SoA node arrays in both bin space (training) and value
space (inference). Here the device grower emits bin-space arrays
(``core.grower.TreeArrays``); this class converts them once to value space
using the dataset's bin mappers and serves numpy prediction, feature
importance and model-text serialization.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..io.binning import BIN_CATEGORICAL, MISSING_NAN, MISSING_NONE, MISSING_ZERO

K_ZERO_THRESHOLD = 1e-35

_MISSING_TYPE_STR = {MISSING_NONE: "None", MISSING_ZERO: "Zero", MISSING_NAN: "NaN"}

# decision_type bit layout (reference: tree.h:19-20, 193-212)
_CAT_MASK = 1
_DEFAULT_LEFT_MASK = 2


class Tree:
    """One trained tree in value space.

    All arrays are numpy; ``num_leaves`` is the realized leaf count (unused
    fixed-capacity slots from the device arrays are trimmed).
    """

    def __init__(self, num_leaves: int,
                 split_feature: np.ndarray,      # original (outer) feature idx
                 threshold: np.ndarray,          # real-valued threshold
                 threshold_bin: np.ndarray,
                 decision_type: np.ndarray,      # packed missing/default-left/cat
                 left_child: np.ndarray, right_child: np.ndarray,
                 leaf_value: np.ndarray, leaf_count: np.ndarray,
                 leaf_weight: np.ndarray,
                 split_gain: np.ndarray, internal_value: np.ndarray,
                 internal_count: np.ndarray, internal_weight: np.ndarray,
                 cat_boundaries: Optional[np.ndarray] = None,
                 cat_threshold: Optional[np.ndarray] = None,
                 shrinkage: float = 1.0):
        self.num_leaves = int(num_leaves)
        self.split_feature = split_feature
        self.threshold = threshold
        self.threshold_bin = threshold_bin
        self.decision_type = decision_type
        self.left_child = left_child
        self.right_child = right_child
        self.leaf_value = leaf_value
        self.leaf_count = leaf_count
        self.leaf_weight = leaf_weight
        self.split_gain = split_gain
        self.internal_value = internal_value
        self.internal_count = internal_count
        self.internal_weight = internal_weight
        # categorical thresholds: bitsets concatenated, indexed by cat_idx
        # (reference: tree.h:83-99 cat_boundaries_/cat_threshold_)
        self.cat_boundaries = (cat_boundaries if cat_boundaries is not None
                               else np.zeros(1, dtype=np.int32))
        self.cat_threshold = (cat_threshold if cat_threshold is not None
                              else np.zeros(0, dtype=np.uint32))
        self.shrinkage = float(shrinkage)

    # ------------------------------------------------------------------
    @classmethod
    def from_device(cls, arrays, dataset, shrinkage: float = 1.0) -> "Tree":
        """Convert device ``TreeArrays`` (bin space) to a value-space tree.

        ``dataset`` supplies bin mappers for real thresholds
        (reference: Dataset::RealThreshold).
        """
        import numpy as _np
        nl = int(arrays.num_leaves)
        nn = max(nl - 1, 0)
        split_feature_inner = _np.asarray(arrays.split_feature)[:nn]
        threshold_bin = _np.asarray(arrays.threshold_bin)[:nn]
        default_left = _np.asarray(arrays.default_left)[:nn]
        bin_bitsets = _np.asarray(arrays.cat_bitset)[:nn]  # u32 [nn, W]

        threshold = _np.zeros(nn, dtype=_np.float64)
        decision_type = _np.zeros(nn, dtype=_np.int32)
        split_feature = _np.zeros(nn, dtype=_np.int32)
        cat_boundaries = [0]
        cat_threshold: List[int] = []
        for i in range(nn):
            inner = int(split_feature_inner[i])
            mapper = dataset.inner_to_mapper(inner)
            split_feature[i] = int(dataset.real_feature_idx[inner])
            dt = _MISSING_SHIFT[mapper.missing_type]
            if mapper.bin_type == BIN_CATEGORICAL:
                dt |= _CAT_MASK
                # translate the grower's bin-space bitset into the model's
                # value-space bitset (reference: tree.cpp Tree::Split cat
                # form + Common::ConstructBitset); the NaN pseudo-category
                # (-1) is dropped — value-space prediction sends missing
                # right, matching CategoricalDecision (tree.h:265-303)
                words = bin_bitsets[i]
                cats = [
                    mapper.bin_2_categorical[b]
                    for b in range(len(mapper.bin_2_categorical))
                    if (int(words[b // 32]) >> (b % 32)) & 1
                    and mapper.bin_2_categorical[b] >= 0
                ]
                n_words = (max(cats) // 32 + 1) if cats else 1
                vw = [0] * n_words
                for cvals in cats:
                    vw[cvals // 32] |= 1 << (cvals % 32)
                threshold[i] = len(cat_boundaries) - 1  # cat index
                cat_threshold.extend(vw)
                cat_boundaries.append(cat_boundaries[-1] + n_words)
            else:
                if default_left[i]:
                    dt |= _DEFAULT_LEFT_MASK
                threshold[i] = mapper.bin_to_value(int(threshold_bin[i]))
            decision_type[i] = dt

        return cls(
            num_leaves=nl,
            split_feature=split_feature,
            threshold=threshold,
            threshold_bin=threshold_bin.astype(_np.int32),
            decision_type=decision_type,
            left_child=_np.asarray(arrays.left_child)[:nn].astype(_np.int32),
            right_child=_np.asarray(arrays.right_child)[:nn].astype(_np.int32),
            leaf_value=_np.asarray(arrays.leaf_value)[:nl].astype(_np.float64),
            leaf_count=_np.asarray(arrays.leaf_count)[:nl].astype(_np.int32),
            leaf_weight=_np.asarray(arrays.leaf_weight)[:nl].astype(_np.float64),
            split_gain=_np.asarray(arrays.split_gain)[:nn].astype(_np.float64),
            internal_value=_np.asarray(arrays.internal_value)[:nn].astype(_np.float64),
            internal_count=_np.asarray(arrays.internal_count)[:nn].astype(_np.int32),
            internal_weight=_np.asarray(arrays.internal_weight)[:nn].astype(_np.float64),
            cat_boundaries=np.asarray(cat_boundaries, dtype=np.int32),
            cat_threshold=np.asarray(cat_threshold, dtype=np.uint32),
            shrinkage=shrinkage,
        )

    # ------------------------------------------------------------------
    def missing_type(self, node: int) -> int:
        return (int(self.decision_type[node]) >> 2) & 3

    def is_categorical(self, node: int) -> bool:
        return bool(self.decision_type[node] & _CAT_MASK)

    def default_left(self, node: int) -> bool:
        return bool(self.decision_type[node] & _DEFAULT_LEFT_MASK)

    def apply_shrinkage(self, rate: float) -> None:
        """(reference: Tree::Shrinkage, tree.h:149-160)."""
        self.leaf_value = self.leaf_value * rate
        self.internal_value = self.internal_value * rate
        self.shrinkage *= rate

    # ------------------------------------------------------------------
    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        """Leaf index per row for raw feature values (vectorized traversal,
        reference: Tree::GetLeaf + NumericalDecision, tree.h:447-530)."""
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int64)
        node = np.zeros(n, dtype=np.int64)
        active = np.ones(n, dtype=bool)
        while active.any():
            nd = node[active]
            fv = X[active, self.split_feature[nd]].astype(np.float64)
            go_left = self._decide(fv, nd)
            nxt = np.where(go_left, self.left_child[nd], self.right_child[nd])
            node[active] = nxt
            active = node >= 0
        return ~node

    def _decide(self, fval: np.ndarray, nodes: np.ndarray) -> np.ndarray:
        """Vectorized NumericalDecision / CategoricalDecision
        (reference: tree.h:221-303)."""
        dt = self.decision_type[nodes]
        missing = (dt >> 2) & 3
        is_cat = (dt & _CAT_MASK).astype(bool)
        default_left = (dt & _DEFAULT_LEFT_MASK).astype(bool)
        thr = self.threshold[nodes]

        nan_mask = np.isnan(fval)
        fv = np.where(nan_mask & (missing != MISSING_NAN), 0.0, fval)
        is_zero = np.abs(fv) <= K_ZERO_THRESHOLD
        is_missing = (((missing == MISSING_ZERO) & is_zero)
                      | ((missing == MISSING_NAN) & np.isnan(fv)))
        numerical = np.where(is_missing, default_left, fv <= thr)

        if is_cat.any():
            # the raw value, NOT the NaN-zeroed fv: the reference's
            # CategoricalDecision casts NaN to a negative int and routes it
            # right before any missing-type handling (tree.h:262-265)
            cat_left = self._cat_decide(fval, nodes)
            return np.where(is_cat, cat_left, numerical)
        return numerical

    def _cat_decide(self, fval: np.ndarray, nodes: np.ndarray) -> np.ndarray:
        """FindInBitset over the node's category set
        (reference: tree.h:265-303, utils/common.h ConstructBitset)."""
        out = np.zeros(len(fval), dtype=bool)
        iv = np.where(np.isnan(fval) | (fval < 0), -1, fval).astype(np.int64)
        for j in range(len(fval)):
            node = int(nodes[j])
            if not self.is_categorical(node):
                continue
            cat_idx = int(self.threshold[node])
            lo = int(self.cat_boundaries[cat_idx])
            hi = int(self.cat_boundaries[cat_idx + 1])
            v = int(iv[j])
            word, bit = v // 32, v % 32
            if v >= 0 and word < hi - lo:
                out[j] = bool((int(self.cat_threshold[lo + word]) >> bit) & 1)
        return out

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Raw (margin) predictions for a dense float matrix."""
        return self.leaf_value[self.predict_leaf(X)]

    @property
    def max_depth(self) -> int:
        if self.num_leaves <= 1:
            return 0
        depth = np.zeros(self.num_leaves - 1, dtype=np.int64)
        # nodes are created in split order, so parents precede children
        md = 1
        for i in range(self.num_leaves - 1):
            for child in (self.left_child[i], self.right_child[i]):
                if child >= 0:
                    depth[child] = depth[i] + 1
                    md = max(md, int(depth[child]) + 1)
        return md


_MISSING_SHIFT = {
    MISSING_NONE: MISSING_NONE << 2,
    MISSING_ZERO: MISSING_ZERO << 2,
    MISSING_NAN: MISSING_NAN << 2,
}
